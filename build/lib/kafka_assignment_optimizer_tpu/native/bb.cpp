// Native exact 0-1 solver for the Kafka partition-reassignment model.
//
// Role: the reference delegates its solve to lp_solve 5.5, an *external*
// native C branch-and-bound MILP solver (/root/reference/README.md:135-137).
// This file is the bundled TPU-framework equivalent: a specialized
// branch-and-bound over the replica-slot representation (models/instance.py)
// rather than the dense 0-1 variable matrix — the same model the LP emitter
// serializes (README.md:144-185), solved exactly, in-process, with no
// external dependency.
//
// Search design:
//   - one decision level per partition: choose (leader, follower set);
//     followers are enumerated as increasing positions in the partition's
//     weight-sorted broker permutation, so each combination is visited once
//     and in roughly best-first order (fast first incumbent, strong pruning)
//   - hard constraint forward-checking on every placement: per-broker total
//     and leader caps, per-rack caps, per-(partition,rack) diversity caps
//   - lower-bound deficits: unmet broker/rack/leader minimums must fit in
//     the remaining unassigned replica slots, else prune
//   - optimistic bound: suffix sum of per-partition unconstrained maxima
//     (leader best + top rf-1 follower weights), pruned against incumbent
//
// Exposed via a C ABI for ctypes (solvers/native.py). All arrays int32,
// row-major; broker index B is the shared null bucket for unused slots.

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

using std::int32_t;
using std::int64_t;

struct Problem {
  int P, B, K, R;
  const int32_t *rf;            // [P]
  const int32_t *rack_of;       // [B]
  const int32_t *wl;            // [P, B+1] leader-role weight
  const int32_t *wf;            // [P, B+1] follower-role weight
  int broker_lo, broker_hi, leader_lo, leader_hi;
  const int32_t *rack_lo;       // [K]
  const int32_t *rack_hi;       // [K]
  const int32_t *part_rack_hi;  // [P]

  int wcols() const { return B + 1; }
  int32_t wlead(int p, int b) const { return wl[p * wcols() + b]; }
  int32_t wfoll(int p, int b) const { return wf[p * wcols() + b]; }
};

struct Stats {
  int64_t nodes = 0;
  bool timed_out = false;
};

class Solver {
 public:
  Solver(const Problem &pr, double time_limit_s)
      : pr_(pr),
        deadline_(std::chrono::steady_clock::now() +
                  std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                      std::chrono::duration<double>(time_limit_s))) {
    const int P = pr_.P, B = pr_.B, K = pr_.K;
    cnt_.assign(B, 0);
    lcnt_.assign(B, 0);
    rcnt_.assign(K, 0);
    pr_rack_.assign((size_t)P * K, 0);
    cur_.assign((size_t)P * pr_.R, B);
    best_.assign((size_t)P * pr_.R, B);

    // process partitions most-constrained first (highest rf, then highest
    // unconstrained weight) — tightens caps early and finds the incumbent
    // near the root
    order_.resize(P);
    for (int p = 0; p < P; ++p) order_[p] = p;
    std::vector<int64_t> pmax(P);
    for (int p = 0; p < P; ++p) pmax[p] = partition_max(p);
    std::stable_sort(order_.begin(), order_.end(), [&](int a, int b) {
      if (pr_.rf[a] != pr_.rf[b]) return pr_.rf[a] > pr_.rf[b];
      return pmax[a] > pmax[b];
    });

    // suffix of per-partition optimistic maxima over the processing order
    suffix_ub_.assign(P + 1, 0);
    for (int i = P - 1; i >= 0; --i)
      suffix_ub_[i] = suffix_ub_[i + 1] + pmax[order_[i]];

    // remaining replica slots / partitions after level i
    rem_replicas_.assign(P + 1, 0);
    for (int i = P - 1; i >= 0; --i)
      rem_replicas_[i] = rem_replicas_[i + 1] + pr_.rf[order_[i]];

    // per-partition broker permutations sorted by weight descending:
    // one by leader weight (leader choice), one by follower weight
    lead_perm_.resize(P);
    foll_perm_.resize(P);
    for (int p = 0; p < P; ++p) {
      lead_perm_[p].resize(B);
      foll_perm_[p].resize(B);
      for (int b = 0; b < B; ++b) lead_perm_[p][b] = foll_perm_[p][b] = b;
      std::stable_sort(lead_perm_[p].begin(), lead_perm_[p].end(),
                       [&](int a, int b) { return pr_.wlead(p, a) > pr_.wlead(p, b); });
      std::stable_sort(foll_perm_[p].begin(), foll_perm_[p].end(),
                       [&](int a, int b) { return pr_.wfoll(p, a) > pr_.wfoll(p, b); });
    }

    // initial lower-bound deficits: everything unmet
    broker_deficit_ = (int64_t)pr_.broker_lo * B;
    leader_deficit_ = (int64_t)pr_.leader_lo * B;
    rack_deficit_ = 0;
    for (int k = 0; k < K; ++k) rack_deficit_ += pr_.rack_lo[k];
  }

  // Install a known-feasible warm start (verified by the caller) so the
  // optimistic bound prunes from the very first node — without it the
  // search is a pure feasibility CSP until the first leaf, which can
  // thrash exponentially under tight capacity bands.
  void warm_start(const int32_t *seed_a, int64_t seed_w) {
    std::memcpy(best_.data(), seed_a, best_.size() * sizeof(int32_t));
    best_w_ = seed_w;
    have_best_ = true;
  }

  // returns status: 0 optimal, 1 time limit w/ incumbent, 2 none found
  int run(int32_t *out_a, int64_t *out_obj, int64_t *out_nodes) {
    dfs(0, 0);
    *out_nodes = stats_.nodes;
    if (!have_best_) return stats_.timed_out ? 2 : 3;  // 3 = proven infeasible
    std::memcpy(out_a, best_.data(), best_.size() * sizeof(int32_t));
    *out_obj = best_w_;
    return stats_.timed_out ? 1 : 0;
  }

 private:
  int64_t partition_max(int p) const {
    // unconstrained per-partition optimum: best leader choice + top rf-1
    // follower weights among the others (mirrors instance.max_weight)
    const int B = pr_.B, rf = pr_.rf[p];
    int64_t best = 0;
    std::vector<int32_t> wfs;
    for (int lead = -1; lead < B; ++lead) {
      int64_t w = lead < 0 ? 0 : pr_.wlead(p, lead);
      if (lead >= 0 && w == 0) continue;  // unweighted leader == lead=-1 case
      wfs.clear();
      for (int b = 0; b < B; ++b)
        if (b != lead && pr_.wfoll(p, b) > 0) wfs.push_back(pr_.wfoll(p, b));
      std::sort(wfs.begin(), wfs.end(), std::greater<int32_t>());
      for (int i = 0; i < (int)wfs.size() && i < rf - 1; ++i) w += wfs[i];
      best = std::max(best, w);
    }
    return best;
  }

  bool time_up() {
    if ((++stats_.nodes & 0xFFF) == 0 &&
        std::chrono::steady_clock::now() >= deadline_)
      stats_.timed_out = true;
    return stats_.timed_out;
  }

  // --- incremental placement bookkeeping ----------------------------
  // Returns false (and leaves state untouched) if caps forbid it.
  bool place(int p, int b, bool leader) {
    const int k = pr_.rack_of[b];
    if (cnt_[b] >= pr_.broker_hi) return false;
    if (rcnt_[k] >= pr_.rack_hi[k]) return false;
    if (pr_rack_[(size_t)p * pr_.K + k] >= pr_.part_rack_hi[p]) return false;
    if (leader && lcnt_[b] >= pr_.leader_hi) return false;
    if (cnt_[b] < pr_.broker_lo) --broker_deficit_;
    ++cnt_[b];
    if (rcnt_[k] < pr_.rack_lo[k]) --rack_deficit_;
    ++rcnt_[k];
    ++pr_rack_[(size_t)p * pr_.K + k];
    if (leader) {
      if (lcnt_[b] < pr_.leader_lo) --leader_deficit_;
      ++lcnt_[b];
    }
    return true;
  }

  void unplace(int p, int b, bool leader) {
    const int k = pr_.rack_of[b];
    if (leader) {
      --lcnt_[b];
      if (lcnt_[b] < pr_.leader_lo) ++leader_deficit_;
    }
    --pr_rack_[(size_t)p * pr_.K + k];
    --rcnt_[k];
    if (rcnt_[k] < pr_.rack_lo[k]) ++rack_deficit_;
    --cnt_[b];
    if (cnt_[b] < pr_.broker_lo) ++broker_deficit_;
  }

  // deficits must be coverable by what is still to be placed
  bool deficits_ok(int next_level) const {
    const int64_t rem = rem_replicas_[next_level];
    const int64_t rem_parts = pr_.P - next_level;  // leaders still to place
    return broker_deficit_ <= rem && rack_deficit_ <= rem &&
           leader_deficit_ <= rem_parts;
  }

  void dfs(int level, int64_t w) {
    if (stats_.timed_out) return;
    if (level == pr_.P) {
      if (broker_deficit_ == 0 && rack_deficit_ == 0 && leader_deficit_ == 0 &&
          w > best_w_) {
        best_w_ = w;
        best_ = cur_;
        have_best_ = true;
      }
      return;
    }
    if (w + suffix_ub_[level] <= best_w_ && have_best_) return;  // bound
    const int p = order_[level];
    // leader-independent follower optimum: top rf-1 follower weights with
    // no broker excluded — an upper bound for ANY leader choice, so it is
    // monotone over the sorted leader scan and safe to break on
    const int64_t ub_f_all = follower_ub(p, /*bl=*/-1);
    // leader choices in descending leader-weight order
    for (int li = 0; li < pr_.B; ++li) {
      if (time_up()) return;
      const int bl = lead_perm_[p][li];
      const int64_t w_lead = pr_.wlead(p, bl);
      // leaders are sorted: once even the best completion with this (or any
      // later) leader can't beat the incumbent, stop scanning leaders
      if (have_best_ &&
          w + w_lead + ub_f_all + suffix_ub_[level + 1] <= best_w_)
        break;
      // exact bound for THIS leader (bl excluded from the follower pool)
      if (have_best_ &&
          w + w_lead + follower_ub(p, bl) + suffix_ub_[level + 1] <= best_w_)
        continue;
      if (!place(p, bl, /*leader=*/true)) continue;
      cur_[(size_t)p * pr_.R + 0] = bl;
      followers(level, p, /*slot=*/1, /*min_pos=*/0, bl, w + w_lead);
      cur_[(size_t)p * pr_.R + 0] = pr_.B;
      unplace(p, bl, true);
    }
  }

  // optimistic total follower weight for partition p given leader bl
  int64_t follower_ub(int p, int bl) const {
    int64_t ub = 0;
    int taken = 0;
    for (int i = 0; i < pr_.B && taken < pr_.rf[p] - 1; ++i) {
      const int b = foll_perm_[p][i];
      if (b == bl) continue;
      const int32_t wv = pr_.wfoll(p, b);
      if (wv <= 0) break;
      ub += wv;
      ++taken;
    }
    return ub;
  }

  // enumerate follower slots as increasing positions in foll_perm_[p]
  void followers(int level, int p, int slot, int min_pos, int bl, int64_t w) {
    if (stats_.timed_out) return;
    if (slot == pr_.rf[p]) {
      if (deficits_ok(level + 1)) dfs(level + 1, w);
      return;
    }
    const int remaining = pr_.rf[p] - slot;
    // not enough brokers left to fill remaining slots → dead end
    for (int pos = min_pos; pos <= pr_.B - remaining; ++pos) {
      if (time_up()) return;
      const int b = foll_perm_[p][pos];
      if (b == bl) continue;
      const int64_t wv = pr_.wfoll(p, b);
      // descending order ⇒ every later position is worth ≤ wv; bound the
      // whole remaining follower block by remaining * wv
      if (have_best_ &&
          w + (int64_t)remaining * wv + suffix_ub_[level + 1] <= best_w_)
        break;
      if (!place(p, b, /*leader=*/false)) continue;
      cur_[(size_t)p * pr_.R + slot] = b;
      followers(level, p, slot + 1, pos + 1, bl, w + wv);
      cur_[(size_t)p * pr_.R + slot] = pr_.B;
      unplace(p, b, false);
    }
  }

  const Problem &pr_;
  std::chrono::steady_clock::time_point deadline_;
  Stats stats_;
  std::vector<int> order_;
  std::vector<int64_t> suffix_ub_, rem_replicas_;
  std::vector<std::vector<int>> lead_perm_, foll_perm_;
  std::vector<int32_t> cnt_, lcnt_, rcnt_, pr_rack_, cur_, best_;
  int64_t broker_deficit_ = 0, leader_deficit_ = 0, rack_deficit_ = 0;
  int64_t best_w_ = -1;
  bool have_best_ = false;
};

}  // namespace

extern "C" {

// status: 0 = proven optimal, 1 = time limit (incumbent returned),
//         2 = time limit with no incumbent, 3 = proven infeasible
int kao_solve(int P, int B, int K, int R, const int32_t *rf,
              const int32_t *rack_of, const int32_t *w_leader,
              const int32_t *w_follower, int broker_lo, int broker_hi,
              int leader_lo, int leader_hi, const int32_t *rack_lo,
              const int32_t *rack_hi, const int32_t *part_rack_hi,
              const int32_t *seed_a, int64_t seed_w, int has_seed,
              double time_limit_s, int32_t *out_a, int64_t *out_objective,
              int64_t *out_nodes) {
  Problem pr{P,       B,         K,         R,         rf,
             rack_of, w_leader,  w_follower, broker_lo, broker_hi,
             leader_lo, leader_hi, rack_lo,  rack_hi,   part_rack_hi};
  Solver s(pr, time_limit_s);
  if (has_seed) s.warm_start(seed_a, seed_w);
  return s.run(out_a, out_objective, out_nodes);
}

}  // extern "C"
