"""Solver-neutral optimization model (the reference's L1-L3 layers).

Builds, from (current assignment, target broker list, topology, target RF),
the single :class:`ProblemInstance` that *every* solver backend consumes —
the LP emitter, the MILP oracle, the native C++ branch-and-bound, and the
JAX/TPU annealing engine. Mirrors the reference's model-builder stage
(``/root/reference/README.md:106-133``) but uses dense index arrays rather
than named LP variables; the ``t{t}b{b}p{p}[_l]`` naming survives only in
the LP emitter.

Key representation decision (TPU-first): candidates are *replica-slot*
arrays ``A[P, R] : int`` of broker **indices** with slot 0 = leader —
matching the reference's leader-first JSON convention
(``README.md:52-78``). This hard-encodes the equality constraints
(replication factor ``README.md:148-151``, one leader ``README.md:153-156``,
per-broker uniqueness ``README.md:168-171``) by construction, leaving only
the inequality families as penalty terms for the search backends.

Constraint families and their bound arithmetic (derived from the worked LP
sample, ``README.md:144-185``):

- replicas/broker  in [floor(R_tot/B), ceil(R_tot/B)]   (``README.md:158-161``)
  NOTE: the reference sample shows ``>= 1`` in a 32-broker/20-replica
  cluster where floor(20/32)=0 — the sample is elided/illustrative and
  underdetermines the exact rule; floor/ceil is the self-consistent choice
  and reproduces the demo optimum (golden test).
- leaders/broker   in [floor(P/B),     ceil(P/B)]       (``README.md:163-166``)
- replicas/rack    in [floor(R_tot*B_k/B), ceil(R_tot*B_k/B)] per rack k with
  B_k brokers — proportional form; reduces to the sample's exact R_tot/K
  when racks are equal-sized (``README.md:173-176``)
- replicas of one partition per rack <= ceil(RF/K)      (``README.md:178-180``)

Objective weights (observed data points ``README.md:146``; ordering rule
"leader-keep > follower-keep > new" per ``README.md:116-133``):

- current preferred leader broker: leader-role weight 4, follower-role 2
- current follower broker:         leader-role weight 2, follower-role 1
- any other broker: 0

This exact rule reproduces every coefficient shown in the reference sample
and the demo's 1-move optimum (golden test).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from .cluster import Assignment, PartitionAssignment, Topology

# Objective weight tiers (README.md:146 observed values).
W_LEADER_KEEP = 4  # current leader stays leader
W_LEADER_DEMOTE = 2  # current leader stays as follower
W_FOLLOWER_PROMOTE = 2  # current follower becomes leader
W_FOLLOWER_KEEP = 1  # current follower stays follower


@dataclass
class ProblemInstance:
    """Dense, index-based optimization model.

    Broker axis is *eligible brokers only* (the target ``--broker-list``);
    ``broker_ids[i]`` maps index -> Kafka broker id. Index ``B`` (one past
    the end) is the shared "null bucket" used for padded replica slots, so
    histograms can be built with scatter-adds without branching.
    """

    # topology / broker axis
    broker_ids: np.ndarray  # [B] int32, sorted eligible Kafka broker ids
    rack_of_broker: np.ndarray  # [B+1] int32 rack index; null bucket -> K
    rack_names: list[str]
    # partition axis (all topics flattened)
    topics: list[str]
    topic_of_part: np.ndarray  # [P] int32 topic index
    part_id: np.ndarray  # [P] int32 kafka partition id within topic
    rf: np.ndarray  # [P] int32 target replication factor
    # current assignment, in broker-*index* space, -? see below
    # A0[p, s] = broker index of current replica in slot s (slot 0 leader),
    #            B (null) if slot unused or broker not eligible.
    a0: np.ndarray  # [P, R] int32
    # current assignment in raw broker-id space (for diffs / weights incl.
    # ineligible brokers)
    current: Assignment = field(repr=False, default=None)
    # objective weights, [P, B+1] int32 (null bucket column always 0)
    w_leader: np.ndarray = field(repr=False, default=None)
    w_follower: np.ndarray = field(repr=False, default=None)
    # inequality-constraint bounds
    broker_lo: int = 0
    broker_hi: int = 0
    leader_lo: int = 0
    leader_hi: int = 0
    rack_lo: np.ndarray = None  # [K] int32
    rack_hi: np.ndarray = None  # [K] int32
    part_rack_hi: np.ndarray = None  # [P] int32: ceil(rf/K)

    # -- sizes ----------------------------------------------------------
    @property
    def num_brokers(self) -> int:
        return int(self.broker_ids.shape[0])

    @property
    def num_parts(self) -> int:
        return int(self.topic_of_part.shape[0])

    @property
    def num_racks(self) -> int:
        return len(self.rack_names)

    @property
    def max_rf(self) -> int:
        return int(self.a0.shape[1])

    @property
    def total_replicas(self) -> int:
        return int(self.rf.sum())

    @property
    def slot_valid(self) -> np.ndarray:
        """[P, R] bool — slot s is a real replica slot for partition p."""
        return np.arange(self.max_rf)[None, :] < self.rf[:, None]

    # -- decode ---------------------------------------------------------
    def decode(self, a: np.ndarray) -> Assignment:
        """Map a candidate ``A[P, R]`` of broker indices back to
        reassignment JSON (leader = slot 0 = ``replicas[0]``,
        ``README.md:65-78``). One vectorized id translation; the Python
        loop only assembles the output objects (at 10k partitions the
        per-element indexing version cost ~0.1 s of the warm solve)."""
        valid = self.slot_valid
        ids = self.broker_ids[np.where(valid, a, 0)].tolist()
        rfs = self.rf.tolist()
        topic_names = [self.topics[t] for t in self.topic_of_part.tolist()]
        pids = self.part_id.tolist()
        parts = [
            PartitionAssignment(
                topic=topic_names[p],
                partition=pids[p],
                replicas=ids[p][: rfs[p]],
            )
            for p in range(self.num_parts)
        ]
        return Assignment(partitions=parts)

    # -- feasibility / scoring (numpy reference; oracle for all backends) --
    def violations(self, a: np.ndarray) -> dict[str, int]:
        """Exact integer violation counts of the inequality families for a
        candidate in index space. All zeros == feasible. Also validates the
        hard-encoded families (rf/leader/uniqueness) defensively."""
        B, K, P, R = self.num_brokers, self.num_racks, self.num_parts, self.max_rf
        valid = self.slot_valid
        a = np.asarray(a)
        flat = np.where(valid, a, B)
        # per-broker totals (replica+leader vars together, README.md:158-161)
        cnt = np.bincount(flat.ravel(), minlength=B + 1)[:B]
        lead = np.bincount(np.where(self.rf > 0, a[:, 0], B), minlength=B + 1)[:B]
        rk = self.rack_of_broker[flat]  # [P, R], null -> K
        rcnt = np.bincount(rk.ravel(), minlength=K + 1)[:K]
        # per (partition, rack) counts
        pr = np.zeros((P, K + 1), dtype=np.int64)
        np.add.at(pr, (np.arange(P)[:, None].repeat(R, 1), rk), 1)
        pr = pr[:, :K]

        def band(x, lo, hi):
            return int(np.maximum(x - hi, 0).sum() + np.maximum(lo - x, 0).sum())

        dup = 0
        for p in range(P):
            reps = flat[p][valid[p]]
            dup += len(reps) - len(np.unique(reps))
        return {
            "broker_balance": band(cnt, self.broker_lo, self.broker_hi),
            "leader_balance": band(lead, self.leader_lo, self.leader_hi),
            "rack_balance": band(rcnt, self.rack_lo, self.rack_hi),
            "part_rack_diversity": int(
                np.maximum(pr - self.part_rack_hi[:, None], 0).sum()
            ),
            # hard-encoded families, checked defensively:
            "slot_out_of_range": int(((flat < 0) | (flat > B)).sum()),
            "null_in_valid_slot": int((flat[valid] >= B).sum()),
            "duplicate_in_partition": dup,
        }

    def is_feasible(self, a: np.ndarray) -> bool:
        return all(v == 0 for v in self.violations(a).values())

    def preservation_weight(self, a: np.ndarray) -> int:
        """Objective value (maximized): sum of kept-assignment weights."""
        P = self.num_parts
        a = np.asarray(a)
        valid = self.slot_valid
        rows = np.arange(P)
        w = int(self.w_leader[rows, a[:, 0]][self.rf > 0].sum())
        if self.max_rf > 1:
            foll = self.w_follower[rows[:, None], a[:, 1:]]
            w += int(foll[valid[:, 1:]].sum())
        return w

    def max_weight(self) -> int:
        """Exact unconstrained per-partition optimum of the preservation
        weight (ignoring the balance constraints): for each partition, the
        best choice of leader among weighted brokers plus the best rf-1
        follower weights among the rest. A true upper bound on any feasible
        plan's objective."""
        total = 0
        for p in range(self.num_parts):
            cand = np.flatnonzero(
                (self.w_leader[p] > 0) | (self.w_follower[p] > 0)
            )
            rf = int(self.rf[p])
            best = 0
            # leader choice: any weighted broker, or an unweighted one (0)
            for lead in [None, *cand.tolist()]:
                w = 0 if lead is None else int(self.w_leader[p, lead])
                others = [int(self.w_follower[p, b]) for b in cand if b != lead]
                others.sort(reverse=True)
                w += sum(x for x in others[: rf - 1] if x > 0)
                best = max(best, w)
            total += best
        return total

    def move_count(self, a: np.ndarray) -> int:
        """Replica moves vs the current assignment: count of valid slots
        whose broker is not in the partition's current (eligible) replica
        set. Membership test uses the weight matrices: every currently
        assigned eligible broker carries nonzero leader weight."""
        a = np.asarray(a)
        member = self.w_leader[np.arange(self.num_parts)[:, None], a] > 0
        return int((~member & self.slot_valid).sum())



def build_instance(
    current: Assignment,
    broker_list: Sequence[int],
    topology: Topology | None = None,
    target_rf: int | dict[str, int] | None = None,
) -> ProblemInstance:
    """Build the solver-neutral model from raw inputs (reference L0->L1-L3,
    ``README.md:46-63, 106-133``)."""
    broker_ids = np.array(sorted(set(int(b) for b in broker_list)), dtype=np.int32)
    B = len(broker_ids)
    if B == 0:
        raise ValueError("empty broker list")
    idx_of_broker = {int(b): i for i, b in enumerate(broker_ids)}

    if topology is None:
        topology = Topology.single_rack(broker_ids.tolist())
    rack_names = sorted({topology.rack(int(b)) for b in broker_ids})
    rack_idx = {r: i for i, r in enumerate(rack_names)}
    K = len(rack_names)
    rack_of_broker = np.full(B + 1, K, dtype=np.int32)
    for i, b in enumerate(broker_ids):
        rack_of_broker[i] = rack_idx[topology.rack(int(b))]

    parts = sorted(current.partitions, key=lambda p: (p.topic, p.partition))
    topics = []
    topic_idx: dict[str, int] = {}
    for p in parts:
        if p.topic not in topic_idx:
            topic_idx[p.topic] = len(topics)
            topics.append(p.topic)
    P = len(parts)

    def rf_for(p: PartitionAssignment) -> int:
        if target_rf is None:
            return len(p.replicas)
        if isinstance(target_rf, dict):
            return int(target_rf.get(p.topic, len(p.replicas)))
        return int(target_rf)

    rf = np.array([rf_for(p) for p in parts], dtype=np.int32)
    if (rf <= 0).any():
        raise ValueError("replication factor must be >= 1")
    if (rf > B).any():
        raise ValueError("replication factor exceeds broker count")
    R = int(rf.max())

    topic_of_part = np.array([topic_idx[p.topic] for p in parts], dtype=np.int32)
    part_id = np.array([p.partition for p in parts], dtype=np.int32)

    # current assignment -> index space; ineligible brokers -> null bucket B
    a0 = np.full((P, R), B, dtype=np.int32)
    for pi, p in enumerate(parts):
        for s, b in enumerate(p.replicas[:R]):
            a0[pi, s] = idx_of_broker.get(int(b), B)

    # objective weights (README.md:116-133, 146): see module docstring
    w_leader = np.zeros((P, B + 1), dtype=np.int32)
    w_follower = np.zeros((P, B + 1), dtype=np.int32)
    for pi, p in enumerate(parts):
        for s, b in enumerate(p.replicas):
            bi = idx_of_broker.get(int(b))
            if bi is None:
                continue  # broker being removed: no preservation reward
            if s == 0:
                w_leader[pi, bi] = W_LEADER_KEEP
                w_follower[pi, bi] = W_LEADER_DEMOTE
            else:
                w_leader[pi, bi] = max(w_leader[pi, bi], W_FOLLOWER_PROMOTE)
                w_follower[pi, bi] = max(w_follower[pi, bi], W_FOLLOWER_KEEP)

    # bound arithmetic (README.md:158-180; SURVEY §2 rules)
    r_tot = int(rf.sum())
    broker_lo, broker_hi = r_tot // B, -(-r_tot // B)
    leader_lo, leader_hi = P // B, -(-P // B)
    rack_sizes = np.array(
        [int((rack_of_broker[:B] == k).sum()) for k in range(K)], dtype=np.int64
    )
    rack_lo = (r_tot * rack_sizes) // B
    rack_hi = -((-r_tot * rack_sizes) // B)
    part_rack_hi = -(-rf // K)

    # --- satisfiability repair (balance bands are preferences: they must
    # never make the instance infeasible). Equal-size racks satisfy every
    # condition below as-is and reproduce the reference sample's exact
    # bounds unchanged (README.md:173-176); lopsided topologies (found by
    # the r2 property fuzz: a 1-broker rack + diversity caps can make the
    # proportional ceilings under-supply r_tot, which the exact MILP
    # reports as infeasible) get the minimal relaxation that admits a
    # plan. Steps:
    #   1. per-partition: the diversity cap c_p must allow rf_p replicas
    #      across racks given each rack's broker count (uniqueness).
    #   2. per-rack: tighten the band to the true implied extremes
    #      [m_k, M_k] (no semantic change), then
    #   3. jointly: relax ceilings/floors until sum(hi) covers r_tot and
    #      sum(lo) <= r_tot.
    #   4. broker bands: every rack's brokers must supply its floor, and
    #      the global per-broker supply must cover r_tot under the rack
    #      ceilings.
    cap_pk = np.minimum(part_rack_hi[:, None], rack_sizes[None, :])
    short = rf - cap_pk.sum(1)
    while (short > 0).any():  # step 1 (terminates: B >= rf checked)
        part_rack_hi = part_rack_hi + (short > 0)
        cap_pk = np.minimum(part_rack_hi[:, None], rack_sizes[None, :])
        short = rf - cap_pk.sum(1)
    M = cap_pk.sum(0)  # [K] true max replicas rack k can hold
    m = np.maximum(  # [K] forced minimum (others at their caps)
        rf[:, None] - (cap_pk.sum(1)[:, None] - cap_pk), 0
    ).sum(0)
    rack_hi = np.maximum(np.minimum(rack_hi, M), m)  # step 2 (m <= M, so
    rack_lo = np.maximum(np.minimum(rack_lo, rack_hi), m)  # lo <= hi holds)
    # steps 3a/3b converge in <= K+1 passes: every non-final pass clips
    # at least one rack at its extreme
    for _ in range(K + 1):  # step 3a: raise ceilings toward M
        deficit = r_tot - int(rack_hi.sum())
        head = M - rack_hi
        if deficit <= 0 or not (head > 0).any():
            break
        add = -(-deficit // max(int((head > 0).sum()), 1))
        rack_hi = np.minimum(rack_hi + np.where(head > 0, add, 0), M)
    for _ in range(K + 1):  # step 3b: lower floors toward m
        excess = int(rack_lo.sum()) - r_tot
        slack = rack_lo - m
        if excess <= 0 or not (slack > 0).any():
            break
        sub = -(-excess // max(int((slack > 0).sum()), 1))
        rack_lo = np.maximum(rack_lo - np.where(slack > 0, sub, 0), m)
    # step 4: per-broker band vs rack floors/ceilings
    broker_hi = max(broker_hi, int(np.max(-(-rack_lo // rack_sizes))))
    supply = lambda h: int(np.minimum(rack_sizes * h, rack_hi).sum())  # noqa: E731
    while supply(broker_hi) < r_tot and broker_hi < r_tot:
        broker_hi += 1
    broker_lo = min(broker_lo, int(np.min(rack_hi // rack_sizes)))

    inst = ProblemInstance(
        broker_ids=broker_ids,
        rack_of_broker=rack_of_broker,
        rack_names=rack_names,
        topics=topics,
        topic_of_part=topic_of_part,
        part_id=part_id,
        rf=rf,
        a0=a0,
        current=current,
        w_leader=w_leader,
        w_follower=w_follower,
        broker_lo=int(broker_lo),
        broker_hi=int(broker_hi),
        leader_lo=int(leader_lo),
        leader_hi=int(leader_hi),
        rack_lo=rack_lo.astype(np.int32),
        rack_hi=rack_hi.astype(np.int32),
        part_rack_hi=part_rack_hi.astype(np.int32),
    )
    return inst
