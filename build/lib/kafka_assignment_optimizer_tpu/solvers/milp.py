"""Exact 0-1 ILP oracle via scipy/HiGHS (native C++ solver, in-process).

Formulates the same binary model the reference feeds to lp_solve
(``/root/reference/README.md:106-185``): one replica variable and one
leader variable per (partition, broker) — the dense cross-product of
``README.md:182-184`` — with the seven constraint families of
``README.md:148-180`` and the move-minimizing objective of
``README.md:116-133``. Serves as the exactness oracle the TPU engine is
tested against (cross-solver parity, SURVEY.md §4.4).

Variable layout (flat index over ``2*P*B`` binaries):
``x[p, b] -> p*B + b`` (follower role), ``y[p, b] -> P*B + p*B + b``
(leader role).
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp
from scipy.optimize import Bounds, LinearConstraint, milp

from ..models.instance import ProblemInstance
from .base import SolveResult, register


def build_milp(inst: ProblemInstance):
    """Return (c, constraints, integrality) for scipy.optimize.milp.

    Exposed separately so tests can count rows against the reference
    sample's structure (``README.md:144-185``; SURVEY.md §3.3 row counts).
    """
    P, B, K = inst.num_parts, inst.num_brokers, inst.num_racks
    n = 2 * P * B

    def xi(p, b):
        return p * B + b

    def yi(p, b):
        return P * B + p * B + b

    # objective: maximize preservation weight -> minimize negated weights
    c = np.zeros(n)
    c[: P * B] = -inst.w_follower[:, :B].ravel()
    c[P * B :] = -inst.w_leader[:, :B].ravel()

    rows: list[sp.csr_matrix] = []
    lbs: list[np.ndarray] = []
    ubs: list[np.ndarray] = []

    def add(mat: sp.spmatrix, lo, hi):
        rows.append(sp.csr_matrix(mat))
        lbs.append(np.atleast_1d(np.asarray(lo, dtype=float)))
        ubs.append(np.atleast_1d(np.asarray(hi, dtype=float)))

    eye_p = sp.eye(P, format="csr")
    ones_b = np.ones((1, B))
    # per-partition sums over brokers: kron(I_P, 1_B)
    sum_b = sp.kron(eye_p, ones_b, format="csr")  # [P, P*B]
    zero = sp.csr_matrix((P, P * B))

    # C4 replication factor: sum_b (x + y) == rf[p]       (README.md:148-151)
    add(sp.hstack([sum_b, sum_b]), inst.rf, inst.rf)
    # C5 one leader: sum_b y == 1                          (README.md:153-156)
    add(sp.hstack([zero, sum_b]), np.ones(P), np.ones(P))
    # C6 broker band: sum_p (x + y) in [lo, hi]            (README.md:158-161)
    sum_p = sp.kron(np.ones((1, P)), sp.eye(B), format="csr")  # [B, P*B]
    add(
        sp.hstack([sum_p, sum_p]),
        np.full(B, inst.broker_lo),
        np.full(B, inst.broker_hi),
    )
    # C7 leader band: sum_p y in [lo, hi]                  (README.md:163-166)
    add(
        sp.hstack([sp.csr_matrix((B, P * B)), sum_p]),
        np.full(B, inst.leader_lo),
        np.full(B, inst.leader_hi),
    )
    # C8 uniqueness: x + y <= 1 per (p, b)                 (README.md:168-171)
    eye_n = sp.eye(P * B, format="csr")
    add(sp.hstack([eye_n, eye_n]), np.zeros(P * B), np.ones(P * B))
    # C9 rack band: sum over rack members x+y in band      (README.md:173-176)
    rack_sel = sp.csr_matrix(
        (np.ones(B), (inst.rack_of_broker[:B], np.arange(B))), shape=(K, B)
    )  # [K, B]
    rack_p = sp.kron(np.ones((1, P)), rack_sel, format="csr")  # [K, P*B]
    add(sp.hstack([rack_p, rack_p]), inst.rack_lo, inst.rack_hi)
    # C10 partition-rack diversity: per (p, k) <= ceil(rf/K)  (README.md:178-180)
    pr = sp.kron(eye_p, rack_sel, format="csr")  # [P*K, P*B]
    hi_pk = np.repeat(inst.part_rack_hi.astype(float), K)
    add(sp.hstack([pr, pr]), np.zeros(P * K), hi_pk)

    A = sp.vstack(rows, format="csr")
    lo = np.concatenate(lbs)
    hi = np.concatenate(ubs)
    return c, LinearConstraint(A, lo, hi), np.ones(n, dtype=np.int64)


@register("milp")
def solve_milp(
    inst: ProblemInstance,
    time_limit_s: float | None = None,
    mip_rel_gap: float = 0.0,
    **_unused,
) -> SolveResult:
    import time

    t0 = time.perf_counter()
    P, B = inst.num_parts, inst.num_brokers
    c, constraint, integrality = build_milp(inst)
    options: dict = {"mip_rel_gap": mip_rel_gap}
    if time_limit_s is not None:
        options["time_limit"] = float(time_limit_s)
    res = milp(
        c,
        constraints=constraint,
        integrality=integrality,
        bounds=Bounds(0, 1),
        options=options,
    )
    if res.x is None:
        raise RuntimeError(f"MILP solve failed: {res.message}")
    x = np.round(res.x[: P * B]).astype(np.int64).reshape(P, B)
    y = np.round(res.x[P * B :]).astype(np.int64).reshape(P, B)

    R = inst.max_rf
    a = np.full((P, R), B, dtype=np.int32)
    for p in range(P):
        leaders = np.flatnonzero(y[p])
        followers = np.flatnonzero(x[p])
        if len(leaders) != 1:
            raise RuntimeError(f"partition {p}: {len(leaders)} leaders in solution")
        reps = [int(leaders[0])] + [int(b) for b in followers]
        if len(reps) != int(inst.rf[p]):
            raise RuntimeError(
                f"partition {p}: RF {len(reps)} != target {int(inst.rf[p])}"
            )
        a[p, : len(reps)] = reps
    wall = time.perf_counter() - t0
    return SolveResult(
        a=a,
        solver="milp",
        wall_clock_s=wall,
        objective=int(-res.fun) if res.fun is not None else None,
        optimal=bool(res.status == 0 and mip_rel_gap == 0.0),
        stats={"status": int(res.status), "message": str(res.message)},
    )
