"""``--solver=tpu`` — the JAX/TPU combinatorial search backend (C17).

Replaces the reference's external native lp_solve MILP solve
(``/root/reference/README.md:135-137``) with the engine BASELINE.json:5
specifies: a population of candidate assignments annealed in HBM by
vmapped Metropolis chains (``.anneal``), seeded from a greedy host-side
repair of the current assignment (``.seed``), sharded across the device
mesh with ICI best-migration (``parallel.mesh``), and verified against the
exact numpy scorer before the plan is emitted.

North-star target (BASELINE.json): plan quality <= lp_solve's move count,
<5 s wall-clock at 256 brokers / 10k partitions / RF=3 on a v5e-8.
"""

from __future__ import annotations

import contextlib
import time

import jax
import jax.numpy as jnp
import numpy as np

from ...models.instance import ProblemInstance
from ...utils import checkpoint as ckpt
from ..base import SolveResult, register
from . import arrays
from .seed import greedy_seed


# partition count at which the sweep-parallel engine takes over from the
# per-move Metropolis chains OFF-TPU: above this, sequential chain steps
# dominate wall-clock (one move per step), while a sweep applies up to
# min(P, B) moves per fused step. On TPU the sweep engine is the default
# at every size (see _defaults).
_SWEEP_THRESHOLD_PARTS = 512


def _defaults(inst: ProblemInstance, platform: str, engine: str | None) -> dict:
    """Search-effort defaults for the RESOLVED engine: scale chains with
    the hardware, steps with the problem. CPU (CI) stays small; TPU uses
    the full batch. The engine must be resolved first — each engine's
    budget is meaningless for the other (a chain budget of 256 sweeps
    would leave the chain engine 1000x under-searched and vice versa)."""
    P = inst.num_parts
    on_tpu = platform == "tpu"
    if engine is not None and engine not in ("chain", "sweep"):
        raise ValueError(
            f"unknown tpu engine {engine!r}; expected 'chain' or 'sweep'"
        )
    # TPU always prefers the sweep engine: measured on v5e (r2), even a
    # 10-partition demo solves 10x faster warm through the Mosaic sweep
    # kernels than through the chain engine's sequential Metropolis scan
    # (0.34 s vs 3.6 s; compile 4 s vs 29 s), at equal quality. The
    # chain engine remains the small-instance default off-TPU, where its
    # O(RF) per-step work beats sweeping whole small populations.
    engine = engine or (
        "sweep" if (on_tpu or P >= _SWEEP_THRESHOLD_PARTS) else "chain"
    )
    if engine == "sweep":
        # sweep engine: sequential depth is `rounds` sweeps, flat in P;
        # chain count trades against per-sweep cost (O(chains * P)).
        # Measured on a real v5e chip (r2): per-sweep wall scales ~1:1
        # with chains (the proposal algebra is VPU/gather-bound, already
        # saturated at 8 chains x 10k partitions), so extra chains buy
        # quality only at full wall-clock price; 8 chains x 128 sweeps
        # reaches the provable move lower bound on the 256-broker/10k-
        # partition headline in ~3.5 s warm.
        return {
            "engine": "sweep",
            "batch": 8,
            "rounds": 128 if on_tpu else 64,
            "steps_per_round": 1,
        }
    return {
        "engine": "chain",
        "batch": 512 if on_tpu else 32,
        "rounds": 24,
        "steps_per_round": max(256, min(4 * P, 20_000)),
    }


@register("tpu")
def solve_tpu(
    inst: ProblemInstance,
    seed: int = 0,
    batch: int | None = None,
    rounds: int | None = None,
    sweeps: int | None = None,  # CLI alias for rounds
    steps_per_round: int | None = None,
    t_hi: float | None = None,
    t_lo: float | None = None,
    n_devices: int | None = None,
    engine: str | None = None,
    checkpoint: str | None = None,
    profile_dir: str | None = None,
    time_limit_s: float | None = None,
    **_unused,
) -> SolveResult:
    t0 = time.perf_counter()
    from ...utils.platform import enable_compile_cache, ensure_backend

    enable_compile_cache()
    platform = ensure_backend()
    d = _defaults(inst, platform, engine)
    engine = d["engine"]
    batch = batch or d["batch"]
    rounds = rounds or sweeps or d["rounds"]
    steps_per_round_ignored = False
    steps_per_round = steps_per_round or d["steps_per_round"]
    if engine == "sweep" and steps_per_round != 1:
        # the sweep engine has no inner step loop: its sequential budget
        # is `rounds` sweeps, each touching every partition once. An
        # explicit user override has no effect — say so in stats instead
        # of silently eating the knob.
        steps_per_round_ignored = True
        steps_per_round = 1
    if t_hi is None:
        t_hi = 2.0 if engine == "sweep" else 2.5
    if t_lo is None:
        t_lo = 0.02 if engine == "sweep" else 0.05

    # host-side greedy repair: near-feasible, near-min-move warm start
    a_seed = greedy_seed(inst)
    assert (a_seed[inst.slot_valid] < inst.num_brokers).all(), (
        "seed left unfilled slots"
    )
    resumed = False
    if checkpoint:
        # fail fast on an unwritable path BEFORE spending solve time
        from pathlib import Path

        Path(checkpoint).parent.mkdir(parents=True, exist_ok=True)
        # resume (SURVEY.md §5): if a prior solve of this exact instance
        # left a plan, seed from whichever of {checkpoint, greedy} ranks
        # higher — the next solve can never regress below the last one
        a_prev = ckpt.load(checkpoint, inst)
        if a_prev is not None:
            def rank(a):
                pen = sum(inst.violations(a).values())
                w = inst.preservation_weight(a)
                return (pen == 0, -pen, w)

            if rank(a_prev) >= rank(a_seed):
                a_seed = a_prev
                resumed = True
    m = arrays.from_instance(inst)
    t_seed = time.perf_counter()

    from ...ops.score import moves_batch
    from ...ops.score_pallas import score_batch_auto
    from ...parallel.mesh import make_mesh, solve_on_mesh
    from .arrays import geometric_temps
    from .polish import polish_jit

    mesh = make_mesh(n_devices)
    n_dev = mesh.devices.size
    chains_per_device = max(1, batch // n_dev)
    key = jax.random.PRNGKey(seed)

    # time_limit_s (VERDICT r1 item 4): the schedule is one geometric
    # ladder either way; under a deadline it is cut into equal chunks
    # (one compiled executable — temps is a runtime arg) and the clock is
    # checked between chunks, so the solve returns the best-so-far plan
    # within ~one chunk of the budget instead of ignoring it.
    temps_full = geometric_temps(t_hi, t_lo, rounds)
    if time_limit_s is None:
        chunks = [temps_full]
    else:
        c = max(8, -(-rounds // 8)) if engine == "sweep" else max(
            1, rounds // 8
        )
        chunks = [temps_full[i:i + c] for i in range(0, rounds, c)]
        if len(chunks) > 1 and chunks[-1].shape[0] < c:
            # pad the tail chunk with t_lo so every chunk shares one
            # compiled shape (extra cold rounds only ever improve)
            pad = c - chunks[-1].shape[0]
            chunks[-1] = jnp.concatenate(
                [chunks[-1], jnp.full((pad,), t_lo, jnp.float32)]
            )

    prof = (
        jax.profiler.trace(profile_dir)  # SURVEY.md §5 tracing/profiling
        if profile_dir
        else contextlib.nullcontext()
    )
    # hot-path scorer (VERDICT r1 items 2-3): on TPU the sweep engine's
    # per-sweep from-scratch rescoring runs through the tiled Pallas
    # kernel (one-hot matmuls on the MXU) instead of XLA scatter-adds;
    # if Mosaic fails to lower on this hardware, fall back to XLA and
    # say so in stats rather than dying
    scorer = "pallas" if (platform == "tpu" and engine == "sweep") else "xla"
    pallas_fallback: str | None = None

    timed_out = False
    rounds_run = 0
    seed_dev = jnp.asarray(a_seed, jnp.int32)
    curves = []
    pop_a = pop_k = None
    with prof:
        deadline = None if time_limit_s is None else t0 + time_limit_s
        # chunk 0's duration is compile-inclusive and wildly overstates a
        # warm chunk, so it must not gate chunk 1 — a cold solve with
        # budget left would otherwise stop after one chunk. The post-chunk
        # deadline check below still bounds the overshoot.
        warm_chunk_s: float | None = None
        for i, temps in enumerate(chunks):
            if deadline is not None and i > 1 and warm_chunk_s is not None:
                left = deadline - time.perf_counter()
                if left < warm_chunk_s * 0.9:  # next chunk won't fit
                    timed_out = True
                    break
            tc = time.perf_counter()
            if len(chunks) == 1:
                sub = key  # bit-identical to the unchunked solve
            else:
                key, sub = jax.random.split(key)
            try:
                pop_a, pop_k, curve = solve_on_mesh(
                    m,
                    seed_dev,
                    sub,
                    mesh,
                    chains_per_device,
                    rounds,
                    steps_per_round,
                    engine=engine,
                    temps=temps,
                    scorer=scorer,
                )
                jax.block_until_ready(pop_a)
            except Exception as e:
                # only a Mosaic/Pallas lowering failure warrants the XLA
                # retry; anything else (OOM, sharding bug, regression)
                # must surface with its real traceback
                msg = f"{type(e).__name__}: {e}"
                is_lowering = scorer == "pallas" and any(
                    s in msg for s in ("Mosaic", "mosaic", "pallas",
                                       "Pallas", "lowering", "Lowering")
                )
                if not is_lowering:
                    raise
                pallas_fallback = repr(e)[:500]
                scorer = "xla"
                pop_a, pop_k, curve = solve_on_mesh(
                    m, seed_dev, sub, mesh, chains_per_device, rounds,
                    steps_per_round, engine=engine, temps=temps,
                    scorer=scorer,
                )
                jax.block_until_ready(pop_a)
            chunk_s = time.perf_counter() - tc
            if i > 0:
                warm_chunk_s = (
                    chunk_s if warm_chunk_s is None
                    else min(warm_chunk_s, chunk_s)
                )
            rounds_run += temps.shape[0]
            curves.append(np.asarray(jax.device_get(curve)))
            if len(chunks) > 1:
                # restart-from-best across chunks: reseed every shard's
                # population with the global best so far (a few hundred
                # KB host round-trip per chunk boundary)
                pk = np.asarray(jax.device_get(pop_k))
                seed_dev = jnp.asarray(
                    jax.device_get(pop_a)[int(np.argmax(pk))]
                )
            if deadline is not None and time.perf_counter() > deadline:
                timed_out = i + 1 < len(chunks)
                break
    t_solve = time.perf_counter()
    curve = np.concatenate(curves, axis=1)

    # final selection: exact-rescore the per-shard winners on device (the
    # Pallas kernel on TPU, XLA elsewhere) and rank by feasibility, then
    # weight, then fewest moves — then drive the champion to 1-move local
    # optimality with the steepest-descent polish. pop_a comes back
    # mesh-sharded; gather it to one device first (it is n_dev candidates,
    # a few hundred KB) — Mosaic kernels cannot be auto-partitioned.
    pop_a = jnp.asarray(jax.device_get(pop_a))
    s = score_batch_auto(pop_a, m)
    moves = moves_batch(pop_a, m)
    # lexicographic in two int32-safe stages (a combined key would overflow
    # int32 at 10k partitions): feasibility/weight first, fewest moves as
    # the tie-break
    primary = jnp.where(s.penalty == 0, s.weight, -s.penalty - 1)
    tied = primary == primary.max()
    best_a = polish_jit(
        m, pop_a[jnp.argmax(jnp.where(tied, -moves, jnp.iinfo(jnp.int32).min))]
    )
    t_polish = time.perf_counter()

    # host-side exact verification (SURVEY.md §4.3 property): the engine's
    # incremental scores must agree with the numpy oracle
    best_a = np.asarray(best_a, dtype=np.int32)
    viol = inst.violations(best_a)
    weight = inst.preservation_weight(best_a)
    feasible = all(v == 0 for v in viol.values())

    if checkpoint:
        ckpt.save(
            checkpoint,
            inst,
            best_a,
            meta={
                "objective": int(weight),
                "feasible": feasible,
                "moves": int(inst.move_count(best_a)),
                "engine": engine,
            },
        )

    return SolveResult(
        a=best_a,
        solver="tpu",
        wall_clock_s=time.perf_counter() - t0,
        objective=int(weight),
        optimal=False,
        stats={
            "platform": platform,
            "engine": engine,
            "devices": n_dev,
            "chains_per_device": chains_per_device,
            "rounds": rounds,
            "rounds_run": rounds_run,
            "timed_out": timed_out,
            "time_limit_s": time_limit_s,
            "steps_per_round": steps_per_round,
            "steps_per_round_ignored": steps_per_round_ignored,
            "scorer": scorer,
            **({"pallas_fallback": pallas_fallback} if pallas_fallback
               else {}),
            # chain: Metropolis steps per chain; sweep: every sweep
            # proposes one move per partition
            "total_steps": rounds_run * steps_per_round
            if engine == "chain"
            else rounds_run * inst.num_parts,
            "seed_s": round(t_seed - t0, 4),
            "anneal_s": round(t_solve - t_seed, 4),
            "polish_s": round(t_polish - t_solve, 4),
            "seed_moves": int(inst.move_count(a_seed)),
            "moves": int(inst.move_count(best_a)),
            "feasible": feasible,
            "violations": sum(viol.values()),
            "resumed_from_checkpoint": resumed,
            # best-score trajectory (max over shards, downsampled): the
            # convergence record SURVEY.md §5 calls for
            "score_curve": _downsample(
                np.asarray(jax.device_get(curve)).max(axis=0), 32
            ),
        },
    )


def _downsample(x: np.ndarray, n: int) -> list[int]:
    if len(x) <= n:
        return [int(v) for v in x]
    idx = np.linspace(0, len(x) - 1, n).round().astype(int)
    return [int(x[i]) for i in idx]
