"""Exact steepest-descent polish: one-move local optimality on device.

The annealer's Metropolis chains explore globally but can park an epsilon
above the ILP optimum (SURVEY.md §7 hard part 1). This stage closes that
gap deterministically: it evaluates the score delta of EVERY legal
single move — all ``(partition, slot, new_broker)`` replacements plus all
in-partition leader swaps — as one dense ``[P, R, B]`` tensor computation
(gathers over the count histograms, no scatter), applies the single best
improving move, and repeats under ``lax.while_loop`` until no move
improves. The result is certifiably 1-move locally optimal under the
exact integer objective with a fewest-moves tie-break (equal-score moves
that restore an original broker are taken): the neighborhood an
lp_solve-style exact solve can only beat with multi-move interactions.

One sweep is O(P·R·B) VPU work (~8M lanes at 256 brokers / 10k
partitions) — microseconds on a TPU core, so even hundreds of polish
moves cost less than one annealing round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .arrays import LAMBDA, SCALE_W, ModelArrays

_NEG = jnp.int32(-(1 << 30))  # mask value for illegal moves


def _band_pen(c, lo, hi):
    return jnp.maximum(c - hi, 0) + jnp.maximum(lo - c, 0)


def _counts(m: ModelArrays, a: jax.Array):
    """Full histograms for a candidate (mirrors ops.score.score_one, plus
    the per-(partition, rack) table the delta pass needs)."""
    P, R = m.a0.shape
    B = m.num_brokers
    K1 = m.rack_lo.shape[0]
    flat = jnp.where(m.slot_valid, a, B)
    cnt = jnp.zeros(B + 1, jnp.int32).at[flat.reshape(-1)].add(1)
    lcnt = jnp.zeros(B + 1, jnp.int32).at[flat[:, 0]].add(1)
    racks = m.rack_of[flat]  # [P, R]
    rcnt = jnp.zeros(K1, jnp.int32).at[racks.reshape(-1)].add(1)
    pr = jnp.zeros((P, K1), jnp.int32).at[
        jnp.arange(P)[:, None].repeat(R, 1), racks
    ].add(1)
    return flat, cnt, lcnt, rcnt, pr


def _replace_deltas(m: ModelArrays, flat, cnt, lcnt, rcnt, pr):
    """Score delta of ``a[p, s] <- b`` for every (p, s, b). [P, R, B]."""
    P, R = flat.shape
    B = m.num_brokers
    blo, bhi = m.broker_band[0], m.broker_band[1]
    llo, lhi = m.leader_band[0], m.leader_band[1]

    is_lead = (jnp.arange(R) == 0)[None, :]  # [1, R]

    # objective delta: role weight of the incoming broker minus outgoing
    w_in_l = m.w_lead[:, :B]  # [P, B]
    w_in_f = m.w_foll[:, :B]
    w_in = jnp.where(is_lead[:, :, None], w_in_l[:, None, :], w_in_f[:, None, :])
    w_out_l = jnp.take_along_axis(m.w_lead, flat, axis=1)  # [P, R]
    w_out_f = jnp.take_along_axis(m.w_foll, flat, axis=1)
    w_out = jnp.where(is_lead, w_out_l, w_out_f)
    dw = w_in - w_out[:, :, None]  # [P, R, B]

    # broker-band delta: one unit leaves b_old, arrives at b
    d_bout = _band_pen(cnt[flat] - 1, blo, bhi) - _band_pen(cnt[flat], blo, bhi)
    d_bin = _band_pen(cnt[:B] + 1, blo, bhi) - _band_pen(cnt[:B], blo, bhi)
    dpen = d_bout[:, :, None] + d_bin[None, None, :]

    # leader-band delta (leader slot only)
    d_lout = _band_pen(lcnt[flat] - 1, llo, lhi) - _band_pen(lcnt[flat], llo, lhi)
    d_lin = _band_pen(lcnt[:B] + 1, llo, lhi) - _band_pen(lcnt[:B], llo, lhi)
    dpen = dpen + jnp.where(
        is_lead[:, :, None], d_lout[:, :, None] + d_lin[None, None, :], 0
    )

    # rack-band + per-partition diversity deltas, zero when the move stays
    # inside one rack
    r_old = m.rack_of[flat]  # [P, R]
    rb = m.rack_of[:B]  # [B]
    same_rack = rb[None, None, :] == r_old[:, :, None]
    d_rout = (_band_pen(rcnt[r_old] - 1, m.rack_lo[r_old], m.rack_hi[r_old])
              - _band_pen(rcnt[r_old], m.rack_lo[r_old], m.rack_hi[r_old]))
    d_rin = (_band_pen(rcnt[rb] + 1, m.rack_lo[rb], m.rack_hi[rb])
             - _band_pen(rcnt[rb], m.rack_lo[rb], m.rack_hi[rb]))
    cap = m.part_rack_hi[:, None]  # [P, 1]
    g_out = (jnp.maximum(jnp.take_along_axis(pr, r_old, 1) - 1 - cap, 0)
             - jnp.maximum(jnp.take_along_axis(pr, r_old, 1) - cap, 0))
    pr_b = pr[:, rb]  # [P, B] — diversity count of b's rack, per partition
    g_in = (jnp.maximum(pr_b + 1 - cap, 0) - jnp.maximum(pr_b - cap, 0))
    dpen = dpen + jnp.where(
        same_rack,
        0,
        (d_rout + g_out)[:, :, None] + d_rin[None, None, :] + g_in[:, None, :],
    )

    delta = SCALE_W * dw - LAMBDA * dpen

    # legality: live slot, and b not already in the partition (covers b ==
    # b_old)
    in_row = (flat[:, :, None] == jnp.arange(B)[None, None, :]).any(1)  # [P, B]
    legal = jnp.logical_and(m.slot_valid[:, :, None], ~in_row[:, None, :])
    return jnp.where(legal, delta, _NEG)


def _lswap_deltas(m: ModelArrays, flat, lcnt):
    """Score delta of promoting slot s (>=1) to leader. [P, R]."""
    llo, lhi = m.leader_band[0], m.leader_band[1]
    bl = flat[:, :1]  # current leader [P, 1]
    wl = jnp.take_along_axis(m.w_lead, flat, axis=1)
    wf = jnp.take_along_axis(m.w_foll, flat, axis=1)
    dw = (wl + jnp.take_along_axis(m.w_foll, bl, 1)) - (
        jnp.take_along_axis(m.w_lead, bl, 1) + wf
    )
    dpen = (
        _band_pen(lcnt[bl] - 1, llo, lhi) - _band_pen(lcnt[bl], llo, lhi)
        + _band_pen(lcnt[flat] + 1, llo, lhi) - _band_pen(lcnt[flat], llo, lhi)
    )
    delta = SCALE_W * dw - LAMBDA * dpen
    legal = jnp.logical_and(m.slot_valid, jnp.arange(flat.shape[1])[None, :] >= 1)
    return jnp.where(legal, delta, _NEG)


def polish(m: ModelArrays, a: jax.Array, max_moves: int = 4096) -> jax.Array:
    """Apply best-improvement moves until 1-move local optimality (or the
    ``max_moves`` safety cap). Jit-compatible; int32 exact arithmetic."""
    P, R = m.a0.shape
    B = m.num_brokers

    def cond(carry):
        a, moves, improved = carry
        return jnp.logical_and(improved, moves < max_moves)

    def body(carry):
        a, moves, _ = carry
        flat, cnt, lcnt, rcnt, pr = _counts(m, a)
        d_rep = _replace_deltas(m, flat, cnt, lcnt, rcnt, pr)  # [P, R, B]
        d_lsw = _lswap_deltas(m, flat, lcnt)  # [P, R]

        # fewest-moves tie-break: the weight tiers alias move counts
        # (4 = 2+2), so zero-delta moves that swap a non-member broker
        # for an original member exist; scale the exact delta by 4 and
        # add the move-count gain in the low bits so such moves count as
        # improving. Per-move deltas are tiny ints — no overflow. The
        # _NEG mask must not be scaled (it would wrap int32).
        member = (m.w_lead[:, :B] > 0)  # [P, B] original-membership
        gain_in = member.astype(jnp.int32)[:, None, :]  # replacing in
        gain_out = jnp.take_along_axis(
            m.w_lead, flat, axis=1
        ).astype(jnp.bool_).astype(jnp.int32)[:, :, None]  # replacing out
        d_rep = jnp.where(
            d_rep == _NEG, _NEG, d_rep * 4 + (gain_in - gain_out)
        )
        d_lsw = jnp.where(d_lsw == _NEG, _NEG, d_lsw * 4)

        best_rep = jnp.max(d_rep)
        best_lsw = jnp.max(d_lsw)
        use_rep = best_rep >= best_lsw
        best = jnp.maximum(best_rep, best_lsw)

        idx_rep = jnp.argmax(d_rep)
        p1, s1, b1 = (
            idx_rep // (R * B),
            (idx_rep // B) % R,
            idx_rep % B,
        )
        idx_lsw = jnp.argmax(d_lsw)
        p2, s2 = idx_lsw // R, idx_lsw % R

        improved = best > 0

        def apply_rep(a):
            return a.at[p1, s1].set(jnp.where(improved, b1, a[p1, s1]))

        def apply_lsw(a):
            lead, foll = a[p2, 0], a[p2, s2]
            a = a.at[p2, 0].set(jnp.where(improved, foll, lead))
            return a.at[p2, s2].set(jnp.where(improved, lead, foll))

        a = lax.cond(use_rep, apply_rep, apply_lsw, a)
        return a, moves + 1, improved

    a, moves, _ = lax.while_loop(
        cond, body, (a.astype(jnp.int32), jnp.int32(0), jnp.bool_(True))
    )
    return a


polish_jit = jax.jit(polish, static_argnames=("max_moves",))
