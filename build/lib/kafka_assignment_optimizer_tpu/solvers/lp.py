"""LP-format emitter + lp_solve subprocess adapter (reference L4/L5).

Emits the exact lp_solve LP-format dialect of the reference's worked sample
(``/root/reference/README.md:144-185``): ``max:`` objective over
``t{topicIdx}b{brokerId}p{partitionId}[_l]`` variables, ``//``-commented
constraint sections in the same order, and a trailing ``bin`` block
declaring the *full* broker x partition cross product binary
(``README.md:182-184``).

The reference solves this text with the external native lp_solve 5.5 C
solver (``README.md:135-137``). When an ``lp_solve`` binary is on PATH,
``--solver=lp_solve`` shells out to it exactly as the reference does;
otherwise the in-process HiGHS backend (`.milp`) covers the exact path.
"""

from __future__ import annotations

import shutil
import subprocess
import tempfile
import time
from pathlib import Path

import numpy as np

from ..models.instance import ProblemInstance
from .base import SolveResult, register


def var_name(inst: ProblemInstance, p: int, b: int, leader: bool) -> str:
    """``t{t}b{b}p{p}`` naming with 1-based topic index (README.md:146)."""
    t = int(inst.topic_of_part[p]) + 1
    broker = int(inst.broker_ids[b])
    part = int(inst.part_id[p])
    return f"t{t}b{broker}p{part}" + ("_l" if leader else "")


def emit_lp(inst: ProblemInstance) -> str:
    """Serialize the model to lp_solve LP format, section-for-section in the
    reference sample's order (README.md:144-185)."""
    P, B, K = inst.num_parts, inst.num_brokers, inst.num_racks
    out: list[str] = []

    # objective (README.md:145-146)
    out.append("// Optimization function, based on current assignment ")
    terms = []
    for p in range(P):
        for b in range(B):
            wl = int(inst.w_leader[p, b])
            wf = int(inst.w_follower[p, b])
            if wl:
                terms.append(f"{wl} {var_name(inst, p, b, True)}")
            if wf:
                terms.append(f"{wf} {var_name(inst, p, b, False)}")
    out.append("max: " + " + ".join(terms) + ";")
    out.append("")

    def row(coeffs: list[str], op: str, rhs: int) -> str:
        return " + ".join(coeffs) + f" {op} {rhs};"

    # C4 replication factor (README.md:148-151)
    out.append("// Constrain on replication factor for every partition")
    for p in range(P):
        vs = [var_name(inst, p, b, r) for b in range(B) for r in (False, True)]
        out.append(row(vs, "=", int(inst.rf[p])))
    out.append("")

    # C5 one leader per partition (README.md:153-156)
    out.append("// Constraint on having one and only one leader per partition")
    for p in range(P):
        out.append(row([var_name(inst, p, b, True) for b in range(B)], "=", 1))
    out.append("")

    # C6 per-broker replica band (README.md:158-161)
    out.append("// Constraint on min/max replicas per broker")
    for b in range(B):
        vs = [var_name(inst, p, b, r) for p in range(P) for r in (False, True)]
        out.append(row(vs, "<=", inst.broker_hi))
        out.append(row(vs, ">=", inst.broker_lo))
    out.append("")

    # C7 per-broker leader band (README.md:163-166)
    out.append("// Constraint on min/max leaders per broker")
    for b in range(B):
        vs = [var_name(inst, p, b, True) for p in range(P)]
        out.append(row(vs, "<=", inst.leader_hi))
        out.append(row(vs, ">=", inst.leader_lo))
    out.append("")

    # C8 uniqueness per (broker, partition) (README.md:168-171)
    out.append("// Constraint on no leader and replicas on the same broker")
    for b in range(B):
        for p in range(P):
            out.append(
                row([var_name(inst, p, b, False), var_name(inst, p, b, True)],
                    "<=", 1)
            )
    out.append("")

    # C9 per-rack replica band (README.md:173-176)
    rack_members = [
        [b for b in range(B) if int(inst.rack_of_broker[b]) == k]
        for k in range(K)
    ]
    # each rack block carries its rack name in the comment, matching the
    # reference sample's "... per racks. tor02 here" (README.md:173)
    for k in range(K):
        members = rack_members[k]
        out.append(
            "// Constrain on min/max total replicas per racks. "
            f"{inst.rack_names[k]} here"
        )
        vs = [
            var_name(inst, p, b, r)
            for b in members
            for p in range(P)
            for r in (False, True)
        ]
        out.append(row(vs, "<=", int(inst.rack_hi[k])))
        out.append(row(vs, ">=", int(inst.rack_lo[k])))
    out.append("")

    # C10 per-partition per-rack diversity (README.md:178-180); comment
    # names the (partition, rack) pair per the sample's "p0 on tor02
    # here" (README.md:178)
    for p in range(P):
        for k in range(K):
            out.append(
                "// Constrain on min/max replicas per partitions per "
                f"racks. p{p} on {inst.rack_names[k]} here"
            )
            vs = [
                var_name(inst, p, b, r)
                for b in rack_members[k]
                for r in (False, True)
            ]
            out.append(row(vs, "<=", int(inst.part_rack_hi[p])))
    out.append("")

    # binary domain over the full cross product (README.md:182-184)
    out.append("// All variables are binary")
    out.append("bin")
    names = [
        var_name(inst, p, b, r)
        for p in range(P)
        for b in range(B)
        for r in (False, True)
    ]
    out.append(", ".join(names) + ";")
    return "\n".join(out) + "\n"


def parse_lp_solve_output(
    inst: ProblemInstance, text: str
) -> np.ndarray:
    """Parse ``lp_solve -S4`` variable listing back to a candidate
    ``A[P, R]`` (reference L6, README.md:65-78)."""
    P, B = inst.num_parts, inst.num_brokers
    x = np.zeros((P, B), dtype=np.int64)
    y = np.zeros((P, B), dtype=np.int64)
    name_to = {}
    for p in range(P):
        for b in range(B):
            name_to[var_name(inst, p, b, False)] = (x, p, b)
            name_to[var_name(inst, p, b, True)] = (y, p, b)
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 2 and parts[0] in name_to:
            arr, p, b = name_to[parts[0]]
            arr[p, b] = int(round(float(parts[1])))
    a = np.full((P, inst.max_rf), B, dtype=np.int32)
    for p in range(P):
        leaders = np.flatnonzero(y[p])
        followers = np.flatnonzero(x[p])
        if len(leaders) != 1:
            raise RuntimeError(
                f"lp_solve solution: partition {p} has {len(leaders)} leaders"
            )
        reps = [int(leaders[0])] + [int(b) for b in followers]
        a[p, : len(reps)] = reps
    return a


def _bundled_lp_solve() -> Path | None:
    """Build (once) and return the bundled lp_solve-compatible CLI.

    Upstream lp_solve 5.5 cannot be fetched here (no network egress), so
    the repo bundles a work-alike (``native/lp_cli.cpp``): a real
    separate binary that parses the emitted LP text and solves the 0-1
    program exactly — the subprocess path executes end to end either
    way. A system ``lp_solve`` on PATH always takes precedence."""
    try:
        from ..native import build_lp_cli

        return build_lp_cli()
    except Exception:  # no g++ / build failure: path simply unavailable
        return None


def _lp_solve_exe() -> tuple[str, bool] | None:
    """(executable, is_system) for the preferred LP-solving subprocess."""
    exe = shutil.which("lp_solve")
    if exe is not None:
        return exe, True
    bundled = _bundled_lp_solve()
    if bundled is not None:
        return str(bundled), False
    return None


def lp_solve_available() -> bool:
    return _lp_solve_exe() is not None


@register("lp_solve")
def solve_lp_solve(
    inst: ProblemInstance, time_limit_s: float = 600.0, **_unused
) -> SolveResult:
    picked = _lp_solve_exe()
    if picked is None:
        raise RuntimeError(
            "no lp_solve binary on PATH and the bundled lp_cli failed to "
            "build; use --solver=milp for the exact in-process backend"
        )
    exe, is_system = picked
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        lp_path = Path(td) / "model.lp"
        lp_path.write_text(emit_lp(inst))
        # both the system lp_solve 5.5 and the bundled CLI honor
        # -timeout and return their best-so-far incumbent as rc=1; the
        # subprocess timeout is only a backstop against a hung binary
        cmd = [exe, "-S4", "-timeout", str(int(max(1, time_limit_s))),
               str(lp_path)]
        try:
            proc = subprocess.run(
                cmd,
                capture_output=True,
                text=True,
                timeout=time_limit_s + 30.0,
            )
        except subprocess.TimeoutExpired as e:
            raise RuntimeError(
                f"lp_solve ignored -timeout and ran past "
                f"{time_limit_s + 30.0:.0f}s; raise --time-limit or use "
                "--solver=milp"
            ) from e
        if proc.returncode == 7:  # timeout before any incumbent
            raise RuntimeError(
                f"lp_solve found no solution within {time_limit_s:.0f}s; "
                "raise --time-limit or use --solver=milp"
            )
        if proc.returncode not in (0, 1):  # 1 = feasible but timed out
            raise RuntimeError(
                f"lp_solve failed (rc={proc.returncode}): "
                f"{(proc.stderr or proc.stdout)[:500]}"
            )
        a = parse_lp_solve_output(inst, proc.stdout)
    return SolveResult(
        a=a,
        solver="lp_solve",
        wall_clock_s=time.perf_counter() - t0,
        objective=inst.preservation_weight(a),
        optimal=proc.returncode == 0,
        stats={"backend": "system" if is_system else "bundled_lp_cli"},
    )
