"""``--solver=native`` — bundled C++ exact branch-and-bound backend.

Plays the role lp_solve plays for the reference — the native exact solver
behind the model (``/root/reference/README.md:135-137``) — but in-process,
specialized to the replica-slot representation, and built from source in
this repo (``native/bb.cpp``). Exactness is cross-checked against the
HiGHS MILP oracle in tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import time

import numpy as np

from ..models.instance import ProblemInstance
from ..native import load
from .base import SolveResult, register

_STATUS = {0: "optimal", 1: "time_limit", 2: "time_limit_no_solution",
           3: "infeasible"}


@register("native")
def solve_native(
    inst: ProblemInstance, time_limit_s: float = 60.0, **_unused
) -> SolveResult:
    lib = load()
    t0 = time.perf_counter()
    P, B, K, R = inst.num_parts, inst.num_brokers, inst.num_racks, inst.max_rf

    def arr(x, dtype=np.int32):
        return np.ascontiguousarray(x, dtype=dtype)

    rf = arr(inst.rf)
    rack_of = arr(inst.rack_of_broker[:B])
    wl = arr(inst.w_leader)
    wf = arr(inst.w_follower)
    rack_lo = arr(inst.rack_lo)
    rack_hi = arr(inst.rack_hi)
    prh = arr(inst.part_rack_hi)
    # warm start: the greedy repair seed, when feasible, as first incumbent
    # (without one the B&B is a pure feasibility CSP until its first leaf)
    from .tpu.seed import greedy_seed

    seed_a = arr(greedy_seed(inst))
    has_seed = int(inst.is_feasible(seed_a))
    seed_w = int(inst.preservation_weight(seed_a)) if has_seed else 0
    out_a = np.full((P, R), B, dtype=np.int32)
    out_obj = np.zeros(1, dtype=np.int64)
    out_nodes = np.zeros(1, dtype=np.int64)

    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)

    def p32(a):
        return a.ctypes.data_as(i32p)

    status = lib.kao_solve(
        P, B, K, R,
        p32(rf), p32(rack_of), p32(wl), p32(wf),
        inst.broker_lo, inst.broker_hi, inst.leader_lo, inst.leader_hi,
        p32(rack_lo), p32(rack_hi), p32(prh),
        p32(seed_a), seed_w, has_seed,
        float(time_limit_s),
        p32(out_a),
        out_obj.ctypes.data_as(i64p),
        out_nodes.ctypes.data_as(i64p),
    )
    wall = time.perf_counter() - t0
    if status in (2, 3):
        raise RuntimeError(
            f"native solver found no solution ({_STATUS[status]}, "
            f"{int(out_nodes[0])} nodes, {wall:.2f}s)"
        )
    return SolveResult(
        a=out_a,
        solver="native",
        wall_clock_s=wall,
        objective=int(out_obj[0]),
        optimal=status == 0,
        stats={"status": _STATUS[status], "nodes": int(out_nodes[0])},
    )
