"""Solver checkpoint / warm-resume (SURVEY.md §5 "checkpoint / resume").

The reference has no persistence story (its mount is a README + one
image); for the TPU build a checkpoint is a trivial by-product of the
search state: the best candidate found so far. Saving it costs one
``[P, RF]`` int array; resuming seeds the next solve's population with
it, so interrupted or iterative solves (e.g. a service re-optimizing a
live cluster every few minutes) never regress below the last plan.

Format: a single ``.npz`` with the candidate plus an instance fingerprint
(broker ids, topic/partition layout, RF, rack map). A checkpoint only
resumes onto the SAME problem; a mismatched fingerprint is ignored with
a note rather than poisoning the seed.
"""

from __future__ import annotations

import hashlib
import json
import zipfile
from pathlib import Path

import numpy as np

from ..models.instance import ProblemInstance


def instance_fingerprint(inst: ProblemInstance) -> str:
    """Stable digest of everything that defines candidate compatibility:
    layout (brokers, racks, partitions, RF) AND the objective/constraint
    data (current assignment a0, weight matrices, bands) — a checkpoint
    must only resume onto the same *problem*, not just the same shapes
    (ADVICE r1: a same-layout instance with a different current
    assignment or different bands is a different problem, and silently
    re-seeding from it would make the saved meta objective a lie)."""
    h = hashlib.sha256()
    for arr in (inst.broker_ids, inst.rack_of_broker, inst.topic_of_part,
                inst.part_id, inst.rf, inst.a0, inst.w_leader,
                inst.w_follower, inst.rack_lo, inst.rack_hi,
                inst.part_rack_hi):
        h.update(np.ascontiguousarray(arr).tobytes())
    h.update(json.dumps([inst.topics, inst.broker_lo, inst.broker_hi,
                         inst.leader_lo, inst.leader_hi]).encode())
    return h.hexdigest()[:32]


def save(path: str | Path, inst: ProblemInstance, a: np.ndarray,
         meta: dict | None = None) -> None:
    """Atomically persist candidate ``a`` as the checkpoint for ``inst``."""
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    np.savez(
        tmp,
        a=np.asarray(a, np.int32),
        fingerprint=np.frombuffer(
            instance_fingerprint(inst).encode(), dtype=np.uint8
        ),
        meta=np.frombuffer(
            json.dumps(meta or {}, default=str).encode(), dtype=np.uint8
        ),
    )
    # np.savez appends .npz to names without it; normalize
    produced = tmp if tmp.exists() else tmp.with_suffix(tmp.suffix + ".npz")
    produced.replace(path)


def load(path: str | Path, inst: ProblemInstance) -> np.ndarray | None:
    """Return the checkpointed candidate if it belongs to ``inst`` (same
    fingerprint and shape), else None."""
    path = Path(path)
    if not path.exists():
        return None
    try:
        with np.load(path) as z:
            fp = bytes(z["fingerprint"]).decode()
            a = np.asarray(z["a"], np.int32)
    except (OSError, KeyError, ValueError, zipfile.BadZipFile):
        # corrupt/truncated/foreign file: fall back to the greedy seed
        return None
    if fp != instance_fingerprint(inst):
        return None
    if a.shape != (inst.num_parts, inst.max_rf):
        return None
    return a
