"""Full-candidate scoring in pure XLA (the reference scorer).

Computes, for one candidate ``A[P, R]`` in broker-index space, the exact
preservation weight (objective, ``/root/reference/README.md:116-133``) and
integer violation counts of the four inequality constraint families
(``README.md:158-180``) — the same quantities
``ProblemInstance.violations`` computes in numpy, but jit/vmap-friendly so
the annealing engine can (re)score whole candidate batches on device.

The Pallas TPU kernel in ``ops.score_pallas`` is the tiled fast path for
large batches; this module is its correctness oracle and the CPU fallback.

Histograms use scatter-add into ``B+1`` buckets: padded/invalid slots hold
the null broker index ``B`` which lands in the dropped last bucket — no
branching, static shapes, fuses cleanly under jit.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..solvers.tpu.arrays import ModelArrays


class Score(NamedTuple):
    weight: jax.Array  # int32 — preservation weight (maximize)
    pen_broker: jax.Array  # int32 — C6 band violations
    pen_leader: jax.Array  # int32 — C7
    pen_rack: jax.Array  # int32 — C9
    pen_part_rack: jax.Array  # int32 — C10
    cnt: jax.Array  # [B+1] per-broker replica+leader totals
    lcnt: jax.Array  # [B+1] per-broker leader totals
    rcnt: jax.Array  # [K+1] per-rack totals

    @property
    def penalty(self) -> jax.Array:
        return self.pen_broker + self.pen_leader + self.pen_rack + self.pen_part_rack

    @property
    def feasible(self) -> jax.Array:
        return self.penalty == 0


def band_violation(x: jax.Array, lo: jax.Array, hi: jax.Array) -> jax.Array:
    return (jnp.maximum(x - hi, 0) + jnp.maximum(lo - x, 0)).sum().astype(jnp.int32)


def score_one(a: jax.Array, m: ModelArrays) -> Score:
    """Score a single candidate ``a[P, R]``. vmap over the leading axis for
    batches; shard the batch axis over the mesh for multi-chip."""
    P, R = m.a0.shape
    B = m.num_brokers
    K = m.num_racks

    flat = jnp.where(m.slot_valid, a, B)  # null out padded slots
    # per-broker totals (replica + leader roles together, README.md:158-161)
    cnt = jnp.zeros(B + 1, jnp.int32).at[flat.reshape(-1)].add(1)
    leaders = jnp.where(m.rf > 0, a[:, 0], B)
    lcnt = jnp.zeros(B + 1, jnp.int32).at[leaders].add(1)
    racks = m.rack_of[flat]  # [P, R], null -> K
    rcnt = jnp.zeros(K + 1, jnp.int32).at[racks.reshape(-1)].add(1)

    pen_broker = band_violation(cnt[:B], m.broker_band[0], m.broker_band[1])
    pen_leader = band_violation(lcnt[:B], m.leader_band[0], m.leader_band[1])
    pen_rack = band_violation(rcnt[:K], m.rack_lo[:K], m.rack_hi[:K])

    # C10: per (partition, rack) count <= ceil(rf/K) — compute via one-hot
    # over racks per partition row (K is small: <= 8 in every benchmark)
    pr = (racks[:, :, None] == jnp.arange(K)[None, None, :]).sum(1)  # [P, K]
    pen_part_rack = (
        jnp.maximum(pr - m.part_rack_hi[:, None], 0).sum().astype(jnp.int32)
    )

    # objective: slot 0 scores leader weight, slots 1.. follower weight
    rows = jnp.arange(P)
    w = m.w_lead[rows, a[:, 0]].astype(jnp.int32)
    w = jnp.where(m.rf > 0, w, 0).sum()
    if R > 1:
        wf = jnp.take_along_axis(m.w_foll, a[:, 1:], axis=1)
        w = w + jnp.where(m.slot_valid[:, 1:], wf, 0).sum()

    return Score(
        weight=w.astype(jnp.int32),
        pen_broker=pen_broker,
        pen_leader=pen_leader,
        pen_rack=pen_rack,
        pen_part_rack=pen_part_rack,
        cnt=cnt,
        lcnt=lcnt,
        rcnt=rcnt,
    )


score_batch = jax.vmap(score_one, in_axes=(0, None))


def moves_one(a: jax.Array, m: ModelArrays) -> jax.Array:
    """Replica-move count vs the current assignment (C15): valid slots
    holding a broker with zero leader weight were not assigned before."""
    rows = jnp.arange(m.num_parts)[:, None]
    member = m.w_lead[rows, a] > 0
    return (jnp.logical_and(~member, m.slot_valid)).sum().astype(jnp.int32)


moves_batch = jax.vmap(moves_one, in_axes=(0, None))
