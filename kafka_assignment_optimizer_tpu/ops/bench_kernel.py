"""Kernel-vs-XLA micro-benchmark: does the Pallas scorer earn its keep?

VERDICT r1 items 2-3: the Pallas kernel (``ops.score_pallas``) had only
ever run under ``interpret=True`` — Mosaic had never lowered it, and no
timing existed against the pure-XLA scorer it is meant to beat. This
module provides the measurement: build the headline-shaped instance
(256 brokers / 10k partitions / RF=3 decommission, BASELINE.json), score
a production-sized candidate batch with both implementations, and report
wall-clock + throughput. ``bench.py --kernel`` embeds the result in the
headline JSON so every round records whether the kernel (a) lowers
cleanly on real TPU and (b) wins.

On CPU the compiled-kernel path does not exist; the report then carries
``{"skipped": "..."}`` plus the XLA timing, so the artifact still shows
the scorer's raw speed.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp

N_CANDIDATES = 256
REPS = 10

# Public HBM-bandwidth specs by device kind (GB/s) — the roofline
# denominator. The scoring hot loop is integer/VPU work with no large
# matmuls, so memory bandwidth — not MXU FLOPs — is the relevant chip
# ceiling (VERDICT r2 item 4: ground "fast" against the hardware, not
# just against XLA).
_PEAK_HBM_GBPS = {
    "v5 lite": 819.0,  # jax reports v5e as "TPU v5 lite"
    "v5e": 819.0,
    "v5p": 2765.0,
    "v4": 1228.0,
    "v3": 900.0,
    "v2": 700.0,
}

# VPU peak (elementwise int32/fp32 ops/s) — the ceiling for the one-hot
# algebra, which is vector compares/adds/MACs, not MXU matmuls.
# Estimate derived from public per-chip specs: peak bf16 TFLOP/s =
# n_MXU * 128*128 * 2 * clock fixes the clock, and the VPU is (8, 128)
# lanes * 4 ALUs at the same clock (TPU architecture docs / scaling
# book), so VPU ops/s = 1024 * 4 * clock. v5e: 197e12 bf16 with 4 MXUs
# -> clock ~1.5 GHz -> ~6.1e12 VPU ops/s. An ESTIMATE (clocks are not
# published per part) — utilization figures quote it as the denominator
# and are meaningful to ~20%.
_PEAK_VPU_TOPS = {
    "v5 lite": 6.1,
    "v5e": 6.1,
    "v5p": 7.4,   # 459e12 bf16, 8 MXUs -> ~1.75 GHz
    "v4": 4.5,    # 275e12 bf16, 8 MXUs -> ~1.05 GHz
}


def _peak_hbm_gbps(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for k, v in _PEAK_HBM_GBPS.items():
        if k in kind:
            return v
    return None


def _peak_vpu_tops(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for k, v in _PEAK_VPU_TOPS.items():
        if k in kind:
            return v
    return None


def _scorer_roofline(inst, P: int, R: int, n: int, best_s: float,
                     device_kind: str) -> dict:
    """Algorithmic HBM floor of the scoring pass, from the tiles the
    kernel actually streams (``score_pallas.score_batch_pallas`` block
    specs): per candidate the grid walks every partition tile, fetching
    the candidate rows (int32), the valid mask (bool), and BOTH
    per-(partition, broker) weight tables (int32) — the weight streams
    dominate at 8*P*B1 bytes/candidate. Blocks with a constant index
    map (rack one-hot, band rows) stay VMEM-resident and are excluded.

    achieved_GBps = floor_bytes / measured_time: a LOWER bound on the
    attained bandwidth (re-fetches only add traffic). Interpretation,
    established by experiment on v5e: utilization ~6% of peak, and a
    partition-major grid variant that amortizes the weight streams
    ~70x (plus tile sizes 256-2048) all time IDENTICAL with bit-equal
    outputs — so the kernel is NOT HBM-bound; the limiter is on-chip
    (the [TP, R, B1] one-hot materialization in VMEM and its
    reductions). Reported against HBM peak anyway so every artifact
    states hardware headroom explicitly, not only a vs-XLA ratio."""
    B1 = inst.num_brokers + 1
    tp = min(256, max(8, -(-P // 8) * 8))
    Pp = -(-P // tp) * tp
    K1 = inst.num_racks + 1
    bytes_per_cand = (
        Pp * (4 * R + R + 8 * B1 + 4)      # a, valid, wl+wf, prh tiles
        + (2 * B1 + K1 + 8) * 4            # histogram + score outputs
    )
    total = bytes_per_cand * n
    peak = _peak_hbm_gbps(device_kind)
    out = {
        "model": "HBM floor from streamed kernel tiles; measured "
                 "limiter is on-chip compute, not HBM (grid-order and "
                 "tile-size invariant)",
        "bytes_per_candidate": int(bytes_per_cand),
        "achieved_GBps": round(total / best_s / 1e9, 2),
        "device_kind": device_kind,
    }
    if peak is not None:
        out["peak_GBps"] = peak
        out["hbm_utilization"] = round(total / best_s / 1e9 / peak, 4)
    # compute-side grounding (VERDICT r3 item 5): the kernel's VPU work
    # is the one-hot algebra — per (partition, slot, broker-column)
    # element one compare + select to build the one-hot, one histogram
    # add, and a 2-op multiply-add against each streamed weight table
    # (leader on slot 0, follower on slots 1..R-1 -> ~1 MAC per
    # element) => ~5 executed int ops per P*R*B1 element. The rack
    # matmul runs on the MXU and is excluded. This counts ops the
    # kernel EXECUTES (the ~B-fold one-hot inflation included), so
    # utilization near 1.0 would mean the VPU is saturated and only a
    # formulation change — not scheduling — could speed it up.
    int_ops_per_cand = 5 * Pp * R * B1
    achieved_tops = int_ops_per_cand * n / best_s / 1e12
    out["int_ops_per_candidate"] = int(int_ops_per_cand)
    out["achieved_int_Tops"] = round(achieved_tops, 3)
    peak_vpu = _peak_vpu_tops(device_kind)
    if peak_vpu is not None:
        out["peak_vpu_Tops"] = peak_vpu
        out["compute_utilization"] = round(achieved_tops / peak_vpu, 4)
    return out


def _timeit(fn, *args, reps: int = REPS) -> float:
    """Median-free simple timing: one warmup (compile), then best of
    ``reps`` synchronous runs — 'best' filters scheduler noise, which is
    the right statistic for a throughput ceiling."""
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def _headline_instance(smoke: bool):
    from ..models.instance import build_instance
    from ..utils import gen

    sc = (
        gen.SCENARIOS["decommission"](**gen.SMOKE_KWARGS["decommission"])
        if smoke
        else gen.SCENARIOS["decommission"]()
    )
    return build_instance(sc.current, sc.broker_list, sc.topology,
                          sc.target_rf)


def kernel_vs_xla(smoke: bool = False, n: int = N_CANDIDATES) -> dict:
    """Time ``score_batch_pallas`` (compiled, interpret=False) against
    ``score_batch`` (pure XLA) on an ``[n, P, R]`` batch of perturbed
    seeds of the headline instance. Returns a JSON-able report."""
    from ..solvers.tpu import arrays
    from ..solvers.tpu.seed import greedy_seed
    from .score import score_batch
    from .score_pallas import score_batch_pallas

    platform = jax.devices()[0].platform
    inst = _headline_instance(smoke)
    m = arrays.from_instance(inst)
    a0 = jnp.asarray(greedy_seed(inst), jnp.int32)
    # n distinct candidates: randomly re-target one slot per partition so
    # histograms/penalties differ per row (defeats CSE, matches the shape
    # the engine rescoring sees)
    key = jax.random.PRNGKey(0)
    P, R = a0.shape
    ks, kb = jax.random.split(key)
    slots = jax.random.randint(ks, (n, P), 0, R)
    brokers = jax.random.randint(kb, (n, P), 0, inst.num_brokers)
    a = jnp.broadcast_to(a0, (n, P, R))
    a = a.at[jnp.arange(n)[:, None], jnp.arange(P)[None, :], slots].set(
        brokers
    )
    a = jax.block_until_ready(a)

    report: dict = {
        "platform": platform,
        "batch": int(n),
        "partitions": int(P),
        "brokers": int(inst.num_brokers),
    }
    # jit the XLA scorer: the engine always runs it fused under jit, and
    # an eager op-by-op pass would bias the comparison against XLA
    xla_s = _timeit(jax.jit(lambda x: score_batch(x, m)), a)
    report["xla_s"] = round(xla_s, 5)
    report["xla_candidates_per_s"] = round(n / xla_s)
    if platform != "tpu":
        report["skipped"] = (
            f"compiled Pallas path needs TPU (platform={platform}); "
            "parity is covered by interpret-mode tests"
        )
        return report
    try:
        pallas_s = _timeit(
            lambda x: score_batch_pallas(x, m, interpret=False), a
        )
        # a fast wrong kernel must never be reported as a win: the
        # artifact's speedup is only evidence if the compiled Mosaic
        # outputs match the XLA oracle integer-for-integer
        sx = jax.jit(lambda x: score_batch(x, m))(a)
        sp_ = score_batch_pallas(a, m, interpret=False)
        import numpy as _np

        parity = bool(
            (_np.asarray(sx.weight) == _np.asarray(sp_.weight)).all()
            and (_np.asarray(sx.penalty)
                 == _np.asarray(sp_.penalty)).all()
        )
        report["pallas_parity"] = parity
        if not parity:
            report["pallas_error"] = "compiled kernel disagrees with XLA oracle"
            pallas_s = None
    except Exception as e:  # noqa: BLE001 - lowering failure IS the signal
        report["pallas_error"] = repr(e)[:500]
        pallas_s = None
    if pallas_s is not None:
        report["pallas_s"] = round(pallas_s, 5)
        report["pallas_candidates_per_s"] = round(n / pallas_s)
        report["pallas_speedup_vs_xla"] = round(xla_s / pallas_s, 3)
        report["roofline"] = _scorer_roofline(
            inst, P, R, n, pallas_s, jax.devices()[0].device_kind
        )

    # the proposal kernel (the sweep hot loop's propose->accept stage):
    # time one sweep-shaped evaluation at engine-shaped batch size
    # (8 chains, the production default) — kernel in the PRODUCTION
    # configuration (Pallas hists, _make_scorer('pallas')) against the
    # all-XLA reference path. Independent of the scoring-kernel result
    # above: the kernels lower separately and each failure is evidence.
    from ..solvers.tpu.sweep import _make_scorer, propose_site

    nprop = 8
    ap = a[:nprop]
    bits = jax.random.bits(jax.random.PRNGKey(2), (nprop, P, 8),
                           jnp.uint32)
    xla_p = _timeit(
        jax.jit(lambda a, b: propose_site(m, a, b, 1.0).prio.sum()),
        ap, bits,
    )
    report["propose_xla_s"] = round(xla_p, 5)
    try:
        sc = _make_scorer("pallas")
        hists_p, propose_p = sc.hists, sc.propose
        pal_p = _timeit(
            jax.jit(lambda a, b: propose_p(
                m, a, b, 1.0, hists=hists_p
            ).prio.sum()),
            ap, bits,
        )
    except Exception as e:  # noqa: BLE001 - lowering failure IS the signal
        report["propose_error"] = repr(e)[:300]
    else:
        report["propose_pallas_s"] = round(pal_p, 5)
        report["propose_speedup_vs_xla"] = round(xla_p / pal_p, 3)

    # end-to-end sweep rate: the production stepper (8 chains, Mosaic
    # kernels, snapshots, migration collectives). Two ladder lengths
    # separate the MARGINAL per-sweep cost (what an extra sweep costs —
    # the number that decides a long ladder's wall-clock) from the
    # dispatch-inclusive short-ladder rate (a 16-sweep chunk over a
    # tunneled TPU pays ~25-30 ms of round-trip latency, which r1-r4
    # artifacts folded into "sweep_ms"). All repeats are recorded so the
    # artifact carries the spread, not one draw (VERDICT r4 item 3).
    # Independent of the kernel results above (own try/except).
    try:
        import numpy as _np

        from ..parallel.mesh import (
            init_sweep_state,
            make_mesh,
            solve_on_mesh,
        )
        from ..solvers.tpu.arrays import geometric_temps

        mesh = make_mesh(None)
        key = jax.random.PRNGKey(3)

        def ladder_times(n_sweeps: int, reps: int = 5) -> list[float]:
            temps = geometric_temps(2.0, 0.02, n_sweeps)

            def run(st):
                _st, pa, _pk, _c = solve_on_mesh(
                    m, a0, key, mesh, 8, n_sweeps, 1, engine="sweep",
                    temps=temps, scorer="pallas", state=st,
                )
                # device_get, not block_until_ready: the sync the
                # latter promises was observed unreliable through the
                # tunneled-TPU client (no-op returns in ~0.1 ms)
                return _np.asarray(jax.device_get(pa)).sum()

            # the sweep solver DONATES its state (parallel.mesh): each
            # run consumes the buffers it is handed, so every repeat
            # gets a fresh identical state (device_put of host views —
            # microseconds, outside the timed region)
            run(init_sweep_state(m, a0, key, mesh, 8))  # warmup/compile
            times = []
            for _ in range(reps):
                state = init_sweep_state(m, a0, key, mesh, 8)
                t0 = time.perf_counter()
                run(state)
                times.append(time.perf_counter() - t0)
            return times

        short_n, long_n = 32, 96
        t_short = ladder_times(short_n)
        t_long = ladder_times(long_n)
        marginal_s = (min(t_long) - min(t_short)) / (long_n - short_n)
        if marginal_s <= 0:
            # RTT jitter can make the short ladder's best draw slower
            # than the long one's; a negative/zero marginal rate must
            # not be reported as a valid sweep_ms (nor divide by zero)
            report["sweep_ms_error"] = (
                f"non-positive marginal ({marginal_s * 1000:.3f} ms): "
                "ladder minima inverted by host jitter; see the raw "
                "repeats"
            )
            marginal_s = None
        else:
            report["sweep_ms"] = round(marginal_s * 1000, 3)
            report["sweeps_per_s"] = round(1.0 / marginal_s, 1)
        report["sweep_ms_method"] = (
            f"marginal: (min ladder[{long_n}] - min ladder[{short_n}]) "
            f"/ {long_n - short_n}, {len(t_short)} repeats each"
        )
        report["sweep_ladder_short_ms"] = [
            round(t * 1000, 2) for t in t_short
        ]
        report["sweep_ladder_long_ms"] = [
            round(t * 1000, 2) for t in t_long
        ]
        # dispatch-inclusive 16-sweep chunk rate: comparable to the
        # r1-r4 artifacts' "sweep_ms" (which measured exactly this)
        t16 = ladder_times(16)
        report["sweep_ms_chunk16_incl_dispatch"] = round(
            min(t16) / 16 * 1000, 3
        )
        # sweep-level bandwidth grounding, on the marginal rate: each
        # snapshot rescoring streams the scorer tiles (1/8 of sweeps);
        # the per-sweep proposal/thin/delta kernels stream the
        # candidate rows + weight tables
        if marginal_s is not None:
            rb = _scorer_roofline(inst, P, R, 8 * (long_n - short_n),
                                  marginal_s * (long_n - short_n),
                                  jax.devices()[0].device_kind)
            rb["model"] = (
                "scorer-tile floor per sweep vs the marginal sweep "
                "rate; proposal/thin/delta kernel work excluded, so "
                "bytes/ops/utilization are lower bounds"
            )
            report["sweep_roofline"] = rb
    except Exception as e:  # noqa: BLE001 - keep the rest of the report
        report["sweep_error"] = repr(e)[:300]
    return report
