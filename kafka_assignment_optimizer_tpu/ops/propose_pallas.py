"""Pallas TPU kernel: fused single-site proposal evaluation.

The sweep engine's hot loop is one ``propose -> accept -> thin -> apply``
pass over every (chain, partition) per sweep. The XLA formulation of the
propose/accept stage (``solvers.tpu.sweep.propose_site``) is ~10 separate
table gathers and one-hot reductions over ``[N, P]`` operands — each one
a full HBM round-trip, and gathers lower poorly on TPU (measured r2:
~2.5-4.5 ms per op at 8 chains x 10k partitions, ~25 ms per sweep
all-in). This kernel fuses the entire stage into ONE pass: each
(chain, partition-tile) grid cell loads its tile once into VMEM and does
every lookup as a one-hot multiply-reduce in registers.

Layout: partitions live in the LANE dimension and brokers in SUBLANES —
tables are streamed as transposed ``[B+1, TP]`` tiles — so every
per-proposal table lookup ``tab[b]`` is ``(onehot(b) * tab).sum(axis=0)``,
a cross-sublane reduction, and the outputs land lane-major exactly as the
``[N, P]`` proposal records downstream thinning consumes.

Bit-parity contract: given the same ``bits [N, P, 8]`` and histograms,
this kernel reproduces ``propose_site`` EXACTLY (same integer arithmetic,
same float32 ops in the same order) — asserted bit-for-bit in
tests/test_propose_pallas.py via interpret mode, so the CPU CI executes
the very code path the TPU runs and either engine path yields identical
trajectories.

Reference scope note: the reference solves this model with host-side
lp_solve (``/root/reference/README.md:135-137``); a device-resident
proposal kernel has no upstream counterpart — it is the TPU-native hot
path SURVEY.md §7 step 6 calls for.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..solvers.tpu.arrays import (
    SCALE_W,
    ModelArrays,
    band_pen as _band,
)
from ..solvers.tpu.sweep import P_LSWAP, P_RESTORE, SiteProposals

# partition-tile width (lanes): multiple of 128
_TP = 256


def _u01(bits):
    """uint32 -> uniform float32 in [0, 1) — must match arrays.u01
    bit-for-bit. Mosaic has no uint32->float32 cast, so hop through
    int32: the shifted value fits in 24 bits, making the detour exact."""
    return (bits >> jnp.uint32(8)).astype(jnp.int32).astype(
        jnp.float32
    ) * jnp.float32(1.0 / (1 << 24))


def _rand_idx(u, hi, hi_f):
    """floor(u * hi) clamped to hi-1 — mirrors sweep._rand_idx."""
    return jnp.minimum((u * hi_f).astype(jnp.int32), hi - 1)


def _propose_kernel(
    # inputs ------------------------------------------------------------
    a_ref,       # [1, R, TP] int32 candidate tile, partitions in lanes
    a0_ref,      # [R, TP] int32 original assignment tile
    rf_ref,      # [1, TP] int32
    prh_ref,     # [1, TP] int32 per-partition rack-diversity cap
    pval_ref,    # [1, TP] int32 1 on real partitions, 0 on lane padding
    wl_ref,      # [B1, TP] int32 leader weights, transposed
    wf_ref,      # [B1, TP] int32 follower weights, transposed
    rackof_ref,  # [B1, 1] int32 broker -> rack index (null -> K)
    rlo_ref,     # [K1, 1] int32
    rhi_ref,     # [K1, 1] int32
    lim_ref,     # [1, 4] int32 (broker_lo, broker_hi, leader_lo, leader_hi)
    temp_ref,    # [1, 2] float32 (temp, lam) — per-lane config is DATA
    bits_ref,    # [1, 8, TP] uint32
    cnt_ref,     # [B1, N] int32 broker histograms, all chains (full block:
                 # Mosaic forbids 1-lane column blocks; the kernel selects
                 # this grid row's chain column with a one-hot over lanes)
    lcnt_ref,    # [B1, N] int32
    rcnt_ref,    # [K1, N] int32
    # outputs ([1, 1, TP] blocks of [N, 1, P] arrays) -------------------
    o_islsw_ref,
    o_s_ref,
    o_bnew_ref,
    o_blead_ref,
    o_bats_ref,
    o_prio_ref,
    # thinning priority maps ([1, B1, LW] blocks, accumulated over the
    # partition-tile grid axis; LW = 128 lanes, max-folded in XLA) -----
    o_mout_ref,
    o_min_ref,
):
    B1, TP = wl_ref.shape
    K1 = rcnt_ref.shape[0]
    R = a0_ref.shape[0]
    B = B1 - 1
    i32 = jnp.int32
    f32 = jnp.float32

    # this grid row's chain: select its histogram columns [.., 1]
    n = pl.program_id(0)
    NN = cnt_ref.shape[1]
    sel = (jax.lax.broadcasted_iota(i32, (1, NN), 1) == n).astype(i32)
    cnt_col = (cnt_ref[...] * sel).sum(1, keepdims=True)    # [B1, 1]
    lcnt_col = (lcnt_ref[...] * sel).sum(1, keepdims=True)  # [B1, 1]
    rcnt_col = (rcnt_ref[...] * sel).sum(1, keepdims=True)  # [K1, 1]

    # every per-partition quantity is a [1, TP] ROW vector — Mosaic
    # cannot lower several ops (e.g. bool truncation) on 1-D vectors
    rf = rf_ref[...]
    rf_f = rf.astype(f32)
    bits = bits_ref[0]

    # ---- proposal: slot + move type + incoming broker ----------------
    u_slot = _u01(bits[0:1, :])
    s_rep = _rand_idx(u_slot, rf, rf_f)
    hi = jnp.maximum(rf - 1, 1)
    s_lsw = 1 + _rand_idx(u_slot, hi, hi.astype(f32))
    is_lsw = jnp.logical_and(_u01(bits[1:2, :]) < P_LSWAP, rf > 1)
    s = jnp.where(is_lsw, s_lsw, s_rep)

    a = a_ref[0]  # [R, TP]
    b_lead = a[0:1, :]
    b_at_s = jnp.zeros_like(b_lead)
    b_orig = jnp.zeros_like(b_lead)
    s_orig = _rand_idx(_u01(bits[3:4, :]), i32(R), f32(R))
    for r in range(R):
        b_at_s = jnp.where(s == r, a[r:r + 1, :], b_at_s)
        b_orig = jnp.where(s_orig == r, a0_ref[r:r + 1, :], b_orig)
    b_old = jnp.where(is_lsw, b_lead, b_at_s)

    b_uni = _rand_idx(_u01(bits[2:3, :]), i32(B), f32(B))
    b_new = jnp.where(
        jnp.logical_and(_u01(bits[4:5, :]) < P_RESTORE, b_orig < B),
        b_orig,
        b_uni,
    )

    # ---- one-hot lookup machinery ------------------------------------
    iota_b = jax.lax.broadcasted_iota(i32, (B1, TP), 0)

    def oh(b):  # [1, TP] -> [B1, TP]
        return (b == iota_b).astype(i32)

    def lut(tab_col, ohb):  # tab [B1, 1] x onehot -> [1, TP]
        return (ohb * tab_col).sum(axis=0, keepdims=True)

    oh_old = oh(b_old)
    oh_new = oh(b_new)
    oh_ats = oh(b_at_s)

    # ---- deltas (replace: slot s <- b_new) ---------------------------
    lead_slot = s == 0
    wl_new = (oh_new * wl_ref[...]).sum(0, keepdims=True)
    wf_new = (oh_new * wf_ref[...]).sum(0, keepdims=True)
    wl_old = (oh_old * wl_ref[...]).sum(0, keepdims=True)
    wf_old = (oh_old * wf_ref[...]).sum(0, keepdims=True)
    dw_rep = jnp.where(lead_slot, wl_new - wl_old, wf_new - wf_old)

    lim = lim_ref[...]
    blo, bhi = lim[0, 0], lim[0, 1]
    llo, lhi = lim[0, 2], lim[0, 3]
    cnt_old = lut(cnt_col, oh_old)
    cnt_new = lut(cnt_col, oh_new)
    d_cnt = (
        _band(cnt_old - 1, blo, bhi) - _band(cnt_old, blo, bhi)
        + _band(cnt_new + 1, blo, bhi) - _band(cnt_new, blo, bhi)
    )
    lcnt_old = lut(lcnt_col, oh_old)
    lcnt_new = lut(lcnt_col, oh_new)
    d_lcnt_rep = jnp.where(
        lead_slot,
        _band(lcnt_old - 1, llo, lhi) - _band(lcnt_old, llo, lhi)
        + _band(lcnt_new + 1, llo, lhi) - _band(lcnt_new, llo, lhi),
        0,
    )

    r_old = lut(rackof_ref[...], oh_old)
    r_new = lut(rackof_ref[...], oh_new)
    iota_k = jax.lax.broadcasted_iota(i32, (K1, TP), 0)
    ohk_old = (r_old == iota_k).astype(i32)
    ohk_new = (r_new == iota_k).astype(i32)
    rc_old = (ohk_old * rcnt_col).sum(0, keepdims=True)
    rc_new = (ohk_new * rcnt_col).sum(0, keepdims=True)
    rlo_old = (ohk_old * rlo_ref[...]).sum(0, keepdims=True)
    rhi_old = (ohk_old * rhi_ref[...]).sum(0, keepdims=True)
    rlo_new = (ohk_new * rlo_ref[...]).sum(0, keepdims=True)
    rhi_new = (ohk_new * rhi_ref[...]).sum(0, keepdims=True)
    d_rcnt = (
        _band(rc_old - 1, rlo_old, rhi_old) - _band(rc_old, rlo_old, rhi_old)
        + _band(rc_new + 1, rlo_new, rhi_new) - _band(rc_new, rlo_new, rhi_new)
    )

    # diversity + row-duplication legality, per live slot
    c_old = jnp.zeros_like(r_old)
    c_new = jnp.zeros_like(r_new)
    # i32 accumulator, not bool: a bool-typed constant lowers through an
    # i8 -> i1 truncation Mosaic does not support
    in_row = jnp.zeros_like(r_old)
    for r in range(R):
        live = r < rf
        flat_r = jnp.where(live, a[r:r + 1, :], B)
        rack_r = lut(rackof_ref[...], oh(flat_r))
        c_old = c_old + (rack_r == r_old).astype(i32)
        c_new = c_new + (rack_r == r_new).astype(i32)
        in_row = in_row + (flat_r == b_new).astype(i32)
    cap = prh_ref[...]

    def g(c):
        return jnp.maximum(c - cap, 0)

    d_div = g(c_old - 1) - g(c_old) + g(c_new + 1) - g(c_new)
    cross_rack = r_old != r_new
    dpen_rep = d_cnt + d_lcnt_rep + jnp.where(cross_rack, d_rcnt + d_div, 0)
    legal_rep = in_row == 0

    # ---- deltas (lswap: promote slot s to leader) --------------------
    wl_ats = (oh_ats * wl_ref[...]).sum(0, keepdims=True)
    wf_ats = (oh_ats * wf_ref[...]).sum(0, keepdims=True)
    dw_lsw = wl_ats + wf_old - wl_old - wf_ats
    lc_f = lut(lcnt_col, oh_ats)
    dpen_lsw = (
        _band(lcnt_old - 1, llo, lhi) - _band(lcnt_old, llo, lhi)
        + _band(lc_f + 1, llo, lhi) - _band(lc_f, llo, lhi)
    )

    dw = jnp.where(is_lsw, dw_lsw, dw_rep)
    dpen = jnp.where(is_lsw, dpen_lsw, dpen_rep)
    # pure i1 logic, not a select of two bool vectors — a bool-typed
    # select materializes i8 operands and Mosaic cannot truncate i8->i1.
    # rf > 0 mirrors sweep.propose_site: bucket-padded rows must never
    # win a thinning token (their apply is a no-op).
    legal = jnp.logical_and(
        jnp.logical_or(
            jnp.logical_and(is_lsw, rf > 1),
            jnp.logical_and(jnp.logical_not(is_lsw), legal_rep),
        ),
        rf > 0,
    )
    # penalty scale as data (mirrors sweep.propose_site bit-for-bit:
    # the int deltas are exact in float32, < 2^24)
    lam = temp_ref[0, 1]
    delta = (SCALE_W * dw).astype(f32) - lam * dpen.astype(f32)

    # ---- Metropolis accept + thinning priority -----------------------
    temp = temp_ref[0, 0]
    accept = jnp.logical_and(
        legal,
        jnp.logical_or(
            delta >= 0,
            _u01(bits[5:6, :]) < jnp.exp(delta / jnp.maximum(temp, 1e-6)),
        ),
    )
    prio = jnp.where(accept, _u01(bits[6:7, :]) + f32(1e-6), 0.0)

    o_islsw_ref[0] = is_lsw.astype(i32)
    o_s_ref[0] = s
    o_bnew_ref[0] = b_new
    o_blead_ref[0] = b_lead
    o_bats_ref[0] = b_at_s
    o_prio_ref[0] = prio

    # ---- thinning priority maps (r5 delta engine) --------------------
    # m_out[b] / m_in[b] = max prio over this chain's proposals whose
    # out/in token is b — the same values sweep._thin_keep builds with
    # scatter-max, accumulated here across partition tiles where the
    # tokens already sit in VMEM. Lane padding is masked (prio -> 0):
    # padded lanes carry synthetic accepted proposals whose tokens would
    # otherwise pollute broker 0's in-map.
    pt = pl.program_id(1)

    @pl.when(pt == 0)
    def _init_maps():
        o_mout_ref[...] = jnp.zeros_like(o_mout_ref)
        o_min_ref[...] = jnp.zeros_like(o_min_ref)

    prio_v = jnp.where(pval_ref[...] > 0, prio, 0.0)  # [1, TP]
    # tok_out = b_old (= where(is_lsw, b_lead, b_at_s)); tok_in below
    oh_tin = jnp.where(jnp.broadcast_to(is_lsw, (B1, TP)), oh_ats, oh_new)
    po = jnp.where(oh_old > 0, jnp.broadcast_to(prio_v, (B1, TP)), 0.0)
    pi = jnp.where(oh_tin > 0, jnp.broadcast_to(prio_v, (B1, TP)), 0.0)
    LW = o_mout_ref.shape[2]
    for c in range(TP // LW):
        seg = slice(c * LW, (c + 1) * LW)
        o_mout_ref[0] = jnp.maximum(o_mout_ref[0], po[:, seg])
        o_min_ref[0] = jnp.maximum(o_min_ref[0], pi[:, seg])


def _pad_lanes(x, tp, value):
    """Pad the LAST axis up to a multiple of tp."""
    pad = (-x.shape[-1]) % tp
    if pad == 0:
        return x
    widths = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
    return jnp.pad(x, widths, constant_values=value)


_LW = 128  # lane width of the in-kernel map accumulators


@functools.partial(jax.jit, static_argnames=("interpret",))
def _propose_call(a, bits, cnt, lcnt, rcnt, temp, lam, a0, rf, prh, wl,
                  wf, rackof, rlo, rhi, lim, *, interpret: bool):
    N, P, R = a.shape
    B1 = wl.shape[0]
    K1 = rlo.shape[0]
    tp = min(_TP, max(128, -(-P // 128) * 128))

    aT = _pad_lanes(jnp.swapaxes(a, 1, 2), tp, B1 - 1)        # [N, R, Pp]
    bitsT = _pad_lanes(jnp.swapaxes(bits, 1, 2), tp, 0)       # [N, 8, Pp]
    a0T = _pad_lanes(jnp.swapaxes(a0, 0, 1), tp, B1 - 1)      # [R, Pp]
    rf_p = _pad_lanes(rf[None, :], tp, 1)                     # [1, Pp]
    prh_p = _pad_lanes(prh[None, :], tp, 1)                   # [1, Pp]
    wlT = _pad_lanes(wl, tp, 0)                               # [B1, Pp]
    wfT = _pad_lanes(wf, tp, 0)                               # [B1, Pp]
    cntT = jnp.swapaxes(cnt, 0, 1)                            # [B1, N]
    lcntT = jnp.swapaxes(lcnt, 0, 1)
    rcntT = jnp.swapaxes(rcnt, 0, 1)                          # [K1, N]
    # (temp, lam) ride one [1, 2] f32 operand: per-lane config is data,
    # so every config shares this executable (docs/PORTFOLIO.md)
    temp_a = jnp.stack(
        [jnp.asarray(temp, jnp.float32), jnp.asarray(lam, jnp.float32)]
    )[None, :]

    Pp = aT.shape[-1]
    pval = (jnp.arange(Pp, dtype=jnp.int32) < P).astype(jnp.int32)[None]
    grid = (N, Pp // tp)
    vm = pltpu.VMEM

    outs = pl.pallas_call(
        _propose_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, R, tp), lambda n, p: (n, 0, p), memory_space=vm),
            pl.BlockSpec((R, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((1, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((1, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((1, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((B1, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((B1, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((B1, 1), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((K1, 1), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((K1, 1), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((1, 4), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((1, 2), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((1, 8, tp), lambda n, p: (n, 0, p), memory_space=vm),
            # full-array blocks: Mosaic forbids 1-lane column blocks, so
            # every chain's histogram column rides along and the kernel
            # one-hot-selects its own (N is small)
            pl.BlockSpec((B1, N), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((B1, N), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((K1, N), lambda n, p: (0, 0), memory_space=vm),
        ],
        # outputs are [N, 1, Pp] (squeezed after the call): Mosaic needs
        # the block's sublane dim to divide 8 or equal the array's, and
        # a (1, tp) block of an [N, Pp] array satisfies neither for N>1
        out_specs=[
            pl.BlockSpec((1, 1, tp), lambda n, p: (n, 0, p),
                         memory_space=vm)
            for _ in range(6)
        ] + [
            pl.BlockSpec((1, B1, _LW), lambda n, p: (n, 0, 0),
                         memory_space=vm)
            for _ in range(2)
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 1, Pp), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, Pp), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, Pp), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, Pp), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, Pp), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, Pp), jnp.float32),
            jax.ShapeDtypeStruct((N, B1, _LW), jnp.float32),
            jax.ShapeDtypeStruct((N, B1, _LW), jnp.float32),
        ],
        interpret=interpret,
    )(aT, a0T, rf_p, prh_p, pval, wlT, wfT, rackof, rlo, rhi, lim, temp_a,
      bitsT, cntT, lcntT, rcntT)
    islsw, s, bnew, blead, bats, prio = (o[:, 0, :P] for o in outs[:6])
    # the padded records + lane-folded maps, for the fused thinning path
    # (ops.thin_pallas); standalone callers ignore them
    padded = tuple(o[:, 0] for o in outs[:6])
    m_out = outs[6].max(-1)  # [N, B1]
    m_in = outs[7].max(-1)
    return islsw, s, bnew, blead, bats, prio, padded, m_out, m_in


def propose_site_pallas(m: ModelArrays, a: jax.Array, bits: jax.Array,
                        temp, hists, *, interpret: bool = False):
    """Drop-in replacement for ``sweep.propose_site`` (same SiteProposals,
    bit-identical records). ``hists`` supplies the sweep-start histograms
    — the Pallas scorer on TPU, so the whole hot loop stays in Mosaic."""
    _flat, _racks, cnt, lcnt, rcnt = hists(m, a)
    lim = jnp.concatenate([m.broker_band, m.leader_band]).astype(
        jnp.int32
    )[None]
    islsw, s, bnew, blead, bats, prio, _pad, _mo, _mi = _propose_call(
        a, bits, cnt, lcnt, rcnt, temp, m.lam,
        m.a0, m.rf, m.part_rack_hi.astype(jnp.int32),
        jnp.swapaxes(m.w_lead.astype(jnp.int32), 0, 1),
        jnp.swapaxes(m.w_foll.astype(jnp.int32), 0, 1),
        m.rack_of.astype(jnp.int32)[:, None],
        m.rack_lo.astype(jnp.int32)[:, None],
        m.rack_hi.astype(jnp.int32)[:, None],
        lim,
        interpret=interpret,
    )
    return SiteProposals(is_lsw=islsw.astype(bool), s=s, b_new=bnew,
                         b_lead=blead, b_at_s=bats, prio=prio)


# ---------------------------------------------------------------------------
# exchange halves: the pair-exchange move's per-partition delta half
# (``sweep._exchange_halves_xla`` reproduced bit-for-bit), same layout
# discipline as the proposal kernel
# ---------------------------------------------------------------------------


def _exchange_kernel(
    a_ref,       # [1, R, TP] int32 candidate tile, partitions in lanes
    rf_ref,      # [1, TP] int32
    prh_ref,     # [1, TP] int32
    wl_ref,      # [B1, TP] int32 leader weights, transposed
    wf_ref,      # [B1, TP] int32 follower weights, transposed
    rackof_ref,  # [B1, 1] int32
    lim_ref,     # [1, 4] int32
    sown_ref,    # [1, TP] int32 own slot
    lother_ref,  # [1, TP] int32 partner slot is the leader slot (0/1)
    bother_ref,  # [1, TP] int32 incoming broker
    lcnt_ref,    # [B1, N] int32 leader histograms, all chains
    # outputs ([1, 1, TP] blocks)
    o_bown_ref,
    o_dw_ref,
    o_ddiv_ref,
    o_dlcnt_ref,
    o_legal_ref,
):
    B1, TP = wl_ref.shape
    R = a_ref.shape[1]
    B = B1 - 1
    i32 = jnp.int32

    n = pl.program_id(0)
    NN = lcnt_ref.shape[1]
    sel = (jax.lax.broadcasted_iota(i32, (1, NN), 1) == n).astype(i32)
    lcnt_col = (lcnt_ref[...] * sel).sum(1, keepdims=True)  # [B1, 1]

    rf = rf_ref[...]
    s_own = sown_ref[0]          # [1, TP] (blocks are [1, 1, TP])
    lead_other = lother_ref[0] > 0
    b_other = bother_ref[0]
    a = a_ref[0]  # [R, TP]

    b_own = jnp.zeros_like(b_other)
    for r in range(R):
        b_own = jnp.where(s_own == r, a[r:r + 1, :], b_own)

    iota_b = jax.lax.broadcasted_iota(i32, (B1, TP), 0)

    def oh(b):
        return (b == iota_b).astype(i32)

    def lut(tab, ohb):
        return (ohb * tab).sum(axis=0, keepdims=True)

    oh_own = oh(b_own)
    oh_oth = oh(b_other)

    # objective half
    lead_own = s_own == 0
    dw_own = jnp.where(
        lead_own,
        lut(wl_ref[...], oh_oth) - lut(wl_ref[...], oh_own),
        lut(wf_ref[...], oh_oth) - lut(wf_ref[...], oh_own),
    )

    # pair-level leader-count term
    lim = lim_ref[...]
    llo, lhi = lim[0, 2], lim[0, 3]
    xor = lead_own != lead_other
    l_out = jnp.where(lead_own, b_own, b_other)
    l_in = jnp.where(lead_own, b_other, b_own)
    lo_c = lut(lcnt_col, oh(l_out))
    li_c = lut(lcnt_col, oh(l_in))
    dlcnt = jnp.where(
        xor,
        _band(lo_c - 1, llo, lhi) - _band(lo_c, llo, lhi)
        + _band(li_c + 1, llo, lhi) - _band(li_c, llo, lhi),
        0,
    )

    # diversity half + row legality, from the own row
    r_out = lut(rackof_ref[...], oh_own)
    r_in = lut(rackof_ref[...], oh_oth)
    c_out = jnp.zeros_like(r_out)
    c_in = jnp.zeros_like(r_in)
    in_row = jnp.zeros_like(r_out)
    for r in range(R):
        live = r < rf
        flat_r = jnp.where(live, a[r:r + 1, :], B)
        rack_r = lut(rackof_ref[...], oh(flat_r))
        c_out = c_out + (rack_r == r_out).astype(i32)
        c_in = c_in + (rack_r == r_in).astype(i32)
        in_row = in_row + (flat_r == b_other).astype(i32)
    cap = prh_ref[...]

    def g(c):
        return jnp.maximum(c - cap, 0)

    ddiv = jnp.where(
        r_out != r_in,
        g(c_out - 1) - g(c_out) + g(c_in + 1) - g(c_in),
        0,
    )

    o_bown_ref[0] = b_own
    o_dw_ref[0] = dw_own
    o_ddiv_ref[0] = ddiv
    o_dlcnt_ref[0] = dlcnt
    o_legal_ref[0] = (in_row == 0).astype(i32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _exchange_call(a, lcnt, s_own, lead_other, b_other, rf, prh, wl, wf,
                   rackof, lim, *, interpret: bool):
    N, P, R = a.shape
    B1 = wl.shape[0]
    tp = min(_TP, max(128, -(-P // 128) * 128))

    aT = _pad_lanes(jnp.swapaxes(a, 1, 2), tp, B1 - 1)
    rf_p = _pad_lanes(rf[None, :], tp, 1)
    prh_p = _pad_lanes(prh[None, :], tp, 1)
    wlT = _pad_lanes(wl, tp, 0)
    wfT = _pad_lanes(wf, tp, 0)
    sown = _pad_lanes(s_own[:, None, :], tp, 0)      # [N, 1, Pp]
    loth = _pad_lanes(lead_other[:, None, :], tp, 0)
    both = _pad_lanes(b_other[:, None, :], tp, 0)
    lcntT = jnp.swapaxes(lcnt, 0, 1)

    Pp = aT.shape[-1]
    grid = (N, Pp // tp)
    vm = pltpu.VMEM

    outs = pl.pallas_call(
        _exchange_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, R, tp), lambda n, p: (n, 0, p), memory_space=vm),
            pl.BlockSpec((1, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((1, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((B1, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((B1, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((B1, 1), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((1, 4), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((1, 1, tp), lambda n, p: (n, 0, p), memory_space=vm),
            pl.BlockSpec((1, 1, tp), lambda n, p: (n, 0, p), memory_space=vm),
            pl.BlockSpec((1, 1, tp), lambda n, p: (n, 0, p), memory_space=vm),
            pl.BlockSpec((B1, N), lambda n, p: (0, 0), memory_space=vm),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, tp), lambda n, p: (n, 0, p),
                         memory_space=vm)
            for _ in range(5)
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 1, Pp), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, Pp), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, Pp), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, Pp), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, Pp), jnp.int32),
        ],
        interpret=interpret,
    )(aT, rf_p, prh_p, wlT, wfT, rackof, lim, sown, loth, both, lcntT)
    return tuple(o[:, 0, :P] for o in outs)


def exchange_halves_pallas(m: ModelArrays, a, lcnt, s_own, lead_other,
                           b_other, b_own=None, *,
                           interpret: bool = False):
    """Drop-in replacement for ``sweep._exchange_halves_xla`` —
    bit-identical half-deltas, fused in VMEM. ``b_own`` is accepted for
    interface parity and ignored: the kernel rebuilds it from the tile,
    where the R-way select costs nothing."""
    del b_own
    lim = jnp.concatenate([m.broker_band, m.leader_band]).astype(
        jnp.int32
    )[None]
    b_own, dw, ddiv, dlcnt, legal = _exchange_call(
        a, lcnt, s_own.astype(jnp.int32),
        lead_other.astype(jnp.int32), b_other,
        m.rf, m.part_rack_hi.astype(jnp.int32),
        jnp.swapaxes(m.w_lead.astype(jnp.int32), 0, 1),
        jnp.swapaxes(m.w_foll.astype(jnp.int32), 0, 1),
        m.rack_of.astype(jnp.int32)[:, None],
        lim,
        interpret=interpret,
    )
    return b_own, dw, ddiv, dlcnt, legal > 0
