"""Pallas TPU kernels: fused conflict-thinning + apply + histogram deltas.

The r5 delta sweep engine (``solvers.tpu.sweep``) eliminated the per-sweep
full rescoring — and profiling the remaining loop on v5e showed the new
floor was the conflict-thinning stage itself: XLA lowers the
priority-map scatter-max and the keep-check gathers of
``sweep._thin_keep`` to serialized scatter/gather ops (~3 ms of a ~5 ms
sweep at 8 chains x 10k partitions), and the carried-histogram updates to
three more [N, P, B] reduction passes (~1.4 ms). This module moves both
stages into Mosaic where the tokens already sit in VMEM:

- the **proposal kernel** (``ops.propose_pallas``) accumulates the
  out/in priority maps across its partition-tile grid — the thinning maps
  cost one masked max over one-hots it already built;
- the **site finish kernel** here consumes the proposal records plus the
  folded maps and produces, in one pass: the keep decision, the applied
  population, and the exact carried-histogram deltas (cnt/lcnt/rcnt) as
  lane-folded accumulators;
- the **exchange maps/finish kernels** do the same for the pair-exchange
  move (leader histogram only — replica and rack totals are
  exchange-invariant by construction).

Bit-parity contract: every output equals the XLA formulation in
``sweep._thin_keep`` / ``sweep._apply_site`` / ``sweep._site_hist_deltas``
/ ``sweep.exchange_thin_apply`` integer-for-integer (float32 priority
maxima are order-independent), so either scorer path replays the same
trajectory — asserted via interpret mode in tests/test_sweep.py and
tests/test_propose_pallas.py.

Reference scope note: the reference solves this model with host-side
lp_solve (``/root/reference/README.md:135-137``); a device-resident
parallel-move thinning stage has no upstream counterpart — it is part of
the TPU-native hot path SURVEY.md §7 step 6 calls for.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import random
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..solvers.tpu.arrays import ModelArrays
from .propose_pallas import _LW, _TP, _pad_lanes, _propose_call


def _fold_add(x, lw):
    """[rows, TP] -> [rows, lw] by summing lane chunks (TP % lw == 0)."""
    tp = x.shape[-1]
    out = x[:, :lw]
    for c in range(1, tp // lw):
        out = out + x[:, c * lw:(c + 1) * lw]
    return out


def _site_finish_kernel(
    a_ref,       # [1, R, TP] int32 candidate tile, partitions in lanes
    islsw_ref,   # [1, 1, TP] int32 proposal records (padded, lane-major)
    s_ref,       # [1, 1, TP] int32
    bnew_ref,    # [1, 1, TP] int32
    blead_ref,   # [1, 1, TP] int32
    bats_ref,    # [1, 1, TP] int32
    prio_ref,    # [1, 1, TP] float32
    rf_ref,      # [1, TP] int32
    pval_ref,    # [1, TP] int32 1 on real partitions
    rackof_ref,  # [B1, 1] int32 broker -> rack (null -> K)
    mout_ref,    # [B1, N] float32 folded out-priority maps, all chains
    min_ref,     # [B1, N] float32
    # outputs -----------------------------------------------------------
    o_a_ref,     # [1, R, TP] block of [N, R, Pp]
    o_dcnt_ref,  # [1, B1, LW] int32, accumulated over partition tiles
    o_dlcnt_ref,  # [1, B1, LW] int32
    o_drcnt_ref,  # [1, K1, LW] int32
):
    B1, TP = mout_ref.shape[0], a_ref.shape[2]
    K1 = o_drcnt_ref.shape[1]
    R = a_ref.shape[1]
    i32 = jnp.int32
    f32 = jnp.float32

    n = pl.program_id(0)
    pt = pl.program_id(1)

    @pl.when(pt == 0)
    def _init():
        o_dcnt_ref[...] = jnp.zeros_like(o_dcnt_ref)
        o_dlcnt_ref[...] = jnp.zeros_like(o_dlcnt_ref)
        o_drcnt_ref[...] = jnp.zeros_like(o_drcnt_ref)

    NN = mout_ref.shape[1]
    sel = (jax.lax.broadcasted_iota(i32, (1, NN), 1) == n).astype(f32)
    mo_col = (mout_ref[...] * sel).sum(1, keepdims=True)  # [B1, 1]
    mi_col = (min_ref[...] * sel).sum(1, keepdims=True)

    is_lsw = islsw_ref[0] > 0   # [1, TP]
    s = s_ref[0]
    b_new = bnew_ref[0]
    b_lead = blead_ref[0]
    b_at_s = bats_ref[0]
    prio = jnp.where(pval_ref[...] > 0, prio_ref[0], 0.0)

    tok_out = jnp.where(is_lsw, b_lead, b_at_s)
    tok_in = jnp.where(is_lsw, b_at_s, b_new)
    iota_b = jax.lax.broadcasted_iota(i32, (B1, TP), 0)
    oh_out = (tok_out == iota_b).astype(i32)
    oh_in = (tok_in == iota_b).astype(i32)

    # keep: this proposal owns BOTH priority maps (sweep._thin_keep)
    mo = (oh_out.astype(f32) * mo_col).sum(0, keepdims=True)  # [1, TP]
    mi = (oh_in.astype(f32) * mi_col).sum(0, keepdims=True)
    keep = jnp.logical_and(
        prio > 0, jnp.logical_and(prio == mo, prio == mi)
    )

    # apply (sweep._apply_site): replace slot s <- b_new; lswap slot 0 <-
    # promotee, slot s <- old leader
    a = a_ref[0]  # [R, TP]
    rows = []
    for r in range(R):
        rep_v = jnp.where(s == r, b_new, a[r:r + 1, :])
        if r == 0:
            lsw_v = b_at_s
        else:
            lsw_v = jnp.where(s == r, b_lead, a[r:r + 1, :])
        new_v = jnp.where(is_lsw, lsw_v, rep_v)
        rows.append(jnp.where(keep, new_v, a[r:r + 1, :]))
    o_a_ref[0] = jnp.concatenate(rows, axis=0)

    # carried-histogram deltas (sweep._site_hist_deltas): one replica
    # unit per kept replace, one leadership unit per kept leader move
    live = rf_ref[...] > 0
    rep = jnp.logical_and(keep, jnp.logical_and(
        jnp.logical_not(is_lsw), live
    ))
    lead_mv = jnp.logical_and(keep, jnp.logical_and(
        jnp.logical_or(is_lsw, s == 0), live
    ))
    rep_b = jnp.broadcast_to(rep, (B1, TP))
    lead_b = jnp.broadcast_to(lead_mv, (B1, TP))
    d = oh_in - oh_out
    lw = o_dcnt_ref.shape[2]
    o_dcnt_ref[0] += _fold_add(jnp.where(rep_b, d, 0), lw)
    o_dlcnt_ref[0] += _fold_add(jnp.where(lead_b, d, 0), lw)

    # rack deltas via the broker -> rack one-hot lut
    r_out = (oh_out * rackof_ref[...]).sum(0, keepdims=True)  # [1, TP]
    r_in = (oh_in * rackof_ref[...]).sum(0, keepdims=True)
    iota_k = jax.lax.broadcasted_iota(i32, (K1, TP), 0)
    dk = (r_in == iota_k).astype(i32) - (r_out == iota_k).astype(i32)
    rep_k = jnp.broadcast_to(rep, (K1, TP))
    o_drcnt_ref[0] += _fold_add(jnp.where(rep_k, dk, 0), lw)


@functools.partial(jax.jit, static_argnames=("K1", "interpret"))
def _site_finish_call(a, padded, m_out, m_in, rf, rackof, *, K1: int,
                      interpret: bool):
    N, P, R = a.shape
    B1 = rackof.shape[0]
    tp = min(_TP, max(128, -(-P // 128) * 128))

    aT = _pad_lanes(jnp.swapaxes(a, 1, 2), tp, B1 - 1)  # [N, R, Pp]
    rf_p = _pad_lanes(rf[None, :], tp, 1)
    Pp = aT.shape[-1]
    pval = (jnp.arange(Pp, dtype=jnp.int32) < P).astype(jnp.int32)[None]
    recs = [x[:, None, :] for x in padded]  # [N, 1, Pp] each
    moT = jnp.swapaxes(m_out, 0, 1)  # [B1, N]
    miT = jnp.swapaxes(m_in, 0, 1)

    grid = (N, Pp // tp)
    vm = pltpu.VMEM
    rec_spec = pl.BlockSpec((1, 1, tp), lambda n, p: (n, 0, p),
                            memory_space=vm)

    a_new, d_cnt, d_lcnt, d_rcnt = pl.pallas_call(
        _site_finish_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, R, tp), lambda n, p: (n, 0, p),
                         memory_space=vm),
            rec_spec, rec_spec, rec_spec, rec_spec, rec_spec, rec_spec,
            pl.BlockSpec((1, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((1, tp), lambda n, p: (0, p), memory_space=vm),
            pl.BlockSpec((B1, 1), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((B1, N), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((B1, N), lambda n, p: (0, 0), memory_space=vm),
        ],
        out_specs=[
            pl.BlockSpec((1, R, tp), lambda n, p: (n, 0, p),
                         memory_space=vm),
            pl.BlockSpec((1, B1, _LW), lambda n, p: (n, 0, 0),
                         memory_space=vm),
            pl.BlockSpec((1, B1, _LW), lambda n, p: (n, 0, 0),
                         memory_space=vm),
            pl.BlockSpec((1, K1, _LW), lambda n, p: (n, 0, 0),
                         memory_space=vm),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, R, Pp), jnp.int32),
            jax.ShapeDtypeStruct((N, B1, _LW), jnp.int32),
            jax.ShapeDtypeStruct((N, B1, _LW), jnp.int32),
            jax.ShapeDtypeStruct((N, K1, _LW), jnp.int32),
        ],
        interpret=interpret,
    )(aT, *recs, rf_p, pval, rackof, moT, miT)
    a_new = jnp.swapaxes(a_new, 1, 2)[:, :P]
    return a_new, d_cnt.sum(-1), d_lcnt.sum(-1), d_rcnt.sum(-1)


def site_step_pallas(m: ModelArrays, a: jax.Array, cnt, lcnt, rcnt,
                     key: jax.Array, temp, *, interpret: bool = False):
    """One full site sweep — propose/accept/thin/apply plus the exact
    carried-histogram update — through the fused Mosaic path. Drop-in
    replacement for ``sweep._site_sweep_delta`` (bit-identical outputs,
    pinned in tests/test_sweep.py)."""
    N, P, R = a.shape
    bits = random.bits(key, (N, P, 8), jnp.uint32)
    lim = jnp.concatenate([m.broker_band, m.leader_band]).astype(
        jnp.int32
    )[None]
    rackof = m.rack_of.astype(jnp.int32)[:, None]
    K1 = m.rack_lo.shape[0]
    *_recs, padded, m_out, m_in = _propose_call(
        a, bits, cnt, lcnt, rcnt, temp, m.lam,
        m.a0, m.rf, m.part_rack_hi.astype(jnp.int32),
        jnp.swapaxes(m.w_lead.astype(jnp.int32), 0, 1),
        jnp.swapaxes(m.w_foll.astype(jnp.int32), 0, 1),
        rackof,
        m.rack_lo.astype(jnp.int32)[:, None],
        m.rack_hi.astype(jnp.int32)[:, None],
        lim,
        interpret=interpret,
    )
    a_new, d_cnt, d_lcnt, d_rcnt = _site_finish_call(
        a, padded, m_out, m_in, m.rf, rackof, K1=K1, interpret=interpret
    )
    return a_new, cnt + d_cnt, lcnt + d_lcnt, rcnt + d_rcnt


# ---------------------------------------------------------------------------
# exchange move: standalone maps kernel + finish kernel
# ---------------------------------------------------------------------------


def _exch_maps_kernel(
    tout_ref,  # [1, 1, TP] int32
    tin_ref,   # [1, 1, TP] int32
    prio_ref,  # [1, 1, TP] float32 (lane padding carries prio 0)
    o_mout_ref,  # [1, B1, LW] float32, accumulated
    o_min_ref,   # [1, B1, LW] float32
):
    B1 = o_mout_ref.shape[1]
    TP = tout_ref.shape[2]
    i32 = jnp.int32
    pt = pl.program_id(1)

    @pl.when(pt == 0)
    def _init():
        o_mout_ref[...] = jnp.zeros_like(o_mout_ref)
        o_min_ref[...] = jnp.zeros_like(o_min_ref)

    prio = prio_ref[0]  # [1, TP]
    iota_b = jax.lax.broadcasted_iota(i32, (B1, TP), 0)
    po = jnp.where(tout_ref[0] == iota_b,
                   jnp.broadcast_to(prio, (B1, TP)), 0.0)
    pi = jnp.where(tin_ref[0] == iota_b,
                   jnp.broadcast_to(prio, (B1, TP)), 0.0)
    lw = o_mout_ref.shape[2]
    for c in range(TP // lw):
        seg = slice(c * lw, (c + 1) * lw)
        o_mout_ref[0] = jnp.maximum(o_mout_ref[0], po[:, seg])
        o_min_ref[0] = jnp.maximum(o_min_ref[0], pi[:, seg])


def _exch_finish_kernel(
    a_ref,     # [1, R, TP] int32
    sown_ref,  # [1, 1, TP] int32 own slot
    both_ref,  # [1, 1, TP] int32 incoming broker
    tout_ref,  # [1, 1, TP] int32 leadership token out (B = none)
    tin_ref,   # [1, 1, TP] int32
    prio_ref,  # [1, 1, TP] float32
    mout_ref,  # [B1, N] float32 folded maps, all chains
    min_ref,   # [B1, N] float32
    o_a_ref,     # [1, R, TP]
    o_dlcnt_ref,  # [1, B1, LW] int32, accumulated
):
    B1, TP = mout_ref.shape[0], a_ref.shape[2]
    R = a_ref.shape[1]
    B = B1 - 1
    i32 = jnp.int32
    f32 = jnp.float32

    n = pl.program_id(0)
    pt = pl.program_id(1)

    @pl.when(pt == 0)
    def _init():
        o_dlcnt_ref[...] = jnp.zeros_like(o_dlcnt_ref)

    NN = mout_ref.shape[1]
    sel = (jax.lax.broadcasted_iota(i32, (1, NN), 1) == n).astype(f32)
    mo_col = (mout_ref[...] * sel).sum(1, keepdims=True)
    mi_col = (min_ref[...] * sel).sum(1, keepdims=True)

    s_own = sown_ref[0]
    b_other = both_ref[0]
    tok_out = tout_ref[0]
    tok_in = tin_ref[0]
    prio = prio_ref[0]

    iota_b = jax.lax.broadcasted_iota(i32, (B1, TP), 0)
    oh_out = (tok_out == iota_b).astype(i32)
    oh_in = (tok_in == iota_b).astype(i32)
    mo = (oh_out.astype(f32) * mo_col).sum(0, keepdims=True)
    mi = (oh_in.astype(f32) * mi_col).sum(0, keepdims=True)
    # token B bypasses its map (count-invariant swaps are conflict-free)
    keep = jnp.logical_and(
        prio > 0,
        jnp.logical_and(
            jnp.logical_or(tok_out == B, prio == mo),
            jnp.logical_or(tok_in == B, prio == mi),
        ),
    )

    a = a_ref[0]
    rows = []
    for r in range(R):
        write = jnp.logical_and(keep, s_own == r)
        rows.append(jnp.where(write, b_other, a[r:r + 1, :]))
    o_a_ref[0] = jnp.concatenate(rows, axis=0)

    # exact lcnt delta: slot-0 diff of the applied tile (unchanged
    # partitions contribute a cancelling +1/-1 pair; replica and rack
    # totals are exchange-invariant and need no update)
    dl = (rows[0] == iota_b).astype(i32) - (a[0:1, :] == iota_b).astype(
        i32
    )
    o_dlcnt_ref[0] += _fold_add(dl, o_dlcnt_ref.shape[2])


def _exch_call(a, s_own, b_other, tok_out, tok_in, prio, B1,
               *, interpret: bool):
    N, P, R = a.shape
    B1 = int(B1)
    B = B1 - 1
    tp = min(_TP, max(128, -(-P // 128) * 128))

    aT = _pad_lanes(jnp.swapaxes(a, 1, 2), tp, B)  # [N, R, Pp]
    sown = _pad_lanes(s_own[:, None, :], tp, 0)
    both = _pad_lanes(b_other[:, None, :], tp, B)
    tout = _pad_lanes(tok_out[:, None, :], tp, B)
    tin = _pad_lanes(tok_in[:, None, :], tp, B)
    pri = _pad_lanes(prio[:, None, :], tp, 0)  # padded lanes: keep false
    Pp = aT.shape[-1]
    grid = (N, Pp // tp)
    vm = pltpu.VMEM
    rec_spec = pl.BlockSpec((1, 1, tp), lambda n, p: (n, 0, p),
                            memory_space=vm)

    m_out, m_in = pl.pallas_call(
        _exch_maps_kernel,
        grid=grid,
        in_specs=[rec_spec, rec_spec, rec_spec],
        out_specs=[
            pl.BlockSpec((1, B1, _LW), lambda n, p: (n, 0, 0),
                         memory_space=vm)
            for _ in range(2)
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, B1, _LW), jnp.float32),
            jax.ShapeDtypeStruct((N, B1, _LW), jnp.float32),
        ],
        interpret=interpret,
    )(tout, tin, pri)
    moT = jnp.swapaxes(m_out.max(-1), 0, 1)  # [B1, N]
    miT = jnp.swapaxes(m_in.max(-1), 0, 1)

    a_new, d_lcnt = pl.pallas_call(
        _exch_finish_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, R, tp), lambda n, p: (n, 0, p),
                         memory_space=vm),
            rec_spec, rec_spec, rec_spec, rec_spec, rec_spec,
            pl.BlockSpec((B1, N), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((B1, N), lambda n, p: (0, 0), memory_space=vm),
        ],
        out_specs=[
            pl.BlockSpec((1, R, tp), lambda n, p: (n, 0, p),
                         memory_space=vm),
            pl.BlockSpec((1, B1, _LW), lambda n, p: (n, 0, 0),
                         memory_space=vm),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, R, Pp), jnp.int32),
            jax.ShapeDtypeStruct((N, B1, _LW), jnp.int32),
        ],
        interpret=interpret,
    )(aT, sown, both, tout, tin, pri, moT, miT)
    return jnp.swapaxes(a_new, 1, 2)[:, :P], d_lcnt.sum(-1)


_exch_call_jit = jax.jit(_exch_call, static_argnames=("B1", "interpret"))


def exchange_step_pallas(m: ModelArrays, a: jax.Array, cnt, lcnt, rcnt,
                         key: jax.Array, temp, *,
                         interpret: bool = False):
    """One full exchange sweep through the fused Mosaic thinning path.
    Drop-in replacement for ``sweep._exchange_sweep_delta``
    (bit-identical outputs). The pair construction (strides, partner
    rolls, half-deltas via the exchange kernel) stays in
    ``sweep.propose_exchange``; this replaces its scatter-max thin/apply
    and the XLA lcnt reduction."""
    from ..solvers.tpu.sweep import propose_exchange
    from .propose_pallas import exchange_halves_pallas

    P = a.shape[1]
    if P < 2:
        return a, cnt, lcnt, rcnt
    halves = functools.partial(exchange_halves_pallas,
                               interpret=interpret)
    prop = propose_exchange(m, a, key, temp, halves=halves, lcnt=lcnt)
    a_new, d_lcnt = _exch_call_jit(
        a, prop.s, prop.b_other, prop.tok_out, prop.tok_in, prop.prio,
        B1=m.num_brokers + 1, interpret=interpret,
    )
    return a_new, cnt, lcnt + d_lcnt, rcnt
