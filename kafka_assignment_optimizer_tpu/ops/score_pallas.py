"""Pallas TPU kernel: tiled batched candidate scoring.

The reference's only compute engine is lp_solve's branch-and-bound on the
host CPU (``/root/reference/README.md:135-137``). In the TPU build, bulk
exact (re)scoring of candidate populations — seed pools, final
verification, polish sweeps — is a first-class device op. This kernel
scores ``A[N, P, R]`` candidates against the full model in one fused pass,
tiled so arbitrarily many partitions stream through VMEM:

- grid = (N, ceil(P / TP)): one candidate per row of the grid, partitions
  in tiles of TP; histograms accumulate in the (revisited) output blocks.
  The partition dim stays INNERMOST on purpose: per-candidate
  accumulators are then revisited at consecutive steps, the only
  revisiting pattern Pallas TPU guarantees (a partition-major variant
  was measured bit-identical AND no faster on v5e — the kernel is
  compute-bound, not weight-stream-bound — so the guaranteed order
  wins).
- everything is formulated as one-hot algebra, not scatter: broker
  histograms are reductions of ``onehot(A_tile)``; rack histograms are a
  single MXU matmul ``onehot @ rack_onehot``; the objective is an
  elementwise product with the streamed weight tiles — scatter/gather-free,
  which is exactly what the VPU/MXU want (SURVEY.md §7 hard part 3).
- band penalties are computed once, on the last partition tile, from the
  accumulated histograms.

``ops.score.score_batch`` (pure XLA) is the correctness oracle and the
non-TPU fallback; parity is asserted in tests/test_score_pallas.py via
interpret mode on the CPU mesh.

Batched multi-instance LANES (``sweep.make_lane_stepper_fn``) reach this
kernel through ``jax.vmap`` over the lane axis: vmap of ``pallas_call``
lifts the lane dimension into a leading grid axis, so an L-lane batch
runs the identical per-lane kernel body with an L-times grid — no
kernel changes, and interpret mode executes the same lifted form on CPU
(lane parity pinned in tests/test_lanes.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..solvers.tpu.arrays import ModelArrays
from .score import Score

# partition-tile height: multiple of the int32 sublane (8); 256 keeps the
# streamed weight tiles ~0.5 MB at 256 brokers
_TP = 256


def _score_kernel(
    a_ref,        # [1, TP, R] int32 candidate tile
    valid_ref,    # [TP, R] bool
    wl_ref,       # [TP, B1] int32 leader-role weights
    wf_ref,       # [TP, B1] int32 follower-role weights
    rack1_ref,    # [B1, K1] float32 broker->rack one-hot
    prh_ref,      # [TP, 1] int32 per-partition rack-diversity cap
    rlo_ref,      # [1, K1] int32 per-rack lower bounds
    rhi_ref,      # [1, K1] int32 per-rack upper bounds
    lim_ref,      # [1, 4] int32 (broker_lo, broker_hi, leader_lo, leader_hi)
    out_ref,      # [1, 1, 8] int32 (weight, pen_b, pen_l, pen_r, pen_pr, ...)
    cnt_ref,      # [1, 1, B1] int32
    lcnt_ref,     # [1, 1, B1] int32
    rcnt_ref,     # [1, 1, K1] int32
):
    pt = pl.program_id(1)
    last = pl.num_programs(1) - 1
    B1 = cnt_ref.shape[2]
    K1 = rcnt_ref.shape[2]
    TP, R = valid_ref.shape
    B = B1 - 1
    K = K1 - 1

    @pl.when(pt == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)
        cnt_ref[...] = jnp.zeros_like(cnt_ref)
        lcnt_ref[...] = jnp.zeros_like(lcnt_ref)
        rcnt_ref[...] = jnp.zeros_like(rcnt_ref)

    a = a_ref[0]                      # [TP, R]
    valid = valid_ref[...]
    flat = jnp.where(valid, a, B)     # null out padded/invalid slots
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (1, 1, B1), 2)
    oh = (flat[:, :, None] == iota_b).astype(jnp.int32)  # [TP, R, B1]

    # broker histograms: replica+leader totals and leader totals
    cnt_ref[0, 0, :] += oh.sum((0, 1))
    lcnt_ref[0, 0, :] += oh[:, 0, :].sum(0)  # invalid slot 0 lands in null col

    # rack algebra on the MXU: onehot(broker) @ onehot(rack-of-broker)
    ohf = oh.reshape(TP * R, B1).astype(jnp.float32)
    pr = jax.lax.dot(ohf, rack1_ref[...],
                     preferred_element_type=jnp.float32)
    pr = pr.reshape(TP, R, K1).sum(1).astype(jnp.int32)  # [TP, K1]
    rcnt_ref[0, 0, :] += pr.sum(0)

    # C10 per-(partition, rack) diversity overflow, real racks only
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (1, K1), 1)
    over = jnp.maximum(pr - prh_ref[...], 0) * (iota_k < K)

    # objective: leader weight on slot 0 + follower weights on slots 1..
    # (null column of the weight tiles is 0, so no masking is needed)
    w = (oh[:, 0, :] * wl_ref[...]).sum()
    if R > 1:
        w += (oh[:, 1:, :] * wf_ref[...][:, None, :]).sum()

    # scalar stores to VMEM are not lowerable on TPU: compose the whole
    # 8-wide accumulator row with iota masks and write it in one shot
    iota8 = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 8), 2)
    out_ref[...] += jnp.where(iota8 == 0, w, 0) + jnp.where(
        iota8 == 4, over.sum(), 0
    )

    @pl.when(pt == last)
    def _bands():
        real_b = jax.lax.broadcasted_iota(jnp.int32, (1, B1), 1) < B

        def band(x, lo, hi):
            v = jnp.maximum(x - hi, 0) + jnp.maximum(lo - x, 0)
            return jnp.where(real_b, v, 0).sum()

        lim = lim_ref[...]
        pen_b = band(cnt_ref[0], lim[0, 0], lim[0, 1])
        pen_l = band(lcnt_ref[0], lim[0, 2], lim[0, 3])
        rv = (jnp.maximum(rcnt_ref[0] - rhi_ref[...], 0)
              + jnp.maximum(rlo_ref[...] - rcnt_ref[0], 0))
        pen_r = jnp.where(iota_k < K, rv, 0).sum()
        out_ref[...] += (
            jnp.where(iota8 == 1, pen_b, 0)
            + jnp.where(iota8 == 2, pen_l, 0)
            + jnp.where(iota8 == 3, pen_r, 0)
        )


def _pad_p(x, tp, value):
    P = x.shape[0]
    pad = (-P) % tp
    if pad == 0:
        return x
    widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, widths, constant_values=value)


@functools.partial(jax.jit, static_argnames=("interpret",))
def score_batch_pallas(
    a: jax.Array, m: ModelArrays, *, interpret: bool = False
) -> Score:
    """Score candidates ``a[N, P, R]`` on TPU via the Pallas kernel.

    Drop-in replacement for ``ops.score.score_batch`` (same Score fields,
    same integer semantics). ``interpret=True`` runs the kernel in the
    Pallas interpreter — the CPU-CI path used by the parity tests.
    """
    N, P, R = a.shape
    B1 = m.w_lead.shape[1]
    K1 = m.rack_lo.shape[0]
    B, K = B1 - 1, K1 - 1
    tp = min(_TP, max(8, -(-P // 8) * 8))

    a_p = _pad_p(jnp.swapaxes(a, 0, 1), tp, B).swapaxes(0, 1)
    valid = _pad_p(m.slot_valid, tp, False)
    wl = _pad_p(m.w_lead.astype(jnp.int32), tp, 0)
    wf = _pad_p(m.w_foll.astype(jnp.int32), tp, 0)
    prh = _pad_p(m.part_rack_hi.astype(jnp.int32)[:, None], tp, 0)
    rack1 = (m.rack_of[:, None] == jnp.arange(K1)[None, :]).astype(jnp.float32)
    lim = jnp.concatenate([m.broker_band, m.leader_band]).astype(jnp.int32)[None]
    rlo = m.rack_lo.astype(jnp.int32)[None]
    rhi = m.rack_hi.astype(jnp.int32)[None]

    Pp = valid.shape[0]
    # candidate-major grid: per-candidate accumulator blocks are only
    # ever revisited at CONSECUTIVE steps — the one revisiting pattern
    # Pallas TPU's output pipelining guarantees (the mosaic interpreter
    # rejects non-consecutive revisits outright). The tempting swap —
    # partition-major, weight tiles resident across candidates — was
    # measured on v5e: bit-identical results and IDENTICAL time at
    # every tile size, i.e. the kernel is compute-bound in VMEM, not
    # weight-stream-bound, so there is nothing to buy by leaving the
    # guaranteed order.
    grid = (N, Pp // tp)
    vm = pltpu.VMEM

    out, cnt, lcnt, rcnt = pl.pallas_call(
        _score_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tp, R), lambda n, p: (n, p, 0), memory_space=vm),
            pl.BlockSpec((tp, R), lambda n, p: (p, 0), memory_space=vm),
            pl.BlockSpec((tp, B1), lambda n, p: (p, 0), memory_space=vm),
            pl.BlockSpec((tp, B1), lambda n, p: (p, 0), memory_space=vm),
            pl.BlockSpec((B1, K1), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((tp, 1), lambda n, p: (p, 0), memory_space=vm),
            pl.BlockSpec((1, K1), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((1, K1), lambda n, p: (0, 0), memory_space=vm),
            pl.BlockSpec((1, 4), lambda n, p: (0, 0), memory_space=vm),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 8), lambda n, p: (n, 0, 0), memory_space=vm),
            pl.BlockSpec((1, 1, B1), lambda n, p: (n, 0, 0), memory_space=vm),
            pl.BlockSpec((1, 1, B1), lambda n, p: (n, 0, 0), memory_space=vm),
            pl.BlockSpec((1, 1, K1), lambda n, p: (n, 0, 0), memory_space=vm),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 1, 8), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, B1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, B1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1, K1), jnp.int32),
        ],
        interpret=interpret,
    )(a_p, valid, wl, wf, rack1, prh, rlo, rhi, lim)
    out, cnt, lcnt, rcnt = out[:, 0], cnt[:, 0], lcnt[:, 0], rcnt[:, 0]

    # padding rows land entirely in the null buckets; remove them so the
    # histograms match the unpadded XLA scorer integer-for-integer
    pad_rows = Pp - P
    cnt = cnt.at[:, B].add(-pad_rows * R)
    lcnt = lcnt.at[:, B].add(-pad_rows)
    rcnt = rcnt.at[:, K].add(-pad_rows * R)
    return Score(
        weight=out[:, 0],
        pen_broker=out[:, 1],
        pen_leader=out[:, 2],
        pen_rack=out[:, 3],
        pen_part_rack=out[:, 4],
        cnt=cnt,
        lcnt=lcnt,
        rcnt=rcnt,
    )


def score_batch_auto(a: jax.Array, m: ModelArrays) -> Score:
    """Pallas kernel on TPU, pure-XLA scorer elsewhere."""
    from .score import score_batch

    if jax.devices()[0].platform == "tpu":
        return score_batch_pallas(a, m)
    return score_batch(a, m)
