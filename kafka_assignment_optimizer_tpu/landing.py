"""Service front door — the human-usable landing page (``GET /``).

The reference hosts a public instance with a usage/extended-example page
in front of its ``POST /submit`` endpoint
(``/root/reference/README.md:189-195``); this is that surface for the
TPU build. Self-contained HTML (no external assets), prefilled with the
reference's worked demo (``README.md:27-91``: 20 brokers across two AZs,
one 10-partition RF=2 topic, decommission broker 19 — optimal plan moves
exactly one replica), plus a machine-readable request schema at
``GET /schema`` for clients that negotiate JSON.
"""

from __future__ import annotations

import json

# The reference README's worked demo (README.md:52-63): prefills the form
# so a first-time visitor can press "Optimize" and see the 1-move optimum.
DEMO_ASSIGNMENT = {
    "version": 1,
    "partitions": [
        {"topic": "x.y.z.t", "partition": 0, "replicas": [7, 18]},
        {"topic": "x.y.z.t", "partition": 1, "replicas": [8, 19]},
        {"topic": "x.y.z.t", "partition": 2, "replicas": [9, 10]},
        {"topic": "x.y.z.t", "partition": 3, "replicas": [0, 11]},
        {"topic": "x.y.z.t", "partition": 4, "replicas": [1, 12]},
        {"topic": "x.y.z.t", "partition": 5, "replicas": [2, 13]},
        {"topic": "x.y.z.t", "partition": 6, "replicas": [3, 14]},
        {"topic": "x.y.z.t", "partition": 7, "replicas": [4, 15]},
        {"topic": "x.y.z.t", "partition": 8, "replicas": [5, 16]},
        {"topic": "x.y.z.t", "partition": 9, "replicas": [6, 17]},
    ],
}


def request_schema() -> dict:
    """Machine-readable request/response shapes (``GET /schema``)."""
    return {
        "service": "kafka-assignment-optimizer-tpu",
        "endpoints": {
            "POST /submit": {
                "request": {
                    "assignment": "reassignment JSON object (required): "
                                  "{version, partitions: [{topic, "
                                  "partition, replicas: [brokerId, ...]}]}",
                    "brokers": "target broker list (required): "
                               "[0, 1, ...] or a range string like '0-18'",
                    "topology": "broker->rack object {'0': 'rackA', ...}, "
                                "'even-odd', or null (single rack)",
                    "rf": "target replication factor: int, "
                          "{topic: int}, or null (keep current)",
                    "solver": "'auto' | 'tpu' | 'milp' | 'native' | "
                              "'lp_solve'",
                    "options": "search knobs: seed, batch, rounds, sweeps, "
                               "steps_per_round, engine, time_limit_s, "
                               "t_hi, t_lo, n_devices",
                    "deadline_s": "optional per-request end-to-end "
                                  "deadline in seconds (queue wait + "
                                  "solve; docs/RESILIENCE.md); expired "
                                  "requests shed with 503 + Retry-After",
                },
                "response": {
                    "assignment": "the optimized reassignment JSON "
                                  "(leader = replicas[0])",
                    "report": "moves, leader changes, feasibility, "
                              "objective weight vs provable upper bound, "
                              "proven_optimal, timings",
                },
            },
            "POST /evaluate": {
                "request": "same as /submit minus solver/options, plus "
                           "'plan': the reassignment JSON to audit",
                "response": "feasibility + per-constraint violation "
                            "counts, replica moves vs the provable "
                            "minimum, objective weight vs its provable "
                            "upper bound, proven_optimal",
            },
            "POST /warmup": {
                "request": "{'shapes': [{'brokers', 'partitions', "
                           "'rf'?, 'racks'?}, ...], 'engine'?: "
                           "'sweep'|'chain', 'lanes'?: bool} — "
                           "precompile executables for these cluster "
                           "shapes (docs/BUCKETING.md), including the "
                           "consolidated lane-padded batch executable "
                           "once per bucket unless lanes=false "
                           "(docs/CONSTRUCTOR.md)",
                "response": "per-shape bucket, wall clock, and compile "
                            "counters (single + lane_*); already_warm "
                            "when cached",
            },
            "POST /clusters/<id>/events": {
                "request": "ONE typed, epoch-fenced cluster change "
                           "(docs/WATCH.md): {'type': 'bootstrap' | "
                           "'broker_add' | 'broker_remove' | "
                           "'broker_drain' | 'rack_fail' | "
                           "'partition_growth' | 'rf_change', "
                           "'epoch': int, ...type fields}; bootstrap "
                           "carries assignment/brokers/topology/rf",
                "response": "200: the new certified plan, warm-started "
                            "from the cluster's previous plan; 202: "
                            "event coalesced behind an in-flight solve "
                            "(fetch GET /clusters/<id>); 409: stale or "
                            "replayed epoch (no solve runs); 503 "
                            "reason=event_storm: backpressure with "
                            "Retry-After",
            },
            "POST /clusters/<id>/rollout/{start,advance,pause,rollback}": {
                "request": {
                    "epoch": "rollout-command epoch (required): a "
                             "non-negative int, strictly greater than "
                             "the rollout's current epoch (stale -> "
                             "structured 409, store untouched)",
                    "broker_cap": "start only: per-wave transfer cap "
                                  "per broker in transfer units "
                                  "(replica copies in + out); default "
                                  "from --rollout-broker-cap",
                    "rack_cap": "start only: per-wave inbound cap per "
                                "rack; default from --rollout-rack-cap",
                    "packer": "start only: 'greedy' | 'scored' "
                              "(docs/ROLLOUT.md)",
                    "canary_ok": "advance past the canary wave only: "
                                 "true applies it and advances, false "
                                 "rolls the rollout back",
                },
                "response": {
                    "200": "the rollout view: status (planned|canary|"
                           "advancing|paused|done|rolled_back), "
                           "wave_index, per-wave transfer accounting, "
                           "and current_wave as upstream-compatible "
                           "reassignment JSON",
                    "409": "stale rollout epoch or a command the state "
                           "machine cannot accept",
                },
            },
            "GET /clusters/<id>/rollout": "the rollout record: wave "
                                          "schedule, caps, applied "
                                          "waves, replans, and the "
                                          "current wave JSON",
            "GET /clusters": "watched clusters + delta-API counters; "
                             "/clusters/<id> returns one cluster's "
                             "state, epoch, and last certified plan",
            "GET /healthz": "service status, available solvers, "
                            "platform, executable-cache + queue state",
            "GET /metrics": "Prometheus text counters (kao_*, incl. "
                            "kao_cache_*, kao_queue_*, the "
                            "kao_phase_seconds / kao_solve_seconds "
                            "histograms with exemplar trace IDs, and "
                            "the kao_slo_* burn rates)",
            "GET /debug/solves": "recent solve-trace IDs; "
                                 "/debug/solves/<trace_id> returns that "
                                 "solve's span-tree report, "
                                 "?format=chrome renders it as Chrome "
                                 "trace-event JSON for Perfetto "
                                 "(docs/OBSERVABILITY.md)",
            "GET /debug/slo": "SLO engine snapshot: per-class "
                              "objectives, multi-window burn rates, "
                              "worst-recent exemplars, drift-alarm "
                              "state, and the tail of the "
                              "flight-record stream",
            "GET /debug/stream": "flight records as newline-delimited "
                                 "JSON, live as they land "
                                 "(?follow=0&tail=N for a snapshot; "
                                 "slow clients shed their own tail, "
                                 "counted in kao_stream_dropped_total)",
            "GET /debug/fleet": "this worker's records merged with "
                                "the --fleet-peers workers: "
                                "fleet-wide burn rates, drift "
                                "alarms, per-worker lag "
                                "(docs/OBSERVABILITY.md, kao-fleet)",
            "GET /schema": "this document",
        },
        "fleet": "run N of these workers behind the kao-router front "
                 "process for bucket-affinity routing, hedged "
                 "failover, and fleet-wide warmup over a shared "
                 "KAO_COMPILE_CACHE (docs/FLEET.md); the router "
                 "proxies /submit, /evaluate, /warmup and /clusters/* "
                 "unchanged, so this schema applies behind it "
                 "verbatim",
        "example": {
            "assignment": DEMO_ASSIGNMENT,
            "brokers": "0-18",
            "topology": "even-odd",
        },
    }


def render_landing() -> str:
    """The ``GET /`` HTML page: usage, worked example, live form."""
    demo = json.dumps(DEMO_ASSIGNMENT, indent=1)
    return f"""<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>kafka-assignment-optimizer-tpu</title>
<style>
  body {{ font: 15px/1.5 system-ui, sans-serif; margin: 2rem auto;
         max-width: 60rem; padding: 0 1rem; color: #1a1a1a; }}
  h1 {{ font-size: 1.5rem; }}  h2 {{ font-size: 1.15rem; margin-top: 2rem; }}
  code, pre, textarea {{ font: 13px/1.45 ui-monospace, monospace; }}
  pre {{ background: #f6f6f4; padding: .75rem; overflow-x: auto;
        border-radius: 6px; }}
  textarea {{ width: 100%; box-sizing: border-box; min-height: 10rem; }}
  input[type=text] {{ font: 13px ui-monospace, monospace; width: 100%;
        box-sizing: border-box; }}
  label {{ display: block; margin-top: .75rem; font-weight: 600; }}
  button {{ margin: 1rem .5rem 0 0; padding: .45rem 1.1rem;
        font-size: .95rem; cursor: pointer; }}
  #out {{ white-space: pre-wrap; }}
  nav a {{ margin-right: 1rem; }}
</style>
</head>
<body>
<h1>kafka-assignment-optimizer-tpu</h1>
<p>Optimal Kafka partition reassignment: given the cluster's current
assignment, a target broker list, and a broker&rarr;rack topology, the
service computes a plan that balances replicas and leaders across racks
while <strong>provably minimizing replica moves</strong> — and reports a
global-optimality certificate when the plan meets its LP/flow bounds.</p>
<nav>
  <a href="/healthz">/healthz</a>
  <a href="/metrics">/metrics</a>
  <a href="/schema">/schema</a>
  <a href="/clusters">/clusters</a>
</nav>

<h2>API</h2>
<pre>curl -s -X POST <span class="origin">http://HOST:PORT</span>/submit \\
  -H 'Content-Type: application/json' \\
  -d '{{"assignment": {{...reassignment JSON...}},
       "brokers": "0-18", "topology": "even-odd"}}'</pre>
<p>Full request/response shapes: <a href="/schema">GET /schema</a>.
Audit an existing plan (yours or
<code>kafka-reassign-partitions</code> output) with
<code>POST /evaluate</code> — same fields plus <code>"plan"</code>.
For clusters that change over time, the delta API
(<code>POST /clusters/&lt;id&gt;/events</code>) remembers each named
cluster's last certified plan and re-solves incrementally per
epoch-fenced change event — broker add/remove/drain, rack failure,
partition growth, RF change (docs/WATCH.md). Execute a certified plan
as bandwidth-budgeted move waves with canary gating and bit-exact
rollback via
<code>POST /clusters/&lt;id&gt;/rollout/{{start,advance,pause,rollback}}</code>
(docs/ROLLOUT.md).</p>

<h2>Extended example (live)</h2>
<p>Prefilled with the worked demo: a 20-broker cluster spread over two
AZs (even brokers in <code>a</code>, odd in <code>b</code>), one topic
with 10 partitions at RF=2, decommissioning broker 19. The optimal plan
changes exactly one replica (partition&nbsp;1:
<code>[8,&thinsp;19]&nbsp;&rarr;&nbsp;[8,&thinsp;1]</code>) — where
Kafka's own tool would reshuffle nearly every partition.</p>

<label for="assignment">Current assignment (reassignment JSON)</label>
<textarea id="assignment">{demo}</textarea>
<label for="brokers">Target brokers (list or range string)</label>
<input type="text" id="brokers" value="0-18">
<label for="topology">Topology (broker&rarr;rack JSON object,
"even-odd", or blank)</label>
<input type="text" id="topology" value="even-odd">
<button id="go">Optimize (POST /submit)</button>
<button id="audit" disabled>Audit result (POST /evaluate)</button>
<h2>Result</h2>
<pre id="out">&mdash;</pre>

<script>
(function () {{
  var lastPlan = null;
  document.querySelectorAll('.origin').forEach(function (el) {{
    el.textContent = location.origin;
  }});
  function payload() {{
    var topo = document.getElementById('topology').value.trim();
    var brokers = document.getElementById('brokers').value.trim();
    var body = {{
      assignment: JSON.parse(document.getElementById('assignment').value),
      brokers: brokers[0] === '[' ? JSON.parse(brokers) : brokers,
    }};
    if (topo) body.topology = topo[0] === '{{' ? JSON.parse(topo) : topo;
    return body;
  }}
  function post(path, body) {{
    var out = document.getElementById('out');
    out.textContent = 'solving\\u2026';
    fetch(path, {{
      method: 'POST',
      headers: {{'Content-Type': 'application/json'}},
      body: JSON.stringify(body),
    }}).then(function (r) {{ return r.json(); }})
      .then(function (j) {{
        out.textContent = JSON.stringify(j, null, 1);
        if (j.assignment) {{
          lastPlan = j.assignment;
          document.getElementById('audit').disabled = false;
        }}
      }})
      .catch(function (e) {{ out.textContent = 'error: ' + e; }});
  }}
  document.getElementById('go').onclick = function () {{
    try {{ post('/submit', payload()); }}
    catch (e) {{ document.getElementById('out').textContent =
                 'bad input: ' + e; }}
  }};
  document.getElementById('audit').onclick = function () {{
    try {{
      var body = payload();
      body.plan = lastPlan;
      post('/evaluate', body);
    }} catch (e) {{ document.getElementById('out').textContent =
                    'bad input: ' + e; }}
  }};
}})();
</script>
</body>
</html>
"""
