"""Finding/rule plumbing shared by every ``kao-check`` pass.

A finding is one (rule, file, line, message) tuple; rules are identified
by stable ``KAO1xx`` IDs (docs/ANALYSIS.md is the catalog). Suppression
is inline and justified::

    print(out)  # kao: disable=KAO106 -- CLI stdout is the product

``# kao: disable=ID[,ID...]`` on the offending line (or the line above,
for lines that would overflow) silences those rules for that line; the
`` -- reason`` tail is the audit trail and is REQUIRED — a disable
without a justification does not suppress, it adds a KAO100 finding, so
the suppression inventory can never silently rot.

File-level suppression (generated code, vendored files) uses
``# kao: disable-file=ID -- reason`` within the first 20 lines.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

# rule catalog: id -> (title, default severity). Kept here (not in the
# rule implementations) so --list-rules and docs render from one table.
RULES: dict[str, str] = {
    "KAO100": "suppression without justification",
    "KAO101": "donated-arg reuse after a donate_argnums call site",
    "KAO102": "pytree leaves initialized from a shared broadcast base",
    "KAO103": "float64-ambiguous numerics in a device path",
    "KAO104": "PRNG key reuse without split/fold_in",
    "KAO105": "Python if/while on a traced value inside a jit body",
    "KAO106": "bare print outside obs/log.py",
    "KAO107": "kao_* metric emitted without HELP/TYPE",
    "KAO108": "chaos/resilience hook inside a traced (jit/solver-factory) body",
    "KAO109": "per-partition Python for loop in a bound/reseat hot module",
    "KAO110": "lane-config value captured as a Python scalar in a "
              "solver factory",
    "KAO111": "serve/router outbound HTTP without causal-trace "
              "injection",
    "KAO112": "per-partition Python for loop in a decompose hot module",
    "KAO113": "host sync inside a scan body (serializes a fused "
              "megachunk)",
    "KAO114": "wall-clock delta outside the accounting funnel in a "
              "dispatch hot module",
    "KAO115": "implicit sharding or stale device snapshot in a mesh "
              "hot module",
    "KAO116": "guarded attribute mutated outside its lock",
    "KAO117": "blocking call while holding a lock",
    "KAO118": "lock-acquisition-order cycle (static deadlock "
              "candidate)",
    "KAO119": "thread started without join/daemon/lifecycle "
              "registration in a serving-plane module",
    "KAO201": "jaxpr contract violation (solver trace)",
    "KAO202": "donation aliasing contract violation",
}

_DISABLE_RE = re.compile(
    r"#\s*kao:\s*(disable|disable-file)\s*=\s*"
    r"(?P<ids>KAO\d{3}(?:\s*,\s*KAO\d{3})*)"
    r"(?P<reason>\s*--\s*\S.*)?"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class Suppressions:
    """Per-file suppression map parsed from the raw source text."""

    by_line: dict[int, set[str]] = field(default_factory=dict)
    whole_file: set[str] = field(default_factory=set)
    unjustified: list[int] = field(default_factory=list)

    def active(self, rule: str, line: int) -> bool:
        if rule in self.whole_file:
            return True
        ids = self.by_line.get(line)
        return bool(ids and rule in ids)


def parse_suppressions(text: str) -> Suppressions:
    sup = Suppressions()
    for lineno, raw in enumerate(text.splitlines(), start=1):
        m = _DISABLE_RE.search(raw)
        if not m:
            continue
        ids = {s.strip() for s in m.group("ids").split(",")}
        if not m.group("reason"):
            # a naked disable never suppresses — it is itself a finding
            sup.unjustified.append(lineno)
            continue
        if m.group(1) == "disable-file" and lineno <= 20:
            sup.whole_file |= ids
        elif raw.lstrip().startswith("#"):
            # a standalone comment line covers the line below it
            sup.by_line.setdefault(lineno + 1, set()).update(ids)
        else:
            # a trailing comment covers ONLY its own line — never the
            # next one, or a copy-pasted second violation under a
            # justified first would be silently suppressed
            sup.by_line.setdefault(lineno, set()).update(ids)
    return sup


def apply_suppressions(
    findings: list[Finding], path: str, sup: Suppressions
) -> list[Finding]:
    out = [
        f for f in findings if not sup.active(f.rule, f.line)
    ]
    out.extend(
        Finding("KAO100", path, ln,
                "kao: disable without a '-- reason' justification "
                "(unjustified suppressions do not suppress)")
        for ln in sup.unjustified
    )
    return out
