"""jaxpr / abstract-eval contract checker (``kao-check --contracts``).

The AST pass reads what the code *says*; this pass reads what the
compiler will actually *do*: it traces the real sweep / lane / chain
solvers (``jax.make_jaxpr`` — abstract eval only, no compile, no
device) on a tiny bucket shape and asserts the static contracts the
engine relies on:

- **no concrete float64 anywhere in the jaxpr** (weak-typed scalar
  literals excluded — they adapt to context): the device consumes
  float32, and a host-float64 dependency is the PR 2 trajectory break.
- **no host callbacks in the hot path**: a stray ``debug_callback`` /
  ``pure_callback`` / ``io_callback`` in the sweep loop serializes
  every round through the host.
- **donation leaf correspondence**: the sweep/lane steppers' carried
  state must come back leaf-for-leaf identical in shape AND dtype —
  the precondition for ``donate_argnums`` updating HBM in place.
- **output shapes match the bucket ladder**: the traced solvers emit
  plans at the canonical padded bucket shape, not the raw instance
  shape (executable reuse depends on it).
- **donated leaves are independent buffers**: the mesh-level initial
  states must not alias two pytree leaves to one device buffer (the
  PR 4 corruption — two views of a shared broadcast base, donated).

Runs on CPU in a couple of seconds; CI-safe.
"""

from __future__ import annotations

from dataclasses import dataclass

from .findings import Finding

_CALLBACK_PRIMS = (
    "pure_callback", "io_callback", "debug_callback", "python_callback",
    "callback", "outside_call", "host_callback",
)


@dataclass
class ContractReport:
    findings: list
    checks_run: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings


def _walk_jaxpr(jaxpr):
    """Yield (eqn, jaxpr) for every equation, recursing into nested
    jaxprs (scan/while/cond bodies, pjit calls)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            for sub in _subjaxprs(p):
                yield from _walk_jaxpr(sub)


def _subjaxprs(p):
    import jax

    core = jax.core
    if isinstance(p, core.ClosedJaxpr):
        yield p.jaxpr
    elif isinstance(p, core.Jaxpr):
        yield p
    elif isinstance(p, (tuple, list)):
        for item in p:
            yield from _subjaxprs(item)


def _avals_of(jaxpr):
    for v in [*jaxpr.invars, *jaxpr.constvars, *jaxpr.outvars]:
        yield getattr(v, "aval", None)
    for eqn in _walk_jaxpr(jaxpr):
        for v in [*eqn.invars, *eqn.outvars]:
            yield getattr(v, "aval", None)


def _check_jaxpr(closed, name: str, findings: list) -> None:
    import numpy as np

    jaxpr = closed.jaxpr
    for aval in _avals_of(jaxpr):
        dtype = getattr(aval, "dtype", None)
        if dtype is None:
            continue
        if dtype == np.float64 and not getattr(aval, "weak_type", False):
            findings.append(Finding(
                "KAO201", name, 0,
                f"concrete float64 aval in the {name} jaxpr "
                f"({aval}); device paths are float32 end to end"))
            break
    for eqn in _walk_jaxpr(jaxpr):
        prim = getattr(eqn.primitive, "name", "")
        if any(cb in prim for cb in _CALLBACK_PRIMS):
            findings.append(Finding(
                "KAO201", name, 0,
                f"host callback primitive '{prim}' in the {name} "
                "hot path"))
            break


def _demo_instance():
    from ..api import build_instance
    from ..models.cluster import (
        demo_assignment, demo_broker_list, demo_topology,
    )

    return build_instance(
        demo_assignment(), demo_broker_list(), demo_topology()
    )


def _leaf_buffer_ids(tree) -> list[set]:
    """Per-leaf sets of device-buffer identities (one per addressable
    shard); two leaves sharing any identity alias one buffer."""
    import jax

    out = []
    for leaf in jax.tree_util.tree_leaves(tree):
        ids = set()
        for shard in getattr(leaf, "addressable_shards", []):
            data = shard.data
            ptr = getattr(data, "unsafe_buffer_pointer", None)
            if callable(ptr):
                try:
                    ids.add(ptr())
                    continue
                except Exception:
                    pass
            ids.add(id(data))
        out.append(ids)
    return out


def run_contracts(chains: int = 2, sweeps: int = 8) -> ContractReport:
    """Trace the real solvers on the demo instance's bucket shape and
    verify every static contract above. Returns a report whose
    ``findings`` (KAO201/KAO202) merge into the lint output."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..parallel import mesh as _mesh
    from ..solvers.tpu import arrays, bucket
    from ..solvers.tpu.anneal import make_solver_fn
    from ..solvers.tpu.seed import greedy_seed
    from ..solvers.tpu.sweep import (
        make_lane_stepper_fn, make_sweep_stepper_fn,
    )

    findings: list = []
    checks = 0
    inst = _demo_instance()
    bkt_p, bkt_r = bucket.bucket_shape(inst)
    if bkt_p < inst.num_parts or bkt_r < inst.max_rf:
        findings.append(Finding(
            "KAO201", "bucket", 0,
            f"bucket_shape({inst.num_parts}, {inst.max_rf}) returned a "
            f"smaller shape ({bkt_p}, {bkt_r}); the ladder must only "
            "pad up"))
    m = arrays.from_instance(inst, num_parts=bkt_p, max_rf=bkt_r)
    if tuple(m.a0.shape) != (bkt_p, bkt_r):
        findings.append(Finding(
            "KAO201", "arrays.from_instance", 0,
            f"padded model shape {tuple(m.a0.shape)} != bucket shape "
            f"({bkt_p}, {bkt_r})"))
    seed = arrays.pad_candidate(
        np.asarray(greedy_seed(inst), np.int32), m
    )
    key = jax.random.PRNGKey(0)
    temps = arrays.geometric_temps(2.0, 0.02, sweeps)
    mesh = _mesh.make_mesh(1)

    # ---- sweep stepper: donation correspondence + jaxpr hygiene
    state = _mesh.init_sweep_state(m, jnp.asarray(seed), key, mesh, chains)
    shard_state = jax.tree.map(lambda x: x[0], state)  # one shard's view
    stepper = make_sweep_stepper_fn(chains)
    closed = jax.make_jaxpr(stepper)(m, shard_state, temps)
    _check_jaxpr(closed, "sweep stepper", findings)
    checks += 1
    in_avals = [
        (x.shape, str(x.dtype))
        for x in jax.tree_util.tree_leaves(shard_state)
    ]
    n_state = len(in_avals)
    out_avals = [
        (tuple(v.aval.shape), str(v.aval.dtype))
        for v in closed.jaxpr.outvars
    ]
    if out_avals[:n_state] != in_avals:
        findings.append(Finding(
            "KAO202", "sweep stepper", 0,
            "carried state does not round-trip leaf-for-leaf "
            f"(in {in_avals} vs out {out_avals[:n_state]}); "
            "donate_argnums cannot update it in place"))
    checks += 1
    if len(out_avals) != n_state + 3:
        # an arity regression is itself the contract violation — it
        # must surface as a finding, never crash the checker
        findings.append(Finding(
            "KAO202", "sweep stepper", 0,
            f"expected {n_state} state leaves + (best_a, best_k, "
            f"curve) outputs, got {len(out_avals)} total"))
        return ContractReport(findings=findings, checks_run=checks)
    best_a_aval, best_k_aval, curve_aval = out_avals[n_state:]
    if best_a_aval[0] != (bkt_p, bkt_r):
        findings.append(Finding(
            "KAO202", "sweep stepper", 0,
            f"best_a shape {best_a_aval[0]} != bucket shape "
            f"({bkt_p}, {bkt_r})"))
    if curve_aval[0] != (sweeps,):
        findings.append(Finding(
            "KAO202", "sweep stepper", 0,
            f"curve shape {curve_aval[0]} != (sweeps,)=({sweeps},)"))
    checks += 1

    # ---- init_sweep_state: donated leaves must be independent buffers
    buf_ids = _leaf_buffer_ids(state)
    for i in range(len(buf_ids)):
        for j in range(i + 1, len(buf_ids)):
            if buf_ids[i] & buf_ids[j]:
                findings.append(Finding(
                    "KAO202", "init_sweep_state", 0,
                    f"state leaves {i} and {j} share a device buffer; "
                    "donation would corrupt them in place (PR 4 bug "
                    "class)"))
    checks += 1

    # ---- lane stepper (the batched path): same contracts, lane axis
    L = 2
    m_stack = arrays.stack_models([m, m])
    lane_seeds = np.stack([seed, seed])
    lane_keys = jax.random.split(key, L)
    lane_state = _mesh.init_lane_state(
        m_stack, lane_seeds, lane_keys, mesh, chains
    )
    lane_shard = jax.tree.map(lambda x: x[0], lane_state)
    lane_stepper = make_lane_stepper_fn(chains)
    closed_l = jax.make_jaxpr(lane_stepper)(m_stack, lane_shard, temps)
    _check_jaxpr(closed_l, "lane stepper", findings)
    checks += 1
    lane_in = [
        (x.shape, str(x.dtype))
        for x in jax.tree_util.tree_leaves(lane_shard)
    ]
    lane_out = [
        (tuple(v.aval.shape), str(v.aval.dtype))
        for v in closed_l.jaxpr.outvars
    ]
    if lane_out[: len(lane_in)] != lane_in:
        findings.append(Finding(
            "KAO202", "lane stepper", 0,
            "lane state does not round-trip leaf-for-leaf; lane "
            "donation cannot update it in place"))
    if len(lane_out) != len(lane_in) + 3:
        findings.append(Finding(
            "KAO202", "lane stepper", 0,
            f"expected {len(lane_in)} state leaves + (best_a, best_k, "
            f"curve) outputs, got {len(lane_out)} total"))
        return ContractReport(findings=findings, checks_run=checks)
    if lane_out[len(lane_in)][0] != (L, bkt_p, bkt_r):
        findings.append(Finding(
            "KAO202", "lane stepper", 0,
            f"lane best_a shape {lane_out[len(lane_in)][0]} != "
            f"({L}, {bkt_p}, {bkt_r})"))
    checks += 1
    lane_bufs = _leaf_buffer_ids(lane_state)
    for i in range(len(lane_bufs)):
        for j in range(i + 1, len(lane_bufs)):
            if lane_bufs[i] & lane_bufs[j]:
                findings.append(Finding(
                    "KAO202", "init_lane_state", 0,
                    f"lane state leaves {i} and {j} share a device "
                    "buffer under donation"))
    checks += 1

    # ---- chain solver: jaxpr hygiene (stateless — no donation leg)
    chain = make_solver_fn(chains, steps_per_round=4)
    closed_c = jax.make_jaxpr(chain)(
        m, jnp.asarray(seed), key, temps
    )
    _check_jaxpr(closed_c, "chain solver", findings)
    chain_out = [tuple(v.aval.shape) for v in closed_c.jaxpr.outvars]
    if not chain_out:
        findings.append(Finding(
            "KAO202", "chain solver", 0, "chain solver has no outputs"))
        return ContractReport(findings=findings, checks_run=checks)
    if chain_out[0] != (bkt_p, bkt_r):
        findings.append(Finding(
            "KAO202", "chain solver", 0,
            f"chain best_a shape {chain_out[0]} != bucket shape"))
    checks += 1

    return ContractReport(findings=findings, checks_run=checks)
