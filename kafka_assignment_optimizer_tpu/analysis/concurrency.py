"""Lock-discipline rules (KAO116-119) over an inferred lock map.

The serving plane mutates shared state across ~50 ``threading.Lock`` /
``RLock`` / ``Condition`` sites; this pass turns that discipline into a
declared, checked artifact instead of reviewer folklore:

- **lock map** — per class (and per module, for module-global locks),
  infer which lock guards which attribute from AST evidence: an
  attribute written lexically inside ``with self._lock:`` at least once
  is treated as guarded by that lock. Explicit declaration beats
  inference: a ``# kao: guards(attr, ...)`` trailing comment on the
  lock's assignment line pins the guarded set.
- **KAO116** — a guarded attribute mutated outside its lock (anywhere
  but ``__init__``, which runs before the object is shared).
- **KAO117** — a blocking call (HTTP, no-timeout ``queue.get``,
  ``subprocess``, bare ``.wait()``/``.join()``, jax compile/dispatch
  entry points) made while a lock is held: the classic "metrics lock
  around a network round-trip" convoy.
- **KAO118** — a lock-acquisition-order cycle (static deadlock
  candidate): ``with A: with B`` in one place, ``with B: with A`` in
  another. Edges also follow one level of same-class ``self.m()`` and
  same-module ``f()`` calls; cross-file cycles are stitched by
  ``lint_paths``.
- **KAO119** — ``threading.Thread(...)`` in a serving-plane module
  (serve.py, fleet/, rollout/, watch/) with no ``daemon=`` decision, no
  ``.join()`` in the same scope, and no attribute registration: an
  orphan that outlives shutdown and deadlocks interpreter exit.

Held regions are lexical: ``with <lock>:`` bodies, plus a coarse
``<lock>.acquire(...)`` extension to the end of the enclosing block
(the ``acquire(timeout=)/try/finally`` idiom). The runtime complement
is :mod:`.lsan`, which observes the real acquisition order.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .findings import Finding

# a lock constructed via threading.Lock()/RLock()/Condition() (bare
# names tolerated for `from threading import Lock` style)
_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# container-mutation method names that count as a write to the receiver
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "sort",
    "appendleft", "popleft",
}

# methods that run before (or while) the object is published; writes
# here are construction, not racing mutation
_CTOR_METHODS = {"__init__", "__new__", "__post_init__"}

_GUARDS_RE = re.compile(r"#\s*kao:\s*guards\(([^)]*)\)")

_THREAD_SCOPE_MARKERS = ("serve.py", "fleet/", "rollout/", "watch/")


def _is_lock_ctor(node: ast.AST) -> ast.Call | None:
    if not isinstance(node, ast.Call):
        return None
    d = _dotted_name(node.func)
    if not d:
        return None
    if d[-1] not in _LOCK_FACTORIES:
        return None
    if len(d) == 1 or d[-2].lstrip("_") == "threading":
        return node
    return None


def _dotted_name(node: ast.AST) -> list[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


@dataclass(frozen=True)
class LockId:
    """Canonical identity of one lock across the project graph."""

    rel: str          # package-relative posix path
    owner: str        # class name, "" for module globals, "?" unresolved
    name: str         # attribute / global name

    def render(self) -> str:
        dot = f"{self.owner}." if self.owner else ""
        return f"{self.rel}::{dot}{self.name}"


@dataclass(frozen=True)
class LockEdge:
    """``held`` was held when ``acquired`` was taken at path:line."""

    held: LockId
    acquired: LockId
    path: str
    rel: str
    line: int


@dataclass
class _ScopeLocks:
    """Lock fields of one class (or the module, owner='')."""

    owner: str
    locks: dict[str, int] = field(default_factory=dict)   # name -> line
    alias: dict[str, str] = field(default_factory=dict)   # cond -> lock
    declared: dict[str, set[str]] = field(default_factory=dict)
    conditions: set[str] = field(default_factory=set)

    def canonical(self, name: str) -> str:
        seen = set()
        while name in self.alias and name not in seen:
            seen.add(name)
            name = self.alias[name]
        return name


def _declared_guards(lines: list[str], lineno: int) -> set[str]:
    if 1 <= lineno <= len(lines):
        m = _GUARDS_RE.search(lines[lineno - 1])
        if m:
            return {a.strip() for a in m.group(1).split(",") if a.strip()}
    return set()


def _collect_class_locks(
    cls: ast.ClassDef, lines: list[str]
) -> _ScopeLocks:
    sc = _ScopeLocks(owner=cls.name)
    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            call = _is_lock_ctor(node.value)
            guards = _declared_guards(lines, node.lineno)
            if call is None and not guards:
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    # a guards() comment on any assignment registers
                    # the field as a lock even when the lock object is
                    # injected rather than constructed here
                    sc.locks[t.attr] = node.lineno
                    if guards:
                        sc.declared[t.attr] = guards
                    d = _dotted_name(call.func) if call else [""]
                    if d[-1] == "Condition":
                        sc.conditions.add(t.attr)
                        if (
                            call.args
                            and isinstance(call.args[0], ast.Attribute)
                            and isinstance(call.args[0].value, ast.Name)
                            and call.args[0].value.id == "self"
                        ):
                            sc.alias[t.attr] = call.args[0].attr
    return sc


def _collect_module_locks(
    tree: ast.Module, lines: list[str]
) -> _ScopeLocks:
    sc = _ScopeLocks(owner="")
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        call = _is_lock_ctor(stmt.value)
        if call is None:
            continue
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                sc.locks[t.id] = stmt.lineno
                guards = _declared_guards(lines, stmt.lineno)
                if guards:
                    sc.declared[t.id] = guards
                if _dotted_name(call.func)[-1] == "Condition":
                    sc.conditions.add(t.id)
    return sc


# ------------------------------------------------------------------
# held-region walk

_BLOCK_FIELDS = {"body", "orelse", "finalbody"}


def _header_exprs(stmt: ast.stmt):
    """Expression children of ``stmt`` excluding nested statement
    blocks (those are walked separately with their own held set)."""
    for name, val in ast.iter_fields(stmt):
        if name in _BLOCK_FIELDS or name == "handlers":
            continue
        vals = val if isinstance(val, list) else [val]
        for v in vals:
            if isinstance(v, ast.AST) and not isinstance(v, ast.stmt):
                yield v


@dataclass
class _Event:
    """One lock acquisition observed during the walk."""

    held: tuple[LockId, ...]
    lock: LockId
    line: int


class _FnWalk:
    """Walks one function's own scope tracking the held-lock stack.

    Produces: ``writes`` (attr/global mutation sites with held set),
    ``calls`` (expression nodes with held set, for KAO117),
    ``events`` (acquisitions, for KAO118 edges), ``self_calls`` and
    ``local_calls`` (depth-1 interprocedural edges).
    """

    def __init__(self, resolve):
        self.resolve = resolve           # expr -> LockId | None
        self.events: list[_Event] = []
        self.exprs: list[tuple[ast.AST, tuple[LockId, ...]]] = []
        self.calls: list[tuple[str, str, tuple[LockId, ...], int]] = []

    def walk(self, stmts: list[ast.stmt], held: tuple[LockId, ...]):
        extra: list[LockId] = []
        for st in stmts:
            cur = held + tuple(extra)
            if isinstance(
                st,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                newly: list[LockId] = []
                for item in st.items:
                    self._note_exprs(item.context_expr, cur)
                    if item.optional_vars is not None:
                        self._note_exprs(item.optional_vars, cur)
                    lid = self.resolve(item.context_expr)
                    if lid is not None:
                        self.events.append(
                            _Event(cur + tuple(newly), lid,
                                   item.context_expr.lineno))
                        newly.append(lid)
                self.walk(st.body, cur + tuple(newly))
                continue
            # the statement node itself carries the write shapes
            # (Assign/AugAssign/AnnAssign/Delete) for _attr_writes
            self.exprs.append((st, cur))
            for e in _header_exprs(st):
                self._note_exprs(e, cur)
                # <lock>.acquire(...) holds to the end of this block
                for n in ast.walk(e):
                    if (
                        isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "acquire"
                    ):
                        lid = self.resolve(n.func.value)
                        if lid is not None:
                            self.events.append(
                                _Event(cur, lid, n.lineno))
                            extra.append(lid)
            for fname in _BLOCK_FIELDS:
                sub = getattr(st, fname, None)
                if sub:
                    self.walk(sub, held + tuple(extra))
            for h in getattr(st, "handlers", None) or []:
                if h.type is not None:
                    self._note_exprs(h.type, held + tuple(extra))
                self.walk(h.body, held + tuple(extra))

    def _note_exprs(self, expr: ast.AST, held: tuple[LockId, ...]):
        stack = [expr]
        while stack:
            n = stack.pop()
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                # nested defs run later, on an unknown held set
                continue
            stack.extend(ast.iter_child_nodes(n))
            self.exprs.append((n, held))
            if isinstance(n, ast.Call):
                if (
                    isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "self"
                ):
                    self.calls.append(
                        ("self", n.func.attr, held, n.lineno))
                elif isinstance(n.func, ast.Name):
                    self.calls.append(
                        ("module", n.func.id, held, n.lineno))


def _function_nodes(tree: ast.AST):
    """Yield (class_name_or_None, fn) for every def in the module."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
            yield from _nested(None, node)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield node.name, sub
                    yield from _nested(node.name, sub)


def _nested(cls: str | None, fn: ast.AST):
    for node in ast.walk(fn):
        if node is fn:
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield cls, node


@dataclass
class ModuleConcurrency:
    """Everything the per-file pass learned about one module."""

    findings: list[Finding] = field(default_factory=list)
    edges: list[LockEdge] = field(default_factory=list)


def _make_resolver(rel, cls_locks: _ScopeLocks | None,
                   mod_locks: _ScopeLocks):
    def resolve(expr: ast.AST) -> LockId | None:
        if isinstance(expr, ast.Call):
            # with self._cluster_lock(cid): — a lock-factory method;
            # all members of the family share one identity (the pass
            # checks the discipline, not per-key aliasing)
            f = expr.func
            if (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
                and _lockish(f.attr)
            ):
                owner = cls_locks.owner if cls_locks else "?"
                return LockId(rel, owner, f.attr + "()")
            return None
        if isinstance(expr, ast.Name):
            if expr.id in mod_locks.locks:
                return LockId(rel, "", mod_locks.canonical(expr.id))
            return None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self":
                if cls_locks is not None \
                        and expr.attr in cls_locks.locks:
                    return LockId(rel, cls_locks.owner,
                                  cls_locks.canonical(expr.attr))
                if _lockish(expr.attr):
                    # lock injected via a parameter: no ctor evidence,
                    # but the name convention is load-bearing
                    owner = cls_locks.owner if cls_locks else "?"
                    return LockId(rel, owner, expr.attr)
                return None
            # other-receiver lock attr (c.lock, w._lock): merge by
            # attribute name within the file — enough for the
            # per-cluster-lock idiom, never stitched across files
            if _lockish(expr.attr):
                return LockId(rel, "?", expr.attr)
        return None
    return resolve


def _lockish(attr: str) -> bool:
    return (attr == "lock" or attr.endswith("_lock")
            or attr in ("_cv", "_cond") or attr.endswith("_cond"))


# ------------------------------------------------------------------
# write-site extraction (KAO116)

def _attr_writes(exprs, owner_is_self=True):
    """Yield (attr_name, lineno, held) write sites against ``self``."""
    for n, held in exprs:
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                n.targets if isinstance(n, ast.Assign) else [n.target]
            )
            for t in targets:
                for e in getattr(t, "elts", None) or [t]:
                    a = _self_attr(e)
                    if a:
                        yield a, n.lineno, held
        elif isinstance(n, ast.Delete):
            for t in n.targets:
                a = _self_attr(t)
                if a:
                    yield a, n.lineno, held
        elif isinstance(n, ast.Call) and isinstance(
            n.func, ast.Attribute
        ) and n.func.attr in _MUTATORS:
            a = _self_attr(n.func.value)
            if a:
                yield a, n.lineno, held


def _self_attr(node: ast.AST) -> str | None:
    """``self.X`` or ``self.X[...]`` -> ``X``."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _global_writes(fn, exprs, mod_names: set[str]):
    """Yield (global_name, lineno, held) mutation sites in ``fn``."""
    declared_global: set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Global):
            declared_global.update(n.names)
    local = _locals_of(fn) - declared_global
    for n, held in exprs:
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                n.targets if isinstance(n, ast.Assign) else [n.target]
            )
            for t in targets:
                for e in getattr(t, "elts", None) or [t]:
                    g = _global_sub(e, mod_names, local,
                                    declared_global)
                    if g:
                        yield g, n.lineno, held
        elif isinstance(n, ast.Call) and isinstance(
            n.func, ast.Attribute
        ) and n.func.attr in _MUTATORS \
                and isinstance(n.func.value, ast.Name):
            g = n.func.value.id
            if g in mod_names and g not in local:
                yield g, n.lineno, held


def _global_sub(node, mod_names, local, declared_global):
    if isinstance(node, ast.Subscript) \
            and isinstance(node.value, ast.Name):
        g = node.value.id
        if g in mod_names and g not in local:
            return g
    if isinstance(node, ast.Name) and node.id in declared_global \
            and node.id in mod_names:
        return node.id
    return None


def _locals_of(fn) -> set[str]:
    names = set()
    a = fn.args
    for arg in (
        list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        + ([a.vararg] if a.vararg else [])
        + ([a.kwarg] if a.kwarg else [])
    ):
        names.add(arg.arg)
    for n in ast.walk(fn):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            names.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)) and n is not fn:
            names.add(n.name)
    return names


# ------------------------------------------------------------------
# KAO117 blocking-call classification

_SUBPROC_FNS = {"run", "Popen", "call", "check_call", "check_output"}
_JAX_BLOCKING = {"block_until_ready", "device_put", "device_get",
                 "compile", "lower"}
_QUEUE_NAME_RE = re.compile(r"(^|_)(q|queue|work|jobs)s?$", re.I)


def _blocking_reason(call: ast.Call) -> str | None:
    d = _dotted_name(call.func)
    if not d:
        return None
    last = d[-1]
    if last == "sleep" and d[0] == "time":
        return "time.sleep()"
    if last == "urlopen":
        return "HTTP round-trip (urlopen)"
    if last in ("request", "getresponse") and len(d) == 2:
        return f"HTTP round-trip (.{last}())"
    if d[0] == "subprocess" and last in _SUBPROC_FNS:
        return f"subprocess.{last}()"
    if last in _JAX_BLOCKING and isinstance(call.func, ast.Attribute):
        return f"jax compile/dispatch ({last})"
    if last in ("solve_tpu", "solve_tpu_batch", "optimize",
                "optimize_delta"):
        return f"solver dispatch ({last})"
    if last == "get" and isinstance(call.func, ast.Attribute):
        recv = _dotted_name(call.func.value)
        if recv and _QUEUE_NAME_RE.search(recv[-1]):
            if not call.args and not any(
                k.arg in ("timeout", "block") for k in call.keywords
            ):
                return "queue.get() without a timeout"
    if last in ("join", "wait") and isinstance(
        call.func, ast.Attribute
    ) and not call.args and not call.keywords:
        return f"unbounded .{last}()"
    return None


# ------------------------------------------------------------------
# the per-file pass

def analyze_module(
    tree: ast.Module, text: str, path: str, rel: str
) -> ModuleConcurrency:
    lines = text.splitlines()
    mod_locks = _collect_module_locks(tree, lines)
    cls_locks: dict[str, _ScopeLocks] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            cls_locks[node.name] = _collect_class_locks(node, lines)

    mod_names = {
        t.id
        for stmt in tree.body
        if isinstance(stmt, (ast.Assign, ast.AnnAssign))
        for t in (stmt.targets if isinstance(stmt, ast.Assign)
                  else [stmt.target])
        if isinstance(t, ast.Name)
    } - set(mod_locks.locks)

    mc = ModuleConcurrency()

    # per-(class, attr) and per-global write ledgers
    cls_writes: dict[tuple[str, str], list] = {}
    glob_writes: dict[str, list] = {}
    # depth-1 interprocedural: direct acquisitions per function
    direct_acq: dict[tuple[str, str], set[LockId]] = {}
    call_sites: list[tuple[str | None, str, str,
                           tuple[LockId, ...], int]] = []

    fns = list(_function_nodes(tree))

    # pass 1: walk every function with an empty held set to learn the
    # lock context of every call site
    call_held: dict[tuple[str, str], list[tuple[LockId, ...]]] = {}
    for cls_name, fn in fns:
        sc = cls_locks.get(cls_name) if cls_name else None
        w = _FnWalk(_make_resolver(rel, sc, mod_locks))
        w.walk(fn.body, ())
        for kind, name, held, _line in w.calls:
            k = (cls_name or "", name) if kind == "self" else ("", name)
            call_held.setdefault(k, []).append(held)

    def _seed(cls_name: str | None, fn) -> tuple[LockId, ...]:
        """Locks assumed held on entry: the ``*_locked`` naming
        convention, plus any lock held at EVERY observed call site
        (depth-1 caller-context propagation — how ``_detector``-style
        helpers called under ``with self._lock:`` stay clean)."""
        seed: set[LockId] = set()
        sc = cls_locks.get(cls_name) if cls_name else None
        if fn.name.endswith("_locked") and sc is not None:
            for name in sc.locks:
                seed.add(LockId(rel, sc.owner, sc.canonical(name)))
        sites = call_held.get((cls_name or "", fn.name), [])
        if sites:
            common = set(sites[0])
            for h in sites[1:]:
                common &= set(h)
            seed |= common
        return tuple(sorted(seed, key=lambda x: x.render()))

    # pass 2: the real walk, with seeded entry contexts
    for cls_name, fn in fns:
        sc = cls_locks.get(cls_name) if cls_name else None
        resolve = _make_resolver(rel, sc, mod_locks)
        w = _FnWalk(resolve)
        w.walk(fn.body, _seed(cls_name, fn))
        key = (cls_name or "", fn.name)
        direct_acq.setdefault(key, set()).update(
            e.lock for e in w.events
        )
        for ev in w.events:
            for h in ev.held:
                if h != ev.lock:
                    mc.edges.append(
                        LockEdge(h, ev.lock, path, rel, ev.line))
        for kind, name, held, line in w.calls:
            call_sites.append((cls_name, kind, name, held, line))
        in_ctor = fn.name in _CTOR_METHODS
        if cls_name:
            for attr, line, held in _attr_writes(w.exprs):
                cls_writes.setdefault((cls_name, attr), []).append(
                    (line, held, in_ctor, fn.name))
        # main() is the process entry point: its config writes happen
        # before any worker thread exists (the module-global analog of
        # the __init__ exemption)
        pre_threading = cls_name is None and fn.name == "main"
        for g, line, held in _global_writes(fn, w.exprs, mod_names):
            glob_writes.setdefault(g, []).append(
                (line, held, pre_threading, fn.name))
        # KAO117: blocking calls on a non-empty held stack
        for n, held in w.exprs:
            if not held or not isinstance(n, ast.Call):
                continue
            reason = _blocking_reason(n)
            if reason is None:
                continue
            # Condition.wait() releases the lock it wraps: legitimate
            if _is_wait_on_held_condition(n, held, sc, mod_locks, rel):
                continue
            mc.findings.append(Finding(
                "KAO117", path, n.lineno,
                f"blocking call ({reason}) while holding "
                f"{held[-1].render()}: every other thread touching "
                "that lock convoys behind this latency; move the "
                "blocking work outside the critical section"))

    # depth-1 interprocedural edges: holding H, call a local def that
    # itself acquires
    for cls_name, kind, name, held, line in call_sites:
        if not held:
            continue
        key = (cls_name or "", name) if kind == "self" else ("", name)
        for lid in direct_acq.get(key, ()):  # noqa: B007
            for h in held:
                if h != lid:
                    mc.edges.append(LockEdge(h, lid, path, rel, line))

    # KAO116: guarded attr written outside its lock
    mc.findings += _unguarded_writes(
        cls_writes, cls_locks, rel, path, per_class=True)
    mc.findings += _unguarded_writes(
        {("", g): w for g, w in glob_writes.items()},
        {"": mod_locks}, rel, path, per_class=False)

    # KAO119: unmanaged thread starts in serving-plane modules
    if any(m in rel for m in _THREAD_SCOPE_MARKERS):
        mc.findings += _thread_lifecycle(tree, path)

    # intra-file cycles (cross-file cycles are stitched in lint_paths)
    mc.findings += cycle_findings(mc.edges)
    return mc


def _is_wait_on_held_condition(call, held, sc, mod_locks, rel) -> bool:
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "wait"):
        return False
    recv = call.func.value
    if isinstance(recv, ast.Attribute) and isinstance(
        recv.value, ast.Name
    ) and recv.value.id == "self" and sc is not None:
        if recv.attr in sc.conditions:
            lid = LockId(rel, sc.owner, sc.canonical(recv.attr))
            return lid in held
    if isinstance(recv, ast.Name) and recv.id in mod_locks.conditions:
        lid = LockId(rel, "", mod_locks.canonical(recv.id))
        return lid in held
    return False


def _unguarded_writes(writes, lock_scopes, rel, path, *, per_class):
    out: list[Finding] = []
    for (owner, attr), sites in sorted(writes.items()):
        sc = lock_scopes.get(owner)
        if sc is None:
            continue
        # declared beats inferred
        guard: str | None = None
        for lock_name, attrs in sc.declared.items():
            if attr in attrs:
                guard = sc.canonical(lock_name)
                break
        if guard is None:
            under = {
                lid.name
                for _, held, in_ctor, _m in sites
                for lid in held
                if lid.owner == owner and lid.rel == rel
            }
            if len(under) != 1:
                continue  # never locked, or ambiguous across locks
            guard = next(iter(under))
        lid = LockId(rel, owner, guard)
        for line, held, in_ctor, meth in sites:
            if in_ctor or lid in held:
                continue
            what = (f"{owner}.{attr}" if per_class and owner
                    else attr)
            out.append(Finding(
                "KAO116", path, line,
                f"'{what}' is guarded by {lid.render()} (see other "
                f"write sites) but mutated here in {meth}() without "
                "it: a racing reader/writer under the lock sees torn "
                "state; take the lock or declare the discipline with "
                "'# kao: guards(...)'"))
    return out


def _thread_lifecycle(tree: ast.Module, path: str) -> list[Finding]:
    out: list[Finding] = []
    joined: set[str] = set()
    registered_lines: set[int] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and isinstance(
            n.func, ast.Attribute
        ) and n.func.attr == "join":
            d = _dotted_name(n.func.value)
            if d:
                joined.add(d[-1])
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Attribute):
                    # self._thread = Thread(...): lifecycle registered
                    for c in ast.walk(n.value):
                        if _is_thread_ctor(c):
                            registered_lines.add(c.lineno)
    assigns: dict[int, str] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name):
            for c in ast.walk(n.value):
                if _is_thread_ctor(c):
                    assigns[c.lineno] = n.targets[0].id
    for n in ast.walk(tree):
        if not _is_thread_ctor(n):
            continue
        if n.lineno in registered_lines:
            continue
        if any(k.arg == "daemon" for k in n.keywords):
            continue
        name = assigns.get(n.lineno)
        if name and name in joined:
            continue
        out.append(Finding(
            "KAO119", path, n.lineno,
            "threading.Thread(...) started with no lifecycle "
            "decision: not daemon=, never join()ed, not registered "
            "on an owner attribute — it outlives drain/shutdown and "
            "can hang interpreter exit; pick one explicitly"))
    return out


def _is_thread_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted_name(node.func)
    return bool(d) and d[-1] == "Thread" and (
        len(d) == 1 or d[-2].lstrip("_") == "threading"
    )


# ------------------------------------------------------------------
# KAO118 cycle detection (shared by lint_source and lint_paths)

def cycle_findings(edges: list[LockEdge]) -> list[Finding]:
    """One KAO118 finding per unordered lock pair on a cycle, anchored
    at the later-discovered edge's site."""
    graph: dict[LockId, set[LockId]] = {}
    for e in edges:
        graph.setdefault(e.held, set()).add(e.acquired)

    def reaches(src: LockId, dst: LockId) -> bool:
        seen = {src}
        stack = [src]
        while stack:
            u = stack.pop()
            for v in graph.get(u, ()):  # noqa: B007
                if v == dst:
                    return True
                if v not in seen:
                    seen.add(v)
                    stack.append(v)
        return False

    out: list[Finding] = []
    reported: set[frozenset] = set()
    for e in edges:
        pair = frozenset((e.held, e.acquired))
        if pair in reported:
            continue
        if reaches(e.acquired, e.held):
            reported.add(pair)
            out.append(Finding(
                "KAO118", e.path, e.line,
                f"lock-order cycle: {e.acquired.render()} is taken "
                f"here while {e.held.render()} is held, but the "
                "reverse order exists elsewhere in the acquisition "
                "graph — two threads running both paths deadlock; "
                "pick one global order (docs/ANALYSIS.md)"))
    return out


def file_concurrency(
    text: str, path: str, rel: str
) -> ModuleConcurrency:
    """Parse + analyze one file; syntax errors yield an empty result
    (lint_source already reports those)."""
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return ModuleConcurrency()
    return analyze_module(tree, text, path, rel)
