"""SARIF 2.1.0 rendering for ``kao-check --format sarif``.

One run, one tool (``kao-check``), the full rule catalog under
``tool.driver.rules`` so viewers can render titles without a second
lookup. Findings tolerated by the baseline ratchet are still emitted —
with a ``suppressions`` entry of kind ``external`` — so code-scanning
UIs show them as accepted debt instead of dropping them; new findings
carry no suppression and surface as actionable.
"""

from __future__ import annotations

from .findings import RULES, Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def _result(f: Finding, *, baselined: bool) -> dict:
    res = {
        "ruleId": f.rule,
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {
                    "uri": f.path,
                    "uriBaseId": "SRCROOT",
                },
                "region": {"startLine": max(f.line, 1)},
            },
        }],
    }
    if baselined:
        res["suppressions"] = [{
            "kind": "external",
            "justification": "accepted in analysis_baseline.json",
        }]
    return res


def render(findings: list[Finding],
           baselined: set[int] | None = None) -> dict:
    """``baselined`` holds indexes into ``findings`` whose entries are
    tolerated by the ratchet (empty/None = no baseline in play)."""
    baselined = baselined or set()
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "kao-check",
                    "informationUri":
                        "docs/ANALYSIS.md",
                    "rules": [
                        {
                            "id": rid,
                            "shortDescription": {"text": title},
                        }
                        for rid, title in sorted(RULES.items())
                    ],
                },
            },
            "results": [
                _result(f, baselined=i in baselined)
                for i, f in enumerate(findings)
            ],
        }],
    }
