"""Runtime sanitizer mode (``KAO_SANITIZE=1`` / ``--sanitize``).

The static passes (rules_ast, contracts) catch the footgun *patterns*;
this module catches the runtime *symptoms* on a live solve, at the three
chokepoints the shipped bugs actually passed through:

- **NaN aborts** — ``jax.config.jax_debug_nans`` is enabled so the
  first NaN produced on device raises at its dispatch instead of
  corrupting a trajectory silently; the engine routes the resulting
  ``FloatingPointError`` through :func:`note_nan_abort` so the event is
  counted on ``/metrics`` (``kao_sanitizer_nan_aborts_total``) before it
  propagates. :func:`check_host` gives host-built float arrays (the
  annealing temperature ladder) the same guard.
- **Recompile sentinel** — ``jax.config.jax_log_compiles`` is enabled
  (every compile becomes a visible log line) and a logging handler on
  jax's loggers feeds :func:`note_compile`; ``parallel.mesh`` calls it
  directly at its AOT compile site with the executable-cache key. A
  (solver, shape-signature) key compiling more than
  ``KAO_SANITIZE_COMPILE_BUDGET`` times (default 2: the legitimate
  maximum — one Pallas attempt plus one XLA fallback) means executable
  thrash — the exact failure the shape-bucketed cache exists to prevent
  — and FAILS the solve (``kao_sanitizer_recompiles_total``).
- **Donation use-after-free guard** — ``parallel.mesh._dispatch``
  refuses to dispatch arguments that were already consumed by a
  donating dispatch, raising :class:`DonationReuseError` with the
  cache key instead of XLA's "buffer deleted" deep in the runtime
  (``kao_sanitizer_donation_reuse_total``).

Everything is a no-op until :func:`enable` runs (or ``KAO_SANITIZE`` is
truthy at import); the guards add one predicate call per dispatch when
off. Counters are process-wide, thread-safe, and rendered with
HELP/TYPE by ``serve.render_metrics``.
"""

from __future__ import annotations

import logging
import os
import threading

__all__ = [
    "SanitizerError", "RecompileBudgetError", "DonationReuseError",
    "enabled", "enable", "disable", "install", "compile_budget",
    "note_compile", "forget_key", "note_nan_abort", "note_nan_abort_once",
    "note_donation_reuse",
    "check_host", "snapshot", "reset",
]


class SanitizerError(RuntimeError):
    """Base class: a sanitizer tripwire fired."""


class RecompileBudgetError(SanitizerError):
    pass


class DonationReuseError(SanitizerError):
    pass


def _env_truthy(name: str) -> bool:
    return os.environ.get(name, "").lower() in ("1", "on", "true", "yes")


_LOCK = threading.Lock()
_ENABLED = _env_truthy("KAO_SANITIZE")
_INSTALLED = False
_COMPILES_BY_KEY: dict = {}
_C = {
    "recompiles_total": 0,       # sentinel trips (budget exceeded)
    "nan_aborts_total": 0,       # NaN guard aborts (device or host)
    "donation_reuse_total": 0,   # use-after-free guard trips
    "compiles_observed_total": 0,   # real AOT compiles (note_compile)
    "compile_log_lines_total": 0,   # jax_log_compiles lines seen (the
                                    # log listener; several per compile)
}


def enabled() -> bool:
    return _ENABLED


def compile_budget() -> int:
    """Expected compiles per executable-cache key: 1 normal + 1 for a
    legitimate Pallas->XLA fallback recompile."""
    try:
        return int(os.environ.get("KAO_SANITIZE_COMPILE_BUDGET", "2"))
    except ValueError:
        return 2


class _CompileLogHandler(logging.Handler):
    """Counts jax's log_compiles records — the operator-visible side of
    the sentinel (the authoritative per-key budget is fed directly by
    parallel.mesh at its compile site)."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            return
        if "compil" in msg.lower():
            # separate counter from compiles_observed_total: a single
            # compile emits several matching log lines, and the mesh
            # compile site already feeds the authoritative count
            with _LOCK:
                _C["compile_log_lines_total"] += 1


_LOG_HANDLER = _CompileLogHandler()
# the compile log lines come from jax._src.dispatch (jit) and
# jax._src.interpreters.pxla (sharded computations); both propagate to
# the "jax" root logger
_JAX_LOGGERS = ("jax._src.dispatch", "jax._src.interpreters.pxla")


def enable() -> None:
    global _ENABLED
    _ENABLED = True
    install()


def disable() -> None:
    """Turn the guards off (tests); the jax config flags are reverted."""
    global _ENABLED, _INSTALLED
    _ENABLED = False
    if _INSTALLED:
        try:
            import jax

            jax.config.update("jax_debug_nans", False)
            jax.config.update("jax_log_compiles", False)
        except Exception:
            pass
        for name in _JAX_LOGGERS:
            logging.getLogger(name).removeHandler(_LOG_HANDLER)
        _INSTALLED = False


def install() -> None:
    """Idempotently flip the jax debug config + attach the compile-log
    listener. Called by the engine/serve entry points when the
    sanitizer is enabled; safe before or after backend init."""
    global _INSTALLED
    if not _ENABLED or _INSTALLED:
        return
    try:
        import jax

        jax.config.update("jax_debug_nans", True)
        jax.config.update("jax_log_compiles", True)
    except Exception:
        pass  # sanitizer must never be the reason a solve cannot start
    for name in _JAX_LOGGERS:
        logging.getLogger(name).addHandler(_LOG_HANDLER)
    _INSTALLED = True


def note_compile(key) -> None:
    """Record one real XLA compile for an executable-cache key; raises
    :class:`RecompileBudgetError` past the per-key budget."""
    if not _ENABLED:
        return
    with _LOCK:
        _C["compiles_observed_total"] += 1
        n = _COMPILES_BY_KEY.get(key, 0) + 1
        _COMPILES_BY_KEY[key] = n
        budget = compile_budget()
        if n <= budget:
            return
        _C["recompiles_total"] += 1
        # the trip ends this thrash episode: reset the key so the NEXT
        # request's cold rebuild is legitimate (without this, a tripped
        # key would recompile-and-trip on every later request — the
        # executable was never cached, so the count must not persist)
        _COMPILES_BY_KEY.pop(key, None)
    from ..obs import log as _olog

    _olog.error("sanitizer_recompile_budget", key=repr(key)[:200],
                compiles=n, budget=budget)
    raise RecompileBudgetError(
        f"sanitizer: executable key compiled {n}x (budget {budget}); "
        "shape-bucket thrash — same-bucket solves must reuse one "
        f"executable. key={key!r}"
    )


def forget_key(key) -> None:
    """The executable cache evicted this key: its NEXT compile is a
    legitimate cold rebuild, not thrash — reset the sentinel's count
    (otherwise a long-lived sanitized service whose traffic spans more
    bucket keys than the LRU holds would fail healthy solves)."""
    with _LOCK:
        _COMPILES_BY_KEY.pop(key, None)


def note_nan_abort_once(exc: BaseException, context: str = "") -> None:
    """Count a NaN abort exactly once per exception object: nested
    solve paths (batch sequential fallback, the chain-engine retry)
    route the SAME FloatingPointError through several handlers."""
    if getattr(exc, "_kao_nan_counted", False):
        return
    try:
        exc._kao_nan_counted = True
    except Exception:
        pass
    note_nan_abort(context)


def note_nan_abort(context: str = "") -> None:
    if not _ENABLED:
        # a host-side FloatingPointError can reach the engine's
        # handlers without the sanitizer armed (numpy errstate etc.);
        # the counter must stay zero-and-inert when off
        return
    with _LOCK:
        _C["nan_aborts_total"] += 1
    from ..obs import log as _olog

    _olog.error("sanitizer_nan_abort", context=context or None)


def note_donation_reuse(key) -> None:
    with _LOCK:
        _C["donation_reuse_total"] += 1
    from ..obs import log as _olog

    _olog.error("sanitizer_donation_reuse", key=repr(key)[:200])
    raise DonationReuseError(
        "sanitizer: dispatch arguments were already consumed by a "
        "donating dispatch (use the RETURNED state — in-place donation "
        f"contract, docs/PIPELINE.md). key={key!r}"
    )


def check_host(arr, context: str = "host array") -> None:
    """NaN guard for host-built float arrays (e.g. the temperature
    ladder) — the device-side jax_debug_nans cannot see these until
    they have already steered a trajectory."""
    if not _ENABLED:
        return
    import numpy as np

    a = np.asarray(arr)
    if a.dtype.kind == "f" and not np.isfinite(a).all():
        note_nan_abort(context)
        raise SanitizerError(
            f"sanitizer: non-finite values in {context}"
        )


def snapshot() -> dict:
    with _LOCK:
        out = dict(_C)
    out["enabled"] = int(_ENABLED)
    return out


def reset() -> None:
    """Zero the counters and per-key compile history (tests)."""
    with _LOCK:
        _COMPILES_BY_KEY.clear()
        for k in _C:
            _C[k] = 0
