"""``kao-check`` — project-native static analysis + runtime sanitizer.

Three layers, one CLI (``python -m kafka_assignment_optimizer_tpu.analysis``):

- :mod:`.rules_ast` — stdlib-``ast`` lint rules for the JAX footguns
  this repo has actually shipped (donation reuse, shared broadcast
  bases, host-float64 leaks, PRNG reuse, trace-time branching, bare
  prints, undocumented metrics). KAO1xx.
- :mod:`.contracts` — ``jax.make_jaxpr`` contract checks over the real
  compiled sweep/lane/chain solvers on a tiny bucket shape (no
  float64, no host callbacks, donation leaf correspondence, bucket
  output shapes, independent donated buffers). KAO2xx.
- :mod:`.sanitize` — the runtime sanitizer (``KAO_SANITIZE=1``): NaN
  aborts, a recompile sentinel over the executable cache, and a
  donation use-after-free guard, all counted on ``/metrics``.

See docs/ANALYSIS.md for the rule catalog and suppression syntax.
"""

from __future__ import annotations

import os

from .findings import RULES, Finding  # noqa: F401
from .rules_ast import lint_source

_SKIP_DIRS = {"__pycache__", "_build", ".git"}


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_py_files(root: str):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d not in _SKIP_DIRS
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_paths(paths=None, rules=None) -> list[Finding]:
    """Run the AST pass over ``paths`` (default: the installed package
    tree). ``rules`` optionally restricts to a set of KAO IDs.

    Lock-order edges (KAO118) are additionally stitched ACROSS files
    here: per-file analysis sees each module's acquisition graph, but
    an inversion split between two modules only closes into a cycle on
    the union graph."""
    from .concurrency import cycle_findings, file_concurrency
    from .findings import parse_suppressions

    root = package_root()
    findings: list[Finding] = []
    edges = []
    texts: dict[str, str] = {}
    for p in paths or [root]:
        for path in iter_py_files(p):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                text = f.read()
            texts[path] = text
            findings.extend(lint_source(text, path, rel=rel))
            edges.extend(file_concurrency(text, path, rel).edges)
    seen = {(f.rule, f.path, f.line) for f in findings}
    for f in cycle_findings(edges):
        if (f.rule, f.path, f.line) in seen:
            continue  # intra-file copy already reported by lint_source
        sup = parse_suppressions(texts.get(f.path, ""))
        if not sup.active(f.rule, f.line):
            findings.append(f)
    if rules:
        findings = [f for f in findings if f.rule in rules]
    return findings
