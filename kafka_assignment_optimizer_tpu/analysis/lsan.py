"""Runtime lock sanitizer (``KAO_LSAN=1``) — the dynamic complement
to the static lock-discipline rules (:mod:`.concurrency`).

``install()`` monkeypatches ``threading.Lock``/``RLock`` with a
factory that returns an instrumented proxy ONLY when the caller's
module lives inside this package (``sys._getframe`` inspection at
construction time, so stdlib locks — ``queue.Queue``'s mutex, logging,
jax internals — stay raw and free). Each proxy records:

- **acquisition order**: a process-wide held-before graph. Taking B
  while holding A adds the edge A→B; if B→A was ever observed, two
  threads running both paths can deadlock — the sanitizer trips
  (:class:`LockOrderInversion`) at the acquisition that closed the
  cycle, naming both creation sites.
- **hold time**: a release after more than ``KAO_LSAN_HOLD_S``
  (default {DEFAULT_HOLD_BUDGET_S}s) records a ``hold_budget``
  :class:`Violation` (recorded, never raised — raising on release
  would corrupt the caller's unwind).

tests/conftest.py arms this under ``KAO_LSAN=1`` so the whole tier-1
suite doubles as a sanitizer run: a session-end hook asserts no
violations were recorded. Tests that deliberately trip the sanitizer
use :func:`scope` to keep their violations out of the session ledger.

Env knobs: ``KAO_LSAN`` (arm), ``KAO_LSAN_HOLD_S`` (hold budget,
seconds), ``KAO_LSAN_RAISE`` (default on; ``0`` records inversions
instead of raising).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass, field

DEFAULT_HOLD_BUDGET_S = 5.0

_PKG = __name__.split(".analysis")[0]

__doc__ = __doc__.replace("{DEFAULT_HOLD_BUDGET_S}",
                          str(DEFAULT_HOLD_BUDGET_S))


def _env_truthy(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in (
        "1", "true", "yes", "on"
    )


class LsanError(AssertionError):
    """Base for sanitizer trips (an AssertionError so a trip inside a
    test fails that test loudly)."""


class LockOrderInversion(LsanError):
    pass


@dataclass(frozen=True)
class Violation:
    kind: str          # "inversion" | "hold_budget"
    detail: str
    site_a: str        # creation site of the held/long-held lock
    site_b: str        # creation site of the acquired lock ("" = n/a)
    thread: str


@dataclass
class _State:
    """One recording scope: the order graph + violation ledger."""

    # (held_site, acquired_site) -> first-observed description
    edges: dict[tuple[str, str], str] = field(default_factory=dict)
    violations: list[Violation] = field(default_factory=list)


_REG_LOCK = threading.Lock()  # guards _STATES and install bookkeeping
_STATES: list[_State] = [_State()]
_INSTALLED = False
# survive a re-import while installed: the factories carry their real
# constructor in _kao_real, so we never capture our own wrapper
_REAL_LOCK = getattr(threading.Lock, "_kao_real", threading.Lock)
_REAL_RLOCK = getattr(threading.RLock, "_kao_real", threading.RLock)
_HELD = threading.local()   # per-thread stack of (proxy, t_acquire)
_HOLD_BUDGET = [DEFAULT_HOLD_BUDGET_S]  # cached; env read at install


def _held_stack() -> list:
    st = getattr(_HELD, "stack", None)
    if st is None:
        st = _HELD.stack = []
    return st


def hold_budget_s() -> float:
    return _HOLD_BUDGET[0]


def _refresh_hold_budget() -> None:
    try:
        _HOLD_BUDGET[0] = float(
            os.environ.get("KAO_LSAN_HOLD_S", "")
            or DEFAULT_HOLD_BUDGET_S
        )
    except ValueError:
        _HOLD_BUDGET[0] = DEFAULT_HOLD_BUDGET_S


def _raise_on_inversion() -> bool:
    v = os.environ.get("KAO_LSAN_RAISE", "").strip().lower()
    return v not in ("0", "false", "no", "off")


class _LsanLock:
    """Instrumented proxy over a real Lock/RLock. Delegates the
    primitive protocol (including the ``Condition`` integration
    surface: ``_release_save``/``_acquire_restore``/``_is_owned``) and
    funnels every transition through the order/hold bookkeeping."""

    def __init__(self, inner, site: str, reentrant: bool):
        self._inner = inner
        self._site = site
        self._reentrant = reentrant
        self._owner: int | None = None
        self._depth = 0

    # -- bookkeeping -------------------------------------------------

    def _note_acquired(self) -> None:
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            self._depth += 1
            return  # re-entry: no new edge, no new hold window
        self._owner, self._depth = me, 1
        stack = _held_stack()
        edges = [
            held._site for held, _t0 in stack
            if held is not self and held._site != self._site
        ]
        # bookkeeping BEFORE any inversion raise, so the stack always
        # matches reality even when the acquisition trips
        stack.append((self, time.monotonic()))
        for held_site in edges:
            _note_edge(held_site, self._site)

    def _note_released(self) -> None:
        if self._reentrant and self._depth > 1:
            self._depth -= 1
            return
        self._owner, self._depth = None, 0
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is self:
                _t, t0 = stack.pop(i)
                held_s = time.monotonic() - t0
                if held_s > hold_budget_s():
                    _record(Violation(
                        "hold_budget",
                        f"lock held {held_s:.3f}s "
                        f"(budget {hold_budget_s():.3f}s)",
                        self._site, "",
                        threading.current_thread().name))
                break

    # -- lock protocol -----------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._note_acquired()
            except LsanError:
                # a trip FAILS the acquisition: undo bookkeeping and
                # release, so the raise from __enter__ (where __exit__
                # will never run) cannot leak a held lock
                stack = _held_stack()
                if stack and stack[-1][0] is self:
                    stack.pop()
                self._owner, self._depth = None, 0
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        self._note_released()
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition integration ---------------------------------------

    def _release_save(self):
        self._note_released()
        inner = getattr(self._inner, "_release_save", None)
        if inner is not None:
            return inner()
        self._inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        inner = getattr(self._inner, "_acquire_restore", None)
        if inner is not None:
            inner(state)
        else:
            self._inner.acquire()
        self._note_acquired()

    def _is_owned(self) -> bool:
        inner = getattr(self._inner, "_is_owned", None)
        if inner is not None:
            return bool(inner())
        return self._owner == threading.get_ident()

    def __repr__(self) -> str:
        return f"<LsanLock {self._site} over {self._inner!r}>"


def _note_edge(held_site: str, acq_site: str) -> None:
    desc = (f"{held_site} held while acquiring {acq_site} "
            f"on {threading.current_thread().name}")
    with _REG_LOCK:
        states = list(_STATES)
    tripped = None
    for st in states:
        st.edges.setdefault((held_site, acq_site), desc)
        if (acq_site, held_site) in st.edges:
            v = Violation(
                "inversion",
                f"lock-order inversion: {desc}; reverse order "
                f"previously seen: {st.edges[(acq_site, held_site)]}",
                held_site, acq_site,
                threading.current_thread().name)
            st.violations.append(v)
            tripped = v
    if tripped is not None:
        _log("lsan_inversion", detail=tripped.detail)
        if _raise_on_inversion():
            raise LockOrderInversion(tripped.detail)


def _record(v: Violation) -> None:
    with _REG_LOCK:
        states = list(_STATES)
    for st in states:
        st.violations.append(v)
    _log(f"lsan_{v.kind}", detail=v.detail, site=v.site_a)


def _log(event: str, **kw) -> None:
    try:
        from ..obs import log as _olog

        _olog.warn(event, **kw)
    except Exception:
        pass


def _caller_site(depth: int = 2) -> tuple[str, str] | None:
    """(module, file:line) of the lock construction site; None when
    the caller is outside the project package."""
    try:
        f = sys._getframe(depth)
    except ValueError:  # pragma: no cover - shallow stack
        return None
    mod = f.f_globals.get("__name__", "")
    if not (mod == _PKG or mod.startswith(_PKG + ".")):
        return None
    return mod, f"{mod}:{f.f_lineno}"


def _lock_factory():
    site = _caller_site()
    if site is None:
        return _REAL_LOCK()
    return _LsanLock(_REAL_LOCK(), site[1], reentrant=False)


def _rlock_factory():
    site = _caller_site()
    if site is None:
        return _REAL_RLOCK()
    return _LsanLock(_REAL_RLOCK(), site[1], reentrant=True)


_lock_factory._kao_real = _REAL_LOCK
_rlock_factory._kao_real = _REAL_RLOCK


def wrap(lock=None, *, site: str = "explicit", reentrant: bool = False):
    """Wrap one lock explicitly (tests, or hot spots outside the
    package) regardless of install state."""
    return _LsanLock(lock if lock is not None else _REAL_LOCK(),
                     site, reentrant)


def install() -> bool:
    """Arm the sanitizer: project-module ``threading.Lock``/``RLock``
    constructions return instrumented proxies from here on. Idempotent;
    returns True when armed. Locks created BEFORE install stay raw, so
    call this before importing the serving modules (conftest does)."""
    global _INSTALLED
    _refresh_hold_budget()
    with _REG_LOCK:
        if _INSTALLED:
            return True
        threading.Lock = _lock_factory
        threading.RLock = _rlock_factory
        _INSTALLED = True
    _log("lsan_installed", hold_budget_s=hold_budget_s())
    return True


def uninstall() -> None:
    global _INSTALLED
    with _REG_LOCK:
        if not _INSTALLED:
            return
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        _INSTALLED = False


def installed() -> bool:
    return _INSTALLED


def violations() -> list[Violation]:
    """The session ledger (the root recording scope)."""
    with _REG_LOCK:
        return list(_STATES[0].violations)


def reset() -> None:
    """Clear the session ledger AND its order graph (tests)."""
    with _REG_LOCK:
        _STATES[0].edges.clear()
        _STATES[0].violations.clear()


class scope:
    """``with lsan.scope() as sc:`` — record into a private ledger;
    violations observed inside land in ``sc.violations`` and are kept
    OUT of the session ledger (deliberate-trip tests)."""

    def __init__(self):
        self._st = _State()
        self.violations = self._st.violations

    def __enter__(self) -> "scope":
        with _REG_LOCK:
            _STATES.append(self._st)
            self._suspended = _STATES.pop(0)
            _STATES.insert(0, _State())  # shield the session ledger
        return self

    def __exit__(self, *exc) -> None:
        with _REG_LOCK:
            _STATES.remove(self._st)
            _STATES.pop(0)
            _STATES.insert(0, self._suspended)
