"""``kao-check`` CLI: ``python -m kafka_assignment_optimizer_tpu.analysis``.

Exit codes: 0 clean, 1 findings, 2 usage/internal error.

The lint pass is pure stdlib and needs no jax; the jaxpr contract pass
(on by default, ``--no-contracts`` to skip) imports jax on CPU — it
traces the real solvers abstractly and never compiles or touches a
device.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import RULES, lint_paths, package_root


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="kao-check",
        description="Project-native static analysis for JAX footguns "
        "(rule catalog: docs/ANALYSIS.md).",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the package tree)",
    )
    ap.add_argument(
        "--rule", action="append", metavar="KAO1xx",
        help="restrict the lint pass to these rule IDs (repeatable)",
    )
    ap.add_argument(
        "--no-contracts", action="store_true",
        help="skip the jaxpr contract pass (lint only; no jax import)",
    )
    ap.add_argument(
        "--contracts-only", action="store_true",
        help="run only the jaxpr contract pass",
    )
    ap.add_argument(
        "--format", choices=["text", "json", "sarif"], default="text",
    )
    ap.add_argument(
        "--baseline", metavar="FILE",
        help="findings-ratchet baseline (analysis_baseline.json): "
        "baselined findings are tolerated, NEW findings fail, and "
        "fixed-but-not-removed baseline entries also fail",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite --baseline FILE from the current findings "
        "(the only sanctioned way to shrink or refresh it)",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.contracts_only and args.no_contracts:
        # both flags together would run zero checks and exit 0 — a
        # silent green no-op gate
        build_parser().error(
            "--contracts-only and --no-contracts are mutually exclusive"
        )
    if args.contracts_only and args.paths:
        # the contract pass traces the installed package's real
        # solvers; explicit paths scope the LINT pass only — accepting
        # both would run zero checks and report a green no-op
        build_parser().error(
            "--contracts-only does not take paths (contracts always "
            "run against the installed package)"
        )
    if args.update_baseline and not args.baseline:
        build_parser().error("--update-baseline requires --baseline")
    if args.rule:
        unknown = sorted(set(args.rule) - set(RULES))
        if unknown:
            # an unknown ID would filter every finding out and turn a
            # typo into a permanently green gate
            build_parser().error(
                f"unknown rule id(s): {', '.join(unknown)} "
                "(see --list-rules)"
            )
    if args.list_rules:
        for rid, title in sorted(RULES.items()):
            # kao: disable=KAO106 -- kao-check's own stdout IS the product
            print(f"{rid}  {title}")
        return 0
    findings = []
    if not args.contracts_only:
        findings += lint_paths(args.paths or None,
                               rules=set(args.rule) if args.rule else None)
    if not args.no_contracts and (args.contracts_only or not args.paths):
        # contracts trace the real solvers — meaningful only for the
        # package itself, so explicit fixture paths skip them
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from .contracts import run_contracts

        rep = run_contracts()
        findings += rep.findings

    from . import baseline as _baseline

    if args.update_baseline:
        _baseline.save(args.baseline, findings)
        # kao: disable=KAO106 -- kao-check's own stdout IS the product
        print(f"kao-check: baseline rewritten with {len(findings)} "
              f"finding(s): {args.baseline}")
        return 0

    ratchet = None
    if args.baseline:
        try:
            entries = _baseline.load(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            build_parser().error(f"--baseline: {exc}")
        ratchet = _baseline.compare(findings, entries)

    if args.format == "json":
        # kao: disable=KAO106 -- kao-check's own stdout IS the product
        print(json.dumps(
            [f.__dict__ for f in findings], indent=2
        ))
    elif args.format == "sarif":
        from . import sarif as _sarif

        known = (set() if ratchet is None else
                 {i for i, f in enumerate(findings)
                  if f in ratchet.known})
        # kao: disable=KAO106 -- kao-check's own stdout IS the product
        print(json.dumps(_sarif.render(findings, known), indent=2))
    else:
        fail_set = findings if ratchet is None else ratchet.new
        for f in fail_set:
            # kao: disable=KAO106 -- kao-check's own stdout IS the product
            print(f.render())
        if ratchet is not None:
            for e in ratchet.stale:
                # kao: disable=KAO106 -- kao-check's own stdout IS the product
                print(f"{e['path']}: stale baseline entry for "
                      f"{e['rule']} ({e['message']!r}) — the finding "
                      "is fixed; run --update-baseline to drop it")
        root = args.paths or [package_root()]
        tail = ("" if ratchet is None else
                f" ({len(ratchet.known)} baselined, "
                f"{len(ratchet.stale)} stale)")
        # kao: disable=KAO106 -- kao-check's own stdout IS the product
        print(
            f"kao-check: {len(fail_set)} "
            f"finding(s) in {', '.join(root)}{tail}"
        )
    if ratchet is not None:
        return 0 if ratchet.clean else 1
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
