"""Findings-ratchet baseline for ``kao-check`` (docs/ANALYSIS.md).

A baseline file (``analysis_baseline.json``, committed) is the list of
findings the project has *accepted for now*. The ratchet is one-way:

- a finding **in** the baseline is tolerated (reported as suppressed in
  SARIF, omitted from the text failure set);
- a finding **not in** the baseline fails the gate — new debt never
  lands silently;
- a baseline entry with **no matching finding** ALSO fails the gate —
  when a finding is fixed, the entry must be removed (run
  ``--update-baseline``) so the baseline only ever shrinks and a stale
  entry can never mask a regression that happens to render the same.

Matching is by (rule, path, message) **multiset**, deliberately ignoring
the line number: unrelated edits above a tolerated finding must not
churn the baseline, but a *second* identical finding in the same file is
new debt and fails. Line numbers are still stored for human navigation.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from .findings import Finding

BASELINE_VERSION = 1


def fingerprint(f: Finding) -> tuple[str, str, str]:
    """Line-drift-tolerant identity of a finding."""
    return (f.rule, f.path, f.message)


@dataclass
class Ratchet:
    """Outcome of comparing current findings against a baseline."""

    known: list[Finding] = field(default_factory=list)
    new: list[Finding] = field(default_factory=list)
    # fixed-but-not-removed baseline entries, as parsed dicts
    stale: list[dict] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.new and not self.stale


def load(path: str) -> list[dict]:
    """Parse a baseline file into entry dicts. A missing file is an
    error (the gate must not silently run baseline-less): callers pass
    ``--baseline`` only when the file is expected to exist, and
    ``--update-baseline`` creates it."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "findings" not in doc:
        raise ValueError(f"{path}: not a kao-check baseline "
                         "(missing 'findings' key)")
    entries = doc["findings"]
    for e in entries:
        for k in ("rule", "path", "message"):
            if not isinstance(e.get(k), str):
                raise ValueError(
                    f"{path}: baseline entry missing '{k}': {e!r}")
    return entries


def save(path: str, findings: list[Finding]) -> None:
    doc = {
        "version": BASELINE_VERSION,
        "tool": "kao-check",
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line,
             "message": f.message}
            for f in sorted(findings,
                            key=lambda f: (f.path, f.line, f.rule))
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def compare(findings: list[Finding], entries: list[dict]) -> Ratchet:
    """Split current findings into known/new and surface stale baseline
    entries, matching by fingerprint multiset."""
    budget: dict[tuple[str, str, str], int] = {}
    for e in entries:
        key = (e["rule"], e["path"], e["message"])
        budget[key] = budget.get(key, 0) + 1
    r = Ratchet()
    for f in findings:
        key = fingerprint(f)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            r.known.append(f)
        else:
            r.new.append(f)
    for e in entries:
        key = (e["rule"], e["path"], e["message"])
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            r.stale.append(e)
    return r
