"""AST lint pass — the project-specific JAX footgun rules.

Every rule here encodes a bug class this repo actually shipped (and
caught only dynamically, alignment- or platform-dependently):

- **KAO101** donated-arg reuse: a value passed at a donated position of
  a ``donate_argnums`` function is consumed by the dispatch; touching it
  afterwards raises "buffer deleted" at runtime — on the lucky days.
- **KAO102** shared broadcast base: two pytree leaves materialized from
  one ``np.broadcast_to`` view can be zero-copied into ONE device
  buffer, and under donation the in-place update corrupts both (the
  exact PR 4 shape; ``np.array(view)`` per leaf is the fix).
- **KAO103** float64-ambiguous numerics in device paths: float-literal
  arrays without an explicit dtype default to float64 on host, and the
  f64→f32 rounding at the device edge made the annealing ladder depend
  on the host's float64 ``**`` (the PR 2 trajectory break).
- **KAO104** PRNG key reuse: the same key fed to two consuming
  ``jax.random`` calls yields correlated streams; keys must be
  ``split``/``fold_in`` between uses.
- **KAO105** Python ``if``/``while`` on traced values inside jit bodies
  (or ``make_*`` solver-factory bodies): trace-time branching either
  crashes (ConcretizationTypeError) or silently bakes one branch into
  the executable.
- **KAO106** bare ``print`` outside ``obs/log.py``: the serving path's
  observability contract is structured key=value logs.
- **KAO107** ``kao_*`` metric families emitted without ``# HELP`` +
  ``# TYPE`` in the same module (the Prometheus exposition contract
  tests/test_metrics_format.py pins).
- **KAO108** chaos/resilience hooks inside traced bodies: a
  ``resilience.chaos`` injection point (or a ladder ``note_rung``)
  reached by jit/vmap/pallas tracing would bake the fault — or its
  absence — into the compiled executable and desynchronize SPMD
  workers; chaos is a HOST-SIDE-ONLY contract (docs/RESILIENCE.md).
- **KAO109** per-partition Python ``for`` loops in the bound/reseat
  hot modules (``models/bounds.py``, ``models/reseat.py``): these sit
  on every solve's certificate critical path, and ISSUE 10 rewrote
  their per-partition interpreter loops as vectorized numpy
  (docs/CONSTRUCTOR.md) — a loop over ``range(...num_parts)`` (or a
  name bound from it) regressing into one of them is almost always a
  multi-second host stall at the 50k-partition scale. Suppressible
  with justification for genuine cold fallbacks.
- **KAO110** lane-config values captured as Python scalars inside
  ``make_*`` solver-factory bodies: the portfolio contract
  (docs/PORTFOLIO.md) is that per-lane config — penalty scale,
  temperature multiplier, move-set gates — is ARRAY DATA on the model
  (``ModelArrays.lam``/``temp_scale``/``comp_enable``), so one
  lane-padded executable per bucket serves every config. A config
  name closed over by a factory's nested (traced) function — or a
  ``float()``/``int()`` coercion of a config attribute inside the
  factory — bakes the value into the jaxpr and silently
  re-specializes the consolidated executable per config: the exact
  compile-count regression PR 11 exists to prevent.
- **KAO111** serve/router outbound HTTP without causal-trace
  injection: the distributed-tracing contract (ISSUE 15,
  docs/OBSERVABILITY.md "Distributed traces") is that every HTTP call
  the serving tier makes on behalf of a request carries the active
  trace context (``obs.trace.inject`` → a ``traceparent`` header) —
  one uninjected hop and the fleet trace silently loses its worker
  half. The rule flags outbound-call sites (``conn.request``/
  ``urlopen``) in ``serve.py`` and ``fleet/`` whose function neither
  references the injection vocabulary nor threads caller-supplied
  headers; read-only telemetry fan-outs with no request context carry
  justified suppressions.
- **KAO112** per-partition Python ``for`` loops in the decompose hot
  modules (``decompose/split.py``, ``decompose/stitch.py``): the
  split/stitch phases run on the ultra-jumbo flat instance (200k+
  partitions, docs/DECOMPOSE.md) BEFORE any solve starts, so an
  interpreter loop over ``range(...num_parts)`` (or a name bound from
  it) there is pure host stall added to every decomposed solve's cold
  path — all per-partition work must be vectorized numpy (bincount /
  fancy-index gathers); Python loops may range only over groups and
  racks. Same detector as KAO109, scoped to the decompose modules.
  Suppressible with justification for genuine cold fallbacks.
- **KAO113** host-sync primitives inside ``lax.scan`` bodies: the
  megachunk contract (ISSUE 17, docs/PIPELINE.md) is that a fused
  K-chunk scan runs device-resident end to end — early exit is a
  masked no-op on the carry, never a host decision. A ``.item()`` /
  ``.tolist()`` call, an ``np.asarray``/``np.array``/
  ``jax.device_get`` of a scan-bound value, or a Python
  ``if``/``while`` on the scan carry inside the body either crashes
  at trace time (ConcretizationTypeError / TracerArrayConversionError)
  or — worse — silently forces a mid-scan host round-trip and the
  fused dispatch degenerates to per-chunk latency. Detected on any
  function passed as the body of a ``lax.scan`` call.
- **KAO114** ad-hoc timer deltas outside the accounting funnel in the
  dispatch hot modules (``parallel/mesh.py``, ``solvers/tpu/
  engine.py``): the attribution-ledger contract (ISSUE 18,
  docs/OBSERVABILITY.md "Attribution ledgers") is that every
  ``time.perf_counter()`` delta measured in a function that reaches a
  dispatch/compile site lands in a recording sink — ``obs.flight``'s
  ``note_*``/``attribute`` windows, a retire/record/span-attr call, a
  result field — never in a local-only computation. A delta that only
  feeds a log line or a branch is wall the ledger cannot see, and the
  sums-to-wall invariant quietly degrades into a growing ``other_s``.
- **KAO115** implicit sharding and stale device snapshots in the mesh
  hot modules (``parallel/``): the sharded-mesh contract (ISSUE 19,
  docs/MESH.md) is that every ``shard_map``/``pjit`` dispatch site
  states its placements explicitly — ``in_specs``/``out_specs`` (or
  ``in_shardings``/``out_shardings``) — because an omitted spec lets
  the partitioner choose replication and silently breaks the
  sharded-vs-unsharded bit-parity replay. Also flags ``jax.devices()``
  snapshots frozen where a later mesh rebuild cannot refresh them: a
  module-scope assignment, a default-argument value, or a device list
  captured from a ``make_*`` factory scope into the closure the
  factory returns (the stale-mesh bug class — the per-bucket sharding
  search rebuilds the mesh between solves).

All rules are stdlib-``ast`` only and run in milliseconds over the whole
package; precision is tuned so the CURRENT tree is clean (real findings
were fixed, deliberate exceptions carry justified suppressions).
"""

from __future__ import annotations

import ast
import re

from .findings import Finding, apply_suppressions, parse_suppressions

# KAO103 applies only where arrays cross the host->device boundary; the
# host-side exact oracles (models/, solvers/lp*, milp) legitimately run
# scipy/LP math in float64.
DEVICE_PATH_MARKERS = ("solvers/tpu", "ops", "parallel")

# jax.random consumers that CONSUME a key (vs derive new keys from it)
_KEY_DERIVERS = {
    "split", "fold_in", "clone", "key_data", "wrap_key_data", "key_impl",
}
# jnp reductions whose appearance in an `if` test means a traced value
# is being branched on
_TRACED_REDUCERS = {"any", "all", "sum", "max", "min", "prod", "mean"}
# attribute reads that are static at trace time
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type", "aval"}

# numpy constructors whose float-literal payloads default to float64
_F64_CONSTRUCTORS = {"array", "asarray", "full", "linspace", "geomspace"}


def _dotted(node: ast.AST) -> list[str]:
    """``a.b.c`` -> ["a", "b", "c"]; empty when not a plain chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def _has_float_literal(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, float):
            return True
    return False


def _kw(call: ast.Call, name: str) -> ast.keyword | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw
    return None


class _Parents(ast.NodeVisitor):
    def __init__(self):
        self.parent: dict[ast.AST, ast.AST] = {}

    def generic_visit(self, node):
        for child in ast.iter_child_nodes(node):
            self.parent[child] = node
        super().generic_visit(node)


def _walk_own_scope(fn):
    """Walk a function's nodes in source order WITHOUT descending into
    nested function definitions (each nested def gets its own pass)."""
    queue = list(ast.iter_child_nodes(fn))
    i = 0
    while i < len(queue):
        node = queue[i]
        i += 1
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        queue.extend(ast.iter_child_nodes(node))


def lint_source(
    text: str, path: str, rel: str | None = None
) -> list[Finding]:
    """Lint one file's source; ``rel`` is the package-relative posix
    path used for path-scoped rules (defaults to ``path``)."""
    rel = (rel or path).replace("\\", "/")
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [Finding("KAO100", path, e.lineno or 1,
                        f"file does not parse: {e.msg}")]
    parents = _Parents()
    parents.visit(tree)
    out: list[Finding] = []
    out += _rule_print(tree, path, rel)
    out += _rule_float64(tree, path, rel)
    out += _rule_metrics_help_type(tree, path)
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        out += _rule_donated_reuse(fn, path)
        out += _rule_broadcast_base(fn, path, parents.parent)
        out += _rule_key_reuse(fn, path)
    out += _rule_traced_branch(tree, path)
    out += _rule_chaos_in_traced(tree, path)
    out += _rule_partition_loop(tree, path, rel)
    out += _rule_decompose_loop(tree, path, rel)
    out += _rule_lane_config_capture(tree, path)
    out += _rule_uninjected_http(tree, path, rel)
    out += _rule_scan_host_sync(tree, path)
    out += _rule_time_delta(tree, path, rel)
    out += _rule_mesh_sharding(tree, path, rel)
    out += _concurrency_findings(tree, text, path, rel)
    return apply_file_suppressions(out, path, text)


def _concurrency_findings(tree, text, path, rel) -> list[Finding]:
    # local import: concurrency imports Finding from .findings only,
    # but keep the layering acyclic and lazy
    from .concurrency import analyze_module
    return analyze_module(tree, text, path, rel).findings


def apply_file_suppressions(
    findings: list[Finding], path: str, text: str
) -> list[Finding]:
    """THE suppression gate: every rule — AST, concurrency, contracts,
    cross-file — funnels its findings through here so ``# kao:
    disable=KAOxxx -- reason`` behaves identically everywhere and a
    reason-less disable surfaces as KAO100 exactly once per line."""
    sup = parse_suppressions(text)
    return apply_suppressions(
        sorted(findings, key=lambda f: (f.line, f.rule)), path, sup)


# ---------------------------------------------------------------- KAO106

def _rule_print(tree, path, rel) -> list[Finding]:
    if rel.endswith("obs/log.py"):
        return []  # the structured logger's own emit site
    return [
        Finding("KAO106", path, n.lineno,
                "bare print(); use obs.log (structured key=value lines) "
                "or suppress where stdout IS the product")
        for n in ast.walk(tree)
        if isinstance(n, ast.Call)
        and isinstance(n.func, ast.Name) and n.func.id == "print"
    ]


# ---------------------------------------------------------------- KAO103

def _rule_float64(tree, path, rel) -> list[Finding]:
    if not any(m in rel for m in DEVICE_PATH_MARKERS):
        return []
    out = []
    for n in ast.walk(tree):
        if isinstance(n, ast.Attribute) and n.attr in (
            "float64", "float_",
        ):
            base = _dotted(n)
            if base and base[0] in ("np", "numpy", "jnp"):
                out.append(Finding(
                    "KAO103", path, n.lineno,
                    f"{'.'.join(base)} in a device path: the device "
                    "consumes float32; build in float32 end to end "
                    "(see arrays.geometric_temps)"))
        if not isinstance(n, ast.Call):
            continue
        kw = _kw(n, "dtype")
        if kw is not None and isinstance(kw.value, ast.Name) \
                and kw.value.id == "float":
            out.append(Finding(
                "KAO103", path, n.lineno,
                "dtype=float is float64 on host; name the width "
                "explicitly (np.float32)"))
        if isinstance(n.func, ast.Attribute) and n.func.attr == "astype" \
                and n.args and isinstance(n.args[0], ast.Name) \
                and n.args[0].id == "float":
            out.append(Finding(
                "KAO103", path, n.lineno,
                ".astype(float) is float64 on host; name the width "
                "explicitly"))
        # dtype-less constructors with float-literal payloads
        chain = _dotted(n.func)
        if (
            len(chain) == 2
            and chain[0] in ("np", "numpy")
            and chain[1] in _F64_CONSTRUCTORS
            and _kw(n, "dtype") is None
            and n.args
            and _has_float_literal(n.args[0] if chain[1] != "full"
                                   else (n.args[1] if len(n.args) > 1
                                         else n.args[0]))
        ):
            out.append(Finding(
                "KAO103", path, n.lineno,
                f"np.{chain[1]} with float literals and no dtype= "
                "defaults to float64; pass dtype=np.float32 (device "
                "paths must not depend on host float64)"))
    return out


# ---------------------------------------------------------------- KAO101

def _donated_positions(call: ast.Call) -> tuple[int, ...] | None:
    kw = _kw(call, "donate_argnums")
    if kw is None:
        return None
    v = kw.value
    if isinstance(v, ast.Constant) and isinstance(v.value, int):
        return (v.value,)
    if isinstance(v, (ast.Tuple, ast.List)):
        pos = tuple(
            e.value for e in v.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        )
        return pos or None
    return None  # dynamic spec: nothing to check statically


def _stmts_in_order(body: list[ast.stmt]):
    for st in body:
        yield st
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue  # nested scopes get their own pass
        for sub in (
            getattr(st, "body", []), getattr(st, "orelse", []),
            getattr(st, "finalbody", []),
        ):
            if isinstance(sub, list):
                yield from _stmts_in_order(sub)
        for h in getattr(st, "handlers", []):
            yield from _stmts_in_order(h.body)


def _rule_donated_reuse(fn, path) -> list[Finding]:
    donators: dict[str, tuple[int, ...]] = {}
    consumed: dict[str, int] = {}  # name -> line it was donated at
    out = []
    for st in _stmts_in_order(fn.body):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested scopes have their own pass
        # loads of already-consumed names (checked before this
        # statement's own stores rebind them)
        for node in ast.walk(st):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in consumed:
                out.append(Finding(
                    "KAO101", path, node.lineno,
                    f"'{node.id}' was donated to a donate_argnums "
                    f"dispatch at line {consumed[node.id]} and is dead; "
                    "use the RETURNED state (in-place donation contract, "
                    "docs/PIPELINE.md)"))
                consumed.pop(node.id)  # one report per donation
        # new donating wrappers: name = jax.jit(..., donate_argnums=...)
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name) \
                and isinstance(st.value, ast.Call):
            pos = _donated_positions(st.value)
            if pos is not None:
                donators[st.targets[0].id] = pos
        # consumption: a call of a known donating wrapper
        for node in ast.walk(st):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in donators:
                for p in donators[node.func.id]:
                    if p < len(node.args) \
                            and isinstance(node.args[p], ast.Name):
                        consumed[node.args[p].id] = node.lineno
        # stores rebind (the returned state replacing the donated one)
        for node in ast.walk(st):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, (ast.Store, ast.Del)):
                consumed.pop(node.id, None)
    return out


# ---------------------------------------------------------------- KAO102

_COPYING_CALLS = {"array", "copy", "ascontiguousarray", "asarray_chkfinite"}


def _rule_broadcast_base(fn, path, parent) -> list[Finding]:
    # HOST-side views only (np.broadcast_to): jnp.broadcast_to inside
    # traced code is functional — it cannot alias two device_put'd
    # pytree leaves to one buffer, which is the bug class here
    bases: dict[str, int] = {}  # name -> assignment line
    for node in _walk_own_scope(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            chain = _dotted(node.value.func)
            if len(chain) == 2 and chain[0] in ("np", "numpy") \
                    and chain[1] == "broadcast_to":
                bases[node.targets[0].id] = node.lineno
    if not bases:
        return []
    out = []
    bare_uses: dict[str, int] = {}
    for node in _walk_own_scope(fn):
        if not (isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in bases):
            continue
        p = parent.get(node)
        # a use is SAFE when the view is immediately materialized into
        # an independent buffer: np.array(view) / view.astype(...) /
        # view.copy() / np.ascontiguousarray(view)
        if isinstance(p, ast.Call) and p.args and p.args[0] is node:
            chain = _dotted(p.func)
            if len(chain) == 2 and chain[0] in ("np", "numpy", "jnp") \
                    and chain[1] in _COPYING_CALLS:
                continue
        if isinstance(p, ast.Attribute) and p.attr in ("astype", "copy"):
            continue
        bare_uses[node.id] = bare_uses.get(node.id, 0) + 1
        if bare_uses[node.id] == 2:
            out.append(Finding(
                "KAO102", path, node.lineno,
                f"'{node.id}' is a broadcast VIEW used as more than one "
                "leaf: device_put can zero-copy both into ONE buffer, "
                "and donation then corrupts them in place (PR 4 bug "
                "class); materialize each leaf with np.array(view)"))
    return out


# ---------------------------------------------------------------- KAO104

def _rule_key_reuse(fn, path) -> list[Finding]:
    keys: set[str] = set()
    uses: dict[str, int] = {}
    out = []
    for st in _stmts_in_order(fn.body):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(st):
            if isinstance(node, ast.Assign):
                tgts = node.targets
                val = node.value
                is_key_src = False
                if isinstance(val, ast.Call):
                    chain = _dotted(val.func)
                    if chain and chain[-1] in ("PRNGKey", "key") \
                            and "random" in chain:
                        is_key_src = True
                    if chain and chain[-1] in ("split", "fold_in") \
                            and "random" in chain:
                        is_key_src = True
                for t in tgts:
                    names = (
                        [t] if isinstance(t, ast.Name)
                        else [e for e in getattr(t, "elts", [])
                              if isinstance(e, ast.Name)]
                    )
                    for nm in names:
                        if is_key_src:
                            keys.add(nm.id)
                        uses.pop(nm.id, None)  # any rebind resets
            if isinstance(node, ast.Call):
                chain = _dotted(node.func)
                if not (chain and "random" in chain
                        and chain[-1] not in _KEY_DERIVERS
                        and chain[-1] not in ("PRNGKey", "key")):
                    continue
                for a in node.args:
                    if isinstance(a, ast.Name) and a.id in keys:
                        uses[a.id] = uses.get(a.id, 0) + 1
                        if uses[a.id] == 2:
                            out.append(Finding(
                                "KAO104", path, node.lineno,
                                f"PRNG key '{a.id}' consumed by a second "
                                "jax.random call without split/fold_in: "
                                "the streams are identical, not "
                                "independent"))
    return out


# ---------------------------------------------------------------- KAO105

def _jitted_names(tree) -> set[str]:
    """Names referenced anywhere inside a ``jax.jit(...)`` call: those
    functions' bodies are traced."""
    names: set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call):
            chain = _dotted(n.func)
            if chain and chain[-1] == "jit":
                for a in ast.walk(n):
                    if isinstance(a, ast.Name) \
                            and isinstance(a.ctx, ast.Load):
                        names.add(a.id)
    return names


def _is_jit_decorated(fn) -> bool:
    for dec in fn.decorator_list:
        chain = _dotted(dec if not isinstance(dec, ast.Call) else dec.func)
        if chain and chain[-1] == "jit":
            return True
        if isinstance(dec, ast.Call) and _dotted(dec.func)[-1:] == [
            "partial"
        ]:
            for a in dec.args:
                if _dotted(a)[-1:] == ["jit"]:
                    return True
    return False


def _traced_fns(tree):
    jitted = _jitted_names(tree)
    for n in ast.walk(tree):
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if _is_jit_decorated(n) or n.name in jitted:
            yield n
            continue
        # nested defs inside a make_* solver factory are the functions
        # the factory returns for jit/vmap/shard_map hosting
        for inner in ast.walk(n):
            if inner is n:
                continue
            if isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n.name.lstrip("_").startswith("make"):
                yield inner


def _test_touches_traced(test: ast.expr, params: set[str]) -> bool:
    """True when an ``if``/``while`` test reads a traced parameter in a
    way that needs a concrete value at trace time."""

    def visit(node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in params
        if isinstance(node, ast.BoolOp):
            return any(visit(v) for v in node.values)
        if isinstance(node, ast.UnaryOp):
            return visit(node.operand)
        if isinstance(node, ast.BinOp):
            return visit(node.left) or visit(node.right)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is a static structure test
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return any(visit(v) for v in
                       [node.left, *node.comparators])
        if isinstance(node, ast.Subscript):
            return visit(node.value)
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False  # shapes/dtypes are static at trace time
            return False  # other attribute reads: conservative skip
        if isinstance(node, ast.Call):
            chain = _dotted(node.func)
            # jnp reductions of traced values inside a Python branch
            # are the classic ConcretizationTypeError
            if len(chain) >= 2 and chain[0] in ("jnp", "jax") \
                    and chain[-1] in _TRACED_REDUCERS:
                return any(visit(a) for a in node.args)
            return False  # len(), isinstance(), helpers: static/opaque
        return False

    return visit(test)


def _rule_traced_branch(tree, path) -> list[Finding]:
    out = []
    seen: set[int] = set()
    for fn in _traced_fns(tree):
        params = {
            a.arg for a in (
                fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
            ) if a.arg != "self"
        }
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)) \
                    and node.lineno not in seen \
                    and _test_touches_traced(node.test, params):
                seen.add(node.lineno)
                kind = "while" if isinstance(node, ast.While) else "if"
                out.append(Finding(
                    "KAO105", path, node.lineno,
                    f"Python `{kind}` on a traced value inside a "
                    "jit/solver-factory body; use jnp.where / "
                    "lax.cond / lax.while_loop"))
    return out


# ---------------------------------------------------------------- KAO108

# the resilience surface that must stay host-side: the chaos harness's
# firing/raising/sleeping entry points and the ladder's rung recorder
# (it takes a lock and emits a log — both trace-hostile side effects)
_CHAOS_HOOKS = {"fires", "raise_if", "sleep_if", "note_rung"}
_CHAOS_MODULES = {"chaos", "ladder", "resilience"}


def _rule_chaos_in_traced(tree, path) -> list[Finding]:
    """Chaos hooks may never execute under jit/vmap/pallas tracing: a
    traced hook bakes the fault (or its absence) into the compiled
    executable — the chaos soak would then replay whatever the trace
    captured instead of injecting live — and a raising hook inside an
    SPMD body desynchronizes workers in front of collectives. Same
    traced-body heuristic as KAO105 (jit-decorated functions plus
    nested defs inside ``make_*`` solver factories)."""
    out = []
    seen: set[int] = set()
    for fn in _traced_fns(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted(node.func)
            if (
                len(chain) >= 2
                and chain[-1] in _CHAOS_HOOKS
                and chain[0].lstrip("_") in _CHAOS_MODULES
                and node.lineno not in seen
            ):
                seen.add(node.lineno)
                out.append(Finding(
                    "KAO108", path, node.lineno,
                    f"{'.'.join(chain)} inside a traced body: chaos "
                    "hooks are host-side only (a traced hook bakes "
                    "the fault into the executable and desyncs SPMD "
                    "workers); inject at the dispatch call site "
                    "instead (docs/RESILIENCE.md)"))
    return out


# ---------------------------------------------------------------- KAO109

# the bound/reseat hot modules: every solve's certificate critical path
# runs through them, so per-partition Python loops there are host
# stalls at scale (ISSUE 10 vectorized them; docs/CONSTRUCTOR.md)
_PARTITION_HOT_FILES = ("models/bounds.py", "models/reseat.py")


def _rule_partition_loop(tree, path, rel) -> list[Finding]:
    """Flag ``for`` loops that iterate per partition inside the
    bound/reseat hot modules: a loop whose iterator is
    ``range(<...>.num_parts ...)`` or ``range(<name>)`` where the name
    was bound from a ``num_parts`` read in the same module. Deliberate
    cold fallbacks carry a justified suppression
    (``# kao: disable=KAO109 -- reason``)."""
    if not rel.endswith(_PARTITION_HOT_FILES):
        return []
    return _partition_loop_findings(
        tree, path, "KAO109",
        "per-partition Python `for` loop in a bound/reseat hot "
        "module: this is host time on every solve's certificate "
        "critical path — vectorize over the padded arrays "
        "(docs/CONSTRUCTOR.md) or suppress with justification "
        "for a genuine cold fallback")


def _partition_loop_findings(tree, path, code, msg) -> list[Finding]:
    # names assigned (anywhere in the module) from a .num_parts read —
    # catches the `P = inst.num_parts` / `for p in range(P)` split
    part_names: set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and _mentions_num_parts(n.value):
            for t in n.targets:
                names = (
                    [t] if isinstance(t, ast.Name)
                    else [e for e in getattr(t, "elts", [])
                          if isinstance(e, ast.Name)]
                )
                part_names.update(nm.id for nm in names)

    out = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.For):
            continue
        it = n.iter
        if not (isinstance(it, ast.Call)
                and _dotted(it.func)[-1:] == ["range"]):
            continue
        hit = any(_mentions_num_parts(a) for a in it.args) or any(
            isinstance(a, ast.Name) and a.id in part_names
            for a in it.args
        )
        if hit:
            out.append(Finding(code, path, n.lineno, msg))
    return out


# ---------------------------------------------------------------- KAO112

# the decompose hot modules: split/stitch run over the ultra-jumbo
# FLAT instance before any solve starts (docs/DECOMPOSE.md), so
# per-partition interpreter loops there are host stalls added to every
# decomposed solve's cold path — Python loops may range only over
# groups and racks
_DECOMPOSE_HOT_FILES = ("decompose/split.py", "decompose/stitch.py")


def _rule_decompose_loop(tree, path, rel) -> list[Finding]:
    """KAO109's detector scoped to the decompose hot modules: flag
    ``for`` loops over ``range(...num_parts)`` (or a name bound from
    it) in ``decompose/split.py`` / ``decompose/stitch.py``.
    Deliberate cold fallbacks carry a justified suppression
    (``# kao: disable=KAO112 -- reason``)."""
    if not rel.endswith(_DECOMPOSE_HOT_FILES):
        return []
    return _partition_loop_findings(
        tree, path, "KAO112",
        "per-partition Python `for` loop in a decompose hot module: "
        "split/stitch run over the ultra-jumbo FLAT instance before "
        "any solve starts, so this is host stall on every decomposed "
        "cold path — vectorize with bincount/fancy-index gathers "
        "(docs/DECOMPOSE.md); loops may range only over groups/racks, "
        "or suppress with justification for a genuine cold fallback")


def _bound_names(fn) -> set[str]:
    """Names a function binds itself: parameters plus own-scope stores
    (nested defs excluded — they have their own scopes)."""
    names = {
        a.arg for a in (
            fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
        )
    }
    if fn.args.vararg:
        names.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        names.add(fn.args.kwarg.arg)
    for node in _walk_own_scope(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            names.add(node.id)
    return names


# ---------------------------------------------------------------- KAO110

# the lane-config vocabulary (arrays.LaneConfig / the ModelArrays
# config leaves): values under these names are per-lane search config
# and must reach traced bodies as MODEL DATA, never as captured Python
# scalars (docs/PORTFOLIO.md)
_LANE_CONFIG_NAMES = {
    "lam", "lambda_", "temp_scale", "comp_enable", "lane_config",
}
_LANE_CONFIG_ATTRS = {"lam", "temp_scale", "comp_enable"}
_SCALAR_COERCERS = {"float", "int", "bool"}


def _rule_lane_config_capture(tree, path) -> list[Finding]:
    """Flag lane-config values materialized as Python scalars inside
    ``make_*`` solver-factory bodies. Two shapes:

    - a nested def (the function the factory returns for jit/vmap
      hosting) reading a config-named value from the FACTORY scope —
      a closure capture, i.e. a compile-time constant per config;
    - ``float(x.lam)`` / ``int(cfg.temp_scale)``-style coercions of a
      config attribute anywhere in the factory body (the value can
      only flow onward as a trace-time constant).

    Both silently re-specialize the consolidated lane executable per
    config; thread the value as model data instead
    (``ModelArrays.lam`` — docs/PORTFOLIO.md)."""
    out = []
    seen: set[int] = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not fn.name.lstrip("_").startswith("make"):
            continue
        factory_cfg = _bound_names(fn) & _LANE_CONFIG_NAMES
        for inner in ast.walk(fn):
            if inner is fn or not isinstance(
                inner, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            shadowed = _bound_names(inner)
            for node in ast.walk(inner):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in factory_cfg
                    and node.id not in shadowed
                    and node.lineno not in seen
                ):
                    seen.add(node.lineno)
                    out.append(Finding(
                        "KAO110", path, node.lineno,
                        f"lane-config value '{node.id}' captured from "
                        f"the enclosing {fn.name}() factory scope: it "
                        "bakes into the traced executable and "
                        "re-specializes the consolidated lane "
                        "executable per config; thread it as model "
                        "data (ModelArrays.lam/temp_scale/"
                        "comp_enable — docs/PORTFOLIO.md)"))
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in _SCALAR_COERCERS
                and node.args
                and isinstance(node.args[0], ast.Attribute)
                and node.args[0].attr in _LANE_CONFIG_ATTRS
                and node.lineno not in seen
            ):
                seen.add(node.lineno)
                out.append(Finding(
                    "KAO110", path, node.lineno,
                    f"{node.func.id}(...{node.args[0].attr}) inside "
                    f"{fn.name}(): coercing a lane-config attribute "
                    "to a Python scalar makes it a trace-time "
                    "constant and re-specializes the consolidated "
                    "executable per config; keep it a device scalar "
                    "(docs/PORTFOLIO.md)"))
    return out


# ---------------------------------------------------------------- KAO111

# the serving tier whose outbound hops must carry the causal context
_HTTP_SCOPE_MARKERS = ("serve.py", "fleet/")


def _is_outbound_http_call(node: ast.AST) -> bool:
    """``conn.request(...)`` / ``urlopen(...)`` call sites — the two
    stdlib outbound-HTTP shapes this tree uses."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in ("request", "urlopen")
    return isinstance(fn, ast.Name) and fn.id == "urlopen"


def _rule_uninjected_http(tree, path, rel) -> list[Finding]:
    """Flag serve/fleet functions making outbound HTTP calls without
    the causal-trace injection vocabulary: no reference to an
    ``inject``-named helper or a ``traceparent`` literal, and no
    header-threading parameter (a function that forwards
    caller-supplied headers delegates propagation to its caller, e.g.
    the router's ``_proxy_once``). One uninjected hop severs the
    router→worker trace join (docs/OBSERVABILITY.md "Distributed
    traces"); genuine non-request traffic (health polls, telemetry
    fan-outs) carries a justified suppression."""
    if not any(m in rel for m in _HTTP_SCOPE_MARKERS):
        return []
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        calls = [
            n for n in _walk_own_scope(fn)
            if _is_outbound_http_call(n)
        ]
        if not calls:
            continue
        params = [
            a.arg for a in (fn.args.posonlyargs + fn.args.args
                            + fn.args.kwonlyargs)
        ]
        satisfied = any("header" in p for p in params)
        for node in _walk_own_scope(fn):
            if satisfied:
                break
            if isinstance(node, ast.Name) and "inject" in node.id:
                satisfied = True
            elif isinstance(node, ast.Attribute) \
                    and "inject" in node.attr:
                satisfied = True
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and "traceparent" in node.value.lower():
                satisfied = True
        if satisfied:
            continue
        out.extend(
            Finding(
                "KAO111", path, call.lineno,
                f"outbound HTTP call in {fn.name}() without causal-"
                "trace injection: propagate the active context "
                "(obs.trace.inject -> a traceparent header, or thread "
                "the caller's headers through) so the fleet trace "
                "join survives this hop (docs/OBSERVABILITY.md "
                "'Distributed traces'); read-only non-request "
                "traffic should carry a justified suppression")
            for call in calls
        )
    return out


# ---------------------------------------------------------------- KAO113

# host-materialization shapes inside a scan body: numpy constructors
# that concretize a tracer, and jax's explicit device->host fetch.
# jnp.asarray stays legal — it is functional and traces fine.
_HOST_SYNC_NP = {"asarray", "array", "ascontiguousarray"}
_HOST_SYNC_ATTRS = {"item", "tolist"}


def _scan_bodies(tree):
    """Functions passed as the body (first argument) of a ``lax.scan``
    call: named defs resolved module-wide by name, plus inline
    lambdas. Everything inside one is traced by construction."""
    named: set[str] = set()
    lambdas: list[ast.Lambda] = []
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and n.args:
            chain = _dotted(n.func)
            if chain[-1:] == ["scan"]:
                f = n.args[0]
                if isinstance(f, ast.Name):
                    named.add(f.id)
                elif isinstance(f, ast.Lambda):
                    lambdas.append(f)
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n.name in named:
            yield n
    yield from lambdas


def _rule_scan_host_sync(tree, path) -> list[Finding]:
    """Host-sync primitives inside ``lax.scan`` bodies (the megachunk
    contract, ISSUE 17 / docs/PIPELINE.md): ``.item()``/``.tolist()``,
    ``np.asarray``/``np.array``/``jax.device_get`` of a scan-bound
    value, and Python ``if``/``while`` on the carry. Inside a fused
    megachunk scan these either crash at trace time or silently force
    a mid-scan host round-trip — exit decisions must stay on-device
    as masked no-ops on the carry."""
    out = []
    seen: set[int] = set()

    def note(lineno, msg):
        if lineno not in seen:
            seen.add(lineno)
            out.append(Finding("KAO113", path, lineno, msg))

    for fn in _scan_bodies(tree):
        params = {
            a.arg for a in (
                fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
            )
        }
        bound = (
            _bound_names(fn)
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            else set(params)
        )
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _HOST_SYNC_ATTRS:
                    note(node.lineno,
                         f".{node.func.attr}() inside a lax.scan body "
                         "is a device->host sync: it crashes at trace "
                         "time or forces a mid-scan round-trip; keep "
                         "the decision on-device in the carry "
                         "(docs/PIPELINE.md megachunks)")
                    continue
                chain = _dotted(node.func)
                is_np_sync = (
                    len(chain) == 2 and chain[0] in ("np", "numpy")
                    and chain[1] in _HOST_SYNC_NP
                )
                is_device_get = chain[-1:] == ["device_get"]
                if (is_np_sync or is_device_get) and node.args and any(
                    isinstance(sub, ast.Name) and sub.id in bound
                    for sub in ast.walk(node.args[0])
                ):
                    note(node.lineno,
                         f"{'.'.join(chain)} of a scan-bound value "
                         "inside a lax.scan body: concretizing a "
                         "tracer is a host sync (TracerArray"
                         "ConversionError at best); stay in jnp "
                         "(docs/PIPELINE.md megachunks)")
            elif isinstance(node, (ast.If, ast.While)) \
                    and _test_touches_traced(node.test, params):
                kind = "while" if isinstance(node, ast.While) else "if"
                note(node.lineno,
                     f"Python `{kind}` on the scan carry inside a "
                     "lax.scan body: the carry is traced — branch "
                     "with jnp.where / lax.cond so the fused "
                     "megachunk stays device-resident "
                     "(docs/PIPELINE.md)")
    return out


def _mentions_num_parts(node: ast.AST) -> bool:
    return any(
        isinstance(sub, ast.Attribute) and sub.attr == "num_parts"
        for sub in ast.walk(node)
    )


# ---------------------------------------------------------------- KAO107

def _string_literals(tree):
    for n in ast.walk(tree):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n.lineno, n.value
        elif isinstance(n, ast.JoinedStr):
            for v in n.values:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    yield n.lineno, v.value


_FAMILY_RE = re.compile(r"^kao_[a-z0-9_]+$")


def _family(sample: str) -> str:
    name = sample.split("{")[0].split()[0]
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            name = name[: -len(suffix)]
    return name


def _rule_metrics_help_type(tree, path) -> list[Finding]:
    emitted: dict[str, int] = {}
    documented: dict[str, set[str]] = {}
    for lineno, s in _string_literals(tree):
        stripped = s.lstrip()
        if stripped.startswith("# HELP ") or stripped.startswith("# TYPE "):
            kind = stripped.split()[1]
            rest = stripped.split()[2:]
            if rest and rest[0].startswith("kao_"):
                documented.setdefault(_family(rest[0]), set()).add(kind)
        elif stripped.startswith("kao_"):
            # only exposition-shaped literals count as emission: the
            # family name must be followed by a label brace, or by
            # nothing but whitespace (an f-string sample prefix like
            # "kao_x " with the value interpolated). A bare "kao_foo"
            # (contextvar names, .so basenames) or prose containing
            # the name is not a metric sample.
            head = stripped.split("{")[0].split()[0]
            rest = stripped[len(head):]
            if not _FAMILY_RE.match(_family(head)):
                continue
            if not (rest.startswith("{")
                    or (rest != "" and rest.strip() == "")):
                continue
            emitted.setdefault(_family(head), lineno)
    return [
        Finding("KAO107", path, line,
                f"metric family '{fam}' emitted without # HELP and "
                "# TYPE in this module (Prometheus exposition "
                "contract, tests/test_metrics_format.py)")
        for fam, line in sorted(emitted.items(), key=lambda kv: kv[1])
        if documented.get(fam, set()) != {"HELP", "TYPE"}
    ]


# ---------------------------------------------------------------- KAO114

# the dispatch hot modules: every wall-clock delta measured here sits
# on a solve's critical path, and the attribution-ledger contract
# (ISSUE 18) is ONE accounting funnel — obs.flight windows, retire/
# record sinks, span attrs, result fields — so the ledger's
# sums-to-wall invariant stays meaningful
_ACCOUNTING_HOT_FILES = ("parallel/mesh.py", "solvers/tpu/engine.py")
_TIMER_FNS = {"perf_counter", "monotonic", "time"}
# a function "reaches a dispatch/compile site" when it calls one of
# these shapes — pure host helpers that merely time themselves are
# out of scope
_DISPATCH_SITE_RE = re.compile(
    r"dispatch|compile|solve_|block_until_ready|fetch_global|lower"
)
# call names that COUNT as the accounting funnel: flight/prof note_*
# hooks, record/observe/retire sinks, span-attr setters, ledger/window
# helpers, and result constructors whose consumers do the recording
_FUNNEL_RE = re.compile(
    r"note_|record|observe|retire|attrs|\.set$|\.update$|SolveResult"
    r"|_select_lanes|ledger|window|attribute|chunk_attrs"
)


def _is_timer_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    return len(d) == 2 and d[0] == "time" and d[1] in _TIMER_FNS


def _is_timer_delta(node: ast.AST) -> bool:
    """A literal wall-clock measurement: ``time.perf_counter() - t0``.
    Timer on the LEFT only — elapsed wall is always now-minus-mark,
    while ``deadline - time.perf_counter()`` (timer on the right) is a
    remaining-headroom check, control flow rather than measurement."""
    return (
        isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
        and _is_timer_call(node.left)
    )


def _call_name(call: ast.Call) -> str:
    d = _dotted(call.func)
    if d:
        return ".".join(d)
    if isinstance(call.func, ast.Attribute):
        # method on a computed receiver (``span(...).set``): the attr
        # alone still identifies the funnel vocabulary
        return "." + call.func.attr
    return ""


def _names_in(node: ast.AST, names) -> set[str]:
    return {
        n.id for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        and n.id in names
    }


def _rule_time_delta(tree, path, rel) -> list[Finding]:
    """Flag ``time.perf_counter()``-style deltas in the dispatch hot
    modules that never reach the accounting funnel. A delta (or a name
    bound from one, through simple assignment chains) is CLEAN when it
    escapes into a funnel call (``note_*``/record/observe/retire/
    span-``.set``/``chunk_attrs``/``SolveResult``/...), a ``return``
    value, an attribute or subscript store, or an augmented assignment
    to a ``nonlocal``/``global`` accumulator — all shapes whose
    consumers land the seconds in a flight record. Anything else
    (a delta feeding only a log line, a print, or a branch) is wall
    the ledger cannot attribute. Suppressible with justification
    (``# kao: disable=KAO114 -- reason``) for genuinely
    non-accountable timing (e.g. test-only instrumentation)."""
    if not rel.endswith(_ACCOUNTING_HOT_FILES):
        return []
    out: list[Finding] = []
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out += _time_delta_findings(fn, path)
    return out


def _time_delta_findings(fn, path) -> list[Finding]:
    own = list(_walk_own_scope(fn))
    deltas = [n for n in own if _is_timer_delta(n)]
    if not deltas:
        return []
    # scope gate: only functions that reach a dispatch/compile site
    if not any(
        isinstance(n, ast.Call)
        and _DISPATCH_SITE_RE.search(_call_name(n))
        for n in own
    ):
        return []
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    shared = {
        nm for n in own
        if isinstance(n, (ast.Nonlocal, ast.Global)) for nm in n.names
    }

    # origins: tainted name -> delta lines it carries; pending/escaped
    # track delta lines still unaccounted vs proven funneled
    origins: dict[str, set[int]] = {}
    pending: set[int] = set()
    escaped: set[int] = set()
    immediate: list[int] = []

    def _stmt_and_funnel(node):
        """Walk up to the enclosing statement; True when any ancestor
        call on the way matches the funnel vocabulary."""
        funneled = False
        cur = node
        while cur in parents and not isinstance(cur, ast.stmt):
            cur = parents[cur]
            if isinstance(cur, ast.Call) \
                    and _FUNNEL_RE.search(_call_name(cur)):
                funneled = True
        return cur, funneled

    for d in deltas:
        stmt, funneled = _stmt_and_funnel(d)
        if funneled or isinstance(stmt, ast.Return):
            continue
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        if targets:
            flat = [
                e for t in targets
                for e in (getattr(t, "elts", None) or [t])
            ]
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in flat):
                continue  # stored on an object/container: escapes
            if isinstance(stmt, ast.AugAssign) and any(
                isinstance(t, ast.Name) and t.id in shared for t in flat
            ):
                continue  # accumulated into a shared tally
            names = [t.id for t in flat if isinstance(t, ast.Name)]
            if names:
                for nm in names:
                    origins.setdefault(nm, set()).add(d.lineno)
                pending.add(d.lineno)
                continue
        immediate.append(d.lineno)

    # propagate taint through assignment chains and find escapes, to a
    # fixpoint (chains are short; this converges in a few passes)
    changed = True
    while changed and pending - escaped:
        changed = False
        for n in own:
            if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)) \
                    and n.value is not None:
                hit = set().union(*(
                    origins[nm] for nm in _names_in(n.value, origins)
                )) if _names_in(n.value, origins) else set()
                if not hit:
                    continue
                targets = (
                    n.targets if isinstance(n, ast.Assign) else [n.target]
                )
                flat = [
                    e for t in targets
                    for e in (getattr(t, "elts", None) or [t])
                ]
                for t in flat:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        if not hit <= escaped:
                            escaped |= hit
                            changed = True
                    elif isinstance(t, ast.Name):
                        if isinstance(n, ast.AugAssign) \
                                and t.id in shared:
                            if not hit <= escaped:
                                escaped |= hit
                                changed = True
                        elif not hit <= origins.setdefault(t.id, set()):
                            origins[t.id] |= hit
                            changed = True
            elif isinstance(n, ast.Call) \
                    and _FUNNEL_RE.search(_call_name(n)):
                hit = set().union(*(
                    origins[nm] for nm in _names_in(n, origins)
                )) if _names_in(n, origins) else set()
                if hit and not hit <= escaped:
                    escaped |= hit
                    changed = True
            elif isinstance(n, ast.Return) and n.value is not None:
                hit = set().union(*(
                    origins[nm] for nm in _names_in(n.value, origins)
                )) if _names_in(n.value, origins) else set()
                if hit and not hit <= escaped:
                    escaped |= hit
                    changed = True

    msg = (
        "wall-clock delta outside the accounting funnel in a "
        "dispatch hot module: this timing never reaches obs.flight "
        "(note_window/note_device/attribute) or a recording sink, so "
        "the attribution ledger's sums-to-wall invariant cannot see "
        "it (docs/OBSERVABILITY.md 'Attribution ledgers'); route it "
        "through the funnel or suppress with justification"
    )
    return [
        Finding("KAO114", path, ln, msg)
        for ln in sorted(set(immediate) | (pending - escaped))
    ]


# ---------------------------------------------------------------- KAO115

# the mesh hot modules: every shard_map/pjit here carries the
# bit-parity sharding contract (ISSUE 19, docs/MESH.md)
_MESH_HOT_MARKER = "parallel/"
# dispatch wrappers and the kwargs that make their placements explicit
_SHARDMAP_NAMES = {"shard_map", "_shard_map"}
_PJIT_NAMES = {"pjit"}


def _is_devices_call(node: ast.AST) -> bool:
    """``jax.devices()`` / ``jax.local_devices()`` (or the bare names
    when imported directly) — the device-list snapshot shapes."""
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    if not d or d[-1] not in ("devices", "local_devices"):
        return False
    return len(d) == 1 or d[0] == "jax"


def _devices_call_in(node: ast.AST) -> ast.Call | None:
    for sub in ast.walk(node):
        if _is_devices_call(sub):
            return sub
    return None


def _rule_mesh_sharding(tree, path, rel) -> list[Finding]:
    """Flag two mesh-contract hazards in the ``parallel/`` hot modules:

    - ``shard_map``/``pjit`` call sites missing explicit placement
      kwargs (``in_specs``+``out_specs`` for shard_map,
      ``in_shardings``+``out_shardings`` for pjit): an omitted spec
      lets the partitioner pick replication, and the sharded replay of
      a bucket silently stops being bit-identical to the unsharded
      trajectory (docs/MESH.md 'Parity contract');
    - ``jax.devices()`` snapshots frozen across mesh rebuilds: a
      module-scope assignment, a default-argument value, or a device
      list bound in a ``make_*`` factory scope and read from a nested
      def (the closure the factory returns). The per-bucket sharding
      search rebuilds the mesh between solves, so any frozen list is
      the stale-mesh bug class — call ``jax.devices()`` at dispatch
      time or accept the mesh as a parameter."""
    if _MESH_HOT_MARKER not in rel:
        return []
    out: list[Finding] = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        d = _dotted(n.func)
        name = d[-1] if d else ""
        if name in _SHARDMAP_NAMES:
            need = ("in_specs", "out_specs")
        elif name in _PJIT_NAMES:
            need = ("in_shardings", "out_shardings")
        else:
            continue
        missing = [k for k in need if _kw(n, k) is None]
        if missing:
            out.append(Finding(
                "KAO115", path, n.lineno,
                f"{name}(...) without explicit "
                f"{'/'.join(missing)}: implicit placements let the "
                "partitioner choose replication and break the "
                "sharded-vs-unsharded bit-parity contract "
                "(docs/MESH.md); state every in/out sharding"))
    # module-scope device snapshot: frozen at import, blind to every
    # later mesh rebuild
    for stmt in tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)) \
                and stmt.value is not None:
            call = _devices_call_in(stmt.value)
            if call is not None:
                out.append(Finding(
                    "KAO115", path, call.lineno,
                    "jax.devices() snapshotted at module scope: the "
                    "list freezes at import and a rebuilt mesh "
                    "(make_mesh/make_solve_mesh) never sees it; call "
                    "at dispatch time (docs/MESH.md)"))
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        defaults = list(fn.args.defaults) + [
            d for d in fn.args.kw_defaults if d is not None
        ]
        for dflt in defaults:
            call = _devices_call_in(dflt)
            if call is not None:
                out.append(Finding(
                    "KAO115", path, call.lineno,
                    f"jax.devices() in a default argument of "
                    f"{fn.name}(): evaluated once at def time and "
                    "frozen across mesh rebuilds (stale-mesh bug "
                    "class); default to None and resolve inside the "
                    "body (docs/MESH.md)"))
        if not fn.name.lstrip("_").startswith("make"):
            continue
        # device lists bound in the factory scope...
        dev_names: set[str] = set()
        for node in _walk_own_scope(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    and node.value is not None \
                    and _devices_call_in(node.value) is not None:
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    for e in (getattr(t, "elts", None) or [t]):
                        if isinstance(e, ast.Name):
                            dev_names.add(e.id)
        if not dev_names:
            continue
        # ...read from a nested def: the returned closure pins the
        # snapshot for its whole lifetime
        for inner in ast.walk(fn):
            if inner is fn or not isinstance(
                inner, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            shadowed = _bound_names(inner)
            for node in ast.walk(inner):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in dev_names
                    and node.id not in shadowed
                ):
                    out.append(Finding(
                        "KAO115", path, node.lineno,
                        f"device list '{node.id}' captured from the "
                        f"enclosing {fn.name}() factory scope into a "
                        "closure: the snapshot outlives every mesh "
                        "rebuild (stale-mesh bug class); resolve "
                        "devices per dispatch or take the mesh as a "
                        "parameter (docs/MESH.md)"))
    return out
