"""Durable per-cluster plan store (docs/WATCH.md).

One JSON record per named cluster under an OPERATOR-chosen directory
(``--watch-dir``; clients never name paths): the cluster state as of
its latest epoch, the last certified plan and the epoch it was solved
for, and a summary of that plan's report. The write discipline is the
same one ``utils.checkpoint`` uses for solver checkpoints:

- **atomic write-rename**: the record is written to a ``.tmp`` sibling,
  flushed AND fsynced, then ``os.replace``d over the real name — a
  ``kill -9`` at any instant leaves either the old complete record or
  the new complete record, never a torn file;
- **fingerprint-verified load**: the record embeds a SHA-256 over its
  canonical payload; a record that fails the check (bit rot, a partial
  copy restored from backup, hand editing) is reported as corrupt and
  treated as absent rather than silently trusted — epoch fencing from
  a corrupt epoch would reject a healthy client stream.

After a restart the registry reloads each cluster lazily on first
touch, so the event stream resumes at exactly the persisted epoch.
"""

from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path

from ..obs import log as _olog
from .events import ClusterState, valid_cluster_id

__all__ = ["PlanStore", "StoreRecord"]

_RECORD_VERSION = 1


class StoreRecord:
    """One cluster's durable record: ``state`` (latest epoch), the
    last certified ``plan``/``plan_epoch``/``plan_report`` (None until
    the first solve lands), and ``pre_plan`` — the ground-truth
    assignment as it stood immediately BEFORE the last plan merge, the
    rewind point a rollout ``start`` executes from (docs/ROLLOUT.md)."""

    __slots__ = ("state", "plan", "plan_epoch", "plan_report",
                 "pre_plan")

    def __init__(self, state: ClusterState, plan: dict | None = None,
                 plan_epoch: int | None = None,
                 plan_report: dict | None = None,
                 pre_plan: dict | None = None):
        self.state = state
        self.plan = plan
        self.plan_epoch = plan_epoch
        self.plan_report = plan_report
        self.pre_plan = pre_plan


def _payload(rec: StoreRecord) -> dict:
    return {
        "version": _RECORD_VERSION,
        "state": rec.state.to_dict(),
        "plan": rec.plan,
        "plan_epoch": rec.plan_epoch,
        "plan_report": rec.plan_report,
        "pre_plan": rec.pre_plan,
    }


def _fingerprint(payload: dict) -> str:
    canon = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


class PlanStore:
    """Filesystem-backed cluster records; every public method is safe
    to call concurrently for DIFFERENT clusters (the manager serializes
    per cluster)."""

    def __init__(self, root: str | Path):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, cluster_id: str) -> Path:
        if not valid_cluster_id(cluster_id):
            raise ValueError(f"bad cluster id {cluster_id!r}")
        return self.root / f"{cluster_id}.json"

    def save(self, rec: StoreRecord) -> None:
        """Atomically persist ``rec`` (write tmp, fsync, rename)."""
        path = self._path(rec.state.cluster_id)
        payload = _payload(rec)
        payload["fingerprint"] = _fingerprint(
            {k: v for k, v in payload.items() if k != "fingerprint"}
        )
        tmp = path.with_suffix(".json.tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def load(self, cluster_id: str) -> StoreRecord | None:
        """The cluster's verified record, or None (absent OR corrupt —
        a corrupt record is logged and ignored, never trusted)."""
        path = self._path(cluster_id)
        if not path.exists():
            return None
        try:
            with open(path) as f:
                payload = json.load(f)
            fp = payload.pop("fingerprint", None)
            if fp != _fingerprint(payload):
                _olog.error("watch_store_corrupt", cluster=cluster_id,
                            path=str(path))
                return None
            if payload.get("version") != _RECORD_VERSION:
                _olog.warn("watch_store_version_skew",
                           cluster=cluster_id,
                           version=payload.get("version"))
                return None
            return StoreRecord(
                state=ClusterState.from_dict(payload["state"]),
                plan=payload.get("plan"),
                plan_epoch=payload.get("plan_epoch"),
                plan_report=payload.get("plan_report"),
                pre_plan=payload.get("pre_plan"),
            )
        except (OSError, ValueError, KeyError) as e:
            _olog.error("watch_store_unreadable", cluster=cluster_id,
                        error=repr(e)[:200])
            return None

    def list_clusters(self) -> list[str]:
        return sorted(
            p.stem for p in self.root.glob("*.json")
            if valid_cluster_id(p.stem)
        )

    # -- rollout records (docs/ROLLOUT.md) ------------------------------
    # Same write discipline, separate namespace: rollout progress lives
    # under ``<root>/rollout/<cluster>.json`` (a subdirectory, not a
    # suffix — cluster ids may contain dots, so ``foo.rollout.json``
    # would collide with a legal cluster named ``foo.rollout`` in
    # ``list_clusters``). The payload is the executor's serialized
    # :class:`~..rollout.state.RolloutRecord`; this layer only verifies
    # integrity, never interprets it.

    def _rollout_path(self, cluster_id: str) -> Path:
        if not valid_cluster_id(cluster_id):
            raise ValueError(f"bad cluster id {cluster_id!r}")
        return self.root / "rollout" / f"{cluster_id}.json"

    def save_rollout(self, cluster_id: str, record: dict) -> None:
        """Atomically persist one rollout record (write tmp, fsync,
        rename — the :meth:`save` discipline verbatim)."""
        path = self._rollout_path(cluster_id)
        path.parent.mkdir(parents=True, exist_ok=True)
        payload = {"version": _RECORD_VERSION, "rollout": record}
        payload["fingerprint"] = _fingerprint(payload)
        tmp = path.with_suffix(".json.tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def load_rollout(self, cluster_id: str) -> dict | None:
        """The verified rollout record payload, or None (absent OR
        corrupt — logged and ignored, never trusted)."""
        path = self._rollout_path(cluster_id)
        if not path.exists():
            return None
        try:
            with open(path) as f:
                payload = json.load(f)
            fp = payload.pop("fingerprint", None)
            if fp != _fingerprint(payload):
                _olog.error("rollout_store_corrupt", cluster=cluster_id,
                            path=str(path))
                return None
            if payload.get("version") != _RECORD_VERSION:
                _olog.warn("rollout_store_version_skew",
                           cluster=cluster_id,
                           version=payload.get("version"))
                return None
            return payload["rollout"]
        except (OSError, ValueError, KeyError) as e:
            _olog.error("rollout_store_unreadable", cluster=cluster_id,
                        error=repr(e)[:200])
            return None
