"""Per-cluster watch state machine: fencing, coalescing, backpressure.

One :class:`WatchRegistry` owns every watched cluster. For each event
(``docs/WATCH.md``):

1. **Epoch fencing** — every event carries a client epoch; only an
   epoch STRICTLY greater than the cluster's latest is admitted. A
   stale or replayed epoch raises :class:`FencedEpoch` (the serve
   layer's structured 409) BEFORE any state change and provably
   without a solve — application is idempotent because a duplicate
   can never get in twice.
2. **Apply + persist** — the pure transition (``events.apply_event``)
   runs under the cluster lock and the new state is durably persisted
   (``store.PlanStore``) before anything else happens; a crash after
   the ack can replay nothing and forget nothing.
3. **Single-flight solve with storm coalescing** — the first event on
   an idle cluster takes the *solver role*: it solves the latest state
   (warm-started from the last certified plan) and returns the plan.
   Events arriving while a solve is in flight are applied, persisted,
   and acknowledged immediately (``status: "accepted"``); the
   in-flight solve's :class:`~..resilience.budget.Budget` is cancelled
   (it is now solving a superseded epoch — the engine retires it at
   the next chunk boundary via the existing ``deadline_truncated``
   rung), and ONE re-solve of the latest state runs afterwards on a
   drain thread, no matter how many events the burst held.
4. **Backpressure** — when more than ``max_backlog`` events pile up
   behind one in-flight solve, further events raise :class:`StormShed`
   (the serve layer's 503 ``event_storm``) with a retry hint derived
   from the coalescing window. Nothing already admitted is ever
   dropped.

The registry is transport-free: ``solve_fn(state, prev_plan, budget)
-> (plan_dict, report_dict)`` is injected by the serve layer (queue +
breaker + metrics), the CLI replay, and the bench harness alike.
"""

from __future__ import annotations

import threading
import time
from dataclasses import replace

from ..models.cluster import Assignment
from ..obs import flight as _oflight
from ..obs import log as _olog
from ..resilience.budget import Budget, backoff_s
from .events import ClusterState, EventError, apply_event, valid_cluster_id
from .store import PlanStore, StoreRecord

__all__ = ["WatchRegistry", "FencedEpoch", "StormShed"]

DEFAULT_WINDOW_S = 0.05
DEFAULT_MAX_BACKLOG = 256
# a drain re-solve that keeps failing retries this many times (jittered
# backoff between attempts) before giving the solver role back; the
# durable state is intact throughout and the next admitted event
# re-solves the latest state
DRAIN_RETRIES = 3


class FencedEpoch(Exception):
    """A stale or replayed epoch hit the fence: nothing was applied,
    no solve ran."""

    def __init__(self, cluster_id: str, got: int, current: int,
                 plan_epoch: int | None):
        super().__init__(
            f"epoch {got} is not newer than cluster {cluster_id!r}'s "
            f"current epoch {current}"
        )
        self.cluster_id = cluster_id
        self.got = got
        self.current = current
        self.plan_epoch = plan_epoch


class StormShed(Exception):
    """Event-storm backpressure: too many events piled up behind one
    in-flight solve; the client should retry after the hint."""

    def __init__(self, cluster_id: str, backlog: int,
                 retry_after_s: float):
        super().__init__(
            f"event storm on cluster {cluster_id!r}: {backlog} events "
            "already coalescing behind the in-flight solve"
        )
        self.cluster_id = cluster_id
        self.backlog = backlog
        self.retry_after_s = retry_after_s


class _Cluster:
    __slots__ = ("lock", "state", "plan", "plan_epoch", "plan_report",
                 "pre_plan", "rollout_hold", "solving",
                 "active_budget", "pending_events")

    def __init__(self):
        self.lock = threading.Lock()
        self.state: ClusterState | None = None
        self.plan: dict | None = None
        self.plan_epoch: int | None = None
        self.plan_report: dict | None = None
        # the assignment as it stood immediately BEFORE the last plan
        # merge: committing a plan ASSUMES the operator applies it, and
        # a rollout `start` revisits that assumption — it rewinds the
        # ground truth here and executes the plan wave by wave
        # (docs/ROLLOUT.md)
        self.pre_plan: dict | None = None
        # True while a rollout owns this cluster's ground truth: set
        # by begin_execution, cleared by end_execution / re-bootstrap,
        # restored from the durable rollout record after a restart.
        # Read UNDER c.lock at commit time — the hold decision and the
        # commit are one atomic step, so a rollout starting mid-solve
        # can never lose a merge/hold race
        self.rollout_hold = False
        self.solving = False
        self.active_budget: Budget | None = None
        self.pending_events = 0


def _report_summary(report: dict) -> dict:
    """The scalar slice of a solve report worth persisting per cluster."""
    keys = (
        "solver", "replica_moves", "leader_changes", "objective_weight",
        "objective_upper_bound", "feasible", "proven_optimal",
        "solver_wall_clock_s", "total_wall_clock_s",
        "solver_warm_started", "solver_engine", "degradations",
    )
    return {k: report[k] for k in keys if k in report}


def _merge_plan(current: Assignment, plan: Assignment) -> Assignment:
    """Adopt the plan's replica lists into ``current`` by partition key,
    keeping partitions the plan does not know (added by events that
    landed while the solve ran) untouched."""
    plan_by = plan.by_key()
    parts = []
    for p in current.partitions:
        q = plan_by.get(p.key)
        parts.append(replace(p, replicas=list(q.replicas)) if q else p)
    return Assignment(partitions=parts, version=current.version)


class WatchRegistry:
    def __init__(self, solve_fn, store: PlanStore | None = None, *,
                 window_s: float = DEFAULT_WINDOW_S,
                 max_backlog: int = DEFAULT_MAX_BACKLOG,
                 solve_budget_s: float | None = None):
        self.solve_fn = solve_fn
        self.store = store
        self.window_s = max(float(window_s), 0.0)
        self.max_backlog = max(int(max_backlog), 1)
        self.solve_budget_s = solve_budget_s
        # streaming plan rollout hook (docs/ROLLOUT.md), registered by
        # rollout.exec.RolloutManager. While a cluster's rollout holds
        # the ground truth (``_Cluster.rollout_hold``, maintained via
        # begin_execution/end_execution and read atomically with the
        # commit), a delta solve's commit persists the PLAN but does
        # NOT fold it into the assignment (the cluster is mid-move;
        # truth advances wave by wave via :meth:`commit_assignment`),
        # and the committed plan is offered to ``replan_fn`` so the
        # remaining waves re-pack against the partially-moved state.
        # Lock ordering: the hook is only ever called while this
        # registry does NOT hold the cluster lock — the rollout side
        # takes its own lock first, then ours (strictly rollout ->
        # cluster, never the reverse).
        self.replan_fn = None
        self._lock = threading.Lock()
        self._clusters: dict[str, _Cluster] = {}
        self._counters = {
            "events_total": 0,        # admitted (post-fence) events
            "fenced_total": 0,        # stale/replayed epochs rejected
            "coalesced_total": 0,     # events acked into a pending re-solve
            "superseded_total": 0,    # in-flight solves cancelled
            "storm_sheds_total": 0,   # events refused by backpressure
            "solves_total": 0,        # delta solves completed
            "warm_solves_total": 0,   # ... that actually warm-started
            "solve_errors_total": 0,
        }

    # -- bookkeeping ----------------------------------------------------

    def _count(self, **updates) -> None:
        with self._lock:
            for k, v in updates.items():
                self._counters[k] += v

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._counters)
        out["clusters"] = len(self._clusters)
        out["window_s"] = self.window_s
        out["max_backlog"] = self.max_backlog
        out["durable"] = int(self.store is not None)
        return out

    def _cluster(self, cluster_id: str) -> _Cluster:
        """The in-memory entry, lazily restored from the durable store
        (first touch after a restart resumes at the persisted epoch)."""
        with self._lock:
            c = self._clusters.get(cluster_id)
            if c is None:
                c = self._clusters[cluster_id] = _Cluster()
        with c.lock:
            if c.state is None and self.store is not None:
                rec = self.store.load(cluster_id)
                if rec is not None:
                    c.state = rec.state
                    c.plan = rec.plan
                    c.plan_epoch = rec.plan_epoch
                    c.plan_report = rec.plan_report
                    c.pre_plan = rec.pre_plan
                    # a restart mid-rollout must keep holding the
                    # ground truth (docs/ROLLOUT.md): restore the hold
                    # from the durable rollout record's status — but
                    # ONLY for the current generation (a record that
                    # predates a re-bootstrap is a dead world and must
                    # not freeze plan merges forever)
                    ro = self.store.load_rollout(cluster_id)
                    c.rollout_hold = bool(
                        ro is not None
                        and ro.get("status") not in ("done",
                                                     "rolled_back")
                        and int(ro.get("generation", 0))
                        == rec.state.generation
                    )
        return c

    def _persist(self, state: ClusterState, plan: dict | None,
                 plan_epoch: int | None,
                 plan_report: dict | None,
                 pre_plan: dict | None = None) -> None:
        """Durably save one record. Caller holds ``c.lock`` and commits
        the same values to the in-memory cluster ONLY after this
        returns: a save that raises (disk full, EIO) must leave memory
        and disk agreeing — an in-memory epoch that advanced past the
        stored one would fence the client's retry of an event that was
        never durably recorded."""
        if self.store is not None and state is not None:
            self.store.save(StoreRecord(
                state=state, plan=plan, plan_epoch=plan_epoch,
                plan_report=plan_report, pre_plan=pre_plan,
            ))

    # -- read surface ---------------------------------------------------

    def list_clusters(self) -> list[str]:
        with self._lock:
            known = set(self._clusters)
        if self.store is not None:
            known |= set(self.store.list_clusters())
        return sorted(known)

    def get_cluster(self, cluster_id: str) -> dict | None:
        if not valid_cluster_id(cluster_id):
            raise EventError(f"bad cluster id {cluster_id!r}")
        c = self._cluster(cluster_id)
        with c.lock:
            if c.state is None:
                return None
            return {
                "cluster_id": cluster_id,
                "epoch": c.state.epoch,
                "brokers": list(c.state.brokers),
                "drained": list(c.state.drained),
                "racks": (
                    c.state.topology.racks() if c.state.topology else []
                ),
                "partitions": len(c.state.assignment.partitions),
                # the current GROUND-TRUTH assignment: equals the last
                # plan between rollouts, but mid-rollout it is the
                # partially-moved cluster the waves have built so far
                "assignment": c.state.assignment.to_dict(),
                "rf": c.state.rf,
                "plan_epoch": c.plan_epoch,
                "plan": c.plan,
                "plan_report": c.plan_report,
                "solving": c.solving,
                "pending_events": c.pending_events,
            }

    def topology_of(self, cluster_id: str):
        """The cluster's current :class:`~..models.cluster.Topology`
        (None when unracked/unknown) — the rollout packer's rack-cap
        input."""
        c = self._cluster(cluster_id)
        with c.lock:
            return c.state.topology if c.state is not None else None

    def commit_assignment(self, cluster_id: str, targets) -> dict:
        """Fold externally-executed replica movements into the
        cluster's ground-truth assignment — the rollout executor's wave
        apply/rollback path (docs/ROLLOUT.md). ``targets`` is an
        iterable of ``(topic, partition, replicas)``; partitions not
        named are untouched, and naming a partition the cluster does
        not know is an :class:`EventError` (a wave can never invent
        state). Persist-before-commit like every other mutation; the
        cluster EVENT epoch does not move — waves are fenced by the
        rollout's own epoch sequence. Returns the new assignment
        dict."""
        c = self._cluster(cluster_id)
        with c.lock:
            if c.state is None:
                raise EventError(f"unknown cluster {cluster_id!r}")
            by = {(t, int(p)): [int(b) for b in r]
                  for t, p, r in targets}
            known = {(p.topic, p.partition)
                     for p in c.state.assignment.partitions}
            unknown = sorted(set(by) - known)
            if unknown:
                raise EventError(
                    f"wave names unknown partition(s) {unknown[:5]}"
                )
            parts = [
                replace(p, replicas=list(
                    by.get((p.topic, p.partition), p.replicas)
                ))
                for p in c.state.assignment.partitions
            ]
            new_assignment = Assignment(
                partitions=parts, version=c.state.assignment.version,
            )
            new_state = replace(c.state, assignment=new_assignment)
            # the optimistic-merge assumption is dead once a wave has
            # physically moved the truth: drop the rewind point so a
            # LATER rollout can never rewind past executed work
            self._persist(new_state, c.plan, c.plan_epoch,
                          c.plan_report, None)
            c.state = new_state
            c.pre_plan = None
            return new_assignment.to_dict()

    def begin_execution(self, cluster_id: str) -> dict:
        """Rollout ``start`` (docs/ROLLOUT.md): the committed plan is a
        DESTINATION, not an applied fact. Rewind the ground-truth
        assignment to the pre-plan truth captured at the last merge —
        CONSUMING the rewind point, so a later start after this rollout
        completes can never rewind real executed state to a stale base
        — and raise the hold: until :meth:`end_execution`, delta-solve
        commits persist their plan without merging it. Returns the
        base assignment dict the rollout executes from."""
        c = self._cluster(cluster_id)
        with c.lock:
            if c.state is None:
                raise EventError(f"unknown cluster {cluster_id!r}")
            if (c.pre_plan is not None and c.plan is not None
                    and c.state.assignment.to_dict() == c.plan):
                base = Assignment.from_dict(c.pre_plan)
                new_state = replace(c.state, assignment=base)
                self._persist(new_state, c.plan, c.plan_epoch,
                              c.plan_report, None)
                c.state = new_state
            elif c.pre_plan is not None:
                # stale rewind point (events moved the world since the
                # merge): consume it DURABLY, or a crash could
                # resurrect it for a later start
                self._persist(c.state, c.plan, c.plan_epoch,
                              c.plan_report, None)
            c.pre_plan = None
            c.rollout_hold = True
            return c.state.assignment.to_dict()

    def end_execution(self, cluster_id: str) -> None:
        """The rollout reached a terminal state (done / rolled_back):
        release the ground-truth hold — future plan commits merge
        normally again."""
        c = self._cluster(cluster_id)
        with c.lock:
            c.rollout_hold = False

    def assignment_of(self, cluster_id: str) -> dict | None:
        """The current ground-truth assignment alone — the rollout
        replan path's accessor (``get_cluster`` serializes the whole
        view; this serializes one assignment)."""
        c = self._cluster(cluster_id)
        with c.lock:
            return (c.state.assignment.to_dict()
                    if c.state is not None else None)

    def plan_info(self, cluster_id: str) -> dict | None:
        """The certified plan + its epoch + the cluster generation,
        WITHOUT serializing the assignment (the plan is stored as a
        dict already, so this is reference-cheap) — the rollout
        ``start``/fence path's accessor."""
        c = self._cluster(cluster_id)
        with c.lock:
            if c.state is None:
                return None
            return {
                "plan": c.plan,
                "plan_epoch": c.plan_epoch,
                "generation": c.state.generation,
            }

    # -- the delta path -------------------------------------------------

    def handle_event(self, cluster_id: str, ev: dict) -> dict:
        """Apply one fenced event; returns the response body. Raises
        :class:`EventError` (bad request), :class:`FencedEpoch` (409),
        :class:`StormShed` (503), or whatever the injected solver
        raises."""
        if not valid_cluster_id(cluster_id):
            raise EventError(
                f"bad cluster id {cluster_id!r} (want "
                "[A-Za-z0-9][A-Za-z0-9._-]{0,63})"
            )
        if not isinstance(ev, dict):
            raise EventError("event must be a JSON object")
        c = self._cluster(cluster_id)
        with c.lock:
            # fencing FIRST, against the persisted-or-live epoch: a
            # replayed epoch must cause no state change and no solve
            epoch = ev.get("epoch")
            if c.state is not None and isinstance(epoch, int) \
                    and not isinstance(epoch, bool) \
                    and epoch <= c.state.epoch:
                self._count(fenced_total=1)
                _olog.warn("watch_epoch_fenced", cluster=cluster_id,
                           got=epoch, current=c.state.epoch)
                raise FencedEpoch(cluster_id, epoch, c.state.epoch,
                                  c.plan_epoch)
            # backpressure BEFORE mutation: an admitted event is never
            # dropped, so admission is where the storm is refused
            if c.solving and c.pending_events >= self.max_backlog:
                self._count(storm_sheds_total=1)
                raise StormShed(
                    cluster_id, c.pending_events,
                    retry_after_s=max(self.window_s * 2.0, 0.25),
                )
            new_state = apply_event(c.state, cluster_id, ev)
            # a (re-)bootstrap re-declares the ground truth: the old
            # pre-plan rewind point describes a dead world, and any
            # in-flight rollout's hold is released (its record is
            # generation-fenced on the rollout side)
            pre = None if ev.get("type") == "bootstrap" else c.pre_plan
            # persist BEFORE the in-memory commit: if the save raises,
            # the epoch has not advanced and the client's retry of the
            # same event is admitted, not fenced
            self._persist(new_state, c.plan, c.plan_epoch,
                          c.plan_report, pre)
            c.state = new_state
            c.pre_plan = pre
            if ev.get("type") == "bootstrap":
                c.rollout_hold = False
            self._count(events_total=1)
            if c.solving:
                # coalesce: ack now, cancel the superseded in-flight
                # solve (ONE cancel per solve), let the drain thread
                # re-solve the latest state once
                c.pending_events += 1
                self._count(coalesced_total=1)
                if c.active_budget is not None \
                        and not c.active_budget.cancelled:
                    c.active_budget.cancel()
                    self._count(superseded_total=1)
                    _olog.log("watch_solve_superseded",
                              cluster=cluster_id, epoch=c.state.epoch)
                return {
                    "cluster_id": cluster_id,
                    "status": "accepted",
                    "epoch": c.state.epoch,
                    "coalesced": True,
                    "pending_events": c.pending_events,
                    "plan_epoch": c.plan_epoch,
                }
            # idle cluster: this thread takes the solver role
            c.solving = True
        try:
            result, retained = self._solve_once(cluster_id, c)
        except BaseException:
            self._count(solve_errors_total=1)
            with c.lock:
                c.active_budget = None
                # events that coalesced behind this failing solve were
                # acked 202 and must not strand: keep the solver role
                # and hand it to a drain thread (bounded retries
                # there). We still hold the role here (solving never
                # went False), so this decision cannot race a new
                # solver.
                has_pending = c.pending_events > 0
                if not has_pending:
                    c.solving = False
            if has_pending:
                self._spawn_drain(cluster_id, c)
            raise
        if retained:
            self._spawn_drain(cluster_id, c)
        return result

    def _solve_once(self, cluster_id: str, c: _Cluster) -> tuple:
        """Run one solve of the cluster's LATEST state (caller holds
        the solver role) and commit the plan. Returns ``(response_body,
        retained)`` where ``retained`` says whether the commit KEPT the
        solver role (events arrived mid-solve, so the caller must
        drain). ``retained`` is decided under the same lock as the
        commit — callers must act on it rather than re-reading
        ``c.solving``, which by then may be a NEW solver's True (the
        role is released inside the commit, and a fresh event can claim
        it the moment the lock drops)."""
        with c.lock:
            target = c.state
            c.pending_events = 0
            budget = Budget(self.solve_budget_s)
            c.active_budget = budget
            prev_plan = (
                Assignment.from_dict(c.plan) if c.plan else None
            )
        # flight-record tagging (obs.flight): any engine solve the
        # injected solve_fn runs on THIS thread lands as kind="delta"
        # with the cluster/epoch identity — the CLI --events replay and
        # bench's --replay-day get per-event flight records for free.
        # (serve's solve_fn hops to a worker thread, where contextvars
        # do not follow; it re-tags inside the worker job itself.)
        with _oflight.context("delta", cluster=cluster_id,
                              epoch=target.epoch):
            plan_dict, report = self.solve_fn(target, prev_plan, budget)
        warm = bool(report.get("solver_warm_started")
                    or report.get("warm_started"))
        self._count(solves_total=1, warm_solves_total=int(warm))
        committed = False
        hold = False
        with c.lock:
            # the plan is the cluster's assignment going forward: the
            # next event diffs against it, so per-event move counts
            # stay per-event. Events that landed DURING the solve may
            # have grown the partition set — merge, never overwrite.
            # Persist first (see _persist): a failed save commits
            # nothing in memory. EXCEPT: a re-bootstrap that coalesced
            # behind this solve re-declared the whole assignment (the
            # generation bumped) — merging this plan over it would
            # clobber the operator's declared ground truth with replica
            # lists from a dead world, so nothing is committed and the
            # drain re-solve plans against the new reality instead.
            if c.state.generation == target.generation:
                summary = _report_summary(report)
                # mid-rollout the assignment is NOT the plan: the
                # waves advance it (commit_assignment); the plan is
                # the destination the remaining waves chase. On a
                # normal merge the pre-merge assignment is kept as the
                # rewind point a later rollout `start` executes from.
                # The hold is read HERE, under the same lock as the
                # commit — a rollout starting mid-solve either lands
                # its begin_execution before this commit (we hold) or
                # after it (it rewinds the merged truth); no ordering
                # loses the race.
                hold = c.rollout_hold
                if hold:
                    merged = c.state.assignment
                    pre = c.pre_plan
                else:
                    merged = _merge_plan(
                        c.state.assignment,
                        Assignment.from_dict(plan_dict)
                    )
                    pre = c.state.assignment.to_dict()
                new_state = replace(c.state, assignment=merged)
                self._persist(new_state, plan_dict, target.epoch,
                              summary, pre)
                c.plan = plan_dict
                c.plan_epoch = target.epoch
                c.plan_report = summary
                c.state = new_state
                c.pre_plan = pre
                committed = True
            superseded = budget.cancelled
            c.active_budget = None
            retained = c.pending_events > 0
            if not retained:
                c.solving = False
        _olog.log("watch_plan", cluster=cluster_id,
                  plan_epoch=target.epoch, warm=warm,
                  superseded=superseded,
                  moves=report.get("replica_moves"),
                  feasible=report.get("feasible"))
        if committed and hold and self.replan_fn is not None:
            # mid-rollout re-plan (docs/ROLLOUT.md): the new plan was
            # solved against the partially-moved truth; hand it to the
            # rollout so the REMAINING waves chase it. Outside c.lock
            # (the hook takes the rollout lock, then may re-enter ours)
            # and exception-proofed on the hook's side.
            self.replan_fn(cluster_id, plan_dict, target.epoch)
        return {
            "cluster_id": cluster_id,
            "status": "planned",
            "epoch": target.epoch,
            "plan_epoch": target.epoch,
            "assignment": plan_dict,
            "report": report,
            "superseded": superseded,
        }, retained

    def _spawn_drain(self, cluster_id: str, c: _Cluster) -> None:
        """Drain thread: the CALLER must hold the solver role when it
        spawns this (``c.solving`` True and no other thread running
        ``_solve_once``) — the role transfers to the thread. Each lap
        waits one coalescing window for the burst to settle, then ONE
        re-solve of the latest state; the loop continues only while its
        OWN commit retained the role (the ``retained`` flag
        ``_solve_once`` decides under the commit lock). It never reads
        ``c.solving`` as a reason to solve — once a commit releases the
        role, a fresh event can claim it the instant the lock drops,
        and a re-read True would be that NEW solver's role; two threads
        in ``_solve_once`` would race commits (epoch regression,
        double-reset of ``pending_events``). A failing re-solve retries
        with jittered backoff up to ``DRAIN_RETRIES`` times — events
        behind it were acked 202 and must not strand — then gives the
        role back; the durable state is intact and the next admitted
        event re-solves the latest state."""

        def run():
            attempts = 0
            while True:
                if self.window_s > 0:
                    time.sleep(self.window_s)
                try:
                    _, retained = self._solve_once(cluster_id, c)
                except BaseException as e:
                    self._count(solve_errors_total=1)
                    attempts += 1
                    _olog.error("watch_drain_solve_failed",
                                cluster=cluster_id, attempt=attempts,
                                error=repr(e)[:200])
                    with c.lock:
                        # the failed solve's budget is dead: an event
                        # landing during the backoff must not "cancel"
                        # it and inflate superseded_total
                        c.active_budget = None
                        if attempts >= DRAIN_RETRIES:
                            c.solving = False
                    if attempts >= DRAIN_RETRIES:
                        return
                    time.sleep(backoff_s(attempts))
                    continue
                attempts = 0
                if not retained:
                    return  # our commit released the role: quiet

        threading.Thread(target=run, daemon=True,
                         name=f"kao-watch-{cluster_id}").start()
