"""Typed cluster-change events and their state transitions.

The delta API's vocabulary is the change sequence the source README
motivates (rolling decommissions, failure response, RF changes) made
explicit: each event is a small JSON object carrying a client ``epoch``
and a ``type``, and applying it to a :class:`ClusterState` is a PURE
function — no I/O, no solver — so fencing, replay, and the event-day
bench all reuse one transition implementation.

Grammar (``docs/WATCH.md``):

=================  ========================================================
``bootstrap``      full state: ``assignment`` (reassignment JSON),
                   ``brokers`` (list or range string), optional
                   ``topology``/``rf`` — registers or re-registers the
                   cluster
``broker_add``     ``brokers`` + optional ``racks`` (id->rack) or ``rack``
``broker_remove``  ``brokers`` — gone from the cluster (and its topology)
``broker_drain``   ``brokers`` — stays racked, must hold no replicas
``rack_fail``      ``rack`` — every broker of that rack drains at once
``partition_growth``  ``topic`` + ``add`` (+ ``rf`` for a new topic):
                   new partitions appear with EMPTY current replica
                   lists — placing them costs moves, which is honest:
                   the data copy is real
``rf_change``      ``rf``: an int for all topics or a topic->int object
=================  ========================================================

Malformed events raise :class:`EventError` (the serve layer's 400);
semantically impossible states (every broker drained, RF above the
surviving broker count) surface when the instance is built, as 422s.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field, replace

from ..models.cluster import (
    Assignment,
    PartitionAssignment,
    Topology,
    parse_broker_list,
)

__all__ = [
    "EVENT_TYPES", "ClusterState", "EventError", "validate_event",
    "apply_event",
]

EVENT_TYPES = (
    "bootstrap", "broker_add", "broker_remove", "broker_drain",
    "rack_fail", "partition_growth", "rf_change",
)

# cluster ids become file names in the plan store: one conservative
# charset, validated at the door
_ID_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9._-]{0,63}")


class EventError(ValueError):
    """A malformed event (unknown type, missing/mistyped field)."""


def valid_cluster_id(cluster_id: str) -> bool:
    return isinstance(cluster_id, str) and bool(
        _ID_RE.fullmatch(cluster_id)
    )


@dataclass
class ClusterState:
    """Everything the optimizer needs to know about one named cluster,
    as of ``epoch``: the current assignment, the eligible (non-drained)
    broker list, the rack topology over ALL known brokers (drained
    brokers stay racked — they may come back), and the target RF."""

    cluster_id: str
    epoch: int
    assignment: Assignment
    brokers: list[int]
    topology: Topology | None = None
    rf: int | dict | None = None
    # brokers known to the cluster but currently drained/failed (kept
    # so a later broker_add can bring one back without re-racking it)
    drained: list[int] = field(default_factory=list)
    # bumped on every (re-)bootstrap: a solve committed against an
    # older generation must NOT merge its plan into a re-declared
    # assignment (the operator's bootstrap is the new ground truth)
    generation: int = 0

    def to_dict(self) -> dict:
        return {
            "cluster_id": self.cluster_id,
            "epoch": self.epoch,
            "assignment": self.assignment.to_dict(),
            "brokers": list(self.brokers),
            "topology": (
                self.topology.to_dict() if self.topology else None
            ),
            "rf": self.rf,
            "drained": list(self.drained),
            "generation": self.generation,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ClusterState":
        topo = d.get("topology")
        return cls(
            cluster_id=d["cluster_id"],
            epoch=int(d["epoch"]),
            assignment=Assignment.from_dict(d["assignment"]),
            brokers=[int(b) for b in d["brokers"]],
            topology=Topology.from_dict(topo) if topo else None,
            rf=d.get("rf"),
            drained=[int(b) for b in d.get("drained", [])],
            generation=int(d.get("generation", 0)),
        )


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise EventError(msg)


def _is_int_key(k) -> bool:
    """JSON object keys are strings; a broker-id key must parse as an
    int so ``apply_event``'s ``int(k)`` can never raise out of the
    validated path (a raw ValueError there would surface as a
    misleading 422 and abort a CLI replay mid-stream)."""
    try:
        int(k)
        return True
    except (TypeError, ValueError):
        return False


def _event_brokers(ev: dict) -> list[int]:
    spec = ev.get("brokers")
    if isinstance(spec, str):
        try:
            return parse_broker_list(spec)
        except ValueError as e:
            raise EventError(f"bad 'brokers' range string: {e}") from e
    _require(
        isinstance(spec, list) and spec and all(
            isinstance(b, int) and not isinstance(b, bool) for b in spec
        ),
        "'brokers' must be a non-empty list of ints or a range string",
    )
    return list(spec)


def _validate_rf_field(rf) -> None:
    if rf is None:
        return
    if isinstance(rf, bool) or not isinstance(rf, (int, dict)):
        raise EventError("'rf' must be an int or a topic->int object")
    if isinstance(rf, int):
        _require(rf >= 1, "'rf' must be >= 1")
        return
    for k, v in rf.items():
        _require(
            isinstance(k, str) and isinstance(v, int)
            and not isinstance(v, bool) and v >= 1,
            "'rf' object must map topic names to ints >= 1",
        )


def validate_event(ev) -> dict:
    """Schema-check one event; returns it unchanged. Raises
    :class:`EventError` on any malformation — epochs are validated here
    structurally (a non-negative int); MONOTONICITY is the manager's
    job (it owns the per-cluster latest epoch)."""
    _require(isinstance(ev, dict), "event must be a JSON object")
    etype = ev.get("type")
    _require(
        etype in EVENT_TYPES,
        f"unknown event type {etype!r}; valid: {list(EVENT_TYPES)}",
    )
    epoch = ev.get("epoch")
    _require(
        isinstance(epoch, int) and not isinstance(epoch, bool)
        and epoch >= 0,
        "'epoch' must be a non-negative int",
    )
    if etype == "bootstrap":
        _require("assignment" in ev, "bootstrap needs 'assignment'")
        _require("brokers" in ev, "bootstrap needs 'brokers'")
        _event_brokers(ev)
        _validate_rf_field(ev.get("rf"))
        topo = ev.get("topology")
        _require(
            topo is None or isinstance(topo, dict) or topo == "even-odd",
            "'topology' must be a broker->rack object, 'even-odd', "
            "or null",
        )
    elif etype in ("broker_add", "broker_remove", "broker_drain"):
        _event_brokers(ev)
        if etype == "broker_add":
            racks = ev.get("racks")
            _require(
                racks is None or (
                    isinstance(racks, dict) and all(
                        isinstance(v, str) for v in racks.values()
                    ) and all(
                        _is_int_key(k) for k in racks
                    )
                ),
                "'racks' must map integer broker ids to rack names",
            )
            rack = ev.get("rack")
            _require(
                rack is None or isinstance(rack, str),
                "'rack' must be a string",
            )
    elif etype == "rack_fail":
        _require(
            isinstance(ev.get("rack"), str) and ev["rack"],
            "rack_fail needs a non-empty 'rack' string",
        )
    elif etype == "partition_growth":
        _require(
            isinstance(ev.get("topic"), str) and ev["topic"],
            "partition_growth needs a non-empty 'topic' string",
        )
        add = ev.get("add")
        _require(
            isinstance(add, int) and not isinstance(add, bool)
            and 1 <= add <= 1_000_000,
            "'add' must be an int in [1, 1000000]",
        )
        rf = ev.get("rf")
        _require(
            rf is None or (
                isinstance(rf, int) and not isinstance(rf, bool)
                and rf >= 1
            ),
            "partition_growth 'rf' must be an int >= 1",
        )
    elif etype == "rf_change":
        _require("rf" in ev, "rf_change needs 'rf'")
        _validate_rf_field(ev["rf"])
        _require(ev["rf"] is not None, "rf_change 'rf' may not be null")
    return ev


def _bootstrap_state(cluster_id: str, ev: dict,
                     generation: int = 0) -> ClusterState:
    try:
        assignment = Assignment.from_dict(ev["assignment"])
    except (KeyError, TypeError, ValueError) as e:
        raise EventError(f"bad bootstrap 'assignment': {e}") from e
    brokers = _event_brokers(ev)
    topo = ev.get("topology")
    try:
        if topo == "even-odd":
            all_ids = sorted(set(brokers) | set(assignment.broker_ids()))
            topology = Topology.even_odd(all_ids)
        elif isinstance(topo, dict):
            topology = Topology.from_dict(topo)
        else:
            topology = None
    except Exception as e:
        raise EventError(f"bad bootstrap 'topology': {e}") from e
    return ClusterState(
        cluster_id=cluster_id,
        epoch=int(ev["epoch"]),
        assignment=assignment,
        brokers=sorted(set(brokers)),
        topology=topology,
        rf=ev.get("rf"),
        generation=generation,
    )


def _drop_brokers(state: ClusterState, ids: list[int], *,
                  forget: bool) -> ClusterState:
    known = set(state.brokers) | set(state.drained)
    unknown = sorted(set(ids) - known)
    _require(not unknown, f"unknown broker(s) {unknown}")
    brokers = [b for b in state.brokers if b not in set(ids)]
    _require(
        bool(brokers),
        "event would leave the cluster with zero eligible brokers",
    )
    drained = sorted(set(state.drained) | set(ids)) if not forget else [
        b for b in state.drained if b not in set(ids)
    ]
    topology = state.topology
    if forget and topology is not None:
        rack_of = {
            b: r for b, r in topology.rack_of.items() if b not in set(ids)
        }
        topology = Topology(rack_of=rack_of)
    return replace(state, brokers=brokers, drained=drained,
                   topology=topology)


def apply_event(state: ClusterState | None, cluster_id: str,
                ev: dict) -> ClusterState:
    """The pure state transition: ``(state, event) -> new state`` with
    the event's epoch stamped on. ``state`` is None only for the first
    event of an unknown cluster, which must be a bootstrap."""
    ev = validate_event(ev)
    etype = ev["type"]
    if state is None:
        _require(
            etype == "bootstrap",
            f"cluster {cluster_id!r} is unknown; the first event must "
            "be a 'bootstrap'",
        )
        return _bootstrap_state(cluster_id, ev)
    if etype == "bootstrap":
        # re-registration (operator rebuilt the cluster record): the
        # fencing contract still applies — the manager admitted this
        # epoch as newer before calling here. The generation bump keeps
        # an in-flight solve from merging its stale plan over the
        # re-declared assignment at commit.
        return _bootstrap_state(cluster_id, ev,
                                generation=state.generation + 1)

    epoch = int(ev["epoch"])
    if etype == "broker_add":
        ids = _event_brokers(ev)
        already = sorted(set(ids) & set(state.brokers))
        _require(not already, f"broker(s) {already} already eligible")
        topology = state.topology
        racks = ev.get("racks") or {}
        if ev.get("rack"):
            racks = {**{str(b): ev["rack"] for b in ids}, **racks}
        if racks:
            rack_of = dict(topology.rack_of if topology else {})
            for b, r in racks.items():
                rack_of[int(b)] = str(r)
            topology = Topology(rack_of=rack_of)
        elif topology is not None:
            missing = [
                b for b in ids
                if b not in topology.rack_of and b not in state.drained
            ]
            _require(
                not missing,
                f"racked topology requires a rack for new broker(s) "
                f"{missing} (pass 'racks' or 'rack')",
            )
        state = replace(
            state,
            brokers=sorted(set(state.brokers) | set(ids)),
            drained=[b for b in state.drained if b not in set(ids)],
            topology=topology,
        )
    elif etype == "broker_remove":
        state = _drop_brokers(state, _event_brokers(ev), forget=True)
    elif etype == "broker_drain":
        state = _drop_brokers(state, _event_brokers(ev), forget=False)
    elif etype == "rack_fail":
        _require(
            state.topology is not None,
            "rack_fail on a cluster with no topology",
        )
        rack = ev["rack"]
        _require(
            rack in state.topology.racks(),
            f"unknown rack {rack!r}; cluster has "
            f"{state.topology.racks()}",
        )
        ids = [
            b for b in state.brokers
            if state.topology.rack(b) == rack
        ]
        _require(
            bool(ids),
            f"rack {rack!r} has no eligible brokers left to fail",
        )
        state = _drop_brokers(state, ids, forget=False)
    elif etype == "partition_growth":
        topic, add = ev["topic"], int(ev["add"])
        existing = [
            p for p in state.assignment.partitions if p.topic == topic
        ]
        rf = ev.get("rf")
        if rf is None:
            _require(
                bool(existing),
                f"new topic {topic!r} needs an explicit 'rf'",
            )
            rf = max(len(p.replicas) for p in existing)
            if isinstance(state.rf, int):
                rf = state.rf
            elif isinstance(state.rf, dict) and topic in state.rf:
                rf = state.rf[topic]
        next_id = 1 + max(
            (p.partition for p in existing), default=-1
        )
        # new partitions hold no data yet: an EMPTY current replica
        # list means zero preservation weight, so the solver places
        # them wherever balance wants — and the move count honestly
        # charges the initial copies
        grown = Assignment(
            partitions=state.assignment.partitions + [
                PartitionAssignment(topic=topic, partition=next_id + i,
                                    replicas=[])
                for i in range(add)
            ],
            version=state.assignment.version,
        )
        # the model derives a partition's RF from its current replica
        # list unless told otherwise; empty lists MUST be told
        new_rf = state.rf
        if new_rf is None:
            new_rf = {topic: int(rf)}
        elif isinstance(new_rf, dict):
            new_rf = {**new_rf, topic: int(rf)}
        elif int(rf) != int(new_rf):
            # an int rf covers every topic; an explicit different rf
            # for the grown topic forces the per-topic form
            new_rf = {
                t: int(new_rf) for t in {
                    p.topic for p in state.assignment.partitions
                }
            }
            new_rf[topic] = int(rf)
        state = replace(state, assignment=grown, rf=new_rf)
    elif etype == "rf_change":
        rf = ev["rf"]
        if isinstance(rf, dict):
            known = {p.topic for p in state.assignment.partitions}
            unknown = sorted(set(rf) - known)
            _require(
                not unknown,
                f"rf_change names unknown topic(s) {unknown}",
            )
            merged = (
                dict(state.rf) if isinstance(state.rf, dict) else {}
            )
            merged.update({k: int(v) for k, v in rf.items()})
            rf = merged
        state = replace(state, rf=rf)
    return replace(state, epoch=epoch)
