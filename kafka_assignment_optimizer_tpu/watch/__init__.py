"""Cluster-watch mode (docs/WATCH.md): the optimizer as an ONLINE
service that remembers each named cluster between decisions.

- :mod:`.events` — the typed event grammar (broker add/remove/drain,
  rack failure, partition growth, RF change) and the pure state
  transition each event applies.
- :mod:`.store` — the durable per-cluster plan store: atomic
  write-rename JSON records, fingerprint-verified on load, surviving
  ``kill -9`` mid-write.
- :mod:`.adapt` — warm-start adaptation: evict dead brokers/racks from
  the previous plan, keep surviving replicas in place, fill the holes
  rack-aware; the result seeds ``engine.solve_tpu(warm_start=...)``.
- :mod:`.manager` — epoch fencing (monotonic, structured 409 on stale
  or replayed epochs), event-storm coalescing (a burst on one cluster
  becomes ONE re-solve of the latest state; superseded solves are
  cancelled through their ``resilience.budget.Budget``), and backlog
  backpressure (the ``event_storm`` shed).

The HTTP surface (``POST /clusters/<id>/events``) lives in ``serve``;
everything here is transport-free and unit-testable with a fake solver.
"""

from .events import ClusterState, EventError, apply_event, validate_event
from .manager import FencedEpoch, StormShed, WatchRegistry
from .store import PlanStore

__all__ = [
    "ClusterState", "EventError", "apply_event", "validate_event",
    "FencedEpoch", "StormShed", "WatchRegistry", "PlanStore",
]
