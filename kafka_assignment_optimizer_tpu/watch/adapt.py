"""Warm-start adaptation: previous plan -> candidate for the new state.

After a cluster event the previous certified plan is almost-right:
most partitions' replicas survived the change. This module maps that
plan onto the post-event :class:`ProblemInstance` —

- replicas on surviving eligible brokers STAY IN THEIR SLOTS (slot 0 is
  the leader; keeping it keeps leadership stable) unless a balance band
  provably forces relocation (pass 3 below),
- replicas on dead/drained/failed brokers are EVICTED and their slots
  refilled greedily: a broker not already in the partition, preferring
  racks the partition under-covers and brokers with the least load so
  far (the same instincts the greedy seed has, applied only to holes),
- a previously-unknown partition (growth) is filled entirely greedily,
- when the previous leader died, the first surviving replica is
  promoted (a metadata-only change) before any refill,
- residual band violations are REPAIRED move-minimally (pass 3): a
  recovery event (``broker_add`` after a rack failure, capacity
  expansion) leaves no holes, so passes 1-2 return the previous plan
  verbatim — concentrated on the old brokers, violating every band the
  restored ones re-tightened. Each repair move strictly lowers the
  total broker+rack band violation and never creates a new one, so only
  moves that EVERY band-feasible plan needs are made.

The result is a structurally valid candidate for
``engine.solve_tpu(warm_start=...)`` — balance bands may still be
violated when the repair gets stuck (the annealer's job), the hard
families (range, fill, uniqueness) are satisfied by construction. When
no valid candidate exists (pathological shrinkage) it returns
``(None, reason)`` and the caller degrades to a cold solve via the
``warm_start_rejected`` rung.
"""

from __future__ import annotations

import numpy as np

from ..models.cluster import Assignment
from ..models.instance import ProblemInstance

__all__ = ["adapt_plan"]


def adapt_plan(
    inst: ProblemInstance, prev_plan: Assignment,
) -> tuple[np.ndarray | None, str]:
    """Adapt ``prev_plan`` to ``inst``; returns ``(candidate, "ok")``
    or ``(None, reason)``."""
    B = inst.num_brokers
    K = inst.num_racks
    R = inst.max_rf
    P = inst.num_parts
    idx_of_broker = {int(b): i for i, b in enumerate(inst.broker_ids)}
    prev_by = {
        (p.topic, p.partition): p.replicas for p in prev_plan.partitions
    }
    rack_of = inst.rack_of_broker[:B]
    topic_names = [inst.topics[t] for t in inst.topic_of_part.tolist()]
    pids = inst.part_id.tolist()
    rfs = inst.rf.tolist()
    if int(max(rfs, default=0)) > B:
        return None, f"rf {max(rfs)} exceeds {B} surviving brokers"

    a = np.full((P, R), B, dtype=np.int32)
    refilled = np.zeros((P, R), dtype=bool)  # slots passes 2-3 placed
    load = np.zeros(B, dtype=np.int64)  # replicas placed per broker
    rtot = np.zeros(K, dtype=np.int64)  # replicas placed per rack
    kept = 0
    evicted = 0
    # pass 1 — survivors stay put, and their load is counted over the
    # WHOLE cluster before any hole is filled: a refill decision that
    # only sees the partitions processed so far systematically overloads
    # the brokers that happen to sort early
    surv_by_p: list[list[int]] = []
    for p in range(P):
        r = rfs[p]
        reps = prev_by.get((topic_names[p], pids[p]), [])
        surv = []
        seen: set[int] = set()
        for b in reps:
            bi = idx_of_broker.get(int(b))
            if bi is not None and bi not in seen:
                surv.append(bi)
                seen.add(bi)
        evicted += max(len(reps) - len(surv), 0)
        surv = surv[:r]
        kept += len(surv)
        # survivors keep their relative order: the surviving leader (or
        # the first surviving follower, promoted) lands in slot 0
        for s, bi in enumerate(surv):
            a[p, s] = bi
            load[bi] += 1
            rtot[rack_of[bi]] += 1
        surv_by_p.append(surv)
    # pass 2 — fill the holes against the instance's OWN balance bands
    # (broker_hi / rack_hi / part_rack_hi), preferring the least-loaded
    # broker among those that keep every cap satisfiable; leader-band
    # repair is the caller's exact reseat, not ours
    b_hi = int(inst.broker_hi)
    for p in range(P):
        r = rfs[p]
        surv = surv_by_p[p]
        if len(surv) >= r:
            continue
        # per-partition rack histogram of the survivors
        pr = np.zeros(K, dtype=np.int64)
        for bi in surv:
            pr[rack_of[bi]] += 1
        cap = int(inst.part_rack_hi[p])
        in_part = set(surv)
        for s in range(len(surv), r):
            # candidate brokers not already hosting this partition;
            # prefer racks under the diversity cap, brokers/racks under
            # their balance caps, then least load
            best = -1
            best_key = None
            for bi in range(B):
                if bi in in_part:
                    continue
                k = rack_of[bi]
                key = (
                    0 if pr[k] < cap else 1,
                    0 if load[bi] < b_hi else 1,
                    0 if rtot[k] < int(inst.rack_hi[k]) else 1,
                    int(pr[k]),
                    int(load[bi]),
                    bi,
                )
                if best_key is None or key < best_key:
                    best, best_key = bi, key
            if best < 0:
                return None, (
                    f"partition {topic_names[p]}/{pids[p]} cannot "
                    f"fill rf={r} from {B} brokers"
                )
            a[p, s] = best
            refilled[p, s] = True
            in_part.add(best)
            pr[rack_of[best]] += 1
            rtot[rack_of[best]] += 1
            load[best] += 1
    rebalanced = _repair_bands(inst, a, refilled, load, rtot, rfs)
    # structural self-check (cheap; the engine re-validates anyway)
    valid = inst.slot_valid
    if (a[valid] >= B).any() or (a[valid] < 0).any():
        return None, "adaptation left unfilled valid slots"
    return a, (
        f"ok kept={kept} evicted={evicted} rebalanced={rebalanced}"
    )


def _repair_bands(
    inst: ProblemInstance, a: np.ndarray, refilled: np.ndarray,
    load: np.ndarray, rtot: np.ndarray, rfs: list[int],
) -> int:
    """Pass 3 — move-minimal broker/rack band repair, in place.

    Donor/receiver pairs are admitted only when the move (a) serves at
    least one band deficit — donor over ``broker_hi`` or its rack over
    ``rack_hi``, receiver under ``broker_lo`` or its rack under
    ``rack_lo`` — and (b) creates none: the donor never drops below a
    low band, the receiver never climbs above a high one (same-rack
    moves leave rack totals untouched and skip the rack guards). Every
    admitted move lowers the summed band violation by at least one, so
    the loop terminates in at most the initial violation count. Within
    the chosen pair, a slot passes 2-3 already placed is relocated
    first (it is a move either way — relocating it costs nothing
    extra); survivors move only when no such slot fits, and the leader
    slot last. Returns the number of moves made; on a stuck repair the
    residual violations simply remain for the annealer."""
    B = inst.num_brokers
    K = inst.num_racks
    P = inst.num_parts
    b_lo, b_hi = int(inst.broker_lo), int(inst.broker_hi)
    r_lo = np.asarray(inst.rack_lo[:K], dtype=np.int64)
    r_hi = np.asarray(inst.rack_hi[:K], dtype=np.int64)
    caps = np.asarray(inst.part_rack_hi[:P], dtype=np.int64)
    rk = np.asarray(inst.rack_of_broker[:B], dtype=np.int64)

    def band_viol() -> int:
        return int(
            np.maximum(load - b_hi, 0).sum()
            + np.maximum(b_lo - load, 0).sum()
            + np.maximum(rtot - r_hi, 0).sum()
            + np.maximum(r_lo - rtot, 0).sum()
        )

    viol = band_viol()
    if not viol:
        return 0
    same = rk[:, None] == rk[None, :]
    moves = 0
    for _ in range(viol):
        gain = (
            (load > b_hi).astype(np.int64)[:, None]
            + (load < b_lo).astype(np.int64)[None, :]
            + np.where(
                same, 0,
                (rtot[rk] > r_hi[rk]).astype(np.int64)[:, None]
                + (rtot[rk] < r_lo[rk]).astype(np.int64)[None, :],
            )
        )
        ok = (
            (load > b_lo)[:, None] & (load < b_hi)[None, :]
            & (same | ((rtot[rk] > r_lo[rk])[:, None]
                       & (rtot[rk] < r_hi[rk])[None, :]))
        )
        np.fill_diagonal(ok, False)
        gain = np.where(ok, gain, 0)
        if int(gain.max()) <= 0:
            break
        pairs = sorted(
            ((-int(gain[d, r]), -int(load[d]), int(load[r]), d, r)
             for d, r in np.argwhere(gain > 0).tolist()),
        )
        moved = False
        for _g, _ld, _lr, bd, br in pairs:
            kd, kr = int(rk[bd]), int(rk[br])
            cand = (a == bd).any(axis=1) & ~(a == br).any(axis=1)
            if kd != kr:
                # per-partition replica count in the receiver's rack
                in_kr = (
                    (a < B) & (rk[np.minimum(a, B - 1)] == kr)
                ).sum(axis=1)
                cand &= in_kr < caps
            ps = np.nonzero(cand)[0]
            if ps.size == 0:
                continue
            pick = None
            for p in ps.tolist():
                ss = [s for s in range(rfs[p]) if int(a[p, s]) == bd]
                s = next(
                    (x for x in reversed(ss) if refilled[p, x]), ss[-1]
                )
                score = (bool(refilled[p, s]), s)
                if pick is None or score > pick[0]:
                    pick = (score, p, s)
            _, p, s = pick
            a[p, s] = br
            refilled[p, s] = True
            load[bd] -= 1
            load[br] += 1
            rtot[kd] -= 1
            rtot[kr] += 1
            moves += 1
            moved = True
            break
        if not moved or not band_viol():
            break
    return moves
