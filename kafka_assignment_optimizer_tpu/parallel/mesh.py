"""Device mesh + shard_map orchestration (the distributed backend).

The reference is a single-process batch tool with one subprocess call and
no distributed execution anywhere (``/root/reference/README.md:1-201``;
SURVEY.md §2 "parallelism strategies"). The TPU-native scaling axes
(BASELINE.json:5, docs/MESH.md) are:

- **candidate-batch data parallelism**: the chain population is sharded
  over the ``'chains'`` axis of an explicit 2-D ``('chains', 'lanes')``
  named mesh; every device anneals its own shard. The ``'lanes'`` axis
  (size 1 unless a per-bucket sharding decision says otherwise) splits
  the portfolio/batch lane axis over devices, so one dispatch can trade
  chain replicas for lane throughput without a second code path.
- **ICI collectives in the hot loop**: once per round, ``pmax``/``psum``
  inside ``shard_map`` locate the globally best chain and clone it over
  each shard's worst chain (migration), so devices share discoveries
  without host round-trips. Under a lane split the migration collectives
  run over ``('chains', 'cblk')`` — the mesh axis plus the in-shard
  chain-block vmap axis — which spans exactly the logical chain shards
  of the unsplit layout, so every sharding of a bucket replays the same
  trajectory bit-for-bit (the parity contract, docs/MESH.md). The final
  plan selection is a host-side argmax over the per-shard bests (a few
  KB).
- **Per-bucket sharding search**: the (chains × lanes) split is not
  hand-written — ``choose_sharding`` consults an evidence table fed by
  timed candidate dispatches (``run_sharding_search``) through the same
  AOT executable cache and profiler funnel as production solves, in the
  mold of ``engine.choose_megachunk_k``.
  ``KAO_MESH_SHARDING=auto|<dc>x<dl>|off`` forces or disables it.
- **Multi-host (DCN)**: after ``parallel.distributed.init_distributed``
  (CLI/serve ``--distributed``) ``jax.devices()`` is the GLOBAL device
  set, so the same named mesh spans hosts; XLA compiles the migration
  collectives to ride ICI within a slice and DCN across hosts. Only the
  once-per-round few-KB winner broadcast ever crosses DCN — the design
  keeps the hot loop on-chip. The sharding chooser stays at the default
  split under multi-controller SPMD (per-process evidence must not fork
  the program — same discipline as ``engine._resolve_megachunk``).

Works identically on one real TPU, a v5e-8 slice, a multi-host pod
slice, or the CPU test mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``, tests/conftest.py).
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..analysis import sanitize as _san
from ..obs import flight as _flight
from ..obs import prof as _oprof
from ..obs import trace as _otrace
from ..resilience import budget as _rbudget
from ..resilience import chaos as _chaos
from ..resilience import ladder as _ladder
from ..solvers.tpu.arrays import ModelArrays
from ..solvers.tpu.bucket import STATS as _CACHE_STATS

AXIS = "chains"
AXIS_LANES = "lanes"
# in-shard chain-block vmap axis (docs/MESH.md): under a lane split the
# chain axis keeps its FULL logical shard count (= total devices) and
# each device vmaps a block of dl chain shards; migration collectives
# run over (AXIS, _CBLK) so they span the same logical shards as the
# unsplit layout — the bit-parity contract rests on this.
_CBLK = "cblk"


def _shard_map(fn, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions. Newer jax exposes it at the
    top level with varying-manual-axes checking (``check_vma``, which the
    Pallas out_shapes defeat — see the call site); older jax (0.4.x) has
    only ``jax.experimental.shard_map`` whose equivalent knob is
    ``check_rep``. Either way the explicit out_specs carry the contract."""
    top = getattr(jax, "shard_map", None)
    if top is not None:
        return top(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    from jax.experimental.shard_map import shard_map as legacy

    return legacy(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def make_mesh(n_devices: int | None = None,
              lane_devices: int = 1) -> Mesh:
    """Build the named solve mesh: ``lane_devices`` (dl) devices on the
    lane axis, the rest on the chain axis — ``(dc, dl)`` with ``dc * dl
    = n_devices``. The default ``dl = 1`` is layout-identical to the
    historical 1-D chains-only mesh (same device order, same ``P(AXIS)``
    placements), so every existing call site is unchanged."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    dl = max(1, int(lane_devices))
    if n % dl:
        raise ValueError(
            f"lane_devices={dl} does not divide device count {n}"
        )
    dc = n // dl
    mesh = Mesh(np.array(devs).reshape(dc, dl), (AXIS, AXIS_LANES))
    with _MESH_LOCK:
        _MESH_STATE["axes"] = {AXIS: dc, AXIS_LANES: dl}
    return mesh


def mesh_spec(mesh: Mesh) -> tuple[int, int]:
    """The ``(dc, dl)`` axis split of a solve mesh. Tolerates foreign
    meshes (no lane axis → ``dl = 1``) so helper code can interrogate
    any mesh it is handed."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    dl = int(shape.get(AXIS_LANES, 1))
    dc = int(shape.get(AXIS, mesh.devices.size // max(dl, 1)))
    return dc, dl


# ---------------------------------------------------------------------------
# Per-bucket sharding search (docs/MESH.md). The (chains × lanes) axis
# split is CHOSEN, not hand-written: candidate splits are timed through
# the real ``solve_lanes`` dispatch path (AOT exec cache + profiler
# funnel — occupancy and dispatch gaps are the cost signal) and the
# winner persists in an evidence table keyed by bucket, in the mold of
# ``engine.choose_megachunk_k``: the chooser NEVER guesses — it returns
# the default chains-only split until a candidate has real evidence.

MESH_ENV = "KAO_MESH_SHARDING"
# evidence quorum: a spec competes only after this many timed solves
# (search evaluations or production dispatches) stand behind it
MESH_MIN_SOLVES = 2

_MESH_LOCK = threading.Lock()
# last-built mesh axis sizes (healthz/metrics) + running counters
_MESH_STATE: dict = {"axes": {AXIS: 1, AXIS_LANES: 1}}
_MESH_COUNTERS = {"search_evals": 0, "reshard_bytes": 0}
# bucket key -> spec "dcxdl" -> {"solves", "device_s", "lanes"}
_SHARD_EVIDENCE: dict[tuple, dict[str, dict]] = {}


def _spec_str(spec: tuple[int, int]) -> str:
    return f"{spec[0]}x{spec[1]}"


def parse_mesh_sharding(val: str | None = None):
    """Parse ``KAO_MESH_SHARDING`` (or an explicit ``val``):
    ``("auto", None)`` | ``("off", None)`` | ``("spec", (dc, dl))`` |
    ``("invalid", None)``. Invalid values degrade to the default split
    (never crash a solve over an env typo) — the mesh snapshot surfaces
    the raw value so the typo is auditable."""
    if val is None:
        val = os.environ.get(MESH_ENV, "auto")
    v = str(val).strip().lower()
    if v in ("", "auto"):
        return ("auto", None)
    if v in ("off", "0", "none", "false"):
        return ("off", None)
    m = re.fullmatch(r"(\d+)x(\d+)", v)
    if m and int(m.group(1)) > 0 and int(m.group(2)) > 0:
        return ("spec", (int(m.group(1)), int(m.group(2))))
    return ("invalid", None)


def candidate_shardings(n_dev: int, lanes: int) -> list[tuple[int, int]]:
    """The (small) candidate space for one bucket shape: every ``(dc,
    dl)`` with ``dc * dl == n_dev`` and ``dl`` dividing the lane count
    (inert-lane padding already canonicalized ``lanes``). The default
    chains-only split is always first."""
    out = []
    for dl in range(1, max(1, int(n_dev)) + 1):
        if n_dev % dl or dl > lanes or lanes % dl:
            continue
        out.append((n_dev // dl, dl))
    return out


def note_sharding_evidence(bucket_key: tuple, spec: tuple[int, int], *,
                           lanes: int, solves: int,
                           device_s: float) -> None:
    """File one observation for (bucket, spec): ``solves`` lane-batched
    dispatches taking ``device_s`` wall seconds at width ``lanes``.
    Production dispatches and search evaluations both land here — the
    chooser cannot tell them apart and should not."""
    if solves <= 0 or device_s <= 0:
        return
    with _MESH_LOCK:
        rows = _SHARD_EVIDENCE.setdefault(tuple(bucket_key), {})
        row = rows.setdefault(
            _spec_str(spec),
            {"solves": 0, "device_s": 0.0, "lanes": int(lanes)},
        )
        row["solves"] += int(solves)
        row["device_s"] += float(device_s)
        row["lanes"] = int(lanes)


def choose_sharding(bucket_key: tuple | None, n_dev: int, lanes: int, *,
                    multi: bool = False) -> tuple[int, int]:
    """Resolve the (dc, dl) split for one dispatch site. Precedence:
    explicit ``KAO_MESH_SHARDING=<dc>x<dl>`` (validated against the
    bucket shape, default on mismatch), ``off`` → default, else the
    evidence table — the spec with the best lane-solve throughput among
    those with ≥ ``MESH_MIN_SOLVES`` observations, default until any
    challenger qualifies. Multi-controller SPMD always takes the
    default: evidence tables are per-process and a diverging choice
    would fork the compiled program across workers (the same hazard
    ``engine._resolve_megachunk`` guards for megachunk K)."""
    default = (max(1, int(n_dev)), 1)
    mode, spec = parse_mesh_sharding()
    if mode == "off" or mode == "invalid":
        return default
    if mode == "spec":
        dc, dl = spec
        if dc * dl == n_dev and dl >= 1 and lanes % max(dl, 1) == 0 \
                and dl <= lanes:
            return (dc, dl)
        return default
    if multi or n_dev <= 1 or lanes <= 1 or bucket_key is None:
        return default
    valid = set(candidate_shardings(n_dev, lanes))
    with _MESH_LOCK:
        rows = dict(_SHARD_EVIDENCE.get(tuple(bucket_key), {}))
    best, best_rate = default, -1.0
    for name, row in rows.items():
        if row["solves"] < MESH_MIN_SOLVES or row["device_s"] <= 0:
            continue
        try:
            dc, dl = (int(x) for x in name.split("x"))
        except ValueError:
            continue
        if (dc, dl) not in valid:
            continue
        rate = row["solves"] * row["lanes"] / row["device_s"]
        if rate > best_rate or (rate == best_rate and (dc, dl) == default):
            best, best_rate = (dc, dl), rate
    if best != default and best_rate > 0:
        # the default must itself be outscored by real evidence, not
        # lose by forfeit: without a qualified default row the chooser
        # stays home (never guesses)
        d_row = rows.get(_spec_str(default))
        if d_row is None or d_row["solves"] < MESH_MIN_SOLVES:
            return default
        d_rate = d_row["solves"] * d_row["lanes"] / d_row["device_s"]
        if d_rate >= best_rate:
            return default
    return best


def make_solve_mesh(n_devices: int | None = None, *,
                    lanes: int | None = None,
                    bucket_key: tuple | None = None,
                    engine: str = "sweep",
                    multi: bool = False) -> Mesh:
    """Engine-facing mesh factory for one dispatch site: resolves the
    per-bucket (chains × lanes) split and builds the mesh. Single-
    instance sites, the chain engine, and 1-device runs always get the
    default chains-only split (``auto`` → current behavior on 1
    device, per the env contract)."""
    n = len(jax.devices()) if n_devices is None else int(n_devices)
    if lanes is None or lanes <= 1 or engine != "sweep" or n <= 1:
        return make_mesh(n_devices)
    dc, dl = choose_sharding(bucket_key, n, lanes, multi=multi)
    return make_mesh(n_devices, lane_devices=dl)


def note_reshard(state, mesh: Mesh) -> None:
    """Count bytes of carried state that arrive at a dispatch under a
    DIFFERENT sharding than the mesh expects — the resharding transfer
    XLA will insert. Zero on every warm chunk boundary (out_specs hand
    the next chunk a pre-partitioned state); nonzero means a host
    gather or a mesh change broke the handoff, surfaced as
    ``kao_mesh_reshard_bytes_total``."""
    dc, dl = mesh_spec(mesh)
    spec = P(AXIS, AXIS_LANES) if dl > 1 else P(AXIS)
    expected = jax.sharding.NamedSharding(mesh, spec)
    bad = 0
    for x in jax.tree_util.tree_leaves(state):
        sh = getattr(x, "sharding", None)
        ndim = getattr(x, "ndim", None)
        if sh is None or ndim is None:
            continue
        try:
            if not sh.is_equivalent_to(expected, ndim):
                bad += int(x.size) * int(x.dtype.itemsize)
        except Exception:
            continue
    if bad:
        with _MESH_LOCK:
            _MESH_COUNTERS["reshard_bytes"] += bad


def mesh_counters() -> dict:
    with _MESH_LOCK:
        return dict(_MESH_COUNTERS)


def mesh_snapshot() -> dict:
    """/healthz evidence: last-built axis sizes, the env override mode,
    running counters, and the per-bucket evidence table with each
    bucket's current choice."""
    mode, spec = parse_mesh_sharding()
    with _MESH_LOCK:
        axes = dict(_MESH_STATE.get("axes") or {})
        counters = dict(_MESH_COUNTERS)
        table = {
            k: {s: dict(row) for s, row in rows.items()}
            for k, rows in _SHARD_EVIDENCE.items()
        }
    n_dev = axes.get(AXIS, 1) * axes.get(AXIS_LANES, 1)
    buckets = {}
    for k, rows in sorted(table.items()):
        lanes = max((row["lanes"] for row in rows.values()), default=1)
        buckets["x".join(str(x) for x in k)] = {
            "chosen": _spec_str(
                choose_sharding(k, n_dev, lanes)
            ),
            "evidence": rows,
        }
    return {
        "axes": axes,
        "sharding_mode": mode,
        "sharding_env": os.environ.get(MESH_ENV, ""),
        "forced_spec": _spec_str(spec) if spec else None,
        "min_solves": MESH_MIN_SOLVES,
        "counters": counters,
        "buckets": buckets,
    }


def reset_mesh_adapt() -> None:
    """Drop sharding evidence and counters (tests + maintenance)."""
    with _MESH_LOCK:
        _SHARD_EVIDENCE.clear()
        for k in _MESH_COUNTERS:
            _MESH_COUNTERS[k] = 0


def run_sharding_search(
    m_stack,
    lane_seeds,
    keys,
    temps,
    *,
    n_devices: int,
    chains_per_device: int,
    bucket_key: tuple,
    scorer: str = "xla",
    repeats: int = 1,
    check_parity: bool = True,
):
    """Automap-style active search: time every candidate (dc × dl)
    split of this bucket through the REAL ``solve_lanes`` dispatch path
    (AOT executable cache, profiler funnel, donation — nothing
    synthetic), file the observations in the evidence table, and return
    the per-candidate results. The first dispatch per candidate warms
    the executable and is excluded from timing; each timed repeat
    re-inits state (the solver donates it). With ``check_parity`` the
    global winners of every split are compared bit-for-bit against the
    default split — the parity contract as a runtime assert.

    Drive this from bench ``--mesh-bench``, the soak mesh step, or
    warmup; production solves only ever *read* the table."""
    lane_seeds = np.asarray(lane_seeds, np.int32)
    lanes = int(lane_seeds.shape[0])
    results = []
    base_k = None
    for dc, dl in candidate_shardings(n_devices, lanes):
        mesh = make_mesh(n_devices, lane_devices=dl)
        device_s = 0.0
        warm_s = 0.0
        n_timed = 0
        for r in range(int(repeats) + 1):
            state = init_lane_state(
                m_stack, lane_seeds, keys, mesh, chains_per_device
            )
            t0 = time.perf_counter()
            _st, _ba, best_k, _curve = solve_lanes(
                m_stack, mesh, chains_per_device, temps, state=state,
                scorer=scorer,
            )
            jax.block_until_ready(best_k)
            dt = time.perf_counter() - t0
            if r > 0:
                device_s += dt
                warm_s = dt if n_timed == 0 else min(warm_s, dt)
                n_timed += 1
        best_k_host = np.asarray(fetch_global(best_k))
        parity = None
        if check_parity:
            if base_k is None:
                base_k, parity = best_k_host, True
            else:
                parity = bool(np.array_equal(base_k, best_k_host))
        note_sharding_evidence(
            bucket_key, (dc, dl), lanes=lanes, solves=max(n_timed, 1),
            device_s=device_s,
        )
        with _MESH_LOCK:
            _MESH_COUNTERS["search_evals"] += 1
        results.append({
            "spec": _spec_str((dc, dl)),
            "warm_s": warm_s,
            "lanes_per_s": (lanes / warm_s) if warm_s > 0 else 0.0,
            "parity_vs_default": parity,
        })
    return results


# compiled sharded solvers, keyed by (device ids, search params); the
# model and the temperature ladder are runtime arguments, so jax.jit's own
# shape keying handles different instance sizes / schedule lengths and
# *warm re-solves of same-shape instances skip compilation entirely*.
# Bounded: a long-lived service solving a stream of differently sized
# instances must not accumulate executables forever.
_COMPILED: dict[tuple, object] = {}
_COMPILED_MAX = 16
# the serve queue runs solves on several worker threads: the LRU
# refresh (get-then-pop) and eviction must be atomic or a concurrent
# same-key refresh raises KeyError mid-solve
_COMPILED_LOCK = threading.Lock()


# AOT executable cache: the jitted solvers above are further specialized
# by argument SHAPES (jax.jit's internal keying) — with shape bucketing
# (solvers.tpu.bucket) those shapes are canonical bucket shapes, so an
# explicit (solver key, arg-shape signature) -> compiled-executable LRU
# makes warmth observable (hit/miss/compile-seconds counters feed
# /metrics and the bench JSON) and lets a warm solve dispatch the
# compiled object directly. Bounded like _COMPILED; on any AOT
# lower/compile/call failure the jitted function itself is the fallback.
_EXECUTABLES: OrderedDict[tuple, object] = OrderedDict()
_EXECUTABLES_MAX = 32
# serve.py drains its solve queue with several worker threads; the LRU
# bookkeeping (get+move_to_end / insert+evict) must be atomic. Compiles
# and executions run OUTSIDE the lock — only the dict ops are guarded.
_EXECUTABLES_LOCK = threading.Lock()


def clear_exec_cache() -> None:
    """Drop the AOT executable LRU (long-lived services pair this with
    ``jax.clear_caches()`` maintenance)."""
    with _EXECUTABLES_LOCK:
        dropped = list(_EXECUTABLES)
        _EXECUTABLES.clear()
    for key in dropped:
        _san.forget_key(key)  # post-clear compiles are cold, not thrash
        _oprof.forget_key(key)  # cost models share the exec lifecycle


# lane-consolidation ledger (ISSUE 10): which RAW batch widths each
# lane-padded executable bucket has served. One lane-padded executable
# per (brokers, racks, part-bucket, rf-bucket) serves every L in
# 2..Lmax via inert-lane masking (solvers.tpu.bucket.lane_bucket), and
# /healthz's cache section renders this so fleet warmup cost — one lane
# compile per bucket, not one per width — is auditable.
_LANE_SERVED: dict[tuple, dict] = {}
_LANE_SERVED_LOCK = threading.Lock()


def note_lane_serve(bucket_key: tuple, lanes: int,
                    lane_bucket: int) -> None:
    """Record one batched dispatch: ``bucket_key`` is (brokers, racks,
    part-bucket, rf-bucket); ``lanes`` the raw width, ``lane_bucket``
    the padded width actually dispatched."""
    with _LANE_SERVED_LOCK:
        row = _LANE_SERVED.setdefault(
            tuple(bucket_key),
            {"lane_buckets": set(), "served_lane_counts": set(),
             "dispatches": 0},
        )
        row["lane_buckets"].add(int(lane_bucket))
        row["served_lane_counts"].add(int(lanes))
        row["dispatches"] += 1


def lane_serve_report() -> dict:
    """{'BxKxPxR': {lane_buckets, served_lane_counts, dispatches}} —
    the /healthz evidence that one lane-padded executable per bucket is
    serving every batch width."""
    with _LANE_SERVED_LOCK:
        rows = {k: dict(v) for k, v in _LANE_SERVED.items()}
    return {
        "x".join(str(x) for x in k): {
            "lane_buckets": sorted(v["lane_buckets"]),
            "served_lane_counts": sorted(v["served_lane_counts"]),
            "dispatches": v["dispatches"],
        }
        for k, v in sorted(rows.items())
    }


def _arg_signature(args) -> tuple:
    return tuple(
        (tuple(x.shape), str(x.dtype))
        for x in jax.tree_util.tree_leaves(args)
    )


def _lower_and_compile(fn, args):
    """One XLA compile (AOT lower + compile). A separate function so
    tests can monkeypatch it to count real compilations."""
    return fn.lower(*args).compile()


def _args_alive(args) -> bool:
    """False when any array in ``args`` was already consumed by a
    donating dispatch: the jit fallback below would only raise a
    confusing "buffer deleted" error on top of the real one, so the
    original exception should propagate instead."""
    for x in jax.tree_util.tree_leaves(args):
        deleted = getattr(x, "is_deleted", None)
        if callable(deleted):
            try:
                if deleted():
                    return False
            except Exception:
                continue
    return True


# in-flight compile dedup: (solver, shapes) keys whose first caller is
# still inside _lower_and_compile. The serve worker pool runs cold
# same-bucket requests CONCURRENTLY, and without this gate each of them
# would pay the full 26-68 s XLA compile of an identical executable
# (and double-count compile_seconds_total).
_INFLIGHT: dict[tuple, threading.Event] = {}


def _dispatch(fn, solver_key: tuple, args: tuple):
    """Run the solver through the executable cache: reuse the compiled
    executable for this (solver, shapes) key, compile-and-cache on first
    contact (concurrent first contacts on one key wait for the single
    compile instead of duplicating it), and fall back to plain jit
    dispatch if the AOT path fails (version quirks, sharding mismatch) —
    correctness never depends on the cache."""
    key = (solver_key, _arg_signature(args))
    if _chaos.fires("exec_evict"):
        # eviction-storm injection (docs/RESILIENCE.md): the warm
        # executable vanishes under this dispatch, exactly as a stream
        # of distinct bucket shapes would force; the path below must
        # recompile-and-serve, never fail
        clear_exec_cache()
    if _san.enabled() and not _args_alive(args):
        # sanitizer donation guard: refuse to dispatch a state that a
        # donating dispatch already consumed — a clear error here beats
        # XLA's "buffer deleted" deep in the runtime (raises)
        _san.note_donation_reuse(key)
    while True:
        with _EXECUTABLES_LOCK:
            ex = _EXECUTABLES.get(key)
            if ex is not None:
                _EXECUTABLES.move_to_end(key)
                inflight = None
            else:
                inflight = _INFLIGHT.get(key)
                if inflight is None:
                    _INFLIGHT[key] = threading.Event()
        if ex is not None:
            try:
                td = time.perf_counter()
                with _otrace.span("dispatch", cache="hit"):
                    out = ex(*args)
                # ledger dispatch leaf (enqueue-only) + profiler
                # pairing stamp: the engine's retire-side device wait
                # closes this dispatch's occupancy window
                _flight.note_window("dispatch",
                                    time.perf_counter() - td)
                _oprof.note_dispatch(key)
                _CACHE_STATS.record_exec(True)
                _flight.note_dispatch("hit")
                return out
            except Exception:
                with _EXECUTABLES_LOCK:
                    _EXECUTABLES.pop(key, None)
                _san.forget_key(key)  # its next compile is a rebuild
                _oprof.forget_key(key)
                if not _args_alive(args):
                    # a donating executable consumed its buffers before
                    # failing — the jit retry cannot run on dead args
                    raise
                _CACHE_STATS.record_exec(False, fallback=True)
                _flight.note_dispatch("fallback")
                _ladder.note_rung("aot_to_jit", cause="exec_failed")
                td = time.perf_counter()
                try:
                    with _otrace.span("dispatch", cache="fallback"):
                        return fn(*args)
                finally:
                    # jit-fallback enqueue (tracing+compile inclusive)
                    # is dispatch machinery cost; no exec key — the
                    # profiler's roofline skips unprofiled dispatches
                    _flight.note_window("dispatch",
                                        time.perf_counter() - td)
        if inflight is None:
            break  # this thread owns the compile
        # another thread is compiling this exact key: wait for it, then
        # re-check the cache (bounded — a wedged compile must not hang
        # the waiter forever; on timeout fall through to jit dispatch,
        # which serializes on jax's own compile cache anyway)
        if not inflight.wait(timeout=600.0):
            _CACHE_STATS.record_exec(False, fallback=True)
            _flight.note_dispatch("fallback")
            _ladder.note_rung("aot_to_jit", cause="compile_wedged")
            td = time.perf_counter()
            try:
                with _otrace.span("dispatch", cache="fallback"):
                    return fn(*args)
            finally:
                _flight.note_window("dispatch",
                                    time.perf_counter() - td)
    t0 = time.perf_counter()
    try:
        try:
            # compile-failure injection point: raised HERE (host side,
            # before lowering) so the fault takes the same route a real
            # AOT lower/compile error takes — the jit fallback below
            with _otrace.span("compile"):
                _chaos.raise_if("compile_fail")
                ex = _lower_and_compile(fn, args)
            # recompile sentinel (analysis.sanitize): a key compiling
            # past its budget means executable thrash — fail the solve
            # rather than paying 26-68 s per request silently
            _san.note_compile(key)
            # cost-model capture (obs.prof): the XLA cost/memory
            # analysis is compile-time state, captured ONCE here and
            # cached under the exec-cache key — every warm dispatch
            # reuses it with zero recomputation
            _oprof.note_cost_model(key, ex, time.perf_counter() - t0)
            with _otrace.span("dispatch", cache="miss"):
                out = ex(*args)
            # no separate dispatch window on first contact: the
            # enqueue is inside compile_s below (note_compile), and
            # splitting it out would double-count the ledger's leaves
            _oprof.note_dispatch(key)
        except _san.SanitizerError:
            raise  # a tripped sentinel must fail the solve, not fall back
        except Exception:
            if not _args_alive(args):
                raise
            _CACHE_STATS.record_exec(False, fallback=True)
            _flight.note_dispatch("fallback")
            _ladder.note_rung("aot_to_jit", cause="compile_failed")
            td = time.perf_counter()
            try:
                with _otrace.span("dispatch", cache="fallback"):
                    return fn(*args)
            finally:
                _flight.note_window("dispatch",
                                    time.perf_counter() - td)
        compile_s = time.perf_counter() - t0
        _CACHE_STATS.record_exec(False, compile_s=compile_s)
        # per-solve attribution (obs.flight): the ambient accumulator
        # gives THIS solve's flight record its own compile seconds and
        # cache movement, not a racy process-global delta
        _flight.note_compile(compile_s)
        _flight.note_dispatch("miss")
        evicted = []
        with _EXECUTABLES_LOCK:
            _EXECUTABLES[key] = ex
            while len(_EXECUTABLES) > _EXECUTABLES_MAX:
                evicted.append(_EXECUTABLES.popitem(last=False)[0])
        for old in evicted:
            # LRU eviction makes the key's next compile legitimate —
            # the sanitizer's recompile sentinel must not count it,
            # and the cost-model cache follows the same lifecycle
            _san.forget_key(old)
            _oprof.forget_key(old)
        return out
    finally:
        with _EXECUTABLES_LOCK:
            ev = _INFLIGHT.pop(key, None)
        if ev is not None:
            ev.set()


def _compiled_solver(
    mesh: Mesh,
    chains_per_device: int,
    steps_per_round: int,
    engine: str = "chain",
    scorer: str = "xla",
):
    _dc, dl = mesh_spec(mesh)
    if dl > 1:
        raise ValueError(
            "single-instance solvers shard chains only — build the "
            "mesh with lane_devices=1 (make_solve_mesh does)"
        )
    cache_key = (
        tuple(d.id for d in mesh.devices.flat),
        chains_per_device, steps_per_round, engine, scorer,
    )
    with _COMPILED_LOCK:
        fn = _COMPILED.get(cache_key)
        if fn is not None:  # LRU refresh: insertion order tracks recency
            _COMPILED[cache_key] = _COMPILED.pop(cache_key)
    if fn is None:
        # shard_map introduces the mesh axis even for a single device, so
        # the solver always anneals with axis_name set here (collectives
        # over a singleton axis are free)
        if engine == "sweep":
            # the chain engine's per-chain budget is rounds*steps_per_round
            # steps; the sweep engine's sequential budget is len(temps)
            # sweeps (each sweep touches every partition). The sweep
            # engine is STATEFUL: chunked solves thread the full chain
            # populations through, so cutting the ladder for certificate
            # checks / time limits does not restart the search.
            from ..solvers.tpu.sweep import make_sweep_stepper_fn

            solve = make_sweep_stepper_fn(
                chains_per_device, axis_name=AXIS, scorer=scorer
            )

            def shard_fn(m_rep: ModelArrays, state, temps: jax.Array):
                state = jax.tree.map(lambda x: x[0], state)
                state, best_a, best_k, curve = solve(m_rep, state, temps)
                state = jax.tree.map(lambda x: x[None], state)
                return state, best_a[None], best_k[None], curve[None]

            in_specs = (P(), P(AXIS), P())
            out_specs = (P(AXIS), P(AXIS), P(AXIS), P(AXIS))
        else:
            from ..solvers.tpu.anneal import make_solver_fn

            solve = make_solver_fn(
                chains_per_device, steps_per_round, axis_name=AXIS
            )

            def shard_fn(m_rep: ModelArrays, seed_rep: jax.Array,
                         keys: jax.Array, temps: jax.Array):
                best_a, best_k, curve = solve(
                    m_rep, seed_rep, keys[0], temps
                )
                return best_a[None], best_k[None], curve[None]

            in_specs = (P(), P(), P(AXIS), P())
            out_specs = (P(AXIS), P(AXIS), P(AXIS))

        # pallas_call's ShapeDtypeStruct out_shapes carry no vma
        # annotation, which jax>=0.9's varying-manual-axes check
        # rejects inside shard_map (found the hard way: the r2 TPU
        # bench run died here while every CPU test passed, because
        # the Pallas scorer route is TPU-only). The out_specs above
        # are explicit, so the check adds nothing we rely on.
        #
        # Sweep engine: the carried state (populations + per-chain best
        # snapshots + RNG keys) is DONATED — every state leaf has an
        # identically shaped/dtyped/sharded output leaf, so XLA updates
        # the chain populations in HBM in place instead of reallocating
        # the full [n_dev, N, P, R] arrays every chunk. The donation
        # invariant (a state is consumed by exactly one dispatch and
        # never touched again — the engine commits the RETURNED state)
        # is enforced by the runtime even on CPU: reuse raises, which
        # is what tests/test_donation_smoke.py pins for CI.
        fn = jax.jit(
            _shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
            ),
            donate_argnums=(1,) if engine == "sweep" else (),
        )
        with _COMPILED_LOCK:
            # a concurrent builder of the same key may have landed
            # first — keep the existing fn so both callers dispatch one
            # executable (building the jit wrapper twice is cheap; the
            # compile is deduplicated by _dispatch's key)
            fn = _COMPILED.setdefault(cache_key, fn)
            while len(_COMPILED) > _COMPILED_MAX:  # evict oldest
                _COMPILED.pop(next(iter(_COMPILED)))
    return fn, cache_key


def _compiled_lane_solver(
    mesh: Mesh,
    chains_per_device: int,
    steps_per_round: int,
    engine: str = "sweep",
    scorer: str = "xla",
):
    """Jitted shard_map host for the BATCHED lane solvers (L independent
    instances, one padded bucket shape, one dispatch): the same
    chains-over-devices sharding as ``_compiled_solver``, with the lane
    axis vmapped INSIDE each shard — so global state leaves are
    ``[n_dev, L, ...]`` sharded on the device axis, and the per-lane
    migration collectives ride the same mesh axis. When the mesh
    carries a lane split (``dl > 1``, docs/MESH.md) the lane axis is
    ADDITIONALLY sharded over devices: the chain axis keeps its full
    ``n_dev`` logical shards — each device vmaps a block of ``dl`` of
    them under the ``'cblk'`` axis name — and the migration collectives
    run over ``('chains', 'cblk')``, spanning exactly the logical
    shards of the unsplit layout, so the trajectory is bit-identical
    and the global output shapes are unchanged. Cached alongside the
    single-instance solvers (the "lanes" / "lanes@<dc>x<dl>" tag keeps
    the keys disjoint); jit's shape keying handles L, so warm
    same-bucket batches of a new size compile once and then dispatch
    the cached executable."""
    dc, dl = mesh_spec(mesh)
    if dl > 1 and engine != "sweep":
        raise ValueError("lane-axis sharding is sweep-engine only")
    tag = "lanes" if dl == 1 else f"lanes@{dc}x{dl}"
    cache_key = (
        tuple(d.id for d in mesh.devices.flat),
        chains_per_device, steps_per_round, engine, scorer, tag,
    )
    with _COMPILED_LOCK:
        fn = _COMPILED.get(cache_key)
        if fn is not None:
            _COMPILED[cache_key] = _COMPILED.pop(cache_key)
    if fn is None:
        if engine == "sweep" and dl > 1:
            from ..solvers.tpu.sweep import make_lane_stepper_fn

            # local block: state [dl, L/dl, ...], m_stack [L/dl, ...].
            # lax.axis_index(('chains', 'cblk')) inside the stepper is
            # chains_idx * dl + cblk_idx — the row-major identity with
            # the unsplit 1-D layout — so migration elects the same
            # owner chain and clones the same rows, bit-for-bit.
            lane_solve = make_lane_stepper_fn(
                chains_per_device, axis_name=(AXIS, _CBLK), scorer=scorer
            )
            solve = jax.vmap(
                lane_solve, in_axes=(None, 0, None), axis_name=_CBLK
            )

            def shard_fn(m_stack, state, temps: jax.Array):
                return solve(m_stack, state, temps)

            in_specs = (P(AXIS_LANES), P(AXIS, AXIS_LANES), P())
            out_specs = (P(AXIS, AXIS_LANES),) * 4
        elif engine == "sweep":
            from ..solvers.tpu.sweep import make_lane_stepper_fn

            solve = make_lane_stepper_fn(
                chains_per_device, axis_name=AXIS, scorer=scorer
            )

            def shard_fn(m_stack, state, temps: jax.Array):
                state = jax.tree.map(lambda x: x[0], state)
                state, best_a, best_k, curve = solve(m_stack, state, temps)
                state = jax.tree.map(lambda x: x[None], state)
                return state, best_a[None], best_k[None], curve[None]

            in_specs = (P(), P(AXIS), P())
            out_specs = (P(AXIS), P(AXIS), P(AXIS), P(AXIS))
        else:
            from ..solvers.tpu.anneal import make_lane_solver_fn

            solve = make_lane_solver_fn(
                chains_per_device, steps_per_round, axis_name=AXIS
            )

            def shard_fn(m_stack, seeds, keys, temps: jax.Array):
                # seeds [L, P, R] replicated; keys [n_dev, L, 2] sharded
                best_a, best_k, curve = solve(m_stack, seeds, keys[0],
                                              temps)
                return best_a[None], best_k[None], curve[None]

            in_specs = (P(), P(), P(AXIS), P())
            out_specs = (P(AXIS), P(AXIS), P(AXIS))

        # lane state is donated exactly like the single-instance sweep
        # state (same leaf-for-leaf in/out correspondence, with a lane
        # axis after the device axis) — a batched chunk updates all L
        # lanes' populations in place
        fn = jax.jit(
            _shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
            ),
            donate_argnums=(1,) if engine == "sweep" else (),
        )
        with _COMPILED_LOCK:
            fn = _COMPILED.setdefault(cache_key, fn)
            while len(_COMPILED) > _COMPILED_MAX:
                _COMPILED.pop(next(iter(_COMPILED)))
    return fn, cache_key


def _compiled_mega_solver(
    mesh: Mesh,
    chains_per_device: int,
    steps_per_round: int,
    engine: str = "sweep",
    scorer: str = "xla",
    lanes: bool = False,
):
    """Jitted shard_map host for the FUSED megachunk steppers
    (docs/PIPELINE.md): K chunk steps scanned inside one executable,
    single-instance (``lanes=False``) or lane-batched. Cached next to
    the per-chunk solvers under a ``"mega"`` / ``"mega-lanes"`` tag —
    the fused width K is NOT part of this key because jit's shape
    keying (and ``_arg_signature`` in the AOT executable cache) already
    splits on the ``temps [K, c]`` stack, so each (bucket, K) pair owns
    exactly one executable and a warm re-solve at the same width never
    compiles. State donation is identical to the per-chunk path: the
    scan carry's leaves alias the input buffers leaf-for-leaf."""
    if engine != "sweep":
        raise ValueError("megachunk fusion is sweep-engine only")
    dc, dl = mesh_spec(mesh)
    if dl > 1 and not lanes:
        raise ValueError(
            "lane-axis sharding needs the lane-batched stepper — "
            "single-instance megachunks use a lane_devices=1 mesh"
        )
    base_tag = "mega-lanes" if lanes else "mega"
    tag = base_tag if dl == 1 else f"{base_tag}@{dc}x{dl}"
    cache_key = (
        tuple(d.id for d in mesh.devices.flat),
        chains_per_device, steps_per_round, engine, scorer, tag,
    )
    with _COMPILED_LOCK:
        fn = _COMPILED.get(cache_key)
        if fn is not None:
            _COMPILED[cache_key] = _COMPILED.pop(cache_key)
    if fn is None:
        from ..solvers.tpu.sweep import (
            make_mega_lane_stepper_fn,
            make_mega_stepper_fn,
        )

        if dl > 1:
            # same chain-block construction as _compiled_lane_solver;
            # the fused stepper's early-exit pmax additionally spans
            # ('laneblk', 'lanes') — the in-shard lane vmap plus its
            # device-sharded complement — so a certificate anywhere
            # still stops every lane (first-to-certify, PR 11).
            solve_l = make_mega_lane_stepper_fn(
                chains_per_device, axis_name=(AXIS, _CBLK),
                scorer=scorer, mesh_lane_axis=AXIS_LANES,
            )
            solve = jax.vmap(
                solve_l, in_axes=(None, 0, None, None, None, None),
                axis_name=_CBLK,
            )

            def shard_fn(m_arg, state, temps, active, cert_k, cert_mv):
                return solve(m_arg, state, temps, active, cert_k,
                             cert_mv)

            in_specs = (P(AXIS_LANES), P(AXIS, AXIS_LANES), P(), P(),
                        P(), P())
            out_specs = (P(AXIS, AXIS_LANES),) * 8
        else:
            build = (make_mega_lane_stepper_fn if lanes
                     else make_mega_stepper_fn)
            solve = build(chains_per_device, axis_name=AXIS,
                          scorer=scorer)

            def shard_fn(m_arg, state, temps, active, cert_k, cert_mv):
                state = jax.tree.map(lambda x: x[0], state)
                (state, top_a, top_k, cert_a, cert_ok, cert_mvs, curves,
                 execd) = solve(m_arg, state, temps, active, cert_k,
                                cert_mv)
                state = jax.tree.map(lambda x: x[None], state)
                return (state, top_a[None], top_k[None], cert_a[None],
                        cert_ok[None], cert_mvs[None], curves[None],
                        execd[None])

            in_specs = (P(), P(AXIS), P(), P(), P(), P())
            out_specs = (P(AXIS),) * 8
        fn = jax.jit(
            _shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
            ),
            donate_argnums=(1,),
        )
        with _COMPILED_LOCK:
            fn = _COMPILED.setdefault(cache_key, fn)
            while len(_COMPILED) > _COMPILED_MAX:
                _COMPILED.pop(next(iter(_COMPILED)))
    return fn, cache_key


def _mega_args(m_arg, state, temps_stack, active, cert_k, cert_mv):
    from ..solvers.tpu.sweep import MEGA_DISARMED_KEY, MEGA_DISARMED_MOVES

    k_steps = int(np.asarray(temps_stack).shape[0] if hasattr(
        temps_stack, "shape") else len(temps_stack))
    if active is None:
        active = np.ones((k_steps,), bool)
    if cert_k is None:
        cert_k = MEGA_DISARMED_KEY
    if cert_mv is None:
        cert_mv = MEGA_DISARMED_MOVES
    return (
        m_arg, state, jnp.asarray(temps_stack),
        jnp.asarray(np.asarray(active, bool)),
        jnp.asarray(cert_k, jnp.int32), jnp.asarray(cert_mv, jnp.int32),
    )


def solve_megachunk(
    m: ModelArrays,
    mesh: Mesh,
    chains_per_device: int,
    temps_stack: jax.Array,
    state,
    *,
    active=None,
    cert_k=None,
    cert_mv=None,
    steps_per_round: int = 1,
    scorer: str = "xla",
):
    """One fused dispatch over K chunk steps: ``temps_stack [K, c]``
    (every group at one bucket shares c and K — short tails pad temps
    and clear ``active``), state from :func:`init_sweep_state` or any
    prior chunk/megachunk. Returns ``(state', top_a [n_dev, P, R],
    top_k [n_dev], cert_a [n_dev, P, R], cert_ok [n_dev], cert_mv
    [n_dev], curves [n_dev, K, c], execd [n_dev, K])`` — the engine
    expands ``curves``/``execd`` back into per-chunk records. Omitting
    the cert args dispatches the group disarmed (sentinels that never
    fire)."""
    fn, solver_key = _compiled_mega_solver(
        mesh, chains_per_device, steps_per_round, "sweep", scorer
    )
    return _dispatch(fn, solver_key, _mega_args(
        m, state, temps_stack, active, cert_k, cert_mv
    ))


def solve_lanes_megachunk(
    m_stack,
    mesh: Mesh,
    chains_per_device: int,
    temps_stack: jax.Array,
    state,
    *,
    active=None,
    cert_k=None,
    cert_mv=None,
    steps_per_round: int = 1,
    scorer: str = "xla",
):
    """Lane-batched :func:`solve_megachunk`: L instances × K fused
    chunk steps in one dispatch. Lane axes ride after the device axis
    exactly as in :func:`solve_lanes` (``curves [n_dev, L, K, c]``,
    ``execd [n_dev, L, K]``). Batch callers leave the cert args at
    their disarmed defaults — independent instances must not share an
    early exit; portfolio callers arm them to stop every lane on the
    first certificate."""
    fn, solver_key = _compiled_mega_solver(
        mesh, chains_per_device, steps_per_round, "sweep", scorer,
        lanes=True,
    )
    note_reshard(state, mesh)
    return _dispatch(fn, solver_key, _mega_args(
        m_stack, state, temps_stack, active, cert_k, cert_mv
    ))


def init_lane_state(
    m_stack,
    lane_seeds: np.ndarray,
    keys: jax.Array,
    mesh: Mesh,
    chains_per_device: int,
):
    """Initial sweep-engine state for L lanes, tiled over the mesh:
    per-lane analogue of :func:`init_sweep_state` with every leaf
    gaining a lane axis after the device axis — ``a [n_dev, L, N, P,
    R]``, ranks ``[n_dev, L, N]``, per-(device, lane) RNG keys
    ``[n_dev, L, 2]``. Lane l's slice is exactly what
    ``init_sweep_state`` would build for that instance alone with key
    ``keys[l]`` (the B=1 bit-parity anchor).

    ``lane_seeds`` is host numpy ``[L, P, R]`` (padded to the bucket);
    ``keys`` is ``[L, 2]`` per-lane PRNG keys.

    The GLOBAL layout is spec-invariant: leaves are always ``[n_dev, L,
    ...]`` with the chain axis carrying ``n_dev`` logical shards; a
    lane-split mesh (``dl > 1``) merely places them ``P('chains',
    'lanes')`` instead of ``P('chains')`` — same bytes, different
    device assignment — which is what makes every sharding of a bucket
    replay the same trajectory (docs/MESH.md)."""
    n_dev = mesh.devices.size
    n = chains_per_device
    lane_seeds = np.asarray(lane_seeds, np.int32)
    L, n_parts, n_slots = lane_seeds.shape
    _dc, dl = mesh_spec(mesh)
    if L % max(dl, 1):
        raise ValueError(
            f"lane count {L} not divisible by lane axis size {dl}"
        )
    k0, mv0 = _lane_seed_rank_fn()(jnp.asarray(lane_seeds), m_stack)
    k0, mv0 = np.asarray(k0), np.asarray(mv0)  # [L]
    tile = np.broadcast_to(
        lane_seeds[None, :, None], (n_dev, L, n, n_parts, n_slots)
    )
    # per-(device, lane) keys: each lane splits ITS key over the device
    # axis, exactly as the single-instance path splits its one key —
    # [L, n_dev, 2] -> [n_dev, L, 2]. The population/snapshot leaves
    # are independent materialized buffers for the same reason as
    # init_sweep_state: the lane solver donates this state.
    dev_keys = jax.vmap(lambda k: jax.random.split(k, n_dev))(keys)
    state = (
        np.array(tile),
        np.broadcast_to(k0[None, :, None], (n_dev, L, n)).astype(k0.dtype),
        np.broadcast_to(mv0[None, :, None], (n_dev, L, n)).astype(np.int32),
        np.array(tile),
        jnp.transpose(dev_keys, (1, 0, 2)),
    )
    sh = jax.sharding.NamedSharding(
        mesh, P(AXIS, AXIS_LANES) if dl > 1 else P(AXIS)
    )
    return jax.tree.map(lambda x: jax.device_put(x, sh), state)


_LANE_SEED_RANK = None


def _lane_seed_rank_fn():
    """Jitted per-lane (best_key, moves) of the L seed candidates —
    ``(seeds [L, P, R], m_stack) -> ([L], [L])``."""
    global _LANE_SEED_RANK
    if _LANE_SEED_RANK is None:
        from ..ops.score import moves_batch, score_batch
        from ..solvers.tpu.sweep import best_key

        @jax.jit
        def f(seeds, m_stack):
            def one(a, m):
                s = score_batch(a[None], m)
                return (
                    best_key(s.weight, s.penalty)[0],
                    moves_batch(a[None], m)[0],
                )

            return jax.vmap(one)(seeds, m_stack)

        _LANE_SEED_RANK = f
    return _LANE_SEED_RANK


def solve_lanes(
    m_stack,
    mesh: Mesh,
    chains_per_device: int,
    temps: jax.Array,
    state=None,
    lane_seeds=None,
    keys=None,
    engine: str = "sweep",
    steps_per_round: int = 1,
    scorer: str = "xla",
):
    """Run L independent same-bucket instances through ONE batched
    dispatch, chains sharded over ``mesh`` and lanes vmapped inside each
    shard. Sweep engine (stateful): pass ``state`` from
    :func:`init_lane_state` (or a previous chunk); returns ``(state',
    best_a [n_dev, L, P, R], best_k [n_dev, L], curve [n_dev, L,
    sweeps])``. Chain engine: pass ``lane_seeds [L, P, R]`` and ``keys
    [L, 2]``; returns ``(best_a, best_k, curve)`` with the same leading
    axes. Dispatches through the AOT executable cache exactly like the
    single-instance path — a warm same-(bucket, L) batch never
    compiles."""
    fn, solver_key = _compiled_lane_solver(
        mesh, chains_per_device, steps_per_round, engine, scorer
    )
    if engine == "sweep":
        if state is None:
            if lane_seeds is None or keys is None:
                raise ValueError(
                    "sweep lanes need state= or (lane_seeds=, keys=)"
                )
            state = init_lane_state(
                m_stack, lane_seeds, keys, mesh, chains_per_device
            )
        else:
            note_reshard(state, mesh)
        return _dispatch(fn, solver_key, (m_stack, state, temps))
    n_dev = mesh.devices.size
    dev_keys = jnp.transpose(
        jax.vmap(lambda k: jax.random.split(k, n_dev))(keys), (1, 0, 2)
    )
    seeds = jnp.asarray(np.asarray(lane_seeds, np.int32))
    return _dispatch(fn, solver_key, (m_stack, seeds, dev_keys, temps))


def init_sweep_state(
    m: ModelArrays,
    a_seed: jax.Array,
    key: jax.Array,
    mesh: Mesh,
    chains_per_device: int,
):
    """Initial sweep-engine population state, tiled over the mesh:
    every chain on every shard starts at the greedy seed (chains then
    diverge through their per-shard RNG streams), and the per-chain best
    snapshots start AT the seed — the engine can never return a plan
    that ranks below it. The per-shard RNG keys ride in the state, so a
    chunked schedule consumes exactly the stream an uncut one would.

    The state is placed with the SAME NamedSharding the solver's
    out_specs produce — otherwise chunk 0 (host layout) and chunk 1+
    (device layout) would be distinct jit signatures and the heavy
    executable would compile twice."""
    n_dev = mesh.devices.size
    n = chains_per_device
    a = jnp.asarray(a_seed, jnp.int32)
    k0, mv0 = _seed_rank_fn()(a, m)
    n_parts, n_slots = a.shape
    # host-side numpy tiling: the eager jnp broadcast/full ops each
    # compile a tiny executable, and over a tunneled TPU every compile
    # costs a ~0.5 s remote round-trip (r5 cold-start profile); numpy
    # tiles cost ~nothing and device_put ships them without compiling.
    # The current-population and best-snapshot leaves are materialized
    # as two INDEPENDENT buffers (not two views of one broadcast):
    # device_put may zero-copy a contiguous-compatible host view, and
    # with the solver donating the state (in-place chunk updates —
    # docs/PIPELINE.md), two leaves silently sharing one buffer would
    # corrupt each other.
    a_np = np.asarray(a)
    tile = np.broadcast_to(a_np, (n_dev, n, n_parts, n_slots))
    state = (
        np.array(tile),
        np.full((n_dev, n), np.asarray(k0), np.asarray(k0).dtype),
        np.full((n_dev, n), np.asarray(mv0), np.int32),
        np.array(tile),
        jax.random.split(key, n_dev),
    )
    sh = jax.sharding.NamedSharding(mesh, P(AXIS))
    return jax.tree.map(lambda x: jax.device_put(x, sh), state)


_SEED_RANK = None


def _seed_rank_fn():
    """Jitted (best_key, moves) of a single candidate — the eager vmap
    path dispatches hundreds of tiny ops and costs seconds cold."""
    global _SEED_RANK
    if _SEED_RANK is None:
        from ..ops.score import moves_batch, score_batch
        from ..solvers.tpu.sweep import best_key

        @jax.jit
        def f(a, m):
            s = score_batch(a[None], m)
            return (
                best_key(s.weight, s.penalty)[0],
                moves_batch(a[None], m)[0],
            )

        _SEED_RANK = f
    return _SEED_RANK


def solve_on_mesh(
    m: ModelArrays,
    a_seed: jax.Array,
    key: jax.Array,
    mesh: Mesh,
    chains_per_device: int,
    rounds: int,
    steps_per_round: int,
    t_hi: float = 2.5,
    t_lo: float = 0.05,
    engine: str = "chain",
    temps: jax.Array | None = None,
    scorer: str = "xla",
    state=None,
):
    """Run the annealer sharded over `mesh`; returns the per-shard winners
    ``(best_a [n_dev, P, R], best_k [n_dev], curve [n_dev, rounds])`` as
    device arrays — the engine re-scores this final population (Pallas
    kernel on TPU), polishes the champion, and logs the best-score
    curve. ``temps`` (a schedule segment) overrides the default
    ``geometric_temps(t_hi, t_lo, rounds)`` ladder — the engine passes
    per-chunk segments when honoring ``time_limit_s``. ``scorer`` picks
    the sweep engine's bulk-rescoring path (Pallas kernel on TPU).

    The sweep engine is stateful: pass ``state`` (from
    ``init_sweep_state`` or a previous chunk) and the return becomes
    ``(state', best_a, best_k, curve)`` — chunked schedules continue the
    same populations. Without ``state`` the seed is expanded into a
    fresh state first (single-shot path)."""
    from ..solvers.tpu.arrays import geometric_temps

    n_dev = mesh.devices.size
    fn, solver_key = _compiled_solver(
        mesh, chains_per_device, steps_per_round, engine, scorer
    )
    if temps is None:
        temps = geometric_temps(t_hi, t_lo, rounds)
    if engine == "sweep":
        if state is None:
            state = init_sweep_state(
                m, a_seed, key, mesh, chains_per_device
            )
        return _dispatch(fn, solver_key, (m, state, temps))
    keys = jax.random.split(key, n_dev)
    return _dispatch(fn, solver_key, (m, a_seed, keys, temps))


def _fetch_once(x):
    if jax.process_count() == 1:
        return jax.device_get(x)
    from jax.experimental import multihost_utils

    return jax.device_get(
        multihost_utils.process_allgather(x, tiled=True)
    )


def _transfer_retryable(e: BaseException) -> bool:
    """Only genuinely transient transfer faults earn the one retry:
    the injected chaos fault and runtime-transport errors (a tunneled
    TPU dropping a DMA). Anything else — dead buffers, sharding bugs —
    must surface with its real traceback."""
    if _chaos.is_fault(e):
        return True
    msg = f"{type(e).__name__}: {e}"
    return any(s in msg for s in ("UNAVAILABLE", "DEADLINE_EXCEEDED"))


def fetch_global(x):
    """``device_get`` that also works under multi-controller SPMD: a
    global array sharded over a multi-process mesh spans devices this
    process cannot address, so it must be allgathered to every host
    first (a few hundred KB of per-shard winners, outside the hot
    loop). Single-process — the common case — stays a plain transfer.

    One transient-fault retry (jittered backoff): a dropped transfer on
    a tunneled device is recoverable and must not abandon a multi-chunk
    anneal; the ``transfer_retry`` ladder rung records it."""
    tt = time.perf_counter()
    try:
        with _otrace.span("device_transfer"):
            return _fetch_guarded(x)
    finally:
        # ledger transfer leaf: counted once even inside a boundary
        # window (obs.flight.attribute nets leaves out of nests)
        _flight.note_window("transfer", time.perf_counter() - tt)


def _fetch_guarded(x):
    try:
        _chaos.raise_if("device_transfer")
        return _fetch_once(x)
    except Exception as e:
        if not _transfer_retryable(e):
            raise
        if jax.process_count() != 1:
            # multi-controller: the fault was observed by THIS
            # process only — peers may have completed their
            # allgather, and a second collective issued from one
            # process desynchronizes the SPMD program order (the
            # engine holds the same workers-must-agree line for
            # its fallbacks), so the fault surfaces instead of
            # earning a local retry
            raise
        _ladder.note_rung("transfer_retry", error=repr(e)[:200])
        time.sleep(_rbudget.backoff_s(0, base_s=0.05, cap_s=0.5))
        return _fetch_once(x)


class _AsyncFetch:
    """Handle on an in-flight device→host transfer started by
    :func:`fetch_global_async`: the DMA begins at construction (single
    process; multi-controller allgathers cannot start early and stay in
    the blocking ``get``), and ``get()`` materializes the host value —
    idempotently, so trace instrumentation may consume it at a chunk
    boundary while the ladder exit still sees the same array."""

    __slots__ = ("_x", "_val", "_done")

    def __init__(self, x):
        self._x = x
        self._val = None
        self._done = False
        if jax.process_count() == 1:
            for leaf in jax.tree_util.tree_leaves(x):
                start = getattr(leaf, "copy_to_host_async", None)
                if callable(start):
                    try:
                        start()
                    except Exception:
                        # the copy is an optimization only — get()
                        # falls back to the ordinary blocking transfer
                        pass

    def get(self):
        if not self._done:
            self._val = fetch_global(self._x)
            self._x = None  # release the device reference
            self._done = True
        return self._val


def fetch_global_async(x):
    """Start the device→host copy of ``x`` without blocking (the engine
    moves per-chunk curve transfers off the critical path this way: the
    copy overlaps the next chunk's device execution — or, synchronous
    mode, the boundary's host work — and ``.get()`` at the next boundary
    or at ladder exit finds it already resident)."""
    return _AsyncFetch(x)


def best_of(best_a, best_k, curve=None):
    """Host-side argmax over the per-shard winners (the final cross-shard
    reduce — a few KB)."""
    best_a, best_k = fetch_global((best_a, best_k))
    top = int(np.argmax(best_k))
    return best_a[top], int(best_k[top])
