"""Multi-host initialization for the distributed backend.

The reference is a single-process batch tool (its only parallelism is
lp_solve's in-process branch-and-bound, ``/root/reference/README.md:135``);
the TPU build's search engines shard chain populations over every device
of a ``jax.sharding.Mesh``. On a multi-host slice (v5e-16+, or any pod
slice spanning workers) that mesh must cover the GLOBAL device set, which
requires ``jax.distributed.initialize`` before the first backend touch.

After initialization nothing else changes: ``parallel.mesh.make_mesh``
builds over ``jax.devices()`` — already global post-init — and the ICI
migration collectives inside ``shard_map`` (``pmax``/``psum``) are
compiled by XLA to ride ICI within a slice and DCN across hosts. The
model arrays are replicated (a few MB); only the few-KB per-shard
winners cross hosts outside the hot loop.

Execution model: multi-controller SPMD — every worker must run the SAME
program. That is exactly a pod launcher running the CLI on all workers
with the same input (``--distributed``); every worker computes the same
plan and the operator reads worker 0's output. It is NOT the HTTP
service: independent per-host request streams cannot drive matching
collectives, so ``serve`` deliberately has no such flag.

Configuration: on cloud TPU pods (GKE/GCE metadata, SLURM, MPI) jax's
cluster auto-detection — which runs inside ``initialize()`` — finds the
coordinator, process count and process id on its own; explicit clusters
pass ``coordinator_address``/``num_processes``/``process_id``, or export
``JAX_COORDINATOR_ADDRESS`` (the env var jax itself reads) plus
``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID`` (read here — jax's cluster
detection has no generic env-var cluster, and a pod launcher that can
export three variables should not need a SLURM/MPI environment). A
single-host launch with no cluster environment is detected (jax raises
``ValueError`` while resolving the spec) and treated as a no-op, so the
flag is safe to leave on in launch scripts that sometimes run one host.
Genuine multi-host misconfiguration (bad coordinator, timeout) raises —
N workers silently solving alone is worse than an error.
"""

from __future__ import annotations

import os

from ..obs import log as _olog

# the multi-process capability probe's one-line child program: form a
# real 2-process jax.distributed cluster on the CPU backend and run ONE
# cross-process collective (a psum-shaped global reduction over a mesh
# spanning both processes' devices) — the exact operation the sharded
# solve path needs and the operation this repo's jax build rejects
# ("Multiprocess computations aren't implemented on the CPU backend",
# docs/ANALYSIS.md tier-1 triage)
_PROBE_CHILD = r"""
import os, sys
addr, pid = sys.argv[1], int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.distributed.initialize(coordinator_address=addr, num_processes=2,
                           process_id=pid)
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()).reshape(2), ("x",))
arr = jax.make_array_from_callback(
    (2,), NamedSharding(mesh, P("x")),
    lambda idx: np.ones((1,), np.float32) * (pid + 1))
out = jax.jit(lambda a: jnp.sum(a),
              out_shardings=NamedSharding(mesh, P()))(arr)
val = float(np.asarray(jax.device_get(out.addressable_data(0))))
assert val == 3.0, val
print("PROBE_OK", val)
"""

_PROBE_MEMO: tuple[bool, str] | None = None


def probe_multiprocess_cpu(timeout_s: float = 120.0,
                           refresh: bool = False) -> tuple[bool, str]:
    """Can THIS jax build run multi-process collectives on the CPU
    backend? Returns ``(supported, finding)`` where ``finding`` is the
    probe's concrete evidence — the collective's result on success,
    the failing build's own error message otherwise.

    The answer gates the two-process distributed test (a structured
    skip naming the finding, per ROADMAP item 1) instead of a blanket
    ``xfail``: the day a jax upgrade ships working CPU multi-process
    collectives, the full test starts running with no edit here. The
    verdict is memoized per process — the probe forms a real
    2-process cluster and costs a few seconds."""
    global _PROBE_MEMO
    if _PROBE_MEMO is not None and not refresh:
        return _PROBE_MEMO
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE_CHILD, addr, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                out, err = p.communicate()
                err = (err or "") + f"\n[probe timeout {timeout_s}s]"
            outs.append((p.returncode, out or "", err or ""))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    ok = all(rc == 0 and "PROBE_OK" in out for rc, out, _ in outs)
    if ok:
        finding = "2-process CPU psum verified: " + "; ".join(
            out.strip().splitlines()[-1] for _, out, _ in outs
        )
    else:
        rc, _, err = next(
            (o for o in outs if o[0] != 0), outs[0]
        )
        tail = [ln for ln in err.strip().splitlines() if ln][-1:]
        finding = (f"probe rc={rc}: "
                   f"{tail[0] if tail else 'no stderr'}")[:300]
    _PROBE_MEMO = (ok, finding)
    _olog.log("distributed_probe", supported=ok, finding=finding)
    return _PROBE_MEMO


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> tuple[int, int]:
    """Initialize jax's multi-host runtime (idempotent) and return
    ``(process_index, process_count)``. See the module docstring for
    the execution model and failure semantics."""
    import jax

    already = getattr(jax.distributed, "is_initialized", None)
    if callable(already) and already():
        return jax.process_index(), jax.process_count()
    # env-var cluster: jax reads JAX_COORDINATOR_ADDRESS itself, but
    # has no generic env detection for the process count/id — accept
    # the two companions here so a plain launcher can form a cluster
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    explicit = any(
        v is not None
        for v in (coordinator_address, num_processes, process_id)
    ) or bool(os.environ.get("JAX_COORDINATOR_ADDRESS"))
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except ValueError:
        # A ValueError out of an explicitly configured launch (args or
        # JAX_COORDINATOR_ADDRESS) is a malformed spec, not "no
        # cluster": downgrading it would leave N workers silently
        # solving alone — the exact failure mode this module promises
        # to surface. Only the truly unconfigured case is a single-host
        # launch to run locally.
        if explicit:
            raise
        _olog.warn(
            "distributed_single_host",
            reason="no cluster environment detected",
        )
    except RuntimeError:
        # the XLA backend is already initialized (initialize() must
        # come first). Harmless on a single host — the process was
        # going to run alone anyway — but an explicit multi-host
        # request that can no longer be honored must fail loudly, not
        # degrade into N workers silently solving alone.
        if explicit or jax.process_count() > 1:
            raise
        _olog.warn(
            "distributed_single_host",
            reason="XLA backend already initialized",
        )
    return jax.process_index(), jax.process_count()
