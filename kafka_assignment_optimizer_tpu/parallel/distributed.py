"""Multi-host initialization for the distributed backend.

The reference is a single-process batch tool (its only parallelism is
lp_solve's in-process branch-and-bound, ``/root/reference/README.md:135``);
the TPU build's search engines shard chain populations over every device
of a ``jax.sharding.Mesh``. On a multi-host slice (v5e-16+, or any pod
slice spanning workers) that mesh must cover the GLOBAL device set, which
requires ``jax.distributed.initialize`` before the first backend touch.

After initialization nothing else changes: ``parallel.mesh.make_mesh``
builds over ``jax.devices()`` — already global post-init — and the ICI
migration collectives inside ``shard_map`` (``pmax``/``psum``) are
compiled by XLA to ride ICI within a slice and DCN across hosts. The
model arrays are replicated (a few MB); only the few-KB per-shard
winners cross hosts outside the hot loop.

Execution model: multi-controller SPMD — every worker must run the SAME
program. That is exactly a pod launcher running the CLI on all workers
with the same input (``--distributed``); every worker computes the same
plan and the operator reads worker 0's output. It is NOT the HTTP
service: independent per-host request streams cannot drive matching
collectives, so ``serve`` deliberately has no such flag.

Configuration: on cloud TPU pods (GKE/GCE metadata, SLURM, MPI) jax's
cluster auto-detection — which runs inside ``initialize()`` — finds the
coordinator, process count and process id on its own; explicit clusters
pass ``coordinator_address``/``num_processes``/``process_id``, or export
``JAX_COORDINATOR_ADDRESS`` (the env var jax itself reads) plus
``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID`` (read here — jax's cluster
detection has no generic env-var cluster, and a pod launcher that can
export three variables should not need a SLURM/MPI environment). A
single-host launch with no cluster environment is detected (jax raises
``ValueError`` while resolving the spec) and treated as a no-op, so the
flag is safe to leave on in launch scripts that sometimes run one host.
Genuine multi-host misconfiguration (bad coordinator, timeout) raises —
N workers silently solving alone is worse than an error.
"""

from __future__ import annotations

import os

from ..obs import log as _olog


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> tuple[int, int]:
    """Initialize jax's multi-host runtime (idempotent) and return
    ``(process_index, process_count)``. See the module docstring for
    the execution model and failure semantics."""
    import jax

    already = getattr(jax.distributed, "is_initialized", None)
    if callable(already) and already():
        return jax.process_index(), jax.process_count()
    # env-var cluster: jax reads JAX_COORDINATOR_ADDRESS itself, but
    # has no generic env detection for the process count/id — accept
    # the two companions here so a plain launcher can form a cluster
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    explicit = any(
        v is not None
        for v in (coordinator_address, num_processes, process_id)
    ) or bool(os.environ.get("JAX_COORDINATOR_ADDRESS"))
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except ValueError:
        # A ValueError out of an explicitly configured launch (args or
        # JAX_COORDINATOR_ADDRESS) is a malformed spec, not "no
        # cluster": downgrading it would leave N workers silently
        # solving alone — the exact failure mode this module promises
        # to surface. Only the truly unconfigured case is a single-host
        # launch to run locally.
        if explicit:
            raise
        _olog.warn(
            "distributed_single_host",
            reason="no cluster environment detected",
        )
    except RuntimeError:
        # the XLA backend is already initialized (initialize() must
        # come first). Harmless on a single host — the process was
        # going to run alone anyway — but an explicit multi-host
        # request that can no longer be honored must fail loudly, not
        # degrade into N workers silently solving alone.
        if explicit or jax.process_count() > 1:
            raise
        _olog.warn(
            "distributed_single_host",
            reason="XLA backend already initialized",
        )
    return jax.process_index(), jax.process_count()
