"""Per-key circuit breaker for the serving path.

A bucket whose executable reliably fails to compile or dispatch (bad
shape interaction, device wedged, chaos) must not make every matching
request pay a full compile-attempt-and-crash cycle: after ``threshold``
consecutive failures on one key the circuit OPENS and matching requests
shed instantly with 503 + ``Retry-After`` until the cooldown passes.
Then exactly one probe request is admitted (half-open); success closes
the circuit, failure re-opens it with an exponentially escalated,
jittered cooldown (resilience.budget.jitter_factor is the shared
jitter shape).

Keys are opaque tuples — serve uses the solve bucket identity for TPU
requests and ``("solver", name)`` otherwise.
"""

from __future__ import annotations

import threading
import time

from .budget import jitter_factor

__all__ = ["CircuitBreaker"]


class _KeyState:
    __slots__ = ("failures", "open_until", "trips", "probing")

    def __init__(self):
        self.failures = 0
        self.open_until = 0.0
        self.trips = 0
        self.probing = False


class CircuitBreaker:
    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 max_cooldown_s: float = 600.0):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.max_cooldown_s = float(max_cooldown_s)
        self._lock = threading.Lock()
        self._keys: dict[tuple, _KeyState] = {}
        self._trips_total = 0

    def configure(self, threshold: int | None = None,
                  cooldown_s: float | None = None) -> None:
        with self._lock:
            if threshold is not None:
                self.threshold = max(1, int(threshold))
            if cooldown_s is not None:
                self.cooldown_s = float(cooldown_s)

    def allow(self, key: tuple) -> tuple[bool, float]:
        """``(admitted, retry_after_s)``: admitted requests proceed;
        shed ones carry the remaining cooldown as the Retry-After
        hint. An expired-cooldown key admits ONE probe; concurrent
        requests behind the probe stay shed until it resolves."""
        now = time.monotonic()
        with self._lock:
            st = self._keys.get(key)
            if st is None or st.open_until <= 0.0:
                return True, 0.0
            if now < st.open_until:
                return False, max(st.open_until - now, 0.1)
            if st.probing:
                # a probe is in flight: hold the line briefly
                return False, 1.0
            st.probing = True  # half-open: this caller is the probe
            return True, 0.0

    def record_success(self, key: tuple) -> None:
        with self._lock:
            self._keys.pop(key, None)

    def release_probe(self, key: tuple) -> None:
        """A probe concluded WITHOUT a solver verdict (the request shed
        on saturation or failed validation before the solver ran):
        clear the half-open latch so a later request may probe again —
        without this, a shed probe would wedge the circuit open
        forever."""
        with self._lock:
            st = self._keys.get(key)
            if st is not None:
                st.probing = False

    def record_failure(self, key: tuple) -> None:
        now = time.monotonic()
        with self._lock:
            st = self._keys.setdefault(key, _KeyState())
            was_probe = st.probing
            st.probing = False
            st.failures += 1
            if st.failures < self.threshold and not was_probe:
                return
            # trip: escalate the cooldown exponentially with jitter
            st.trips += 1
            self._trips_total += 1
            st.failures = 0
            base = min(
                self.cooldown_s * (2.0 ** (st.trips - 1)),
                self.max_cooldown_s,
            )
            st.open_until = now + base * jitter_factor(0.25)
            key_r, trips = repr(key)[:120], st.trips
        from ..obs import log as _olog

        _olog.error("breaker_open", key=key_r, trips=trips)

    def open_keys(self) -> int:
        now = time.monotonic()
        with self._lock:
            return sum(
                1 for st in self._keys.values() if st.open_until > now
            )

    def snapshot(self) -> dict:
        with self._lock:
            tracked = len(self._keys)
            trips = self._trips_total
        return {
            "open": self.open_keys(),
            "tracked": tracked,
            "trips_total": trips,
        }

    def reset(self) -> None:
        with self._lock:
            self._keys.clear()
            self._trips_total = 0
