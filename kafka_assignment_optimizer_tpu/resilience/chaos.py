"""Fault-injection harness (``KAO_CHAOS=<spec>`` / ``--chaos <spec>``).

Every failure path this service owns — Pallas→XLA drain-and-retry,
sweep→chain engine fallback, queue shedding, checkpoint persistence,
worker recovery — was historically exercised only when a real fault
happened to fire. This module makes failure a first-class, *testable*
input: named injection points threaded through ``parallel.mesh``,
``solvers.tpu.engine`` and ``serve`` that are strict no-ops unless armed
(one dict lookup behind a module-level ``None`` check), and
deterministic under a seed so any chaos run can be replayed.

Spec grammar (comma-separated)::

    KAO_CHAOS="seed=7,delay=0.2,pallas_fault,nan_chunk:0.5,exec_evict:1:3"

- ``point[:prob[:times]]`` — arm ``point``; each eligible call site
  fires with probability ``prob`` (default 1.0) at most ``times`` times
  (default 1; ``-1`` = unlimited). Unknown point names are a hard error
  — a typo must not silently disarm a chaos soak.
- ``seed=N`` — seed the harness RNG (replayable probabilistic faults).
- ``delay=S`` — seconds slept by delay-type points (``chunk_overrun``,
  ``slow_client``); default 0.25.

Contract: chaos hooks are HOST-SIDE ONLY. They may never run inside a
jit/vmap/pallas-traced body — a traced hook would bake the fault (or
its absence) into the compiled executable and desynchronize SPMD
workers. kao-check rule KAO108 enforces this statically; the catalog of
points and what each one simulates lives in docs/RESILIENCE.md.
"""

from __future__ import annotations

import os
import random
import threading
import time

__all__ = [
    "POINTS", "ChaosFault", "arm", "disarm", "armed", "spec_string",
    "fires", "raise_if", "sleep_if", "delay_s", "is_fault",
    "is_pallas_fault", "snapshot", "reset_counters",
]

# the injection-point catalog: point -> (layer, what it simulates).
# docs/RESILIENCE.md renders this table; tests/test_resilience.py has
# one test per point.
POINTS: dict[str, tuple[str, str]] = {
    "compile_fail": (
        "parallel.mesh", "AOT lower/compile failure (falls back to jit)"),
    "device_transfer": (
        "parallel.mesh", "device->host transfer error (retried once)"),
    "exec_evict": (
        "parallel.mesh", "executable-cache eviction storm"),
    "pallas_fault": (
        "solvers.tpu.engine", "Mosaic/Pallas kernel lowering fault"),
    "megachunk_fault": (
        "solvers.tpu.engine", "fault inside a fused megachunk scan "
        "dispatch (drains to the per-chunk path)"),
    "nan_chunk": (
        "solvers.tpu.engine", "NaN surfacing from an annealing chunk"),
    "chunk_overrun": (
        "solvers.tpu.engine", "chunk running far past its warm estimate"),
    "checkpoint_write": (
        "solvers.tpu.engine", "checkpoint persistence write failure"),
    "decompose_reduce": (
        "decompose", "reduce-phase boundary/stitch failure "
        "(degrades decompose_to_flat)"),
    "worker_crash": (
        "serve", "solve worker thread dies mid-request"),
    "queue_overload": (
        "serve", "solve queue reports no capacity"),
    "slow_client": (
        "serve", "slow client holding a handler thread"),
}

_DEFAULT_DELAY_S = 0.25


class ChaosFault(RuntimeError):
    """An injected fault. Carries the point name so fault-specific
    handling (e.g. the engine's lowering-failure classifier) can key on
    it without string matching."""

    def __init__(self, point: str, message: str | None = None):
        super().__init__(
            message or f"chaos: injected fault at point {point!r}"
        )
        self.point = point


_LOCK = threading.Lock()
# None = disarmed (the fast path — ``fires`` returns before the lock);
# armed: point -> {"prob": float, "left": int (-1 = unlimited)}
_SPEC: dict[str, dict] | None = None
_SPEC_STRING: str | None = None
_DELAY = _DEFAULT_DELAY_S
_RNG = random.Random()
_FIRED: dict[str, int] = {}


def parse_spec(spec: str) -> tuple[dict[str, dict], int | None, float]:
    """``"seed=7,delay=0.1,pallas_fault:0.5:2"`` ->
    ``(points, seed, delay_s)``; raises ValueError on anything
    malformed (a chaos spec typo must fail loudly, not no-op)."""
    points: dict[str, dict] = {}
    seed: int | None = None
    delay = _DEFAULT_DELAY_S
    for raw in spec.split(","):
        part = raw.strip()
        if not part:
            continue
        if part.startswith("seed="):
            seed = int(part[5:])
            continue
        if part.startswith("delay="):
            delay = float(part[6:])
            if delay < 0:
                raise ValueError(f"chaos delay must be >= 0: {part!r}")
            continue
        fields = part.split(":")
        name = fields[0]
        if name not in POINTS:
            raise ValueError(
                f"unknown chaos point {name!r}; known: "
                f"{sorted(POINTS)}"
            )
        if len(fields) > 3:
            raise ValueError(f"bad chaos point spec {part!r}; "
                             "want point[:prob[:times]]")
        prob = float(fields[1]) if len(fields) > 1 else 1.0
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"chaos probability out of [0,1]: {part!r}")
        times = int(fields[2]) if len(fields) > 2 else 1
        if times == 0 or times < -1:
            raise ValueError(
                f"chaos times must be >= 1 or -1 (unlimited): {part!r}"
            )
        points[name] = {"prob": prob, "left": times}
    if not points:
        raise ValueError(f"chaos spec arms no points: {spec!r}")
    return points, seed, delay


def arm(spec: str) -> None:
    """Parse and arm ``spec`` (replaces any previous arming)."""
    global _SPEC, _SPEC_STRING, _DELAY
    points, seed, delay = parse_spec(spec)
    with _LOCK:
        _SPEC = points
        _SPEC_STRING = spec
        _DELAY = delay
        if seed is not None:
            _RNG.seed(seed)


def disarm() -> None:
    global _SPEC, _SPEC_STRING
    with _LOCK:
        _SPEC = None
        _SPEC_STRING = None


def armed() -> bool:
    return _SPEC is not None


def spec_string() -> str | None:
    """The armed spec verbatim (healthz / replay logging)."""
    return _SPEC_STRING


def delay_s() -> float:
    return _DELAY


def fires(point: str) -> bool:
    """True when the armed spec says ``point`` faults NOW (consumes one
    of the point's remaining fires). Disarmed: one ``is None`` check."""
    spec = _SPEC
    if spec is None:
        return False
    with _LOCK:
        cfg = spec.get(point)
        if cfg is None or cfg["left"] == 0:
            return False
        if cfg["prob"] < 1.0 and _RNG.random() >= cfg["prob"]:
            return False
        if cfg["left"] > 0:
            cfg["left"] -= 1
        _FIRED[point] = _FIRED.get(point, 0) + 1
    from ..obs import log as _olog

    _olog.warn("chaos_fired", point=point)
    return True


def raise_if(point: str, exc_type: type[BaseException] | None = None) -> None:
    """Raise the point's fault when armed-and-firing. ``exc_type``
    shapes the fault like the real failure it simulates (e.g.
    ``FloatingPointError`` for ``nan_chunk``, ``OSError`` for
    ``checkpoint_write``); default is :class:`ChaosFault`."""
    if not fires(point):
        return
    if exc_type is None:
        raise ChaosFault(point)
    raise exc_type(f"chaos: injected fault at point {point!r}")


def sleep_if(point: str) -> None:
    """Delay-type injection: sleep the armed delay when firing."""
    if fires(point):
        time.sleep(_DELAY)


def is_fault(e: BaseException) -> bool:
    return isinstance(e, ChaosFault)


def is_pallas_fault(e: BaseException) -> bool:
    """True for the injected Mosaic/Pallas fault — the engine's
    lowering-failure classifier accepts it regardless of the active
    scorer, so CPU test meshes exercise the same drain-and-retry path
    a real TPU lowering failure takes."""
    return isinstance(e, ChaosFault) and e.point == "pallas_fault"


def snapshot() -> dict:
    """{"armed": 0|1, "spec": str|None, "fired": {point: n}}."""
    with _LOCK:
        return {
            "armed": int(_SPEC is not None),
            "spec": _SPEC_STRING,
            "fired": dict(_FIRED),
        }


def reset_counters() -> None:
    """Zero the fired counters (tests)."""
    with _LOCK:
        _FIRED.clear()


# arm-from-environment at import: a typo'd KAO_CHAOS must fail the
# process loudly, never silently run without chaos
_env = os.environ.get("KAO_CHAOS", "").strip()
if _env:
    arm(_env)
del _env
