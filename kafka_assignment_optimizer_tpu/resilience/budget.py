"""Per-solve deadline/retry budget — the ONE remaining-time object.

Before this module, deadline handling was ad-hoc ``t0 + time_limit_s``
arithmetic repeated at every join/retry site in the engine and the
serving path, and the satellite bug class it bred was real: a fallback
retry granted the FULL original budget after the first attempt had
already spent it. :class:`Budget` fixes the shape of the problem — the
budget is created once per request/solve, every wait and retry asks it
for ``remaining()``, and composition (a server-side request deadline
capping a client time limit) is ``min`` over remainings.

Retries across the ladder (worker respawn, transfer retry, circuit
probation) share one jittered exponential backoff, :func:`backoff_s` —
jitter decorrelates retry storms, the cap keeps a retry from eating the
budget, and a Budget-bound sleep never overshoots the deadline.
"""

from __future__ import annotations

import random
import time

__all__ = ["Budget", "backoff_s", "jitter_factor"]

_RNG = random.Random()


def jitter_factor(jitter: float) -> float:
    """Uniform scale factor in ``[1-jitter, 1+jitter]`` (floored at
    0) — the one jitter shape every retry/cooldown in the ladder
    shares, so storms decorrelate the same way everywhere."""
    lo = max(0.0, 1.0 - jitter)
    return lo + (1.0 + jitter - lo) * _RNG.random()


def backoff_s(attempt: int, base_s: float = 0.05, cap_s: float = 2.0,
              jitter: float = 0.5) -> float:
    """Jittered exponential backoff: ``base * 2**attempt`` capped at
    ``cap_s``, scaled by :func:`jitter_factor`. ``attempt`` counts
    from 0 (the first retry)."""
    raw = min(float(base_s) * (2.0 ** max(int(attempt), 0)), float(cap_s))
    return raw * jitter_factor(jitter)


class Budget:
    """Remaining-time accounting for one solve/request.

    ``Budget(None)`` is the unlimited budget: ``remaining()`` is None,
    ``expired()`` is False, ``cap()`` passes timeouts through — so call
    sites need no ``if time_limit_s is None`` forests.

    :meth:`cancel` collapses the remaining budget to zero from another
    thread: every deadline gate that already asks ``remaining()`` then
    stops at its next check. This is how a superseded solve is reclaimed
    (watch-mode event storms, docs/WATCH.md) — the engine's existing
    ``deadline_truncated`` rung retires it with its best-so-far plan, no
    new cancellation protocol required."""

    __slots__ = ("t0", "limit_s", "cancelled")

    def __init__(self, limit_s: float | None, t0: float | None = None):
        self.limit_s = None if limit_s is None else float(limit_s)
        self.t0 = time.perf_counter() if t0 is None else float(t0)
        self.cancelled = False

    def cancel(self) -> None:
        """Collapse the budget: ``remaining()`` is 0.0 and ``expired()``
        is True from now on, even on an unlimited budget. Thread-safe by
        virtue of being a monotonic one-way flag."""
        self.cancelled = True

    @property
    def deadline(self) -> float | None:
        """Absolute ``time.perf_counter()`` deadline (None = none)."""
        if self.limit_s is None:
            return None
        return self.t0 + self.limit_s

    def remaining(self) -> float | None:
        """Seconds left (clamped at 0.0); None = unlimited. A cancelled
        budget always reports 0.0 — unlimited included."""
        if self.cancelled:
            return 0.0
        if self.limit_s is None:
            return None
        return max(0.0, self.t0 + self.limit_s - time.perf_counter())

    def expired(self) -> bool:
        r = self.remaining()
        return r is not None and r <= 0.0

    def cap(self, timeout_s: float | None) -> float | None:
        """``timeout_s`` bounded by the remaining budget — the join/wait
        timeout helper (None in, remaining out; unlimited budget passes
        ``timeout_s`` through unchanged)."""
        r = self.remaining()
        if r is None:
            return timeout_s
        if timeout_s is None:
            return r
        return min(float(timeout_s), r)

    def sleep_backoff(self, attempt: int, base_s: float = 0.05,
                      cap_s: float = 2.0) -> float:
        """Sleep one jittered-backoff step, never past the deadline;
        returns the seconds actually slept."""
        s = backoff_s(attempt, base_s, cap_s)
        r = self.remaining()
        if r is not None:
            s = min(s, r)
        if s > 0:
            time.sleep(s)
        return s

    def __repr__(self) -> str:
        r = self.remaining()
        return (
            f"Budget(unlimited)" if r is None
            else f"Budget(limit={self.limit_s:.3f}s, left={r:.3f}s)"
        )
