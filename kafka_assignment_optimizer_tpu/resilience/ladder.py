"""The graceful-degradation ladder — one ordered, observable policy.

The repo grew its fallbacks one incident at a time (Pallas→XLA
drain-and-retry, sweep→chain engine retry, pipeline drain, deadline
truncation, checkpoint-skip, serve worker respawn); each worked but
none were legible as a SYSTEM. This module names the rungs, orders
them from cheapest to most drastic, and makes every step down
observable in all three places at once:

- the solve's ``stats["degradations"]`` list (ambient collector,
  activated by the engine entry points);
- a zero-duration ``degrade`` span mark on the active solve trace
  (``/debug/solves/<id>``);
- the ``kao_degradations_total{rung=...}`` counter on ``/metrics``.

The acceptance contract (tests/test_resilience.py) is that for every
injected fault the three views agree. Rung semantics and the full
policy table live in docs/RESILIENCE.md.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading

from ..obs import trace as _otrace

__all__ = [
    "RUNGS", "note_rung", "collect", "collect_lane", "snapshot", "reset",
]

# the ladder, cheapest rung first. Results stay bit-identical through
# "pipelined_to_sync"; from "pallas_to_xla" down the executable changes
# but the trajectory contract holds (scorer parity); "sweep_to_chain"
# changes the search; "anneal_to_construct" abandons the device search
# for the host constructor/greedy path (flagged degraded unless it
# certifies); the rest are serving/persistence containment steps.
RUNGS: tuple[str, ...] = (
    "megachunk_to_chunked",  # fused scan drained; per-chunk ladder re-entry
    "pipelined_to_sync",    # drain speculation, retry chunk synchronously
    "aot_to_jit",           # AOT executable path failed; plain jit dispatch
    "transfer_retry",       # device->host transfer retried after a fault
    "pallas_to_xla",        # Mosaic scorer fault; chunk re-run on XLA
    "deadline_truncated",   # budget bit: ladder stopped early, best-so-far
    "checkpoint_skipped",   # checkpoint write failed; solve continued
    "warm_start_rejected",  # delta-API warm seed unusable; solved cold
    "decompose_to_flat",    # failed map-reduce stitch; flat solve instead
    "sweep_to_chain",       # defaulted sweep infeasible; chain engine retry
    "anneal_to_construct",  # device path unusable; host greedy/constructor
    "worker_restart",       # serve worker crashed; respawned (+1 retry)
)

_LOCK = threading.Lock()
_COUNTS: dict[str, int] = {r: 0 for r in RUNGS}

# ambient per-solve rung collector: the OUTERMOST engine entry point
# owns the list (nested solves — the chain retry, per-lane fallbacks —
# feed the same one), and copies it into stats["degradations"].
_ACTIVE: contextvars.ContextVar[list | None] = contextvars.ContextVar(
    "kao_degradation_rungs", default=None
)


def note_rung(rung: str, **attrs) -> None:
    """Record one step down the ladder: counter + trace mark +
    structured log + the ambient per-solve collector."""
    with _LOCK:
        _COUNTS[rung] = _COUNTS.get(rung, 0) + 1
    lst = _ACTIVE.get()
    if lst is not None:
        lst.append(rung)
    _otrace.mark("degrade", rung=rung, **attrs)
    from ..obs import log as _olog

    _olog.warn("degradation", rung=rung, **attrs)


@contextlib.contextmanager
def collect():
    """Activate the per-solve rung collector on this context; yields
    the list, or None when an OUTER collector is already active (nested
    solves append to the outermost one, so a retry's rungs land on the
    request-level stats exactly once)."""
    if _ACTIVE.get() is not None:
        yield None
        return
    lst: list = []
    token = _ACTIVE.set(lst)
    try:
        yield lst
    finally:
        _ACTIVE.reset(token)


@contextlib.contextmanager
def collect_lane():
    """Per-lane scope inside a batch solve: rungs taken here land on
    the yielded list ONLY, shadowing the batch-level collector — a
    single lane's sequential fallback must not flag the other lanes'
    stats as degraded (counter and trace marks still fire globally)."""
    lst: list = []
    token = _ACTIVE.set(lst)
    try:
        yield lst
    finally:
        _ACTIVE.reset(token)


def snapshot() -> dict[str, int]:
    """rung -> times taken, every cataloged rung present (zeros
    included, so /metrics pre-declares the full family)."""
    with _LOCK:
        out = {r: 0 for r in RUNGS}
        out.update(_COUNTS)
        return out


def reset() -> None:
    """Zero the counters (tests)."""
    with _LOCK:
        for k in list(_COUNTS):
            _COUNTS[k] = 0
