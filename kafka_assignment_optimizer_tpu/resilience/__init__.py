"""Resilience subsystem: failure as a first-class, testable input.

Four small, dependency-light modules (stdlib + obs only — importable
from the lowest layers without cycles):

- :mod:`.chaos` — the ``KAO_CHAOS`` / ``--chaos`` fault-injection
  harness: named, host-side-only injection points threaded through
  ``parallel.mesh``, ``solvers.tpu.engine`` and ``serve`` (kao-check
  rule KAO108 keeps chaos hooks out of traced bodies).
- :mod:`.budget` — the per-solve/request deadline-and-retry budget
  (remaining-time threading + the shared jittered exponential backoff).
- :mod:`.ladder` — the graceful-degradation ladder: named rungs,
  recorded simultaneously in solve stats, trace spans and the
  ``kao_degradations_total{rung=}`` metric.
- :mod:`.breaker` — the serving path's per-bucket circuit breaker.

Catalog, rung semantics and the budget contract: docs/RESILIENCE.md.
"""

from . import breaker, budget, chaos, ladder  # noqa: F401

__all__ = ["breaker", "budget", "chaos", "ladder"]
