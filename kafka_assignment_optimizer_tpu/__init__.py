"""kafka_assignment_optimizer_tpu — TPU-native Kafka partition-reassignment
optimizer.

A from-scratch rebuild of the capabilities of
``killerwhile/kafka-assignment-optimizer`` (reference mounted read-only at
``/root/reference``): replica placement as constrained combinatorial
optimization, minimizing replica moves under rack-awareness, balance, and
leader constraints (``/root/reference/README.md:106-185``).

Layer map (mirrors SURVEY.md §1):

- ``models``  — L0/L1/L3: ingest, solver-neutral model, weights, bounds
- ``solvers`` — L4/L5/L6: LP emitter + lp_solve/MILP oracles, native C++
  branch-and-bound, and the flagship JAX/TPU annealing engine
- ``ops``     — scoring ops (XLA + Pallas TPU kernels)
- ``parallel``— device mesh, shard_map solve, ICI collectives
- ``watch``   — cluster-watch delta mode: events, plan store, fencing
- ``utils``   — reporting, RNG, checkpointing
"""

from .api import (  # noqa: F401
    evaluate,
    optimize,
    optimize_delta,
    OptimizeResult,
)
from .models.cluster import (  # noqa: F401
    Assignment,
    MoveReport,
    PartitionAssignment,
    Topology,
    move_diff,
    parse_broker_list,
)
from .models.instance import ProblemInstance, build_instance  # noqa: F401

__version__ = "0.1.0"
