"""Solve-trace telemetry: nested timed spans with attributes.

The engine runs a multi-stage pipeline (bounds → constructor race →
seed → chunked anneal ladder → polish → oracle verify) across a batched
serving path; this module is the instrument that says *which phase ate
the budget* and *what the annealer actually did*. Design constraints:

- **Dependency-free**: stdlib only (``contextvars``/``threading``/
  ``time``) — importable from the lowest layers (``parallel.mesh``)
  without cycles.
- **Negligible overhead when disabled** (the default): every
  instrumentation site (``span``/``mark``/``set_attrs``) starts with one
  contextvar read; with no active trace, ``span`` returns a shared
  ``nullcontext`` — no allocation, no timestamps, and keyword attrs at
  the call sites are kept cheap (expensive attrs are computed only under
  ``if sp is not None``).
- **Thread-safe**: child-span attachment takes the trace lock;
  ``wrap()`` carries a span onto worker threads (contextvars do not
  cross threads by themselves). Attribute writes stay on the owning
  thread.

Propagation is ambient: ``begin()`` activates a trace on the current
context, and every ``span()`` underneath — engine phases, mesh
dispatch/compile, device transfers — attaches to it automatically, so
the serving path can trace a whole request without threading a handle
through every signature. Across PROCESS boundaries propagation is
explicit (docs/OBSERVABILITY.md "Distributed traces"): ``inject()``
renders the active context as a W3C ``traceparent`` header, and
``extract()`` + ``begin(remote_parent=...)`` adopt it on the far side,
so a router-edge trace and the worker-side solve trace share one ID
and re-join under ``GET /debug/traces/<id>``. ``finish()`` closes the
trace, builds the solve report (span tree + per-phase seconds +
optional annealing trajectory), registers it in the ``RECENT`` ring
buffer (the ``/debug/solves`` surface) subject to the tail-retention
policy (``KAO_TRACE_TAIL``, :class:`TailPolicy`), and feeds the
per-phase latency histograms rendered as
``kao_phase_seconds{phase=...}`` on ``/metrics``.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import re
import threading
import time
import uuid
from collections import OrderedDict, deque, namedtuple

_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "kao_current_span", default=None
)
# the shared disabled-path context manager: span() must not allocate
# when tracing is off (it sits on per-chunk and per-dispatch hot paths)
_NULL = contextlib.nullcontext()

# constructor sub-phases (ISSUE 10, docs/CONSTRUCTOR.md): nested spans
# whose TOTAL seconds are rolled up into the solve report's ``phases``
# dict alongside the root-level pipeline phases, so flight records and
# bench's construct_host_s column attribute host time to the exact loop
# the vectorized constructor rewrote. Summed (not first-occurrence like
# the root phases) because e.g. "greedy" legitimately runs both in a
# race worker and in _pick_seed within one solve.
SUB_PHASES = ("bounds_flow", "greedy", "reseat", "adopt")


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[16:]


# --------------------------------------------------------------------------
# W3C traceparent codec (docs/OBSERVABILITY.md "Distributed traces")
#
# One trace must survive the process hop: the kao-router begins a trace
# at its edge, ``inject()``s it into the upstream request headers, and
# the worker ``extract()``s it so the solve's span tree carries the
# router's trace ID (the /debug/traces join key). The wire format is
# the W3C Trace Context ``traceparent`` header —
# ``00-{trace-id:32hex}-{parent-span-id:16hex}-{flags:2hex}`` — so any
# W3C-speaking proxy or client interoperates. Internal compact 16-hex
# trace IDs are left-padded with zeros on the wire and stripped back on
# extract; a foreign full-width 32-hex ID is adopted verbatim.
# Malformed or unusable headers are tolerated: extract() returns None
# (the request gets a fresh root; the remote link is dropped) and the
# rejection is counted, never raised.
# --------------------------------------------------------------------------

TRACEPARENT = "traceparent"
_TP_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)
_HEX_RE = re.compile(r"^[0-9a-f]+$")
_TID_PAD = "0" * 16

# a remote causal context: the upstream trace ID to adopt and the
# caller-side span that parents this process's root span
RemoteContext = namedtuple("RemoteContext", ("trace_id", "span_id"))

_PROP_LOCK = threading.Lock()
# codec traffic counters (kao_trace_context_total{event=}):
# extracted = remote contexts adopted, malformed = rejected headers
# (new root, remote link dropped), injected = contexts propagated
PROPAGATION = {"extracted": 0, "malformed": 0, "injected": 0}


def _prop_count(event: str) -> None:
    with _PROP_LOCK:
        PROPAGATION[event] += 1


def inject(trace_id: str | None = None,
           span_id: str | None = None) -> str | None:
    """The ``traceparent`` header value for the given context — or for
    the ACTIVE one when called without arguments (the current span gets
    a lazily-assigned span ID so the receiver can parent onto it).
    Returns None when there is nothing propagable: no active trace, or
    an ID the wire format cannot carry."""
    if trace_id is None:
        sp = _CURRENT.get()
        if sp is None:
            return None
        trace_id = sp.trace.trace_id
        span_id = sp.sid()
    tid = str(trace_id).lower()
    sid = str(span_id).lower() if span_id else new_span_id()
    if len(tid) > 32 or not _HEX_RE.match(tid) \
            or len(sid) > 16 or not _HEX_RE.match(sid):
        return None
    _prop_count("injected")
    return f"00-{tid.rjust(32, '0')}-{sid.rjust(16, '0')}-01"


def extract(value) -> RemoteContext | None:
    """Parse a ``traceparent`` header into a :class:`RemoteContext`, or
    None when absent/unusable (malformed syntax, all-zero IDs, the
    reserved ``ff`` version) — the caller then starts a fresh root and
    the remote link is dropped, never an error. A 32-hex ID carrying
    our compact left-pad round-trips back to the 16-hex internal form;
    a genuinely foreign full-width ID is adopted as-is."""
    if not value or not isinstance(value, str):
        return None
    m = _TP_RE.match(value.strip().lower())
    if m is None:
        _prop_count("malformed")
        return None
    version, tid, sid, _flags = m.groups()
    if version == "ff" or tid == "0" * 32 or sid == "0" * 16:
        _prop_count("malformed")
        return None
    if tid.startswith(_TID_PAD):
        tid = tid[len(_TID_PAD):]
    _prop_count("extracted")
    return RemoteContext(tid, sid)


def _jsonable(v):
    """Coerce an attr value to something json.dumps handles (numpy
    scalars carry .item(); anything else falls back to str)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    item = getattr(v, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(v)


class Span:
    """One timed pipeline step: name, start/end, attrs, children."""

    __slots__ = ("name", "trace", "start", "end", "attrs", "children",
                 "span_id")

    def __init__(self, name: str, trace: "Trace", attrs: dict | None = None):
        self.name = name
        self.trace = trace
        self.start = time.perf_counter()
        self.end: float | None = None
        self.attrs = dict(attrs) if attrs else {}
        self.children: list[Span] = []
        # lazily assigned (sid()): only spans that actually propagate
        # across a process boundary pay for an ID — chunk/dispatch
        # spans on the hot path never do
        self.span_id: str | None = None

    def sid(self) -> str:
        """This span's ID, assigned on first use (under the trace lock:
        a router attempt span can be read by the report serializer
        while the attempt thread assigns it)."""
        with self.trace._lock:
            if self.span_id is None:
                self.span_id = new_span_id()
            return self.span_id

    def set(self, **attrs) -> None:
        # under the trace lock: a wrap()-ed worker span can still be
        # mutating while another thread serializes the report (a solve
        # legitimately returns past a straggling bounds worker)
        with self.trace._lock:
            self.attrs.update(attrs)

    @property
    def wall_s(self) -> float | None:
        return None if self.end is None else self.end - self.start

    def to_dict(self, t0: float) -> dict:
        # snapshot under the trace lock, serialize outside it: in-flight
        # worker spans may mutate attrs/children concurrently
        with self.trace._lock:
            attrs = dict(self.attrs)
            children = list(self.children)
            end = self.end
            span_id = self.span_id
        d: dict = {
            "name": self.name,
            "start_s": round(self.start - t0, 6),
            # None = still running when the report was built (e.g. a
            # straggling bounds worker past the solve's return)
            "wall_s": (
                None if end is None else round(end - self.start, 6)
            ),
        }
        if span_id is not None:
            # only propagation-relevant spans carry one (see sid())
            d["span_id"] = span_id
        if attrs:
            d["attrs"] = {k: _jsonable(v) for k, v in attrs.items()}
        if children:
            d["spans"] = [c.to_dict(t0) for c in children]
        return d


class Trace:
    """One solve's span tree. Created via :func:`begin`; the root span
    is activated on the current context so nested :func:`span` calls
    attach automatically."""

    def __init__(self, trace_id: str | None = None, name: str = "solve",
                 **attrs):
        self.trace_id = trace_id or new_trace_id()
        self.name = name
        self.started_unix = time.time()
        self._lock = threading.Lock()
        self.root = Span(name, self, attrs)
        self.trajectory: dict | None = None
        self._token = None

    def attach(self, parent: Span, child: Span) -> None:
        with self._lock:
            parent.children.append(child)

    def report(self) -> dict:
        """The solve report: span tree + per-phase seconds (first
        occurrence of each direct child of the root, plus the SUMMED
        constructor sub-phases from anywhere in the tree — see
        ``SUB_PHASES``) + trajectory."""
        t0 = self.root.start
        phases: dict[str, float] = {}
        with self._lock:
            children = list(self.root.children)
        for c in children:
            # SUB_PHASES names are excluded here even as direct root
            # children (the host-fallback path opens "greedy" at root
            # level): they get SUMMED totals below, and first-occurrence
            # recording would otherwise shadow every later occurrence
            if c.end is not None and c.name not in phases \
                    and c.name not in SUB_PHASES:
                phases[c.name] = round(c.end - c.start, 6)
        sub: dict[str, float] = {}
        stack = list(children)
        while stack:
            sp = stack.pop()
            with self._lock:
                stack.extend(sp.children)
            if sp.name in SUB_PHASES and sp.end is not None:
                sub[sp.name] = sub.get(sp.name, 0.0) + (sp.end - sp.start)
        for k, v in sub.items():
            phases[k] = round(v, 6)
        rep = {
            "trace_id": self.trace_id,
            "name": self.name,
            "started_unix": round(self.started_unix, 3),
            "wall_s": (
                None if self.root.end is None
                else round(self.root.end - self.root.start, 6)
            ),
            "phases": phases,
            "spans": self.root.to_dict(t0),
        }
        if self.trajectory:
            rep["annealing"] = self.trajectory
        return rep


class _SpanCtx:
    """Context manager for one child span of ``parent``."""

    __slots__ = ("_parent", "_name", "_attrs", "_span", "_token")

    def __init__(self, parent: Span, name: str, attrs: dict):
        self._parent = parent
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> Span:
        tr = self._parent.trace
        sp = Span(self._name, tr, self._attrs)
        tr.attach(self._parent, sp)
        self._span = sp
        self._token = _CURRENT.set(sp)
        return sp

    def __exit__(self, exc_type, exc, tb):
        sp = self._span
        sp.end = time.perf_counter()
        if exc is not None:
            sp.attrs.setdefault("error", repr(exc)[:200])
        _CURRENT.reset(self._token)
        return False


def span(name: str, **attrs):
    """``with span("chunk", index=i) as sp:`` — a nested timed span, or
    a shared no-op context (yielding None) when no trace is active.
    Keyword attrs are evaluated at the call site even when disabled, so
    keep them cheap there; compute expensive attrs under
    ``if sp is not None: sp.set(...)``."""
    parent = _CURRENT.get()
    if parent is None:
        return _NULL
    return _SpanCtx(parent, name, attrs)


def mark(name: str, **attrs) -> None:
    """Zero-duration span: records a pipeline phase that did not run
    (``skipped=True``) or a point event, keeping the span tree's phase
    vocabulary complete on every path."""
    parent = _CURRENT.get()
    if parent is None:
        return
    sp = Span(name, parent.trace, attrs)
    sp.end = sp.start
    parent.trace.attach(parent, sp)


def set_attrs(**attrs) -> None:
    """Merge attrs into the current span (no-op when untraced)."""
    sp = _CURRENT.get()
    if sp is not None:
        sp.set(**attrs)


def current_span() -> Span | None:
    return _CURRENT.get()


def active() -> bool:
    return _CURRENT.get() is not None


def current_trace_id() -> str | None:
    sp = _CURRENT.get()
    return None if sp is None else sp.trace.trace_id


def wrap(name: str, fn, **attrs):
    """Bind ``fn`` to a child span of the CURRENT span so it can run on
    another thread (contextvars do not cross threads). Returns ``fn``
    unchanged when no trace is active — zero overhead on the default
    path. The span stays open until the wrapped call returns; a report
    built before then shows it with ``wall_s: null`` (in flight)."""
    parent = _CURRENT.get()
    if parent is None:
        return fn
    tr = parent.trace

    def run():
        sp = Span(name, tr, attrs)
        tr.attach(parent, sp)
        tok = _CURRENT.set(sp)
        try:
            return fn()
        except BaseException as e:
            # via the lock (Span.set): the solve may be serializing the
            # report on its own thread at this very moment
            if "error" not in sp.attrs:
                sp.set(error=repr(e)[:200])
            raise
        finally:
            sp.end = time.perf_counter()
            _CURRENT.reset(tok)

    return run


def set_trajectory(**summary) -> None:
    """Merge annealing-trajectory summary fields into the active trace
    (rendered as the solve report's ``annealing`` block)."""
    sp = _CURRENT.get()
    if sp is not None:
        tr = sp.trace
        tr.trajectory = {**(tr.trajectory or {}), **summary}


def open_span(parent: Span | None, name: str, **attrs) -> Span | None:
    """An explicitly-parented span for structured cross-thread work
    (the router's attempt/hedge races): created and attached NOW, never
    touching the ambient contextvar, so any thread may open children of
    any parent it holds. Close with :func:`close_span`. None-in/None-out
    so call sites stay unconditional."""
    if parent is None:
        return None
    sp = Span(name, parent.trace, attrs)
    parent.trace.attach(parent, sp)
    return sp


def close_span(sp: Span | None, **attrs) -> None:
    """Stamp the end (and final attrs) of an :func:`open_span` span."""
    if sp is None:
        return
    if attrs:
        sp.set(**attrs)
    sp.end = time.perf_counter()


def begin(trace=None, *, name: str = "solve",
          remote_parent: str | None = None, **attrs) -> Trace | None:
    """Start a trace when ``trace`` is truthy (``True`` → generated ID,
    a string → that ID) and activate it on the current context. Returns
    None — tracing disabled — otherwise. Nesting is legal: the token
    restores the outer context at :func:`finish`.

    ``remote_parent`` records a propagated upstream context (the
    ``traceparent`` parent span ID from :func:`extract`): the root span
    becomes a remote-parented server span — ``parent_span_id`` /
    ``span_kind: "server"`` in its attrs — which is how the fleet
    trace merge re-attaches this process's tree under the exact router
    attempt that caused it."""
    if not trace:
        return None
    tid = trace if isinstance(trace, str) else None
    tr = Trace(trace_id=tid, name=name, **attrs)
    if remote_parent:
        tr.root.attrs.setdefault("parent_span_id", str(remote_parent))
        tr.root.attrs.setdefault("span_kind", "server")
    tr._token = _CURRENT.set(tr.root)
    return tr


def finish(tr: Trace | None) -> dict | None:
    """Close ``tr``: deactivate it, build the solve report, register it
    in the ring buffer (subject to the tail-retention policy — see
    :class:`TailPolicy`), and feed the per-phase latency histograms.
    Idempotent-ish on None for uniform call sites."""
    if tr is None:
        return None
    tr.root.end = time.perf_counter()
    if tr._token is not None:
        try:
            _CURRENT.reset(tr._token)
        except ValueError:
            # finished on a different thread/context than begin(): just
            # detach rather than corrupt the finishing thread's context
            pass
        tr._token = None
    rep = tr.report()
    decision = TAIL.decide(rep)
    if TAIL.enabled:
        rep["retention"] = decision
    if decision != "dropped":
        RECENT.put(rep)
    # histograms see EVERY trace either way: retention bounds the ring,
    # never the metrics
    _observe_tree(tr.root)
    return rep


# --------------------------------------------------------------------------
# tail-based trace retention (KAO_TRACE_TAIL — docs/OBSERVABILITY.md
# "Distributed traces")
# --------------------------------------------------------------------------

TAIL_DECISIONS = ("full", "head", "dropped")
# span names whose presence anywhere in the tree marks a trace
# tail-worthy: degradation rungs (resilience.ladder), chaos marks, and
# the engine's sweep→chain retry
_TAIL_KEEP_SPANS = frozenset({"degrade", "chaos", "retry"})
# root/span attrs that mark a trace tail-worthy regardless of latency
_TAIL_KEEP_ATTRS = ("error", "hedged", "chaos")


class TailPolicy:
    """Decide, at finish(), whether a trace's full span tree is worth
    ring residency. Disabled (the default) every trace is kept — the
    PR 3 behavior. Enabled (``KAO_TRACE_TAIL=1`` or a spec, below),
    full trees are kept only for traces that ended *interesting*:

    - **slow** — wall clock at or above the rolling p-``quantile``
      (default 0.99) of the last ``window`` traces of the same name
      (the SLO-window p99 shape: per-class, recent);
    - **degraded** — any ``degrade``/``retry`` mark in the tree
      (resilience rung > 0), or an ``error`` attr anywhere;
    - **chaos-touched** — a ``chaos`` mark or attr;
    - **hedged** — the router stamped ``hedged`` on the root (the
      duplicate-race traces a tail investigation always wants).

    Everything else is *head-sampled*: kept iff a deterministic hash of
    the trace ID lands in the 1-in-``head_every`` sample (the unbiased
    baseline a dashboard compares the tail against), dropped from the
    ring otherwise — so ring memory stays bounded at fleet request
    rates while every trace an operator will actually chase is
    retrievable in full. Dropped traces still feed every histogram.

    Spec grammar: ``KAO_TRACE_TAIL=1`` (defaults) or comma-separated
    ``head=N,window=N,quantile=F,min=N``. A typo fails loudly at
    configure time (the chaos-spec discipline)."""

    def __init__(self, enabled: bool = False, head_every: int = 16,
                 window: int = 512, quantile: float = 0.99,
                 min_samples: int = 64):
        self.enabled = bool(enabled)
        self.head_every = max(int(head_every), 1)
        self.window = max(int(window), 8)
        self.quantile = min(max(float(quantile), 0.0), 1.0)
        self.min_samples = max(int(min_samples), 1)
        self._lock = threading.Lock()
        self._durations: dict[str, deque] = {}
        self.counters = {d: 0 for d in TAIL_DECISIONS}

    @classmethod
    def from_spec(cls, spec: str | None) -> "TailPolicy":
        spec = (spec or "").strip().lower()
        if not spec or spec in ("0", "off", "false"):
            return cls(enabled=False)
        kw: dict = {}
        if spec not in ("1", "on", "true"):
            keys = {"head": "head_every", "window": "window",
                    "quantile": "quantile", "min": "min_samples"}
            for part in spec.split(","):
                k, sep, v = part.strip().partition("=")
                if not sep or k not in keys:
                    raise ValueError(
                        f"bad KAO_TRACE_TAIL part {part!r}; want '1' "
                        "or comma-separated head=N,window=N,"
                        "quantile=F,min=N"
                    )
                try:
                    kw[keys[k]] = (float(v) if k == "quantile"
                                   else int(v))
                except ValueError as e:
                    raise ValueError(
                        f"bad KAO_TRACE_TAIL value {part!r}: {e}"
                    ) from e
        return cls(enabled=True, **kw)

    def configure(self, spec: str | None) -> None:
        """Re-arm from a spec string (serve boot / tests); resets the
        rolling windows but keeps the lifetime counters."""
        fresh = TailPolicy.from_spec(spec)
        with self._lock:
            self.enabled = fresh.enabled
            self.head_every = fresh.head_every
            self.window = fresh.window
            self.quantile = fresh.quantile
            self.min_samples = fresh.min_samples
            self._durations.clear()

    @staticmethod
    def _signals(report: dict) -> bool:
        """True when the span tree carries a tail signal (degraded /
        chaos-touched / hedged / errored) — an iterative walk over the
        already-serialized report, once per finish."""
        stack = [report.get("spans") or {}]
        while stack:
            sp = stack.pop()
            if sp.get("name") in _TAIL_KEEP_SPANS:
                return True
            attrs = sp.get("attrs") or {}
            for key in _TAIL_KEEP_ATTRS:
                if attrs.get(key):
                    return True
            stack.extend(sp.get("spans") or ())
        return False

    def _slow(self, name: str, wall) -> bool:
        """Feed the rolling per-name duration window; True when this
        trace sits at/above the configured quantile of the RECENT
        distribution (insufficient evidence during warmup reads as not
        slow — head sampling covers the cold start)."""
        if wall is None:
            return False
        with self._lock:
            dq = self._durations.get(name)
            if dq is None:
                dq = self._durations[name] = deque(maxlen=self.window)
            slow = False
            if len(dq) >= self.min_samples:
                ranked = sorted(dq)
                k = min(int(len(ranked) * self.quantile),
                        len(ranked) - 1)
                slow = wall >= ranked[k]
            dq.append(float(wall))
        return slow

    def decide(self, report: dict) -> str:
        """``"full"`` | ``"head"`` | ``"dropped"`` for one finished
        report. Deterministic: the head sample hashes the trace ID, so
        a replayed seeded load makes identical decisions."""
        if not self.enabled:
            return "full"
        name = report.get("name") or "solve"
        slow = self._slow(name, report.get("wall_s"))
        if slow or self._signals(report):
            decision = "full"
        else:
            tid = str(report.get("trace_id") or "")
            try:
                h = int(tid[-8:], 16)
            except ValueError:
                h = sum(tid.encode())
            decision = ("head" if h % self.head_every == 0
                        else "dropped")
        with self._lock:
            self.counters[decision] += 1
        return decision

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "head_every": self.head_every,
                "window": self.window,
                "quantile": self.quantile,
                "min_samples": self.min_samples,
                "decisions": dict(self.counters),
            }


TAIL = TailPolicy.from_spec(os.environ.get("KAO_TRACE_TAIL"))


def trace_families() -> list:
    """The ``kao_trace_*`` exposition families shared by every surface
    that renders them (serve's /metrics and the kao-router — the
    obs.expo contract, validated by tests/test_metrics_format.py)."""
    snap = TAIL.snapshot()
    with _PROP_LOCK:
        prop = dict(PROPAGATION)
    return [
        ("kao_trace_tail_enabled", "gauge",
         "tail-based trace retention armed (KAO_TRACE_TAIL; "
         "docs/OBSERVABILITY.md)",
         [(None, int(snap["enabled"]))]),
        ("kao_trace_retained_total", "counter",
         "finished traces by retention decision (full = slow/degraded/"
         "chaos/hedged tail keep; head = deterministic baseline "
         "sample; dropped = fast-clean, histograms only)",
         [({"decision": d}, snap["decisions"][d])
          for d in TAIL_DECISIONS]),
        ("kao_trace_context_total", "counter",
         "W3C traceparent codec traffic (extracted = remote contexts "
         "adopted, malformed = rejected headers tolerated as new "
         "roots, injected = contexts propagated downstream)",
         [({"event": e}, prop[e])
          for e in ("extracted", "malformed", "injected")]),
    ]


# --------------------------------------------------------------------------
# per-phase latency histograms (rendered on /metrics as
# kao_phase_seconds{phase=...} — Prometheus histogram convention)
# --------------------------------------------------------------------------

PHASE_BUCKETS = (0.005, 0.025, 0.1, 0.5, 2.5, 10.0, 60.0)
# an exemplar older than this is replaced by ANY fresh observation in
# its bucket at write time, and dropped from snapshots at read time —
# a stale worst-case must not keep advertising a trace the report ring
# has already evicted
EXEMPLAR_TTL_S = 600.0


class ExemplarHistogram:
    """A keyed Prometheus-style histogram with a worst-recent exemplar
    per (key, containment bucket) — shared by the per-phase latency
    histograms here and the per-class solve histograms in
    ``obs.flight``, so the bucket math, the exemplar policy, and the
    snapshot shapes can never drift apart.

    Exemplar policy: a bigger observation always takes its bucket's
    exemplar; a smaller one only replaces an exemplar older than
    ``ttl_s``. Reads (:meth:`exemplars`) drop entries past the TTL
    entirely — a quiet bucket must not advertise a dead trace ID
    forever."""

    def __init__(self, buckets: tuple, ttl_s: float = EXEMPLAR_TTL_S):
        self.buckets = tuple(buckets)
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        # key -> [per-bucket cumulative counts..., count, sum]
        self._rows: dict[str, list] = {}
        # (key, bucket_index) -> (value, trace_id, unix_ts); index
        # len(buckets) is the +Inf bucket (containment, per the
        # OpenMetrics exemplar convention — non-cumulative)
        self._exemplars: dict[tuple, tuple] = {}

    def observe(self, key: str, seconds: float,
                trace_id: str | None = None) -> None:
        s = float(seconds)
        idx = len(self.buckets)
        for i, le in enumerate(self.buckets):
            if s <= le:
                idx = i
                break
        now = time.time()
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                row = self._rows[key] = (
                    [0] * len(self.buckets) + [0, 0.0]
                )
            for i in range(idx, len(self.buckets)):
                row[i] += 1
            row[-2] += 1
            row[-1] += s
            if trace_id:
                cur = self._exemplars.get((key, idx))
                if (cur is None or s >= cur[0]
                        or now - cur[2] > self.ttl_s):
                    self._exemplars[(key, idx)] = (s, trace_id, now)

    def snapshot(self) -> dict[str, dict]:
        """{key: {"buckets": [(le_str, cumulative_count), ...],
        "count": n, "sum": seconds}} — buckets cumulative per the
        Prometheus histogram convention (+Inf bucket is ``count``)."""
        with self._lock:
            rows = {k: list(v) for k, v in self._rows.items()}
        out = {}
        for key, row in rows.items():
            out[key] = {
                "buckets": [
                    (repr(le), row[i])
                    for i, le in enumerate(self.buckets)
                ],
                "count": row[-2],
                "sum": round(row[-1], 6),
            }
        return out

    def exemplars(self, label: str) -> list[dict]:
        """Live (younger than the TTL) worst-recent exemplars, one per
        non-empty (key, bucket): ``{label, "le", "trace_id", "value",
        "age_s"}``."""
        now = time.time()
        with self._lock:
            items = list(self._exemplars.items())
        out = []
        for (key, idx), (val, tid, ts) in sorted(items):
            age = now - ts
            if age > self.ttl_s:
                continue  # the linked report is long evicted
            le = (
                repr(self.buckets[idx]) if idx < len(self.buckets)
                else "+Inf"
            )
            out.append({
                label: key, "le": le, "trace_id": tid,
                "value": round(val, 6), "age_s": round(age, 1),
            })
        return out

    def reset(self) -> None:
        with self._lock:
            self._rows.clear()
            self._exemplars.clear()


PHASE_HIST = ExemplarHistogram(PHASE_BUCKETS)


def observe_phase(phase: str, seconds: float,
                  trace_id: str | None = None) -> None:
    PHASE_HIST.observe(phase, seconds, trace_id=trace_id)


def phase_snapshot() -> dict[str, dict]:
    return PHASE_HIST.snapshot()


def phase_exemplars() -> list[dict]:
    """Worst-recent exemplars per (phase, bucket) — the metric-to-
    trace link rendered next to ``kao_phase_seconds`` on /metrics."""
    return PHASE_HIST.exemplars("phase")


def reset_phase_stats() -> None:
    PHASE_HIST.reset()


def _observe_tree(root: Span) -> None:
    """Feed every finished, non-skipped span into the phase histograms
    (span names are a small fixed vocabulary: the pipeline phases plus
    chunk/dispatch/compile/device_transfer). Each observation carries
    the trace ID so the histogram's worst-recent exemplar links back
    to this solve's report."""
    lock = root.trace._lock
    tid = root.trace.trace_id
    stack = [root]
    while stack:
        sp = stack.pop()
        with lock:  # in-flight workers may still attach children
            stack.extend(sp.children)
            skipped = sp.attrs.get("skipped")
        if sp is root or sp.end is None or skipped:
            continue
        observe_phase(sp.name, sp.end - sp.start, trace_id=tid)


# --------------------------------------------------------------------------
# solve-report ring buffer (GET /debug/solves/<trace_id>)
# --------------------------------------------------------------------------


def _truncate_report(report: dict, max_bytes: int) -> tuple[dict, int]:
    """Cap one report's serialized size by pruning the DEEPEST span
    level first (a pathological ladder's ten-thousand chunk children go
    before the phase skeleton an operator actually reads). Each pruned
    parent records ``spans_dropped``; a touched report is marked
    ``"truncated": true``. Returns ``(report, serialized_size)`` —
    the original object is never mutated (finish() hands the same dict
    to the caller's ``stats["solve_report"]``)."""
    size = len(json.dumps(report, default=str))
    if size <= max_bytes:
        return report, size
    report = json.loads(json.dumps(report, default=str))  # private copy
    report["truncated"] = True

    def depth_of(span: dict) -> int:
        kids = span.get("spans") or ()
        return 1 + max((depth_of(c) for c in kids), default=0)

    def prune_at(span: dict, level: int) -> None:
        kids = span.get("spans") or ()
        if level <= 1:
            if kids:
                span["spans_dropped"] = (
                    span.get("spans_dropped", 0) + len(kids)
                )
                del span["spans"]
            return
        for c in kids:
            prune_at(c, level - 1)

    root = report.get("spans")
    while size > max_bytes:
        if isinstance(root, dict):
            d = depth_of(root)
            if d > 1:
                prune_at(root, d - 1)
                size = len(json.dumps(report, default=str))
                continue
        # span tree exhausted: shed the trajectory, then give up (the
        # scalar skeleton is as small as this report gets)
        if report.pop("annealing", None) is None:
            break
        size = len(json.dumps(report, default=str))
    return report, size


class ReportRing:
    """Bounded most-recent-solve-reports map, keyed by trace ID.

    Two bounds, both resident-memory caps rather than entry counts
    alone: ``capacity`` entries, and ``max_total_bytes`` of serialized
    payload (oldest evicted first). Each report is additionally capped
    at ``max_report_bytes`` via :func:`_truncate_report` — a single
    pathological ladder (tens of thousands of chunk spans) cannot grow
    the ring unbounded."""

    def __init__(self, capacity: int = 128,
                 max_report_bytes: int = 256 << 10,
                 max_total_bytes: int = 8 << 20):
        self.capacity = max(1, int(capacity))
        self.max_report_bytes = max(4096, int(max_report_bytes))
        self.max_total_bytes = max(self.max_report_bytes,
                                   int(max_total_bytes))
        self._lock = threading.Lock()
        self._d: OrderedDict[str, tuple[dict, int]] = OrderedDict()
        self._bytes = 0
        self.truncated_total = 0

    def put(self, report: dict) -> None:
        tid = report.get("trace_id")
        if not tid:
            return
        report, size = _truncate_report(report, self.max_report_bytes)
        with self._lock:
            if report.get("truncated"):
                self.truncated_total += 1
            old = self._d.pop(tid, None)
            if old is not None:
                self._bytes -= old[1]
            self._d[tid] = (report, size)
            self._bytes += size
            while self._d and (
                len(self._d) > self.capacity
                or self._bytes > self.max_total_bytes
            ):
                if len(self._d) == 1:
                    break  # always retain the newest report
                _, (_, osz) = self._d.popitem(last=False)
                self._bytes -= osz

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            row = self._d.get(trace_id)
        return None if row is None else row[0]

    def ids(self) -> list[str]:
        """Most recent first."""
        with self._lock:
            return list(reversed(self._d))

    def stats(self) -> dict:
        with self._lock:
            return {
                "reports": len(self._d),
                "bytes": self._bytes,
                "capacity": self.capacity,
                "max_report_bytes": self.max_report_bytes,
                "max_total_bytes": self.max_total_bytes,
                "truncated_total": self.truncated_total,
            }


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


RECENT = ReportRing(
    _env_int("KAO_TRACE_RING", 128),
    max_report_bytes=_env_int("KAO_TRACE_REPORT_BYTES", 256 << 10),
    max_total_bytes=_env_int("KAO_TRACE_RING_BYTES", 8 << 20),
)
