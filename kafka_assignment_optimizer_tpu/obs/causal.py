"""Cross-process causal trace assembly (docs/OBSERVABILITY.md
"Distributed traces").

One request through the ``kao-router`` leaves span trees in SEVERAL
processes: the router's route/attempt/hedge spans, the owning worker's
solve phases, and — when a hedge fired — the duplicate's phases on a
second worker, all sharing ONE trace ID via ``traceparent``
propagation (``obs.trace.inject``/``extract``). This module re-joins
them:

- :func:`collect_remote` fans a ``GET /debug/solves/<trace_id>`` out
  to the live workers concurrently (N dead workers cost ~one timeout,
  the ``/debug/fleet`` discipline) and returns whatever reports exist;
- :func:`merge_fleet_trace` unions those remote span trees under the
  router's root report: each worker tree declares its remote parent
  (the ``parent_span_id`` its root recorded at ``extract`` time), the
  merge finds the router span carrying that ``span_id`` and marks the
  join on both sides, so the causal chain "route decision → attempt →
  worker solve phases" reads as one tree.

Time bases: span ``start_s`` offsets are per-process
(``perf_counter``-relative), so the merge carries each process's
``offset_s`` — the wall-clock delta between its root's
``started_unix`` and the router's — which the multi-process Chrome
export (``obs.chrome.to_chrome_fleet``) uses to align the track
groups. Cross-host clock skew shifts a track, never corrupts a tree;
the offset rides in the merged view so a reader can judge it.

Stdlib-only (urllib + threads): the router imports this without jax.
"""

from __future__ import annotations

import json
import threading
import urllib.request

__all__ = ["collect_remote", "merge_fleet_trace"]

DEFAULT_TIMEOUT_S = 10.0


def _fetch_http(url: str, trace_id: str, timeout_s: float) -> dict | None:
    """One worker's report for ``trace_id``, or None when the worker
    does not hold it (404 — e.g. the hedge loser's ring evicted it, or
    the request never reached this worker)."""
    try:
        # read-only telemetry fan-out: there is no client request
        # context to propagate here
        # kao: disable=KAO111 -- debug-surface GET, no active request
        with urllib.request.urlopen(
            f"{url}/debug/solves/{trace_id}", timeout=timeout_s
        ) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def collect_remote(urls: list[str], trace_id: str, *,
                   timeout_s: float = DEFAULT_TIMEOUT_S,
                   fetch=None) -> tuple[list[dict], dict]:
    """Fan ``GET /debug/solves/<trace_id>`` out to ``urls``
    CONCURRENTLY. Returns ``(reports, errors)`` where ``reports`` is
    ``[{"process": url, "report": {...}}, ...]`` (workers without the
    trace are simply absent) and ``errors`` maps unreachable workers to
    their failure — a dead peer degrades the view, never the request.
    ``fetch`` is injectable (url, trace_id -> report|None) for tests."""
    fetch = fetch or (
        lambda u, tid: _fetch_http(u, tid, timeout_s)
    )
    reports: list[dict] = []
    errors: dict = {}
    lock = threading.Lock()

    def run(u):
        try:
            rep = fetch(u, trace_id)
        except Exception as e:
            with lock:
                errors[u] = repr(e)[:200]
            return
        if isinstance(rep, dict) and rep.get("trace_id") == trace_id:
            with lock:
                reports.append({"process": u, "report": rep})

    threads = [threading.Thread(target=run, args=(u,), daemon=True)
               for u in urls]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # deterministic order for the merged view (thread finish order
    # is not)
    reports.sort(key=lambda r: r["process"])
    return reports, errors


def _span_index(span: dict, index: dict) -> None:
    """span_id -> span dict, for every ID-carrying span in the tree."""
    sid = span.get("span_id")
    if sid:
        index[sid] = span
    for child in span.get("spans") or ():
        _span_index(child, index)


def merge_fleet_trace(trace_id: str, root_report: dict | None,
                      remotes: list[dict]) -> dict:
    """Union remote span trees under the router's root report.

    ``remotes`` entries are ``{"process": label, "report": report}``
    (the :func:`collect_remote` shape). Each remote report whose root
    recorded a ``parent_span_id`` is attached to the router span
    carrying that ``span_id``: the router span gains
    ``attrs.remote_process``, the process entry records
    ``attached_to``, and ``offset_s`` aligns its clock to the router's.
    The router report is deep-copied — the ring's copy is never
    mutated. Works degraded with ``root_report=None`` (the router's
    ring evicted its half): the worker trees still union side by
    side."""
    root = (json.loads(json.dumps(root_report, default=str))
            if root_report else None)
    index: dict = {}
    if root and root.get("spans"):
        _span_index(root["spans"], index)
    base_unix = (root or {}).get("started_unix")
    processes = []
    for entry in remotes:
        rep = entry.get("report") or {}
        span_root = rep.get("spans") or {}
        parent = (span_root.get("attrs") or {}).get("parent_span_id")
        attached_to = None
        if parent and parent in index:
            attached_to = parent
            attrs = index[parent].setdefault("attrs", {})
            attrs["remote_process"] = entry.get("process")
            attrs["remote_trace"] = True
        offset_s = None
        if base_unix is not None and rep.get("started_unix") is not None:
            offset_s = round(rep["started_unix"] - base_unix, 6)
        processes.append({
            "process": entry.get("process"),
            "attached_to": attached_to,
            "offset_s": offset_s,
            "report": rep,
        })
    return {
        "trace_id": trace_id,
        "name": "fleet_trace",
        "processes_total": len(processes) + int(root is not None),
        "root": root,
        "processes": processes,
    }
