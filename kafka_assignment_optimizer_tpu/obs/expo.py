"""Shared Prometheus text-exposition helpers.

Every surface that renders ``kao_*`` families — ``serve.py``'s
``/metrics``, the ``kao-fleet`` merger, the ``kao-router`` front
process — owes the same contract: one ``# HELP`` + ``# TYPE`` pair per
family (KAO107), legal names, quoted label values, and no duplicate
samples (``tests/test_metrics_format.validate_prometheus`` is the
arbiter). This module is the one implementation of that shape so new
surfaces cannot drift from it.

A *family* here is ``(name, kind, help_text, samples)`` where
``samples`` is a list of ``(labels, value)`` and ``labels`` is a dict
(or None for an unlabeled sample). Families with no samples still emit
their HELP/TYPE pair — pre-declaring a family at zero rows is how
dashboards see it before the first event.
"""

from __future__ import annotations

__all__ = ["family_lines", "render"]


def _label_str(labels: dict | None) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v)}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


def family_lines(name: str, kind: str, help_text: str,
                 samples: list) -> list[str]:
    """One family as exposition lines: HELP/TYPE pair, then every
    ``(labels, value)`` sample."""
    lines = [f"# HELP {name} {help_text}", f"# TYPE {name} {kind}"]
    for labels, value in samples:
        lines.append(f"{name}{_label_str(labels)} {value}")
    return lines


def render(families: list) -> str:
    """A full exposition body from ``(name, kind, help, samples)``
    tuples (trailing newline included, as the format requires)."""
    lines: list[str] = []
    for name, kind, help_text, samples in families:
        lines.extend(family_lines(name, kind, help_text, samples))
    return "\n".join(lines) + "\n"
