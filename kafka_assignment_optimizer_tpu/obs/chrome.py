"""Chrome trace-event export for solve reports (docs/OBSERVABILITY.md).

Converts an ``obs.trace`` solve report (the span tree behind
``GET /debug/solves/<id>``) into Chrome trace-event JSON — the format
``chrome://tracing`` and Perfetto (https://ui.perfetto.dev) load
natively, turning a JSON span tree into a zoomable flame chart.
Surfaces: ``GET /debug/solves/<id>?format=chrome`` on serve, the
merged multi-process ``GET /debug/traces/<id>?format=chrome`` on the
kao-router (:func:`to_chrome_fleet`), and the ``kao-trace`` CLI
offline.

Mapping:

- a finished span -> one complete (``ph: "X"``) event: ``ts``/``dur``
  in integer microseconds from the root start;
- a zero-duration mark (skipped phases, ``degrade`` rungs) -> an
  instant (``ph: "i"``, thread scope) event;
- a still-running span (``wall_s: null`` — e.g. a straggling bounds
  worker) -> a complete event with ``dur: 0`` and
  ``args.in_flight: true``;
- span attrs ride in ``args``; the root carries the trace ID.

Thread lanes: Chrome nests complete events on one ``tid`` purely by
interval containment, so two OVERLAPPING siblings on one lane render
corrupted. A child nests on its parent's lane while it starts past the
previous sibling placed there; an overlapping sibling (a ``wrap()``-ed
worker span — bounds prefetch, constructor race) opens a fresh lane,
exactly like the thread it actually ran on. Events are emitted sorted
by ``ts`` (longer spans first at equal ``ts``, so parents precede
children), making ``ts`` monotonic non-decreasing — pinned by the
golden-file test.

Multi-process (:func:`to_chrome_fleet`): each process in a merged
fleet trace (``obs.causal.merge_fleet_trace``) becomes its own ``pid``
track group — the router first, then every worker, each with a
``process_name`` metadata event — aligned on the router's timeline via
the merge's per-process ``offset_s`` (wall-clock deltas between the
processes' root ``started_unix`` stamps; cross-host skew shifts a
track, never corrupts a tree).
"""

from __future__ import annotations

import json

__all__ = ["to_chrome", "to_chrome_fleet", "report_to_json"]


def _us(seconds) -> int:
    return int(round(float(seconds) * 1e6))


def _place(span: dict, lane: int, *, pid: int, offset_us: int,
           events: list, lanes_used: set, next_lane: list) -> None:
    """Emit ``span`` (and, recursively, its children with the lane
    assignment described in the module docstring) onto ``events``."""
    lanes_used.add(lane)
    ts = offset_us + _us(span.get("start_s") or 0.0)
    wall = span.get("wall_s")
    args = dict(span.get("attrs") or {})
    ev: dict = {
        "name": span.get("name") or "span",
        "ph": "X",
        "ts": ts,
        "dur": _us(wall) if wall else 0,
        "pid": pid,
        "tid": lane,
        "cat": "solve",
    }
    if wall == 0:
        ev["ph"] = "i"
        ev["s"] = "t"  # thread-scoped instant
        del ev["dur"]
    elif wall is None:
        args["in_flight"] = True
    if args:
        ev["args"] = args
    events.append(ev)
    # children: each takes the first lane (parent's first) whose
    # frontier — the end of the previous span placed DIRECTLY on
    # it under this parent — it does not overlap
    frontier: dict[int, int] = {lane: -1}
    for child in span.get("spans") or ():
        cts = offset_us + _us(child.get("start_s") or 0.0)
        cwall = child.get("wall_s")
        cend = cts + (_us(cwall) if cwall else 0)
        child_lane = next(
            (ln for ln, end in frontier.items() if cts >= end),
            None,
        )
        if child_lane is None:
            child_lane = next_lane[0]
            next_lane[0] += 1
        frontier[child_lane] = cend
        _place(child, child_lane, pid=pid, offset_us=offset_us,
               events=events, lanes_used=lanes_used,
               next_lane=next_lane)


def to_chrome(report: dict) -> dict:
    """One solve report -> ``{"traceEvents": [...], ...}`` (the Chrome
    trace-event JSON object form)."""
    events: list[dict] = []
    lanes_used: set[int] = set()
    next_lane = [1]
    root = report.get("spans") or None
    if root:
        root = dict(root)
        root["attrs"] = {
            "trace_id": report.get("trace_id"),
            **(root.get("attrs") or {}),
        }
        _place(root, 0, pid=1, offset_us=0, events=events,
               lanes_used=lanes_used, next_lane=next_lane)
    events.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
    meta = [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0, "ts": 0,
         "args": {"name": f"kao {report.get('name') or 'solve'}"}},
    ]
    for lane in sorted(lanes_used):
        meta.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": lane,
            "ts": 0,
            "args": {"name": "main" if lane == 0 else f"worker-{lane}"},
        })
    out: dict = {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": report.get("trace_id"),
            "name": report.get("name"),
            "started_unix": report.get("started_unix"),
            "wall_s": report.get("wall_s"),
        },
    }
    if report.get("annealing"):
        out["otherData"]["annealing"] = report["annealing"]
    return out


def to_chrome_fleet(merged: dict) -> dict:
    """A merged fleet trace (``obs.causal.merge_fleet_trace``) -> ONE
    Chrome trace-event JSON with per-process track groups: pid 1 is
    the router's route/attempt spans, pid 2.. are the workers' solve
    trees in :data:`merged["processes"]` order, labeled by process and
    sorted into that order in the Perfetto UI."""
    events: list[dict] = []
    meta: list[dict] = []
    groups: list[tuple[str, float | None, dict]] = []
    root = merged.get("root")
    if root:
        groups.append(("router", None, root))
    for prc in merged.get("processes") or ():
        rep = prc.get("report")
        if rep:
            label = prc.get("process") or f"process-{len(groups)}"
            groups.append((label, prc.get("offset_s"), rep))
    for sort_index, (label, offset_s, rep) in enumerate(groups):
        pid = sort_index + 1
        # negative skew clamps to the router's zero so ts stays
        # non-negative; the raw offset still rides in otherData below
        offset_us = max(_us(offset_s), 0) if offset_s else 0
        lanes_used: set[int] = set()
        next_lane = [1]
        span_root = rep.get("spans") or None
        if span_root:
            span_root = dict(span_root)
            span_root["attrs"] = {
                "trace_id": rep.get("trace_id"),
                "process": label,
                **(span_root.get("attrs") or {}),
            }
            _place(span_root, 0, pid=pid, offset_us=offset_us,
                   events=events, lanes_used=lanes_used,
                   next_lane=next_lane)
        meta.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "ts": 0,
            "args": {"name": f"kao {label}"},
        })
        meta.append({
            "name": "process_sort_index", "ph": "M", "pid": pid,
            "tid": 0, "ts": 0,
            "args": {"sort_index": sort_index},
        })
        for lane in sorted(lanes_used):
            meta.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": lane, "ts": 0,
                "args": {"name": ("main" if lane == 0
                                  else f"worker-{lane}")},
            })
    events.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": merged.get("trace_id"),
            "name": merged.get("name") or "fleet_trace",
            "processes": [
                {"pid": i + 1, "process": label,
                 "offset_s": offset_s}
                for i, (label, offset_s, _) in enumerate(groups)
            ],
        },
    }


def report_to_json(report: dict, indent: int | None = None) -> str:
    return json.dumps(to_chrome(report), indent=indent, default=str,
                      sort_keys=False)
