"""Chrome trace-event export for solve reports (docs/OBSERVABILITY.md).

Converts an ``obs.trace`` solve report (the span tree behind
``GET /debug/solves/<id>``) into Chrome trace-event JSON — the format
``chrome://tracing`` and Perfetto (https://ui.perfetto.dev) load
natively, turning a JSON span tree into a zoomable flame chart.
Surfaces: ``GET /debug/solves/<id>?format=chrome`` on serve, and the
``kao-trace`` CLI offline.

Mapping:

- a finished span -> one complete (``ph: "X"``) event: ``ts``/``dur``
  in integer microseconds from the root start;
- a zero-duration mark (skipped phases, ``degrade`` rungs) -> an
  instant (``ph: "i"``, thread scope) event;
- a still-running span (``wall_s: null`` — e.g. a straggling bounds
  worker) -> a complete event with ``dur: 0`` and
  ``args.in_flight: true``;
- span attrs ride in ``args``; the root carries the trace ID.

Thread lanes: Chrome nests complete events on one ``tid`` purely by
interval containment, so two OVERLAPPING siblings on one lane render
corrupted. A child nests on its parent's lane while it starts past the
previous sibling placed there; an overlapping sibling (a ``wrap()``-ed
worker span — bounds prefetch, constructor race) opens a fresh lane,
exactly like the thread it actually ran on. Events are emitted sorted
by ``ts`` (longer spans first at equal ``ts``, so parents precede
children), making ``ts`` monotonic non-decreasing — pinned by the
golden-file test.
"""

from __future__ import annotations

import json

__all__ = ["to_chrome", "report_to_json"]


def _us(seconds) -> int:
    return int(round(float(seconds) * 1e6))


def to_chrome(report: dict) -> dict:
    """One solve report -> ``{"traceEvents": [...], ...}`` (the Chrome
    trace-event JSON object form)."""
    events: list[dict] = []
    lanes_used: set[int] = set()
    next_lane = [1]

    def place(span: dict, lane: int) -> None:
        lanes_used.add(lane)
        ts = _us(span.get("start_s") or 0.0)
        wall = span.get("wall_s")
        args = dict(span.get("attrs") or {})
        ev: dict = {
            "name": span.get("name") or "span",
            "ph": "X",
            "ts": ts,
            "dur": _us(wall) if wall else 0,
            "pid": 1,
            "tid": lane,
            "cat": "solve",
        }
        if wall == 0:
            ev["ph"] = "i"
            ev["s"] = "t"  # thread-scoped instant
            del ev["dur"]
        elif wall is None:
            args["in_flight"] = True
        if args:
            ev["args"] = args
        events.append(ev)
        # children: each takes the first lane (parent's first) whose
        # frontier — the end of the previous span placed DIRECTLY on
        # it under this parent — it does not overlap
        frontier: dict[int, int] = {lane: -1}
        for child in span.get("spans") or ():
            cts = _us(child.get("start_s") or 0.0)
            cwall = child.get("wall_s")
            cend = cts + (_us(cwall) if cwall else 0)
            child_lane = next(
                (ln for ln, end in frontier.items() if cts >= end),
                None,
            )
            if child_lane is None:
                child_lane = next_lane[0]
                next_lane[0] += 1
            frontier[child_lane] = cend
            place(child, child_lane)

    root = report.get("spans") or None
    if root:
        root = dict(root)
        root["attrs"] = {
            "trace_id": report.get("trace_id"),
            **(root.get("attrs") or {}),
        }
        place(root, 0)
    events.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
    meta = [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0, "ts": 0,
         "args": {"name": f"kao {report.get('name') or 'solve'}"}},
    ]
    for lane in sorted(lanes_used):
        meta.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": lane,
            "ts": 0,
            "args": {"name": "main" if lane == 0 else f"worker-{lane}"},
        })
    out: dict = {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": report.get("trace_id"),
            "name": report.get("name"),
            "started_unix": report.get("started_unix"),
            "wall_s": report.get("wall_s"),
        },
    }
    if report.get("annealing"):
        out["otherData"]["annealing"] = report["annealing"]
    return out


def report_to_json(report: dict, indent: int | None = None) -> str:
    return json.dumps(to_chrome(report), indent=indent, default=str,
                      sort_keys=False)
