"""Device-occupancy sampler (docs/OBSERVABILITY.md "Fleet plane").

The roofline claim the portfolio/fleet work spends — "~15% HBM / ~4%
compute, the device is mostly idle" — was a one-off bench measurement.
This sampler turns it into a continuously observed signal: a single
low-overhead daemon thread (default OFF; ``--sample-devices HZ`` on
serve, ``KAO_SAMPLE_DEVICES`` anywhere) periodically reads

- **jax device memory stats** (``device.memory_stats()``:
  ``bytes_in_use`` / ``bytes_limit`` where the backend reports them —
  TPU/GPU do, CPU usually returns nothing) into per-device gauges
  (``kao_device_hbm_bytes{device=...}``), and
- the **dispatch-accumulator duty cycle**: the flight recorder
  accumulates every completed solve's ``device_s`` + ``dispatch_s``
  (``obs.flight.duty_totals``); the sampler differences that between
  ticks and divides by wall time — the fraction of real time the
  device spent serving dispatched work (``kao_device_duty_cycle``;
  EWMA-smoothed, so a 60 s solve landing its record all at once reads
  as sustained occupancy, not a spike),

plus a **rolling per-bucket roofline summary** from the recent flight
records (device fraction of wall per bucket, n solves) surfaced in
``/healthz``'s ``devices`` section.

Overhead contract: each tick is a handful of dict reads plus
``memory_stats()`` calls — microseconds to fractions of a millisecond
of CPU. The sampler self-accounts in THREAD CPU time
(``sample_seconds_total`` / ``overhead_frac``; wall-clock would count
GIL waits under a busy solve, which cost the solve nothing) and the
test suite pins the per-tick budget, so the <1% overhead budget at
the default 1 Hz is measured, not asserted.
Arming the sampler never imports the solve stack (device reads wait
until ``jax`` is already in ``sys.modules``); in a process where no
solve has touched a device yet, the sampler's FIRST read pays the
one-time backend init on its own thread — an operator who armed
device sampling asked for device contact — and that init is excluded
from the steady-state overhead accounting. ``/metrics`` scrapes read
only the cached tick state either way.
"""

from __future__ import annotations

import sys
import threading
import time

from . import flight as _oflight
from . import log as _olog

__all__ = ["DeviceSampler", "SAMPLER"]

DEFAULT_HZ = 1.0
ROOFLINE_WINDOW_S = 300.0  # recent-records window for the bucket summary
_DUTY_ALPHA = 0.3          # duty-cycle EWMA weight per tick


class DeviceSampler:
    """The process's periodic device-occupancy sampler."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None
        self.hz = 0.0
        self.samples_total = 0
        self.sample_seconds_total = 0.0
        self._started_monotonic: float | None = None
        self._devices: dict[str, dict] = {}
        self.duty_cycle = 0.0
        self._last_tick: float | None = None
        self._last_duty_s: float | None = None
        self._init_seen = False

    def enabled(self) -> bool:
        return self._thread is not None

    def configure(self, hz: float | None) -> None:
        """Start the sampler at ``hz`` (<= 0 or None stops it).
        Idempotent; restarts cleanly on a rate change. Each arming
        session starts its accounting fresh — a re-armed sampler's
        ``overhead_frac`` describes THIS session, not a stale one."""
        self.stop()
        if not hz or hz <= 0:
            return
        with self._lock:
            self.hz = float(hz)
            self._stop = threading.Event()
            self._started_monotonic = time.monotonic()
            self.samples_total = 0
            self.sample_seconds_total = 0.0
            self.duty_cycle = 0.0
            self._devices = {}
            self._last_tick = None
            self._last_duty_s = None
            self._thread = threading.Thread(
                target=self._run, args=(self._stop,), daemon=True,
                name="kao-device-sampler",
            )
            self._thread.start()
        _olog.log("device_sampler_started", hz=float(hz))

    def stop(self) -> None:
        with self._lock:
            stop, thread = self._stop, self._thread
            self._stop = None
            self._thread = None
        if stop is not None:
            stop.set()
        if thread is not None:
            thread.join(timeout=2.0)

    def _run(self, stop: threading.Event) -> None:
        period = 1.0 / max(self.hz, 1e-3)
        while not stop.wait(period):
            try:
                self._tick()
            except Exception as e:  # sampling must never crash serving
                _olog.warn("device_sample_failed", error=repr(e)[:200])

    def _tick(self) -> None:
        # self-accounting in THREAD CPU time, not wall: under a busy
        # solve the tick thread spends most of its wall waiting for
        # the GIL, which costs the solve nothing — thread_time is the
        # CPU the sampler actually takes from the box, the number the
        # <1% budget is about
        t0 = time.thread_time()
        now = time.monotonic()
        devices: dict[str, dict] = {}
        # device stats only from an ALREADY-imported jax — arming the
        # sampler never imports the solve stack
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                devs = jax.devices()
            except Exception:
                devs = []
            if not self._init_seen:
                # the FIRST read may pay one-time backend init (an
                # armed sampler in a process where no solve has
                # touched a device yet): it lands on this thread,
                # once, and is excluded from the steady-state per-tick
                # accounting below
                self._init_seen = True
                t0 = time.thread_time()
            try:
                for d in devs:
                    stats = d.memory_stats() or {}
                    if not stats:
                        continue
                    devices[f"{d.platform}:{d.id}"] = {
                        "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                        "bytes_limit": int(stats.get("bytes_limit", 0)),
                    }
            except Exception:
                devices = {}
        duty = _oflight.duty_totals()
        busy = duty["device_s"] + duty["dispatch_s"]
        with self._lock:
            self._devices = devices
            if self._last_tick is not None:
                dt = max(now - self._last_tick, 1e-6)
                inst = min((busy - (self._last_duty_s or 0.0)) / dt, 1.0)
                self.duty_cycle += _DUTY_ALPHA * (
                    max(inst, 0.0) - self.duty_cycle
                )
            self._last_tick = now
            self._last_duty_s = busy
            self.samples_total += 1
            self.sample_seconds_total += time.thread_time() - t0

    def _roofline(self) -> dict:
        """Per-bucket device occupancy over the recent record window:
        {bucket: {solves, device_frac, dispatch_frac, wall_s}}."""
        cutoff = time.time() - ROOFLINE_WINDOW_S
        rows: dict[str, dict] = {}
        for rec in _oflight.recent():
            if float(rec.get("ts") or 0.0) < cutoff:
                continue
            bucket = rec.get("bucket")
            key = ("x".join(str(b) for b in bucket)
                   if isinstance(bucket, list) else "unbucketed")
            split = rec.get("split") or {}
            row = rows.setdefault(key, {
                "solves": 0, "wall_s": 0.0,
                "_device_s": 0.0, "_dispatch_s": 0.0,
            })
            row["solves"] += 1
            row["wall_s"] += float(rec.get("wall_s") or 0.0)
            row["_device_s"] += float(split.get("device_s") or 0.0)
            row["_dispatch_s"] += float(split.get("dispatch_s") or 0.0)
        out = {}
        for key, row in sorted(rows.items()):
            wall = max(row["wall_s"], 1e-9)
            out[key] = {
                "solves": row["solves"],
                "wall_s": round(row["wall_s"], 3),
                "device_frac": round(row["_device_s"] / wall, 4),
                "dispatch_frac": round(row["_dispatch_s"] / wall, 4),
            }
        return out

    def stats(self) -> dict:
        """The /metrics gauge source: cached tick scalars + the
        per-device map, nothing else — a scrape must stay O(devices),
        not rebuild the per-bucket roofline summary each poll (that
        lives in :meth:`snapshot`, the /healthz payload)."""
        with self._lock:
            enabled = self._thread is not None
            elapsed = (
                time.monotonic() - self._started_monotonic
                if enabled and self._started_monotonic is not None
                else 0.0
            )
            return {
                "enabled": int(enabled),
                "samples_total": self.samples_total,
                "overhead_frac": round(
                    self.sample_seconds_total / elapsed, 6
                ) if elapsed > 0 else 0.0,
                "duty_cycle": round(self.duty_cycle, 4),
                "devices": {k: dict(v)
                            for k, v in self._devices.items()},
            }

    def snapshot(self) -> dict:
        """The ``/healthz`` ``devices`` section: the full view incl.
        the rolling per-bucket roofline summary. Never touches jax
        (reads cached tick state + the record ring)."""
        with self._lock:
            enabled = self._thread is not None
            elapsed = (
                time.monotonic() - self._started_monotonic
                if enabled and self._started_monotonic is not None
                else 0.0
            )
            avg = (self.sample_seconds_total / self.samples_total
                   if self.samples_total else 0.0)
            out = {
                "enabled": int(enabled),
                "hz": self.hz if enabled else 0.0,
                "samples_total": self.samples_total,
                "sample_seconds_total": round(
                    self.sample_seconds_total, 6),
                "avg_sample_s": round(avg, 6),
                "overhead_frac": round(
                    self.sample_seconds_total / elapsed, 6
                ) if elapsed > 0 else 0.0,
                "duty_cycle": round(self.duty_cycle, 4),
                "devices": {k: dict(v) for k, v in self._devices.items()},
            }
        out["duty_totals"] = _oflight.duty_totals()
        out["roofline"] = self._roofline() if enabled else {}
        return out


SAMPLER = DeviceSampler()
