"""Sliding-window SLO engine over the flight-record stream
(docs/OBSERVABILITY.md).

Each record class (``solve`` — /submit and CLI solves, ``delta`` —
cluster-watch events, ``lane`` — coalesced batch lanes) carries a
configurable objective: a latency bound and a success target. An
observation breaches **latency** when ``wall_s`` exceeds the bound,
and **quality** when the plan is infeasible or a sanitizer/degraded
terminal state made it untrustworthy. Burn rate is the standard SRE
ratio::

    burn = breach_fraction_in_window / (1 - target)

computed over MULTIPLE windows (default 5 m and 1 h): burn > 1 on the
short window alone is a blip; > 1 on BOTH is a fast burn — the page
condition (`status: "fast_burn"`). Surfaces:

- ``kao_slo_*`` families on ``/metrics`` (events/breach counters per
  class, burn-rate + objective gauges per class x window);
- the ``/healthz`` ``slo`` section (worst status across classes);
- ``GET /debug/slo`` — the full snapshot, including the worst recent
  observation per class with its trace ID (the exemplar that links a
  burn straight to ``GET /debug/solves/<id>``).

Window semantics (pinned by the boundary unit test): an observation at
age exactly ``window`` is OUT — membership is ``now - ts < window``.
``observe``/``snapshot`` accept an explicit ``now`` so tests replay a
synthetic flight log deterministically.

Configuration grammar (``--slo`` / ``KAO_SLO``)::

    class:latency_s[:target][,class:latency_s[:target]...]
    e.g. "solve:5:0.99,delta:2:0.995,lane:5:0.99"

Unknown classes are allowed (a future record kind gets an objective
before the code ships); malformed specs fail loudly at parse time.
"""

from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["SLOEngine", "ENGINE", "parse_spec", "DEFAULT_OBJECTIVES",
           "WINDOWS"]

# (seconds, label) — short to long; the LAST window bounds retention
WINDOWS = ((300.0, "5m"), (3600.0, "1h"))

DEFAULT_OBJECTIVES = {
    # /submit + CLI solves: the north-star budget (BASELINE.json)
    "solve": {"latency_s": 5.0, "target": 0.99},
    # watch deltas are warm-started and often warm-certify: tighter
    "delta": {"latency_s": 2.0, "target": 0.99},
    # coalesced batch lanes share one dispatch; same budget as solve
    "lane": {"latency_s": 5.0, "target": 0.99},
}

_MAX_EVENTS = 100_000  # hard cap on retained observations


def parse_spec(spec: str) -> dict[str, dict]:
    """``"solve:5:0.99,delta:2"`` -> objectives dict; raises ValueError
    on any malformed entry (a typo'd SLO silently defaulting would be
    an unwatched objective)."""
    out: dict[str, dict] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if not 2 <= len(fields) <= 3:
            raise ValueError(
                f"bad SLO entry {part!r}; want class:latency_s[:target]"
            )
        cls = fields[0].strip()
        if not cls.isidentifier():
            raise ValueError(f"bad SLO class name {fields[0]!r}")
        try:
            latency = float(fields[1])
            target = float(fields[2]) if len(fields) == 3 else 0.99
        except ValueError as e:
            raise ValueError(f"bad SLO numbers in {part!r}: {e}") from e
        if not latency > 0:
            raise ValueError(f"SLO latency must be > 0 in {part!r}")
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"SLO target must be in (0, 1) in {part!r}"
            )
        out[cls] = {"latency_s": latency, "target": target}
    if not out:
        raise ValueError(f"empty SLO spec {spec!r}")
    return out


def _quality_ok(rec: dict) -> bool:
    q = rec.get("quality") or {}
    return bool(q.get("feasible")) and not q.get("degraded")


class SLOEngine:
    """Multi-window burn-rate accounting over flight records."""

    def __init__(self, objectives: dict | None = None,
                 windows=WINDOWS):
        self._lock = threading.Lock()
        self.windows = tuple(windows)
        self.objectives = {
            k: dict(v)
            for k, v in (objectives or DEFAULT_OBJECTIVES).items()
        }
        # (ts, class, latency_s, lat_ok, qual_ok)
        self._events: deque = deque()
        # monotonic counters (rendered as kao_slo_*_total)
        self.events_total: dict[str, int] = {}
        self.latency_breaches_total: dict[str, int] = {}
        self.quality_breaches_total: dict[str, int] = {}
        # class -> (latency_s, trace_id, ts): worst recent observation
        self._worst: dict[str, tuple] = {}

    def configure(self, spec: str | None = None,
                  objectives: dict | None = None) -> None:
        obj = parse_spec(spec) if spec else (objectives or {})
        with self._lock:
            for cls, o in obj.items():
                self.objectives[cls] = dict(o)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self.events_total.clear()
            self.latency_breaches_total.clear()
            self.quality_breaches_total.clear()
            self._worst.clear()

    def _objective(self, cls: str) -> dict:
        return self.objectives.get(cls) or self.objectives.get(
            "solve", {"latency_s": 5.0, "target": 0.99}
        )

    def observe(self, cls: str, latency_s: float, quality_ok: bool,
                trace_id: str | None = None,
                now: float | None = None) -> None:
        now = time.time() if now is None else float(now)
        obj = self._objective(cls)
        lat_ok = latency_s <= obj["latency_s"]
        with self._lock:
            self._events.append(
                (now, cls, float(latency_s), lat_ok, bool(quality_ok))
            )
            self.events_total[cls] = self.events_total.get(cls, 0) + 1
            if not lat_ok:
                self.latency_breaches_total[cls] = (
                    self.latency_breaches_total.get(cls, 0) + 1
                )
            if not quality_ok:
                self.quality_breaches_total[cls] = (
                    self.quality_breaches_total.get(cls, 0) + 1
                )
            worst = self._worst.get(cls)
            if (worst is None or latency_s >= worst[0]
                    or now - worst[2] > self.windows[-1][0]):
                self._worst[cls] = (float(latency_s), trace_id, now)
            self._prune(now)

    def observe_record(self, rec: dict) -> None:
        """The flight-recorder feed: one record in, one observation."""
        self.observe(
            rec.get("kind") or "solve",
            float(rec.get("wall_s") or 0.0),
            _quality_ok(rec),
            trace_id=rec.get("trace_id"),
            now=rec.get("ts"),
        )

    def _prune(self, now: float) -> None:
        horizon = now - self.windows[-1][0]
        ev = self._events
        while ev and (ev[0][0] <= horizon or len(ev) > _MAX_EVENTS):
            ev.popleft()

    def snapshot(self, now: float | None = None) -> dict:
        """Per class: objective, cumulative totals, per-window counts,
        breach fractions, burn rates, and the page-logic status."""
        now = time.time() if now is None else float(now)
        with self._lock:
            events = list(self._events)
            totals = dict(self.events_total)
            lat_tot = dict(self.latency_breaches_total)
            qual_tot = dict(self.quality_breaches_total)
            worst = dict(self._worst)
            objectives = {k: dict(v) for k, v in self.objectives.items()}
        classes = sorted(set(totals) | set(objectives))
        # ONE pass over the event deque, accumulating per-(class,
        # window) counts — snapshot() runs on every /metrics scrape
        # and /healthz probe, and a per-(class, window) rescan of a
        # deque near the 100k cap would make monitoring O(N*C*W)
        counts: dict[str, list] = {}
        for ts, cls, _lat, lat_ok, qual_ok in events:
            age = now - ts
            rows = counts.get(cls)
            if rows is None:
                rows = counts[cls] = [
                    [0, 0, 0, 0] for _ in self.windows
                ]
            for wi, (w_s, _label) in enumerate(self.windows):
                if age < w_s:
                    row = rows[wi]
                    row[0] += 1
                    row[1] += not lat_ok
                    row[2] += not qual_ok
                    row[3] += not (lat_ok and qual_ok)
        out: dict = {"windows": [w[1] for w in self.windows],
                     "classes": {}}
        overall = "ok"
        rank = {"ok": 0, "burn": 1, "fast_burn": 2}
        for cls in classes:
            obj = objectives.get(cls) or self._objective(cls)
            budget = 1.0 - obj["target"]
            wins = {}
            burns = []
            cls_rows = counts.get(cls) or [
                [0, 0, 0, 0] for _ in self.windows
            ]
            for wi, (w_s, label) in enumerate(self.windows):
                n, lat_b, qual_b, bad = cls_rows[wi]
                frac = (bad / n) if n else 0.0
                burn = (frac / budget) if budget > 0 else 0.0
                burns.append(burn if n else 0.0)
                wins[label] = {
                    "events": n,
                    "latency_breaches": lat_b,
                    "quality_breaches": qual_b,
                    "breach_fraction": round(frac, 6),
                    "burn_rate": round(burn, 4),
                }
            if burns and all(b > 1.0 for b in burns):
                status = "fast_burn"
            elif burns and burns[0] > 1.0:
                status = "burn"
            else:
                status = "ok"
            if rank[status] > rank[overall]:
                overall = status
            w = worst.get(cls)
            if w is not None and now - w[2] > self.windows[-1][0]:
                # same read-time staleness rule as the histogram
                # exemplars: a quiet class must not keep advertising a
                # trace the report ring evicted long ago
                w = None
            out["classes"][cls] = {
                "objective": obj,
                "events_total": totals.get(cls, 0),
                "latency_breaches_total": lat_tot.get(cls, 0),
                "quality_breaches_total": qual_tot.get(cls, 0),
                "windows": wins,
                "status": status,
                **({"worst_recent": {
                    "latency_s": round(w[0], 4),
                    "trace_id": w[1],
                    "age_s": round(now - w[2], 1),
                }} if w else {}),
            }
        out["status"] = overall
        return out


ENGINE = SLOEngine()
