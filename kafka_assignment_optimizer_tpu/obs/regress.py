"""Noise-aware bench-artifact comparator — the perf-regression gate
(docs/OBSERVABILITY.md; ``bench.py --compare OLD NEW``).

``bench.py`` has emitted one JSON artifact per round since PR 1
(``BENCH_r0*.json``), but nothing ever *compared* them: the perf
trajectory had no regression gate. This module diffs two artifacts and
returns a structured verdict. Design constraints, in order:

**Environment gate first.** Latency numbers from different machines,
device counts, or ``XLA_FLAGS`` are not comparable — an 8-device CPU
mesh run vs a single-device run "regresses" 4x without a line of code
changing. ``bench.py --all`` stamps its artifact with git SHA, device
count, platform and ``XLA_FLAGS`` (ISSUE 9 satellite); the comparator
REFUSES to compare artifacts whose platform/devices/xla_flags differ
(verdict ``incomparable``), instead of reporting a bogus regression.
The git SHA is informational — differing SHAs are the whole point.

**Ratio thresholds, never absolute deltas.** CHANGES.md documents
±60 % per-test wall-clock jitter on the build container, so "warm went
from 0.9 s to 1.3 s" means nothing in isolation. Latency checks
compare ``median(new_runs) / median(old_runs)`` (median-of-N where the
artifact carries run arrays — ``jumbo_cold_runs``,
``search_cold_runs`` — the scalar otherwise, which for warm numbers is
already a best-of-3):

- ratio > ``hard_ratio`` (default 2.5): **confirmed** on its own — no
  plausible jitter doubles-and-a-half a median;
- ``soft_ratio`` (default 1.6) < ratio <= hard: **suspect** — one
  suspect is jitter; a QUORUM of suspects (at least
  ``max(2, half the latency metrics checked)``) moving together is a
  real slowdown (independent jitter does not correlate across
  scenarios);
- throughput/speedup metrics (batch solves/s, ``pipeline_speedup``)
  invert the ratio (lower is worse).

**Quality is noise-free.** Feasibility, certification
(``proved_optimal``), move counts vs a tight lower bound, the
replay-day paired-quality verdict, and storm drops are deterministic
signals: any quality regression is confirmed regardless of ratios.

Verdict: ``regression`` iff any confirmed latency finding, a suspect
quorum, or any quality regression; an identical-artifact self-compare
is ``ok`` by construction (every ratio is 1.0).

``seed_slowdown(artifact, factor)`` builds the synthetic
slowed-by-``factor`` fixture CI uses to prove the gate actually trips
(soak.yml): every latency field multiplied, every throughput field
divided, quality untouched.

**Efficiency is its own axis (ISSUE 18).** The bench ``profile`` block
carries the roofline observatory's measured occupancy and ledger
shares (obs.prof); a >= 2x occupancy collapse or a > 0.25 absolute
device-share drop is confirmed on its own — walls can stay flat while
the same work quietly doubles its device windows.
``seed_occupancy_drop(artifact, factor)`` builds that gate's CI
trip-wire fixture (walls untouched, occupancy divided).

CLI: ``python -m kafka_assignment_optimizer_tpu.obs.regress OLD NEW``
(exit 0 ok / 3 regression / 4 incomparable), or
``--seed-slowdown F IN OUT`` / ``--seed-occupancy-drop F IN OUT``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

__all__ = ["compare", "seed_slowdown", "seed_occupancy_drop",
           "load_artifact", "main"]

DEFAULT_SOFT_RATIO = 1.6
DEFAULT_HARD_RATIO = 2.5
# floor below which a latency sample is ignored entirely: at
# low-millisecond scale the ratio of two scheduler hiccups is pure
# noise (20 ms keeps the --smoke headline's best-of-3 warm number in
# play — the CI trip-wire needs at least two latency metrics)
MIN_MEANINGFUL_S = 0.02

ENV_KEYS = ("platform", "devices", "xla_flags")
# process-topology keys (ISSUE 19 satellite): compared only when BOTH
# artifacts carry the key — artifacts stamped before the topology
# fields existed must stay comparable against new ones — but a present
# mismatch (1-host vs 2-host, or a different chains×lanes mesh split)
# makes per-dispatch numbers incomparable, never a "regression"
TOPOLOGY_KEYS = ("n_processes", "mesh_axes")


def load_artifact(path: str) -> dict:
    """A bench artifact: the raw stdout-line JSON, or a driver wrapper
    whose ``parsed`` field holds it (``BENCH_r0*.json``)."""
    doc = json.loads(Path(path).read_text())
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if "metric" not in doc and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if "metric" not in doc:
        raise ValueError(
            f"{path}: not a bench artifact (no 'metric' field)"
        )
    return doc


def _median(xs) -> float | None:
    xs = [float(x) for x in xs if isinstance(x, (int, float))]
    if not xs:
        return None
    xs.sort()
    n = len(xs)
    mid = n // 2
    return xs[mid] if n % 2 else (xs[mid - 1] + xs[mid]) / 2.0


def _schema_fields(schema: str) -> list[str]:
    """Split a rows_schema string on top-level commas (the
    ``phase_s[bounds,...]`` group is ONE positional field)."""
    fields, cur, depth = [], "", 0
    for ch in schema:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        if ch == "," and depth == 0:
            fields.append(cur)
            cur = ""
        else:
            cur += ch
    if cur:
        fields.append(cur)
    return [f.split("[", 1)[0].strip() for f in fields]


def _rows_by_scenario(artifact: dict) -> dict[str, dict]:
    """Positional ``scenarios`` rows -> {scenario: {field: value}},
    driven by the artifact's OWN rows_schema (schemas grow across
    PRs; positions must never be hard-coded)."""
    schema = artifact.get("rows_schema")
    rows = artifact.get("scenarios")
    if not schema or not rows:
        return {}
    names = _schema_fields(schema)
    out = {}
    for row in rows:
        if not isinstance(row, list) or not row:
            continue
        d = {
            names[i]: row[i]
            for i in range(min(len(names), len(row)))
        }
        out[str(d.get("scenario"))] = d
    return out


def _env_verdict(old: dict, new: dict, force: bool) -> tuple[bool, str]:
    oe, ne = old.get("env"), new.get("env")
    if not isinstance(oe, dict) or not isinstance(ne, dict):
        if force:
            return True, "unstamped artifact(s); compared under --force"
        missing = [
            side for side, e in (("old", oe), ("new", ne))
            if not isinstance(e, dict)
        ]
        return False, (
            f"{'/'.join(missing)} artifact carries no env stamp "
            "(re-run bench.py --all on a build that stamps git SHA / "
            "devices / XLA_FLAGS, or pass --force)"
        )
    mismatches = [
        f"{k}: {oe.get(k)!r} != {ne.get(k)!r}"
        for k in ENV_KEYS if oe.get(k) != ne.get(k)
    ]
    mismatches += [
        f"{k}: {oe.get(k)!r} != {ne.get(k)!r}"
        for k in TOPOLOGY_KEYS
        if k in oe and k in ne and oe.get(k) != ne.get(k)
    ]
    if mismatches and not force:
        return False, (
            "environments are not comparable (" + "; ".join(mismatches)
            + ")"
        )
    return True, (
        "env mismatch overridden by --force: " + "; ".join(mismatches)
        if mismatches else "ok"
    )


def _latency_pairs(old: dict, new: dict) -> list[tuple[str, float, float]]:
    """Every comparable (name, old_seconds, new_seconds) latency
    metric present in BOTH artifacts. Lower is better for all."""
    pairs: list[tuple[str, float, float]] = []

    def add(name, ov, nv):
        # the noise floor gates on the LARGER side: tiny-vs-tiny is
        # scheduler noise, but a sub-floor baseline blowing up to
        # seconds (a broken warm-certify path) must stay visible
        if (isinstance(ov, (int, float)) and isinstance(nv, (int, float))
                and ov > 0 and max(float(ov), float(nv))
                >= MIN_MEANINGFUL_S):
            pairs.append((name, float(ov), float(nv)))

    orows, nrows = _rows_by_scenario(old), _rows_by_scenario(new)
    if not (orows and nrows):
        # headline-only artifacts (the CI smoke runs): the top-level
        # fields are the only numbers. With scenario rows present they
        # are the headline row's warm_s/cold_s VERBATIM — adding both
        # would count one jittery measurement as two correlated
        # "suspects" and defeat the independent-jitter quorum
        add("headline_warm_s", old.get("value"), new.get("value"))
        add("headline_cold_s", old.get("cold_wall_clock_s"),
            new.get("cold_wall_clock_s"))
    add("headline_cold_cached_s", old.get("cold_cached_wall_clock_s"),
        new.get("cold_cached_wall_clock_s"))
    for sc in sorted(set(orows) & set(nrows)):
        add(f"{sc}.warm_s", orows[sc].get("warm_s"),
            nrows[sc].get("warm_s"))
        add(f"{sc}.cold_s", orows[sc].get("cold_s"),
            nrows[sc].get("cold_s"))
    om, nm = _median(old.get("jumbo_cold_runs") or ()), \
        _median(new.get("jumbo_cold_runs") or ())
    add("jumbo_cold_median_s", om, nm)
    osc, nsc = old.get("search_cold_runs") or {}, \
        new.get("search_cold_runs") or {}
    for sc in sorted(set(osc) & set(nsc)):
        add(f"{sc}.cold_median_s", _median(osc[sc]), _median(nsc[sc]))
    ord_, nrd = old.get("replay_day") or {}, new.get("replay_day") or {}
    for k in ("warm_p50_s", "warm_p99_s", "cold_p50_s", "cold_p99_s"):
        add(f"replay_day.{k}", ord_.get(k), nrd.get(k))
    opa, npa = old.get("portfolio_ab") or {}, \
        new.get("portfolio_ab") or {}
    for k in ("ttfc_p50_s", "wall_p50_single_s",
              "wall_p50_portfolio_s"):
        add(f"portfolio_ab.{k}", opa.get(k), npa.get(k))
    oro, nro = old.get("rollout") or {}, new.get("rollout") or {}
    for k in ("pack_s", "replan_s", "total_s"):
        add(f"rollout.{k}", oro.get(k), nro.get(k))
    # decomposed rung (docs/DECOMPOSE.md): the ultra-jumbo cold wall is
    # the tentpole latency number — the decomposed-vs-flat speedup is
    # compared as a throughput ratio below, not double-counted here
    odc, ndc = old.get("decompose") or {}, new.get("decompose") or {}
    add("decompose.ultra_jumbo_cold_s", odc.get("ultra_jumbo_cold_s"),
        ndc.get("ultra_jumbo_cold_s"))
    # fleet latency: p99 ONLY — p50 and p99 of the same closed-loop
    # run move together, and two correlated draws must not fill the
    # suspect quorum as independent evidence (the same reasoning that
    # excludes the headline fields when scenario rows are present)
    ofl, nfl = old.get("fleet") or {}, new.get("fleet") or {}
    add("fleet.p99_s", ofl.get("p99_s"), nfl.get("p99_s"))
    # fused-megachunk arm (docs/PIPELINE.md): the fused warm wall ONLY
    # — wall_chunked_s is the adversarial warm_s already compared
    # above, and the speedup ratio is those two walls divided (quorum
    # honesty: one independent draw, counted once)
    oma, nma = old.get("megachunk_ab") or {}, \
        new.get("megachunk_ab") or {}
    add("megachunk_ab.wall_mega_s", oma.get("wall_mega_s"),
        nma.get("wall_mega_s"))
    return pairs


def _throughput_pairs(old: dict,
                      new: dict) -> list[tuple[str, float, float]]:
    """(name, old, new) where HIGHER is better."""
    pairs: list[tuple[str, float, float]] = []

    def add(name, ov, nv):
        if (isinstance(ov, (int, float)) and isinstance(nv, (int, float))
                and ov > 0):
            pairs.append((name, float(ov), float(nv)))

    obt, nbt = old.get("batch_throughput") or {}, \
        new.get("batch_throughput") or {}
    for k in ("b1", "b2", "b4", "b8"):
        add(f"batch.{k}_solves_per_s", obt.get(k), nbt.get(k))
    orows, nrows = _rows_by_scenario(old), _rows_by_scenario(new)
    for sc in sorted(set(orows) & set(nrows)):
        add(f"{sc}.pipeline_speedup",
            orows[sc].get("pipeline_speedup"),
            nrows[sc].get("pipeline_speedup"))
    # fleet capacity (docs/FLEET.md): aggregate solves/s through the
    # router. speedup is throughput/single_throughput — correlated
    # with it, so only one of the pair is compared (quorum honesty)
    ofl, nfl = old.get("fleet") or {}, new.get("fleet") or {}
    add("fleet.throughput", ofl.get("throughput"),
        nfl.get("throughput"))
    # decomposed-vs-flat speedup (docs/DECOMPOSE.md): higher means the
    # map-reduce rung buys more over the flat path at the A/B size
    odc, ndc = old.get("decompose") or {}, new.get("decompose") or {}
    add("decompose.speedup", odc.get("decompose_speedup"),
        ndc.get("decompose_speedup"))
    # ladder dispatch accounting (ISSUE 17, docs/PIPELINE.md): the
    # device share of the busy wall per scenario (higher = less host
    # round-trip overhead), and the fused arm's measured dispatch
    # amplification at K=8 (a counter ratio, not a wall clock — near
    # deterministic, so a drop is strong evidence). The fused wall
    # itself is a latency pair; megachunk_speedup is those two walls
    # divided and is NOT double-counted here.
    for sc in sorted(set(orows) & set(nrows)):
        add(f"{sc}.duty_cycle", orows[sc].get("duty_cycle"),
            nrows[sc].get("duty_cycle"))
    oma, nma = old.get("megachunk_ab") or {}, \
        new.get("megachunk_ab") or {}
    add("megachunk_ab.dispatch_reduction", oma.get("dispatch_reduction"),
        nma.get("dispatch_reduction"))
    # roofline occupancy (obs.prof, ISSUE 18): achieved/peak of the
    # dominant executable. Ratios between same-env artifacts are
    # meaningful even though the absolute peak is configurable; higher
    # is better. The ratio check here catches drift; a >= 2x collapse
    # is additionally CONFIRMED in _quality_regressions (the seeded
    # occupancy-halving fixture must trip without a quorum).
    opf, npf = old.get("profile") or {}, new.get("profile") or {}
    for k in ("occupancy_hbm", "occupancy_flops"):
        add(f"profile.{k}", opf.get(k), npf.get(k))
    # sharded-mesh A/B (docs/MESH.md): the best split's lane throughput
    # ONLY — lane_scaling is best/default divided, and the per-spec
    # curve points are correlated draws of the same run (quorum
    # honesty, same reasoning as megachunk_speedup). Topology mismatch
    # between artifacts is already an incomparability above.
    omb, nmb = old.get("mesh_bench") or {}, new.get("mesh_bench") or {}
    add("mesh_bench.best_lanes_per_s", omb.get("best_lanes_per_s"),
        nmb.get("best_lanes_per_s"))
    return pairs


# deterministic verdict keys per artifact block: their PRESENCE in both
# artifacts counts as a performed check (see compare() — an artifact
# whose only numbers sit under the latency noise floor, like the smoke
# rollout bench, is still genuinely compared on these), and their
# regression logic lives in _quality_regressions
_DETERMINISTIC_KEYS = (
    ("replay_day", ("quality_ok", "storm_dropped")),
    ("portfolio_ab", ("quality_win", "feasible_portfolio",
                      "worst_viol_portfolio")),
    ("batch_throughput", ("lanes_feasible", "moves_at_bound")),
    ("rollout", ("caps_ok", "terminal_ok")),
    ("fleet", ("affinity_ok", "quality_ok", "spread_ok", "dropped")),
    ("decompose", ("stitched_feasible", "gap_ok")),
    ("megachunk_ab", ("parity_ok", "feasible_mega")),
    ("profile", ("ledger_ok",)),
    ("mesh_bench", ("parity_ok",)),
)


def _quality_checks(old: dict, new: dict) -> int:
    """How many deterministic verdict keys are present in BOTH
    artifacts — the denominator that keeps a quality-only artifact
    from reading as 'nothing compared'."""
    n = 0
    for block, keys in _DETERMINISTIC_KEYS:
        ob, nb = old.get(block) or {}, new.get(block) or {}
        n += sum(1 for k in keys if k in ob and k in nb)
    return n


def _quality_regressions(old: dict, new: dict) -> list[dict]:
    regs: list[dict] = []
    orows, nrows = _rows_by_scenario(old), _rows_by_scenario(new)
    for sc in sorted(set(orows) & set(nrows)):
        o, n = orows[sc], nrows[sc]
        if o.get("feasible") == 1 and n.get("feasible") == 0:
            regs.append({"metric": f"{sc}.feasible",
                         "old": True, "new": False})
        if o.get("proved_optimal") == 1 and n.get("proved_optimal") == 0:
            regs.append({"metric": f"{sc}.proved_optimal",
                         "old": True, "new": False})
        lb = o.get("min_moves_lb")
        om, nm = o.get("moves"), n.get("moves")
        if (isinstance(lb, (int, float))
                and isinstance(om, (int, float))
                and isinstance(nm, (int, float))
                and om <= lb < nm):
            # the old build met a PROVABLY tight bound; the new one
            # does not — deterministic quality loss, not annealer luck
            regs.append({"metric": f"{sc}.moves_vs_bound",
                         "old": om, "new": nm, "bound": lb})
    ovb, nvb = old.get("vs_baseline"), new.get("vs_baseline")
    if (isinstance(ovb, (int, float)) and ovb > 0
            and isinstance(nvb, (int, float)) and nvb == 0):
        # vs_baseline is quality-gated to 0 on an infeasible/over-bound
        # headline plan — a zeroed score IS a quality regression
        regs.append({"metric": "headline.vs_baseline_zeroed",
                     "old": ovb, "new": nvb})
    ord_, nrd = old.get("replay_day") or {}, new.get("replay_day") or {}
    if ord_.get("quality_ok") is True and nrd.get("quality_ok") is False:
        regs.append({"metric": "replay_day.quality_ok",
                     "old": True, "new": False})
    if (ord_.get("storm_dropped") == 0
            and isinstance(nrd.get("storm_dropped"), (int, float))
            and nrd["storm_dropped"] > 0):
        regs.append({"metric": "replay_day.storm_dropped",
                     "old": 0, "new": nrd["storm_dropped"]})
    obt, nbt = old.get("batch_throughput") or {}, \
        new.get("batch_throughput") or {}
    for k in ("lanes_feasible", "moves_at_bound"):
        if obt.get(k) is True and nbt.get(k) is False:
            regs.append({"metric": f"batch.{k}",
                         "old": True, "new": False})
    # portfolio A/B quality (docs/PORTFOLIO.md): the worst-case-quality
    # win, the per-arm feasible counts, and the worst case's violation
    # count are all deterministic signals — any backslide is confirmed
    opa, npa = old.get("portfolio_ab") or {}, \
        new.get("portfolio_ab") or {}
    if opa.get("quality_win") is True and npa.get("quality_win") is False:
        regs.append({"metric": "portfolio_ab.quality_win",
                     "old": True, "new": False})
    of, nf = opa.get("feasible_portfolio"), npa.get("feasible_portfolio")
    if (isinstance(of, (int, float)) and isinstance(nf, (int, float))
            and nf < of):
        regs.append({"metric": "portfolio_ab.feasible_portfolio",
                     "old": of, "new": nf})
    ow, nw = opa.get("worst_viol_portfolio"), \
        npa.get("worst_viol_portfolio")
    if (isinstance(ow, (int, float)) and isinstance(nw, (int, float))
            and nw > ow):
        regs.append({"metric": "portfolio_ab.worst_viol_portfolio",
                     "old": ow, "new": nw})
    # streaming-rollout quality (docs/ROLLOUT.md): the cap contract and
    # the terminal verdict are deterministic — a wave exceeding its
    # transfer cap or a rollout failing to terminate cleanly is a
    # confirmed regression, never annealer luck
    oro, nro = old.get("rollout") or {}, new.get("rollout") or {}
    for k in ("caps_ok", "terminal_ok"):
        if oro.get(k) is True and nro.get(k) is False:
            regs.append({"metric": f"rollout.{k}",
                         "old": True, "new": False})
    # fleet-router quality (docs/FLEET.md): the affinity-rate floor,
    # the equal-quality verdict, the shared-cache spread proof, and
    # zero drops are all deterministic — a router that starts routing
    # cold, duplicating compiles, or dropping requests is a confirmed
    # regression regardless of wall-clock ratios
    ofl, nfl = old.get("fleet") or {}, new.get("fleet") or {}
    for k in ("affinity_ok", "quality_ok", "spread_ok"):
        if ofl.get(k) is True and nfl.get(k) is False:
            regs.append({"metric": f"fleet.{k}",
                         "old": True, "new": False})
    if (ofl.get("dropped") == 0
            and isinstance(nfl.get("dropped"), (int, float))
            and nfl["dropped"] > 0):
        regs.append({"metric": "fleet.dropped",
                     "old": 0, "new": nfl["dropped"]})
    # decomposed-rung quality (docs/DECOMPOSE.md): the oracle-checked
    # stitched feasibility and the certificate-or-gap verdict are
    # deterministic — a stitch that stops satisfying the ORIGINAL flat
    # instance, or a bound gap blowing past the tolerance, is a
    # confirmed regression, never annealer luck
    odc, ndc = old.get("decompose") or {}, new.get("decompose") or {}
    for k in ("stitched_feasible", "gap_ok"):
        if odc.get(k) is True and ndc.get(k) is False:
            regs.append({"metric": f"decompose.{k}",
                         "old": True, "new": False})
    # fused-megachunk quality (ISSUE 17, docs/PIPELINE.md): the fused
    # scan's bit-identical-plan parity and the fused plan's feasibility
    # are deterministic — a K=8 megachunk producing a different (or
    # infeasible) plan than the per-chunk ladder is a confirmed
    # trajectory break, never annealer luck. parity_ok is null when
    # the two arms walked different round counts (deadline noise);
    # null never trips the gate.
    oma, nma = old.get("megachunk_ab") or {}, \
        new.get("megachunk_ab") or {}
    for k in ("parity_ok", "feasible_mega"):
        if oma.get(k) is True and nma.get(k) is False:
            regs.append({"metric": f"megachunk_ab.{k}",
                         "old": True, "new": False})
    # efficiency regressions (obs.prof, ISSUE 18): occupancy collapsing
    # to half or worse is confirmed on its own — walls can stay flat
    # while the same work suddenly needs 2x the device windows (a
    # de-fused scan, a broken donation) and the latency quorum would
    # miss it. An attribution share shift (device share of wall falling
    # by > 0.25 absolute) is the same failure seen from the ledger
    # side. Tiny occupancies are excluded: below 1e-6 the ratio of two
    # measurement artifacts is noise, not evidence. The ledger
    # sums-to-wall conformance bit is deterministic like any parity.
    opf, npf = old.get("profile") or {}, new.get("profile") or {}
    for k in ("occupancy_hbm", "occupancy_flops"):
        ov, nv = opf.get(k), npf.get(k)
        if (isinstance(ov, (int, float)) and isinstance(nv, (int, float))
                and ov > 1e-6 and nv > 0 and ov / nv >= 2.0):
            regs.append({"metric": f"profile.{k}_collapse",
                         "old": ov, "new": nv,
                         "ratio": round(ov / nv, 3)})
    ods, nds = opf.get("device_share"), npf.get("device_share")
    if (isinstance(ods, (int, float)) and isinstance(nds, (int, float))
            and ods - nds > 0.25):
        regs.append({"metric": "profile.device_share_shift",
                     "old": ods, "new": nds})
    if opf.get("ledger_ok") is True and npf.get("ledger_ok") is False:
        regs.append({"metric": "profile.ledger_ok",
                     "old": True, "new": False})
    # sharded-mesh quality (ISSUE 19, docs/MESH.md): every candidate
    # (chains × lanes) split replaying the default split bit-for-bit
    # is the mesh's load-bearing contract — a parity flip means a
    # collective or placement change altered the trajectory, a
    # confirmed regression regardless of how the walls moved
    omm, nmm = old.get("mesh_bench") or {}, new.get("mesh_bench") or {}
    if omm.get("parity_ok") is True and nmm.get("parity_ok") is False:
        regs.append({"metric": "mesh_bench.parity_ok",
                     "old": True, "new": False})
    return regs


def compare(old: dict, new: dict, *,
            soft_ratio: float = DEFAULT_SOFT_RATIO,
            hard_ratio: float = DEFAULT_HARD_RATIO,
            force: bool = False) -> dict:
    """Diff two bench artifacts; returns the verdict dict (see module
    docstring for the noise model)."""
    comparable, reason = _env_verdict(old, new, force)
    base = {
        "gate": "kao-perf-regress",
        "thresholds": {"soft_ratio": soft_ratio,
                       "hard_ratio": hard_ratio},
        "env": {"old": old.get("env"), "new": new.get("env"),
                "note": reason},
    }
    if not comparable:
        return {**base, "comparable": False, "verdict": "incomparable",
                "reason": reason}
    # a bench run that failed outright emits an "error" artifact with
    # no real numbers — comparing it would read a broken bench as
    # "no regression"
    for side, art in (("old", old), ("new", new)):
        if art.get("error"):
            return {
                **base, "comparable": False,
                "verdict": "incomparable",
                "reason": (f"{side} artifact records a bench failure: "
                           f"{str(art['error'])[:200]}"),
            }

    confirmed, suspect, improved = [], [], []

    def judge(name, ratio, ov, nv):
        row = {"metric": name, "old": ov, "new": nv,
               "ratio": round(ratio, 3)}
        if ratio > hard_ratio:
            confirmed.append(row)
        elif ratio > soft_ratio:
            suspect.append(row)
        elif ratio < 1.0 / soft_ratio:
            improved.append(row)

    lat = _latency_pairs(old, new)
    for name, ov, nv in lat:
        judge(name, (nv / ov) if ov > 0 else 1.0, ov, nv)
    thr = _throughput_pairs(old, new)
    for name, ov, nv in thr:
        judge(name, (ov / nv) if nv > 0 else float("inf"), ov, nv)

    quality = _quality_regressions(old, new)
    n_checked = len(lat) + len(thr)
    n_quality = _quality_checks(old, new)
    if n_checked == 0 and n_quality == 0 and not quality:
        # nothing was comparable (disjoint scenario sets, stripped
        # artifacts): an empty check list must not read as a green
        # gate
        return {
            **base, "comparable": False, "verdict": "incomparable",
            "reason": "no comparable metrics between the artifacts",
        }
    quorum = max(2, -(-n_checked // 2))  # ceil(n/2), floor 2
    quorum_hit = len(suspect) + len(confirmed) >= quorum
    regression = bool(confirmed or quality) or quorum_hit
    return {
        **base,
        "comparable": True,
        "verdict": "regression" if regression else "ok",
        "checked": n_checked,
        "checked_quality": n_quality,
        "suspect_quorum": quorum,
        "latency": {
            "confirmed": confirmed,
            "suspect": suspect,
            "improved": improved,
        },
        "quality_regressions": quality,
        **({"reason": (
            "confirmed latency regression" if confirmed
            else "quality regression" if quality
            else f"{len(suspect)} correlated suspects >= quorum "
                 f"{quorum}"
        )} if regression else {}),
    }


def seed_slowdown(artifact: dict, factor: float) -> dict:
    """A synthetic copy of ``artifact`` slowed by ``factor``: every
    latency field multiplied, every throughput field divided, quality
    and the env stamp untouched. The CI gate's trip-wire fixture."""
    art = json.loads(json.dumps(artifact))
    f = float(factor)

    def scale(d, key, mul):
        v = d.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            d[key] = round(v * mul, 4)

    for k in ("value", "cold_wall_clock_s", "cold_cached_wall_clock_s"):
        scale(art, k, f)
    names = _schema_fields(art.get("rows_schema") or "")
    for row in art.get("scenarios") or ():
        if not isinstance(row, list):
            continue
        for field, mul in (("warm_s", f), ("cold_s", f),
                           ("compile_s", f)):
            if field in names:
                i = names.index(field)
                if i < len(row) and isinstance(row[i], (int, float)) \
                        and not isinstance(row[i], bool):
                    row[i] = round(row[i] * mul, 4)
    for k in ("jumbo_cold_runs",):
        if isinstance(art.get(k), list):
            art[k] = [round(x * f, 4) for x in art[k]]
    for sc, runs in (art.get("search_cold_runs") or {}).items():
        art["search_cold_runs"][sc] = [round(x * f, 4) for x in runs]
    rd = art.get("replay_day")
    if isinstance(rd, dict):
        for k in ("warm_p50_s", "warm_p99_s", "cold_p50_s",
                  "cold_p99_s"):
            scale(rd, k, f)
    bt = art.get("batch_throughput")
    if isinstance(bt, dict):
        for k in ("b1", "b2", "b4", "b8"):
            scale(bt, k, 1.0 / f)
    pa = art.get("portfolio_ab")
    if isinstance(pa, dict):
        for k in ("ttfc_p50_s", "wall_p50_single_s",
                  "wall_p50_portfolio_s"):
            scale(pa, k, f)
    fl = art.get("fleet")
    if isinstance(fl, dict):
        scale(fl, "p99_s", f)
        scale(fl, "throughput", 1.0 / f)
    dc = art.get("decompose")
    if isinstance(dc, dict):
        scale(dc, "ultra_jumbo_cold_s", f)
        scale(dc, "decompose_speedup", 1.0 / f)
    pf = art.get("profile")
    if isinstance(pf, dict):
        # a uniform slowdown stretches every device window, so the
        # achieved occupancy falls by the same factor (flops/window
        # against an unchanged peak)
        for k in ("occupancy_hbm", "occupancy_flops"):
            scale(pf, k, 1.0 / f)
    return art


def seed_occupancy_drop(artifact: dict, factor: float) -> dict:
    """A synthetic copy of ``artifact`` whose roofline occupancy
    collapsed by ``factor`` with every wall clock UNTOUCHED — the
    efficiency regression the latency quorum cannot see (the same work
    suddenly costing ``factor``x the device windows). CI's trip-wire
    fixture for the ISSUE 18 efficiency gate: ``factor`` >= 2 must
    trip exit 3 via the confirmed ``profile.*_collapse`` check."""
    art = json.loads(json.dumps(artifact))
    f = float(factor)
    pf = art.get("profile")
    if isinstance(pf, dict):
        for k in ("occupancy_hbm", "occupancy_flops",
                  "occupancy_hbm_p50", "occupancy_hbm_p99"):
            v = pf.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                pf[k] = round(v / f, 8)
        # the ledger view of the same collapse: device share of wall
        # shrinks toward zero as the lost time lands in other/gaps
        ds = pf.get("device_share")
        if isinstance(ds, (int, float)) and not isinstance(ds, bool):
            pf["device_share"] = round(ds / f, 4)
            shares = pf.get("ledger_shares")
            if isinstance(shares, dict):
                moved = ds - pf["device_share"]
                shares["device_s"] = round(
                    float(shares.get("device_s") or ds) / f, 4)
                shares["other_s"] = round(
                    float(shares.get("other_s") or 0.0) + moved, 4)
    return art


def run_compare(old_path: str, new_path: str, *,
                force: bool = False,
                soft_ratio: float = DEFAULT_SOFT_RATIO,
                hard_ratio: float = DEFAULT_HARD_RATIO) -> int:
    """Load, compare, print the verdict JSON FIRST (the CI contract:
    the verdict is replayable verbatim from the job log), return the
    gate's exit code: 0 ok / 3 regression / 4 incomparable."""
    try:
        old, new = load_artifact(old_path), load_artifact(new_path)
    except (OSError, ValueError) as e:
        # kao: disable=KAO106 -- the verdict JSON on stdout IS the product
        print(json.dumps({"gate": "kao-perf-regress",
                          "verdict": "error", "error": str(e)}))
        return 2
    verdict = compare(old, new, force=force, soft_ratio=soft_ratio,
                      hard_ratio=hard_ratio)
    # kao: disable=KAO106 -- the verdict JSON on stdout IS the product
    print(json.dumps(verdict, indent=2, default=str))
    if verdict["verdict"] == "incomparable":
        return 4
    return 3 if verdict["verdict"] == "regression" else 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kafka_assignment_optimizer_tpu.obs.regress",
        description="Noise-aware bench-artifact regression gate "
                    "(docs/OBSERVABILITY.md)",
    )
    ap.add_argument("old", nargs="?", help="baseline artifact JSON")
    ap.add_argument("new", nargs="?", help="candidate artifact JSON")
    ap.add_argument("--force", action="store_true",
                    help="compare despite missing/mismatched env stamps")
    ap.add_argument("--soft-ratio", type=float,
                    default=DEFAULT_SOFT_RATIO)
    ap.add_argument("--hard-ratio", type=float,
                    default=DEFAULT_HARD_RATIO)
    ap.add_argument("--seed-slowdown", type=float, metavar="FACTOR",
                    default=None,
                    help="instead of comparing: write a copy of OLD "
                         "slowed by FACTOR to NEW (the CI trip-wire "
                         "fixture)")
    ap.add_argument("--seed-occupancy-drop", type=float,
                    metavar="FACTOR", default=None,
                    help="instead of comparing: write a copy of OLD "
                         "whose roofline occupancy collapsed by FACTOR "
                         "(walls untouched) to NEW — the efficiency-"
                         "gate trip-wire fixture (ISSUE 18)")
    args = ap.parse_args(argv)
    if args.old is None or args.new is None:
        ap.error("need OLD and NEW artifact paths")
    if args.seed_slowdown is not None:
        if args.seed_slowdown <= 0:
            ap.error("--seed-slowdown must be > 0")
        art = load_artifact(args.old)
        Path(args.new).write_text(
            json.dumps(seed_slowdown(art, args.seed_slowdown)) + "\n"
        )
        return 0
    if args.seed_occupancy_drop is not None:
        if args.seed_occupancy_drop <= 0:
            ap.error("--seed-occupancy-drop must be > 0")
        art = load_artifact(args.old)
        Path(args.new).write_text(
            json.dumps(seed_occupancy_drop(art, args.seed_occupancy_drop))
            + "\n"
        )
        return 0
    return run_compare(args.old, args.new, force=args.force,
                       soft_ratio=args.soft_ratio,
                       hard_ratio=args.hard_ratio)


if __name__ == "__main__":
    sys.exit(main())
