"""Structured logging: one line per event, ``key=value`` pairs.

Replaces the ad-hoc ``print(..., file=sys.stderr)`` call sites in the
serving path with a single greppable format::

    ts=2026-08-03T12:00:00Z level=info event=solve trace_id=ab12... \
        solver=tpu wall_s=0.42 feasible=True

The active trace ID (``obs.trace``) is appended automatically when a
trace is live on the calling context, so serve/engine log lines join to
their ``/debug/solves`` report without any plumbing. Values containing
spaces, quotes, ``=`` or newlines are double-quoted with backslash
escapes; everything stays on one line.
"""

from __future__ import annotations

import sys
import threading
import time

_LOCK = threading.Lock()


def _fmt(v) -> str:
    if isinstance(v, float):
        v = round(v, 6)
    s = str(v)
    if s == "" or any(ch in s for ch in ' "=\n\t'):
        s = (
            '"'
            + s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
            + '"'
        )
    return s


def log(event: str, _level: str = "info", _stream=None, **fields) -> None:
    """Emit one structured line to ``_stream`` (default stderr). None
    values are dropped so call sites can pass optional fields blindly."""
    from .trace import current_trace_id

    parts = [
        "ts=" + time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        f"level={_level}",
        f"event={_fmt(event)}",
    ]
    tid = current_trace_id()
    if tid and "trace_id" not in fields:
        parts.append(f"trace_id={tid}")
    parts += [f"{k}={_fmt(v)}" for k, v in fields.items() if v is not None]
    line = " ".join(parts)
    stream = _stream if _stream is not None else sys.stderr
    with _LOCK:
        print(line, file=stream)


def info(event: str, **fields) -> None:
    log(event, **fields)


def warn(event: str, **fields) -> None:
    log(event, _level="warn", **fields)


def error(event: str, **fields) -> None:
    log(event, _level="error", **fields)
