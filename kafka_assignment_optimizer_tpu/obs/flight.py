"""Solve-cost flight recorder (docs/OBSERVABILITY.md).

Every completed product solve — a ``/submit`` solve, a cluster-watch
delta, or one lane of a coalesced batch — lands ONE compact JSON record
here: bucket identity, per-phase seconds, the compile/device/host
split, cache movement, degradation rungs, warm/cold provenance, and
plan quality (objective, certification, move count, warm-certify hit).
The record stream is what the SLO engine (``obs.slo``), the
``kao_solve_seconds`` histograms, and the perf-regression trajectory
all read from — ``/metrics`` says *that* p99 moved, the flight log says
*which solves* moved it and *what they paid for*.

Three sinks, fed by one :func:`record` call:

- an **in-memory ring** (``RECENT``, bounded) behind ``GET /debug/slo``
  and the ``kao-trace flight`` CLI;
- the **SLO engine** (``obs.slo.ENGINE.observe``) driving burn-rate
  windows and ``kao_slo_*`` metrics;
- an optional **append-only JSONL file** under ``--flight-dir`` /
  ``KAO_FLIGHT_DIR``. Appends are line-atomic best-effort; the reader
  (:func:`iter_records`) tolerates a torn final line, so a ``kill -9``
  mid-write costs at most one record. Rotation reuses the
  ``watch/store.py`` discipline: the live file is ``os.replace``d to an
  archived name (atomic on POSIX), a fresh live file is opened, and
  archives beyond the cap are pruned oldest-first.

Fleet plane (docs/OBSERVABILITY.md "Fleet plane"): every record is
additionally stamped with this process's worker identity
(host/pid/port/boot-id) plus a per-worker monotonic ``seq`` — the
``(worker, seq)`` key the fleet merge (``obs.fleet``) orders and
dedups on — and fanned out to live-stream subscribers
(``GET /debug/stream``; bounded per-client queues, slow clients shed
their own tail) and the drift monitor (``obs.drift``).

Recording must NEVER fail a solve: every sink is wrapped, failures are
counted (``kao_flight_write_errors_total``) and logged once per breed.

Per-solve accounting (``start_accounting``/``note_compile``/
``note_dispatch``): a contextvar accumulator the mesh dispatch layer
feeds so each record carries ITS OWN compile seconds and cache
hit/miss movement instead of a racy process-global delta. The watch
manager tags delta solves via :func:`context` (kind + cluster/epoch),
which the engine-level :func:`record_solve` merges in.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import queue as _queue
import socket
import threading
import time
import uuid
from collections import deque

from . import log as _olog
from .trace import ExemplarHistogram

# latency buckets for kao_solve_seconds{class=...}: warm solves sit
# around 1 s, cold ~2-70 s (compile-bound), delta warm-certify in the
# tens of ms — the ladder must resolve all three regimes
SOLVE_BUCKETS = (0.025, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 120.0,
                 600.0)

_RECENT_CAP = 512
DEFAULT_MAX_BYTES = 8 << 20   # rotate the live JSONL past this
DEFAULT_MAX_FILES = 4         # archived rotations kept

# live-stream fan-out (GET /debug/stream, docs/OBSERVABILITY.md "Fleet
# plane"): bounded per-client queues; a slow client sheds its OWN tail
# (kao_stream_dropped_total), never backpressures the solve path
MAX_STREAM_CLIENTS = int(os.environ.get("KAO_STREAM_CLIENTS", "8"))
STREAM_QUEUE_LEN = int(os.environ.get("KAO_STREAM_QUEUE", "256"))


# --------------------------------------------------------------------------
# worker identity + per-worker monotonic sequence (fleet plane)
# --------------------------------------------------------------------------

# every flight record is stamped with the worker that produced it —
# host/pid/port/boot-id — plus a per-worker monotonic ``seq``. The
# fleet merge (obs.fleet) orders WITHIN a worker by seq (immune to that
# worker's clock) and dedups on (worker, seq); readers treat records
# without these fields as legacy (single pseudo-worker, file order).
_WORKER = {
    "host": socket.gethostname(),
    "pid": os.getpid(),
    "port": None,
    "boot": uuid.uuid4().hex[:8],
}


def worker_identity() -> dict:
    """This process's worker identity stamp (copied into records)."""
    return dict(_WORKER)


def set_worker_port(port: int | None) -> None:
    """Serve calls this once the listener is bound, so records name the
    port peers would use to reach this worker."""
    _WORKER["port"] = int(port) if port is not None else None


def worker_key(rec: dict) -> str:
    """Stable merge key for the worker that produced ``rec``:
    ``host:pid:boot`` (port changes on restart reuse; boot-id breaks
    pid-recycling collisions). Legacy records collapse to one
    pseudo-worker."""
    w = rec.get("worker")
    if not isinstance(w, dict):
        return "legacy"
    return f"{w.get('host')}:{w.get('pid')}:{w.get('boot')}"


# --------------------------------------------------------------------------
# per-solve accounting (fed by parallel.mesh's dispatch/compile sites)
# --------------------------------------------------------------------------


# attribution-ledger categories (docs/OBSERVABILITY.md "Reading a
# roofline"). LEAF windows are measured directly at their source —
# mesh's compile/dispatch-enqueue/transfer sites, the engine's device
# waits — and are disjoint by construction (all on the solve thread,
# none nested in another leaf). NESTED windows (boundary, constructor)
# wrap blocks that may CONTAIN leaf windows; :func:`attribute` nets the
# leaf seconds accrued inside back out, so a transfer inside a chunk
# boundary is counted once as transfer, never twice.
LEDGER_LEAVES = ("compile", "dispatch", "device", "transfer")
LEDGER_NESTED = ("boundary", "constructor")
# sums-to-wall epsilon: 8 components rounded at 4 decimals plus
# cross-thread clock skew; relative term covers long solves
LEDGER_EPS_S = 0.005
LEDGER_EPS_FRAC = 0.01


class _SolveAcc:
    __slots__ = ("compile_s", "compiles", "cache_hits", "cache_misses",
                 "cache_fallbacks", "seconds", "leaf_s")

    def __init__(self):
        self.compile_s = 0.0
        self.compiles = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_fallbacks = 0
        # per-category measured seconds for the attribution ledger;
        # leaf_s tracks the LEAF total so nested windows can net out
        # the leaf time accrued inside them
        self.seconds = dict.fromkeys(LEDGER_LEAVES + LEDGER_NESTED, 0.0)
        self.leaf_s = 0.0


_ACC: contextvars.ContextVar = contextvars.ContextVar(
    "kao_flight_acc", default=None
)
# delta/batch context: the watch manager (and any future wrapper) tags
# the solves it drives with a kind + extra identity fields
_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "kao_flight_ctx", default=None
)


def start_accounting():
    """Begin a per-solve compile/cache accumulator on this context;
    returns the token for :func:`end_accounting`."""
    try:
        from . import prof as _oprof

        # a stale speculative dispatch from a previous solve on this
        # context must not mispair with this solve's device waits
        _oprof.reset_pending()
    except Exception:
        pass
    return _ACC.set(_SolveAcc())


def accounting_active() -> bool:
    """True when a solve accumulator is live on this context — the
    engine's nesting guard: a retry/lane solve running INSIDE another
    recorded solve must not land its own record (its compiles flow
    into the outer accumulator instead)."""
    return _ACC.get() is not None


def end_accounting(token) -> _SolveAcc | None:
    acc = _ACC.get()
    try:
        _ACC.reset(token)
    except ValueError:  # crossed threads: keep the numbers anyway
        pass
    return acc


def note_compile(seconds: float) -> None:
    """One XLA compile attributed to the current solve (mesh calls
    this next to its process-global counters). Also the ledger's
    compile LEAF window — on first contact the enqueue of the freshly
    compiled executable is inside this measurement (docs/PIPELINE.md's
    compile-inclusive-dispatch convention, inverted), so the miss path
    records NO separate dispatch window."""
    acc = _ACC.get()
    if acc is not None:
        acc.compile_s += float(seconds)
        acc.compiles += 1
        acc.seconds["compile"] += float(seconds)
        acc.leaf_s += float(seconds)


def note_dispatch(cache: str) -> None:
    """One executable dispatch: ``cache`` is hit/miss/fallback."""
    acc = _ACC.get()
    if acc is None:
        return
    if cache == "hit":
        acc.cache_hits += 1
    elif cache == "miss":
        acc.cache_misses += 1
    else:
        acc.cache_fallbacks += 1


def note_window(category: str, seconds: float) -> None:
    """One LEAF attribution window measured at its source: ``dispatch``
    (mesh's enqueue time around ``ex(*args)``, compile-exclusive),
    ``device`` (the engine's retire-side ``block_until_ready`` wait),
    ``transfer`` (``fetch_global``). Leaves are disjoint on the solve
    thread by construction; :func:`attribute` blocks net them out."""
    acc = _ACC.get()
    if acc is not None:
        acc.seconds[category] += float(seconds)
        acc.leaf_s += float(seconds)


def note_device(seconds: float) -> None:
    """One retire-side device wait: the ledger's device leaf AND the
    profiler's occupancy pairing (enqueue→retire window against the
    executable's cached cost model) in one call — the engine's walkers
    feed both planes through this single funnel."""
    note_window("device", seconds)
    try:
        from . import prof as _oprof

        _oprof.note_device(seconds)
    except Exception:
        pass


@contextlib.contextmanager
def attribute(category: str):
    """Measure a NESTED attribution window (``boundary``,
    ``constructor``): the block's wall minus whatever leaf windows
    accrued inside it — a ``fetch_global`` inside a chunk boundary
    lands once under transfer, and the boundary figure is the host
    work that remains. Never double-counts by construction."""
    acc = _ACC.get()
    if acc is None:
        yield
        return
    t0 = time.perf_counter()
    leaf0 = acc.leaf_s
    try:
        yield
    finally:
        net = (time.perf_counter() - t0) - (acc.leaf_s - leaf0)
        if net > 0:
            acc.seconds[category] += net


def ledger_marks() -> dict:
    """Cumulative funnel totals of the CURRENT solve accumulator —
    the engine differences these around a ladder so the megachunk
    evidence table is fed from the same measured windows the ledger
    lands (one accounting funnel; the two can never disagree)."""
    acc = _ACC.get()
    if acc is None:
        return {"dispatches": 0, "dispatch_s": 0.0, "device_s": 0.0}
    return {
        "dispatches": (acc.cache_hits + acc.cache_misses
                       + acc.cache_fallbacks),
        "dispatch_s": acc.seconds["dispatch"],
        "device_s": acc.seconds["device"],
    }


# queue-wait tagging (serve's worker hop): the seconds a request sat in
# the solve queue before a worker picked it up. A dedicated contextvar
# (not `context()`): the watch manager's delta tagging REPLACES the
# ambient context, and the queue share must survive that
_QWAIT: contextvars.ContextVar = contextvars.ContextVar(
    "kao_flight_qwait", default=0.0
)


def set_queue_wait(seconds: float):
    """Tag solves on this context with measured queue-wait seconds
    (serve's ``_SolveQueue._execute`` hop); returns the reset token."""
    return _QWAIT.set(max(float(seconds), 0.0))


def reset_queue_wait(token) -> None:
    try:
        _QWAIT.reset(token)
    except ValueError:
        pass


def _ledger(acc: _SolveAcc | None, wall_s: float,
            trace_id=None) -> dict:
    """The wall-clock attribution ledger: every measured category plus
    the unattributed remainder, summing to ``wall_s`` + queue wait
    within epsilon. ``ok=False`` (plus a profiler counter) marks a
    ledger whose measured components exceeded the wall beyond epsilon
    — surfaced, never silently clamped."""
    secs = acc.seconds if acc is not None else {}
    queue_wait = _QWAIT.get()
    comp = {
        "constructor_s": secs.get("constructor", 0.0),
        "compile_s": secs.get("compile", 0.0),
        "dispatch_gap_s": secs.get("dispatch", 0.0),
        "device_s": secs.get("device", 0.0),
        "transfer_s": secs.get("transfer", 0.0),
        "boundary_s": secs.get("boundary", 0.0),
    }
    measured = sum(comp.values())
    other = wall_s - measured
    eps = max(LEDGER_EPS_S, LEDGER_EPS_FRAC * wall_s)
    ok = other >= -eps
    if not ok:
        try:
            from . import prof as _oprof

            _oprof.note_ledger_overrun()
        except Exception:
            pass
    led = {
        "wall_s": round(queue_wait + wall_s, 4),
        "queue_wait_s": round(queue_wait, 4),
        **{k: round(v, 4) for k, v in comp.items()},
        "other_s": round(max(other, 0.0), 4),
        "ok": ok,
    }
    return led


@contextlib.contextmanager
def context(kind: str, **extra):
    """Tag solves under this block with ``kind`` (e.g. ``delta``) and
    identity fields (cluster, epoch) merged into their records."""
    tok = _CTX.set({"kind": kind, **extra})
    try:
        yield
    finally:
        try:
            _CTX.reset(tok)
        except ValueError:
            pass


# --------------------------------------------------------------------------
# kao_solve_seconds{class=...} histograms with worst-recent exemplars
# (the shared machinery lives in obs.trace.ExemplarHistogram so the
# bucket math and exemplar policy cannot drift from kao_phase_seconds)
# --------------------------------------------------------------------------

SOLVE_HIST = ExemplarHistogram(SOLVE_BUCKETS)


def observe_solve(cls: str, seconds: float,
                  trace_id: str | None = None) -> None:
    SOLVE_HIST.observe(cls, seconds, trace_id=trace_id)


def solve_snapshot() -> dict[str, dict]:
    """{class: {"buckets": [(le_str, cumulative), ...], "count": n,
    "sum": s}} — same shape as ``obs.trace.phase_snapshot``."""
    return SOLVE_HIST.snapshot()


def solve_exemplars() -> list[dict]:
    """Live worst-recent exemplars, one per non-empty (class, bucket):
    ``{"class", "le", "trace_id", "value", "age_s"}``."""
    return SOLVE_HIST.exemplars("class")


def reset_solve_stats() -> None:
    SOLVE_HIST.reset()


# --------------------------------------------------------------------------
# the recorder
# --------------------------------------------------------------------------


class FlightRecorder:
    """Append-only JSONL sink with atomic rotation. Disabled (memory
    ring + SLO feed only) until :meth:`configure` names a directory."""

    def __init__(self):
        self._lock = threading.Lock()
        self._dir: str | None = None
        self._fh = None
        self._bytes = 0
        self.max_bytes = DEFAULT_MAX_BYTES
        self.max_files = DEFAULT_MAX_FILES
        self.records_total = 0
        self.write_errors_total = 0
        self.rotations_total = 0
        self._seq = 1
        self._warned = False

    @property
    def path(self) -> str | None:
        return (
            os.path.join(self._dir, "flight.jsonl") if self._dir else None
        )

    def configure(self, directory: str | None,
                  max_bytes: int | None = None,
                  max_files: int | None = None) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            self._dir = directory or None
            if max_bytes is not None:
                self.max_bytes = max(int(max_bytes), 4096)
            if max_files is not None:
                self.max_files = max(int(max_files), 1)
            if self._dir:
                os.makedirs(self._dir, exist_ok=True)
                # resume the archive sequence past any prior process's
                # rotations so names stay unique and time-ordered
                self._seq = 1 + max(
                    (self._archive_seq(f)
                     for f in os.listdir(self._dir)),
                    default=0,
                )
                # probe-open the live file NOW: an existing-but-
                # unwritable directory must be a boot-time error
                # (serve maps it to ap.error, the CLI to exit 2), not
                # a per-solve warn loop silently dropping the ledger
                self._open_locked()

    @staticmethod
    def _archive_seq(name: str) -> int:
        if name.startswith("flight-") and name.endswith(".jsonl"):
            try:
                return int(name[len("flight-"):-len(".jsonl")])
            except ValueError:
                return 0
        return 0

    def enabled(self) -> bool:
        return self._dir is not None

    def _open_locked(self) -> None:
        self._fh = open(self.path, "a", encoding="utf-8")
        self._bytes = self._fh.tell()

    def _rotate_locked(self) -> None:
        """watch/store.py discipline: fsync the live file, atomically
        ``os.replace`` it to an archived name, reopen fresh, prune
        archives past the cap oldest-first."""
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
        except OSError:
            pass
        self._fh = None
        # zero-padded sequence names sort lexicographically in write
        # order (and before the live "flight.jsonl": '-' < '.'), so
        # iter_records over the directory replays chronologically
        dst = os.path.join(self._dir, f"flight-{self._seq:08d}.jsonl")
        self._seq += 1
        os.replace(self.path, dst)
        self.rotations_total += 1
        archives = sorted(
            f for f in os.listdir(self._dir)
            if f.startswith("flight-") and f.endswith(".jsonl")
        )
        for old in archives[: max(len(archives) - self.max_files, 0)]:
            try:
                os.remove(os.path.join(self._dir, old))
            except OSError:
                pass
        self._open_locked()

    def write(self, rec: dict) -> None:
        """Append one record; never raises (errors are counted and
        logged once). ``records_total`` counts SUCCESSFUL appends only
        — with no directory configured (or a failed write) it stays
        put, so the counter always agrees with the JSONL contents."""
        with self._lock:
            if self._dir is None:
                return
            try:
                if self._fh is None:
                    self._open_locked()
                line = json.dumps(rec, separators=(",", ":"),
                                  default=str)
                self._fh.write(line + "\n")
                self._fh.flush()
                self._bytes += len(line) + 1
                self.records_total += 1
                if self._bytes >= self.max_bytes:
                    self._rotate_locked()
            except OSError as e:
                self.write_errors_total += 1
                self._fh = None  # reopen on the next write
                if not self._warned:
                    self._warned = True
                    _olog.warn("flight_write_failed",
                               path=self.path, error=repr(e)[:200])

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": int(self._dir is not None),
                "dir": self._dir,
                "records_total": self.records_total,
                "write_errors_total": self.write_errors_total,
                "rotations_total": self.rotations_total,
                "max_bytes": self.max_bytes,
                "max_files": self.max_files,
            }


RECORDER = FlightRecorder()
# the in-memory tail of the record stream (GET /debug/slo, tests)
_RECENT_LOCK = threading.Lock()
RECENT: deque = deque(maxlen=_RECENT_CAP)
# records that entered the STREAM (ring + SLO + histograms) — distinct
# from the recorder's records_total, which counts only disk appends
_STREAM_TOTAL = [0]
# per-worker monotonic sequence, stamped into every record under the
# same lock that orders the ring — seq order IS ring order
_SEQ = [0]
# device-occupancy duty accounting (obs.sampler): cumulative device /
# dispatch seconds landed by completed solves; the sampler differences
# these between ticks to derive the dispatch-accumulator duty cycle
_DUTY_LOCK = threading.Lock()
_DUTY = {"device_s": 0.0, "dispatch_s": 0.0, "wall_s": 0.0, "solves": 0}


def duty_totals() -> dict:
    with _DUTY_LOCK:
        return dict(_DUTY)


def _note_duty(rec: dict) -> None:
    split = rec.get("split") or {}
    with _DUTY_LOCK:
        _DUTY["device_s"] += float(split.get("device_s") or 0.0)
        _DUTY["dispatch_s"] += float(split.get("dispatch_s") or 0.0)
        _DUTY["wall_s"] += float(rec.get("wall_s") or 0.0)
        _DUTY["solves"] += 1


class StreamClient:
    """One ``GET /debug/stream`` subscriber: a bounded queue the record
    fan-out offers into. A full queue (slow client) drops the NEWEST
    record for THIS client only and counts it — the solve path never
    blocks on a reader."""

    __slots__ = ("_q", "dropped_total")

    def __init__(self, maxlen: int = STREAM_QUEUE_LEN):
        self._q: _queue.Queue = _queue.Queue(maxsize=max(int(maxlen), 1))
        self.dropped_total = 0

    def get(self, timeout: float | None = None) -> dict | None:
        """Next record, or None on timeout (heartbeat opportunity)."""
        try:
            return self._q.get(timeout=timeout)
        except _queue.Empty:
            return None

    def _offer(self, rec: dict) -> None:
        try:
            self._q.put_nowait(rec)
        except _queue.Full:
            self.dropped_total += 1
            with _STREAM_LOCK:
                _STREAM_DROPPED[0] += 1


_STREAM_LOCK = threading.Lock()
_STREAM_CLIENTS: list[StreamClient] = []
_STREAM_DROPPED = [0]


def subscribe(maxlen: int = STREAM_QUEUE_LEN) -> StreamClient:
    """Register a live-stream subscriber; raises RuntimeError at the
    client cap (the caller sheds with 503 + Retry-After)."""
    client = StreamClient(maxlen)
    with _STREAM_LOCK:
        if len(_STREAM_CLIENTS) >= MAX_STREAM_CLIENTS:
            raise RuntimeError(
                f"stream client cap reached ({MAX_STREAM_CLIENTS}); "
                "retry later or raise KAO_STREAM_CLIENTS"
            )
        _STREAM_CLIENTS.append(client)
    return client


def unsubscribe(client: StreamClient) -> None:
    with _STREAM_LOCK:
        try:
            _STREAM_CLIENTS.remove(client)
        except ValueError:
            pass


def stream_stats() -> dict:
    with _STREAM_LOCK:
        return {
            "clients": len(_STREAM_CLIENTS),
            "dropped_total": _STREAM_DROPPED[0],
        }


def configure(directory: str | None, **kw) -> None:
    RECORDER.configure(directory, **kw)


def enabled() -> bool:
    return RECORDER.enabled()


def snapshot() -> dict:
    with _RECENT_LOCK:
        stream = _STREAM_TOTAL[0]
    return {**RECORDER.snapshot(), "stream_records_total": stream}


def recent(n: int | None = None, kind: str | None = None) -> list[dict]:
    with _RECENT_LOCK:
        recs = list(RECENT)
    if kind is not None:
        recs = [r for r in recs if r.get("kind") == kind]
    return recs[-n:] if n else recs


def reset_recent() -> None:
    with _RECENT_LOCK:
        RECENT.clear()


def record(rec: dict) -> None:
    """Land one flight record on every sink. Never raises.

    Stamps the worker identity + per-worker monotonic ``seq`` here —
    the ONE funnel every record builder goes through — so the fleet
    merge key exists on solve, failure, delta, lane, and exact-oracle
    records alike. Also fans the record out to live-stream subscribers
    (``GET /debug/stream``) and the drift monitor (``obs.drift``)."""
    try:
        with _RECENT_LOCK:
            _SEQ[0] += 1
            rec.setdefault("worker", worker_identity())
            rec.setdefault("seq", _SEQ[0])
            RECENT.append(rec)
            _STREAM_TOTAL[0] += 1
        RECORDER.write(rec)
        with _STREAM_LOCK:
            clients = list(_STREAM_CLIENTS)
        for c in clients:
            c._offer(rec)
        _note_duty(rec)
        observe_solve(rec.get("kind") or "solve",
                      float(rec.get("wall_s") or 0.0),
                      rec.get("trace_id"))
        from . import drift as _drift
        from . import slo as _slo

        _slo.ENGINE.observe_record(rec)
        _drift.MONITOR.observe_record(rec)
    except Exception as e:  # telemetry must never fail a solve
        _olog.warn("flight_record_failed", error=repr(e)[:200])


def _split(stats: dict, acc: _SolveAcc | None, wall_s: float) -> dict:
    """The compile/device/host wall split: device + dispatch seconds
    come from the ladder accounting, compile from this solve's own
    accumulator, host is the remainder — the components sum to
    ~wall_s. Dispatch is compile-INCLUSIVE on first contact
    (docs/PIPELINE.md), so the remainder subtracts
    ``max(dispatch, compile)`` rather than both: subtracting both
    would double-count the compile that happened inside the enqueue
    window."""
    device_s = float(stats.get("device_s") or 0.0)
    dispatch_s = float(stats.get("dispatch_s") or 0.0)
    compile_s = round(acc.compile_s, 4) if acc else 0.0
    host_s = max(wall_s - device_s - max(dispatch_s, compile_s), 0.0)
    out = {
        "compile_s": compile_s,
        "device_s": round(device_s, 4),
        "dispatch_s": round(dispatch_s, 4),
        "host_s": round(host_s, 4),
    }
    # ladder dispatch count + duty cycle (ISSUE 17): duty is the
    # fraction of the solve's device-facing wall the device was
    # actually computing — megachunk fusion raises it by collapsing
    # per-chunk enqueue round-trips (docs/OBSERVABILITY.md)
    if stats.get("dispatches") is not None:
        out["dispatches"] = int(stats["dispatches"])
        busy = device_s + dispatch_s
        out["duty_cycle"] = round(device_s / busy, 4) if busy > 0 else None
    return out


def record_solve(result, inst=None, acc: _SolveAcc | None = None,
                 *, kind: str | None = None,
                 wall_s: float | None = None,
                 extra: dict | None = None) -> dict | None:
    """Build and land the compact record for one finished engine solve
    (``result``: a SolveResult). The ambient :func:`context` supplies
    the kind + identity for delta solves; ``kind`` overrides (batch
    lanes). Returns the record (tests), or None on failure — recording
    never raises into the solve path."""
    try:
        st = result.stats
        ctx = _CTX.get() or {}
        k = kind or ctx.get("kind") or "solve"
        wall = float(wall_s if wall_s is not None
                     else result.wall_clock_s)
        bucket = None
        if inst is not None:
            bucket = [
                int(inst.num_brokers), int(inst.num_racks),
                int(st["bucket_parts"]) if st.get("bucket_parts")
                is not None else None,
                int(st["bucket_rf"]) if st.get("bucket_rf")
                is not None else None,
            ]
        rep = st.get("solve_report") or {}
        phases = {
            p: round(float(v), 4)
            for p, v in (rep.get("phases") or {}).items()
        } or {
            # untraced solve: the engine's own coarse phase clocks
            "seed": round(float(st.get("seed_s") or 0.0), 4),
            "ladder": round(float(st.get("anneal_s") or 0.0), 4),
            "polish": round(float(st.get("polish_s") or 0.0), 4),
        }
        construct_path = st.get("construct_path")
        rec = {
            "ts": round(time.time(), 3),
            "kind": k,
            "trace_id": st.get("trace_id"),
            "engine": st.get("engine"),
            "bucket": bucket,
            "wall_s": round(wall, 4),
            "phases": phases,
            # wall-clock attribution (docs/OBSERVABILITY.md "Reading a
            # roofline"): queue-wait / constructor / compile /
            # dispatch-gap / device / transfer / boundary / other,
            # summing to wall + queue within epsilon
            "ledger": _ledger(acc, wall),
            "split": _split(st, acc, wall),
            "cache": {
                "hits": acc.cache_hits if acc else 0,
                "misses": acc.cache_misses if acc else 0,
                "fallbacks": acc.cache_fallbacks if acc else 0,
                "compiles": acc.compiles if acc else 0,
            },
            "degradations": list(st.get("degradations") or ()),
            "warm": {
                # warm path = no compile paid by THIS solve
                "warm_path": not (acc.compiles if acc else 0),
                "warm_started": bool(st.get("warm_started")),
                "warm_certify": construct_path == "warm",
                "resumed": bool(st.get("resumed_from_checkpoint")),
                "construct_path": construct_path,
            },
            "quality": {
                "feasible": bool(st.get("feasible")),
                "certified": bool(st.get("proved_optimal")),
                "moves": st.get("moves"),
                "objective": getattr(result, "objective", None),
                "timed_out": bool(st.get("timed_out")),
                "degraded": bool(st.get("degraded")),
            },
        }
        if st.get("portfolio"):
            # winner-lane provenance (docs/PORTFOLIO.md): which lane
            # config produced the plan, whether a first-to-certify
            # boundary retired the ladder, and when
            rec["portfolio"] = dict(st["portfolio"])
        if st.get("megachunk"):
            # fused-ladder provenance (ISSUE 17, docs/PIPELINE.md):
            # resolved width + chooser mode, group/chunk counts, and
            # whether an on-device certificate retired the scan
            rec["megachunk"] = dict(st["megachunk"])
        if st.get("decompose"):
            # map-reduce provenance (docs/DECOMPOSE.md): sub-problem
            # count, map<->reduce iterations, and the certificate-or-
            # bound-gap outcome of the stitched plan
            d = st["decompose"]
            rec["decompose"] = {
                "subproblems": d.get("subproblems"),
                "iterations": d.get("iterations"),
                "boundary_parts": d.get("boundary_parts"),
                "certified": bool(d.get("certified")),
                "bound_gap": d.get("bound_gap"),
            }
        for key, v in {**ctx, **(extra or {})}.items():
            if key != "kind" and key not in rec:
                rec[key] = v
        if rep:
            # dispatch-gap series from the solve report's span
            # timestamps (obs.prof): p99-gap exemplars carry this
            # trace_id into the ISSUE 15 trace chain
            from . import prof as _oprof

            _oprof.observe_gaps(rep, rec.get("trace_id"))
        record(rec)
        return rec
    except Exception as e:
        _olog.warn("flight_record_failed", error=repr(e)[:200])
        return None


def record_failure(inst, acc: _SolveAcc | None, wall_s: float,
                   error: BaseException, *,
                   kind: str | None = None) -> dict | None:
    """The record for a solve that RAISED: no plan, no quality — but
    the failure must burn the SLO quality budget and land in the
    ledger, or a total outage of the solve path reads as zero burn
    ("the page condition never fires because nothing completed").
    Never raises."""
    try:
        ctx = _CTX.get() or {}
        rec = {
            "ts": round(time.time(), 3),
            "kind": kind or ctx.get("kind") or "solve",
            "trace_id": None,
            "engine": None,
            "bucket": (
                [int(inst.num_brokers), int(inst.num_racks), None,
                 None] if inst is not None else None
            ),
            "wall_s": round(float(wall_s), 4),
            "phases": {},
            # failures still carry their measured windows — whatever
            # the solve paid before raising is attributed, the rest
            # lands in other
            "ledger": _ledger(acc, float(wall_s)),
            "split": _split({}, acc, float(wall_s)),
            "cache": {
                "hits": acc.cache_hits if acc else 0,
                "misses": acc.cache_misses if acc else 0,
                "fallbacks": acc.cache_fallbacks if acc else 0,
                "compiles": acc.compiles if acc else 0,
            },
            "degradations": [],
            "warm": {"warm_path": False, "warm_started": False,
                     "warm_certify": False, "resumed": False,
                     "construct_path": None},
            "quality": {"feasible": False, "certified": False,
                        "moves": None, "objective": None,
                        "timed_out": False, "degraded": False},
            "error": repr(error)[:200],
        }
        from . import trace as _otrace

        rec["trace_id"] = _otrace.current_trace_id()
        for key, v in ctx.items():
            if key != "kind" and key not in rec:
                rec[key] = v
        record(rec)
        return rec
    except Exception as e:
        _olog.warn("flight_record_failed", error=repr(e)[:200])
        return None


def record_optimize(result) -> dict | None:
    """Reduced record for a non-TPU (exact-oracle) solve —
    ``api.optimize`` calls this when the resolved solver has no
    engine-level recorder, so exact-solver traffic (the small-instance
    path ``auto`` routes to MILP/native) still lands in the SLO ledger.
    Phase/split/cache columns are annealing-engine concepts and stay
    empty; quality is computed against the same oracle every solver
    answers to. Never raises."""
    try:
        solve = result.solve
        inst = result.instance
        viol = inst.violations(solve.a)
        from . import trace as _otrace

        ctx = _CTX.get() or {}
        rec = {
            "ts": round(time.time(), 3),
            "kind": ctx.get("kind") or "solve",
            "trace_id": (solve.stats.get("trace_id")
                         or _otrace.current_trace_id()),
            "engine": solve.solver,
            "bucket": [int(inst.num_brokers), int(inst.num_racks),
                       None, None],
            "wall_s": round(float(result.wall_clock_s), 4),
            "phases": {},
            # exact-oracle solves pay no device windows: the ledger is
            # degenerate (queue + other = wall) but PRESENT, so every
            # record kind answers the same attribution query
            "ledger": _ledger(None, float(result.wall_clock_s)),
            "split": {"compile_s": 0.0, "device_s": 0.0,
                      "dispatch_s": 0.0,
                      "host_s": round(float(result.wall_clock_s), 4)},
            "cache": {"hits": 0, "misses": 0, "fallbacks": 0,
                      "compiles": 0},
            "degradations": list(solve.stats.get("degradations") or ()),
            "warm": {
                "warm_path": True,  # exact solvers never compile
                "warm_started": False,
                "warm_certify": False,
                "resumed": False,
                "construct_path": solve.solver,
            },
            "quality": {
                "feasible": all(v == 0 for v in viol.values()),
                "certified": bool(solve.optimal),
                "moves": result.moves.replica_moves,
                "objective": solve.objective,
                "timed_out": False,
                "degraded": bool(solve.stats.get("degraded")),
            },
        }
        for key, v in ctx.items():
            if key != "kind" and key not in rec:
                rec[key] = v
        record(rec)
        return rec
    except Exception as e:
        _olog.warn("flight_record_failed", error=repr(e)[:200])
        return None


def iter_records(path: str):
    """Yield records from one flight JSONL file (or every file,
    archives first, when ``path`` is a directory). A torn/corrupt line
    — the kill -9 tail — is skipped, never fatal."""
    paths = [path]
    if os.path.isdir(path):
        names = sorted(
            f for f in os.listdir(path)
            if f.startswith("flight") and f.endswith(".jsonl")
        )
        # archives (flight-*) sort before the live file (flight.jsonl)
        # lexicographically already: '-' < '.'
        paths = [os.path.join(path, f) for f in names]
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except ValueError:
                        continue  # torn tail / bit rot: skip
        except OSError:
            continue


def _parse_lines(chunk: bytes, buf: bytes):
    """Split ``buf + chunk`` into complete JSON lines; returns
    (records, remaining_partial). A torn trailing line stays buffered
    until its newline lands — never parsed early. Bytes in, so byte
    offsets stay exact for the :func:`snapshot_records` resume
    handoff."""
    buf += chunk
    out = []
    while b"\n" in buf:
        line, buf = buf.split(b"\n", 1)
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue  # bit rot mid-stream: skip the line
    return out, buf


def _live_and_dir(path: str) -> tuple[str, str]:
    if os.path.isdir(path):
        return os.path.join(path, "flight.jsonl"), path
    return path, os.path.dirname(path) or "."


def _list_archives(dirpath: str) -> list:
    """[(seq, fullpath, inode)] for the dir's archives, seq-sorted
    (the writer's zero-padded names make seq == write order)."""
    rows = []
    try:
        names = os.listdir(dirpath)
    except OSError:
        return rows
    for name in names:
        seq = FlightRecorder._archive_seq(name)
        if seq <= 0:
            continue
        full = os.path.join(dirpath, name)
        try:
            rows.append((seq, full, os.stat(full).st_ino))
        except OSError:
            continue  # pruned between listdir and stat
    rows.sort()
    return rows


def snapshot_records(path: str) -> tuple[list, tuple]:
    """Every record currently on disk (archives in write order, then
    the live file), plus a RESUME token for :func:`follow_records` —
    the gap-free ``kao-trace flight --tail --follow`` handoff: a
    record landing between this snapshot and the follow's first read
    is delivered by the follow, never skipped and never duplicated.

    The token is ``(live_inode, live_byte_offset, seen_archive_seq)``;
    ``seen_archive_seq`` is captured BEFORE the live read, so a
    rotation racing the snapshot leaves the rotated-in archive above
    the watermark for the follower to catch up."""
    live, dirpath = _live_and_dir(path)
    archives = _list_archives(dirpath)
    seen_seq = max((s for s, _f, _i in archives), default=0)
    records: list = []
    for _seq, full, _ino in archives:
        try:
            with open(full, "rb") as fh:
                recs, _rest = _parse_lines(fh.read(), b"")
                records.extend(recs)
        except OSError:
            continue
    ino, offset = None, 0
    try:
        with open(live, "rb") as fh:
            ino = os.fstat(fh.fileno()).st_ino
            data = fh.read()
        # resume at the byte after the last COMPLETE line: a torn tail
        # stays for the follower, which buffers it until the newline
        offset = data.rfind(b"\n") + 1
        recs, _rest = _parse_lines(data[:offset], b"")
        records.extend(recs)
    except OSError:
        pass
    return records, (ino, offset, seen_seq)


def follow_records(path: str, *, poll_s: float = 0.2,
                   stop=None, from_start: bool = False,
                   resume: tuple | None = None):
    """``tail -f`` the live flight JSONL, surviving rotation
    (``kao-trace flight --follow``).

    Rotation contract (matches :meth:`FlightRecorder._rotate_locked`):
    the writer ``os.replace``s the live file to a ``flight-NNNNNNNN``
    archive and opens a fresh, EMPTY live file. The follower holds the
    OLD fd, so on detecting the swap it (1) drains every record still
    unread from that fd, (2) reads any archives that rotated in SINCE
    it last looked — a fast writer can rotate several times between
    polls — skipping the archive whose inode it just drained and
    anything at or below the highest archive sequence already
    consumed, then (3) reopens the new live file FROM ITS START, which
    contains only post-rotation records. A record is therefore never
    yielded twice; none is skipped short of archive pruning outrunning
    the follower. Partial trailing lines are buffered until their
    newline lands.

    ``stop`` is an optional zero-arg callable polled between reads;
    ``from_start=False`` (the default) begins at the live file's
    current end, like ``tail -f``; ``resume`` is the token from
    :func:`snapshot_records` — the follow continues at the exact byte
    the snapshot stopped at (rotation-safe), so snapshot + follow
    covers the stream gap-free."""
    path, dirpath = _live_and_dir(path)

    def _read_archive(full: str, start: int = 0):
        try:
            with open(full, "rb") as af:
                if start:
                    af.seek(start)
                recs, _rest = _parse_lines(af.read(), b"")
                return recs
        except OSError:
            return []  # pruned mid-read: its records are gone

    fh = None
    ino = None
    buf = b""
    first_open = True
    resume_pending = resume is not None
    if resume is not None:
        resume_ino, resume_offset, seen_seq = resume
    else:
        resume_ino, resume_offset = None, 0
        # archives present at start are history, never re-read
        seen_seq = max(
            (s for s, _f, _i in _list_archives(dirpath)), default=0
        )
    while True:
        if fh is None:
            try:
                fh = open(path, "rb")
                ino = os.fstat(fh.fileno()).st_ino
            except OSError:
                fh = None
            if fh is not None:
                if resume_pending:
                    resume_pending = False
                    if ino == resume_ino:
                        # no rotation since the snapshot (archives only
                        # appear via rotation, which changes the live
                        # inode): continue at the exact byte it
                        # stopped at
                        fh.seek(resume_offset)
                    else:
                        # rotations since the snapshot: the snapshot's
                        # live file is an archive now — read it from
                        # the snapshot offset, newer archives in full;
                        # the just-opened live file reads from start
                        for seq, full, a_ino in _list_archives(dirpath):
                            if a_ino == ino:
                                # the fd we JUST opened rotated out
                                # before this listing: it reads these
                                # bytes itself (from offset 0), so
                                # reading the archive too would yield
                                # every record twice
                                seen_seq = max(seen_seq, seq)
                                continue
                            if seq <= seen_seq:
                                continue
                            yield from _read_archive(
                                full,
                                resume_offset if a_ino == resume_ino
                                else 0,
                            )
                            seen_seq = max(seen_seq, seq)
                elif first_open and not from_start:
                    fh.seek(0, os.SEEK_END)
            first_open = False
        got = b""
        if fh is not None:
            try:
                got = fh.read()
            except OSError:
                got = b""
            if got:
                recs, buf = _parse_lines(got, buf)
                yield from recs
        if fh is not None and not got:
            # at EOF of the fd we hold: has the live path moved on?
            try:
                cur = os.stat(path).st_ino
            except OSError:
                cur = None  # between os.replace and the fresh open
            if cur != ino:
                # final drain: the writer may have appended between our
                # last read and the swap; the archived inode is frozen
                # now, so read-to-EOF is complete
                while True:
                    try:
                        tail_chunk = fh.read()
                    except OSError:
                        break
                    if not tail_chunk:
                        break
                    recs, buf = _parse_lines(tail_chunk, buf)
                    yield from recs
                try:
                    fh.close()
                except OSError:
                    pass
                fh = None
                buf = b""
                # catch up on archives that rotated in since the last
                # look: skip the one we just drained by inode, and
                # everything already consumed by sequence
                for seq, full, a_ino in _list_archives(dirpath):
                    if a_ino == ino:
                        seen_seq = max(seen_seq, seq)
                        continue  # the fd above already delivered it
                    if seq <= seen_seq:
                        continue
                    yield from _read_archive(full)
                    seen_seq = max(seen_seq, seq)
                continue  # reopen the new live file from its start
        if stop is not None and stop():
            return
        if not got:
            time.sleep(poll_s)
