"""Fleet telemetry plane: merge many workers' flight streams into one
ordered view (docs/OBSERVABILITY.md "Fleet plane").

A pod-scale serving fleet is N processes each landing its own flight
records (``obs.flight``) — N rings, N SLO engines, N JSONL dirs. This
module is the aggregation layer, built BEFORE the multi-host mesh
exists so every fleet PR lands with its denominator instrumented
(DrJAX's framing: aggregation as first-class map/reduce over
distributed leaves, PAPERS.md):

- **merge**: N workers' flight JSONL dirs (or live ``/debug/stream``
  snapshots) into one ordered stream. Clock-skew tolerant: WITHIN a
  worker, records are ordered by their per-worker monotonic ``seq``
  (that worker's clock cannot reorder them); ACROSS workers a k-way
  merge orders by timestamp. Duplicates — a record read from both an
  archive and a live snapshot — dedup on ``(worker, seq)``. Torn
  kill-9 tails and mid-merge rotation are absorbed by the reader
  (``obs.flight.iter_records``); records without worker/seq stamps
  are legacy and collapse to one pseudo-worker in file order.
- **fleet SLO**: the PR-8 burn-rate engine re-run over the merged
  stream — the SAME ``kao_slo_*`` families a single worker exposes,
  now fleet-wide — plus ``kao_fleet_workers`` /
  ``kao_fleet_lag_seconds{worker=}``.
- **fleet drift**: the ``obs.drift`` monitor over the merged stream
  (``kao_drift_*``), so a fleet-wide mid-run slowdown trips even if
  no single worker's share crossed its own threshold.

Surfaces: the ``kao-fleet`` console script (offline dirs or live
peers) and ``GET /debug/fleet`` on any worker pointed at peer URLs
(``--fleet-peers``) — the bucket-affinity router's future data source.
"""

from __future__ import annotations

import argparse
import heapq
import json
import os
import sys
import time
import urllib.request

from . import drift as _odrift
from . import flight as _oflight
from . import slo as _oslo

__all__ = ["merge_sources", "build_view", "fetch_records",
           "render_fleet_metrics", "main"]

DEFAULT_TAIL = 512
DEFAULT_TIMEOUT_S = 5.0


# --------------------------------------------------------------------------
# merge
# --------------------------------------------------------------------------


def _rec_ts(rec: dict) -> float:
    try:
        return float(rec.get("ts") or 0.0)
    except (TypeError, ValueError):
        return 0.0


def merge_sources(sources) -> tuple[list, dict, int]:
    """``sources``: iterable of ``(label, iterable_of_records)``.
    Returns ``(records, per_worker, duplicates_dropped)``.

    Per worker: stamped records sort by ``seq`` (skew inside a worker
    cannot reorder its own stream) and dedup on ``(worker, seq)``;
    legacy records (no stamp) keep arrival order and never dedup.
    Across workers: a k-way heap merge on ``ts`` — it only ever pops
    stream heads, so per-worker seq order survives even when worker
    clocks disagree."""
    per: dict[str, list] = {}
    seen: set = set()
    dups = 0
    for label, records in sources:
        for arrival, rec in enumerate(records):
            if not isinstance(rec, dict):
                continue
            wkey = _oflight.worker_key(rec)
            seq = rec.get("seq")
            if isinstance(seq, int):
                if (wkey, seq) in seen:
                    dups += 1
                    continue
                seen.add((wkey, seq))
                order = (0, seq, arrival)
            else:
                order = (1, arrival, 0)  # legacy: after, in file order
            per.setdefault(wkey, []).append((order, rec))
    per_worker: dict[str, dict] = {}
    streams = []
    for wkey, rows in per.items():
        rows.sort(key=lambda r: r[0])
        recs = [r[1] for r in rows]
        seqs = [r.get("seq") for r in recs if isinstance(r.get("seq"), int)]
        info: dict = {
            "records": len(recs),
            "first_ts": _rec_ts(recs[0]),
            "last_ts": _rec_ts(recs[-1]),
        }
        if seqs:
            info["min_seq"] = seqs[0]
            info["max_seq"] = seqs[-1]
            # seq holes = records this merge never saw (pruned archive,
            # a worker that died mid-write): surfaced, never silent
            info["seq_gaps"] = (seqs[-1] - seqs[0] + 1) - len(seqs)
        per_worker[wkey] = info
        streams.append(recs)
    merged = list(heapq.merge(*streams, key=_rec_ts))
    return merged, per_worker, dups


def iter_source(spec: str, *, tail: int = DEFAULT_TAIL,
                timeout: float = DEFAULT_TIMEOUT_S):
    """One merge source from a CLI spec: an ``http(s)://`` worker base
    URL (live stream snapshot) or a flight JSONL file/dir."""
    if spec.startswith(("http://", "https://")):
        return fetch_records(spec, tail=tail, timeout=timeout)
    if not os.path.exists(spec):
        raise OSError(f"no such flight file or directory: {spec}")
    return list(_oflight.iter_records(spec))


def fetch_records(url: str, *, tail: int = DEFAULT_TAIL,
                  timeout: float = DEFAULT_TIMEOUT_S) -> list:
    """Snapshot a live worker's recent records over HTTP:
    ``GET <url>/debug/stream?follow=0&tail=N`` (newline-delimited
    JSON; blank heartbeat lines skipped, torn lines dropped)."""
    full = f"{url.rstrip('/')}/debug/stream?follow=0&tail={int(tail)}"
    out = []
    with urllib.request.urlopen(full, timeout=timeout) as resp:
        for raw in resp:
            line = raw.decode("utf-8", "replace").strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


# --------------------------------------------------------------------------
# the merged view
# --------------------------------------------------------------------------


def build_view(sources, *, now: float | None = None,
               objectives: dict | None = None,
               slo_spec: str | None = None,
               errors: dict | None = None) -> dict:
    """Merge ``sources`` and recompute the single-worker telemetry
    fleet-wide: burn rates (``obs.slo``, identical math to one
    worker's engine over the concatenated input — pinned by test),
    drift alarms (``obs.drift``), per-worker lag and seq coverage."""
    records, per_worker, dups = merge_sources(sources)
    if now is None:
        now = time.time()
    engine = _oslo.SLOEngine(objectives=objectives)
    if slo_spec:
        engine.configure(spec=slo_spec)
    # quiet: this replays HISTORICAL records — a dashboard polling
    # /debug/fleet must not re-log/re-mark a long-resolved alarm on
    # every poll; the snapshot still reports the alarms
    monitor = _odrift.DriftMonitor(quiet=True)
    for rec in records:
        engine.observe_record(rec)
        monitor.observe_record(rec)
    lag = 0.0
    for info in per_worker.values():
        info["lag_s"] = round(max(now - info["last_ts"], 0.0), 3)
        lag = max(lag, info["lag_s"])
    return {
        "workers": len(per_worker),
        "records": len(records),
        "duplicates_dropped": dups,
        "lag_seconds": round(lag, 3),
        "now": round(now, 3),
        "per_worker": per_worker,
        "slo": engine.snapshot(now=now),
        "drift": monitor.snapshot(),
        "drift_rows": monitor.metric_rows(),
        **({"errors": errors} if errors else {}),
    }


def merged_records(sources) -> list:
    """The ordered, dedup'd record stream alone (``--format records``)."""
    return merge_sources(sources)[0]


# --------------------------------------------------------------------------
# exposition (kao_fleet_* / kao_slo_* / kao_drift_*)
# --------------------------------------------------------------------------


def render_fleet_metrics(view: dict) -> str:
    """The merged view as Prometheus text exposition: the same
    ``kao_slo_*`` family shapes a single worker's ``/metrics`` serves
    (now fleet-wide), plus ``kao_fleet_*`` merge gauges and the
    ``kao_drift_*`` families. Validated by the exposition-format test
    suite; every family carries its HELP/TYPE pair (KAO107)."""
    from . import expo as _expo

    lines: list[str] = []

    def gauge(name: str, help_text: str, value) -> None:
        lines.extend(_expo.family_lines(name, "gauge", help_text,
                                        [(None, value)]))

    gauge("kao_fleet_workers", "distinct workers in the merged view",
          view["workers"])
    gauge("kao_fleet_records", "records in the merged view",
          view["records"])
    gauge("kao_fleet_duplicates",
          "records dropped by (worker, seq) dedup in this merge",
          view["duplicates_dropped"])
    lines.extend(_expo.family_lines(
        "kao_fleet_lag_seconds", "gauge",
        "seconds since each worker's newest record",
        [({"worker": wkey}, view["per_worker"][wkey]["lag_s"])
         for wkey in sorted(view["per_worker"])],
    ))
    lines.extend(_expo.family_lines(
        "kao_fleet_seq_gaps", "gauge",
        "per-worker sequence holes the merge never saw (pruned "
        "archives, dead workers)",
        [({"worker": wkey}, view["per_worker"][wkey]["seq_gaps"])
         for wkey in sorted(view["per_worker"])
         if view["per_worker"][wkey].get("seq_gaps") is not None],
    ))
    classes = (view.get("slo") or {}).get("classes") or {}
    if classes:
        slo_families = (
            ("kao_slo_events_total", "counter",
             "fleet-wide flight records observed per SLO class",
             lambda c: c["events_total"]),
            ("kao_slo_latency_breaches_total", "counter",
             "fleet-wide observations over the class latency objective",
             lambda c: c["latency_breaches_total"]),
            ("kao_slo_quality_breaches_total", "counter",
             "fleet-wide infeasible/degraded plans per SLO class",
             lambda c: c["quality_breaches_total"]),
            ("kao_slo_latency_objective_seconds", "gauge",
             "configured per-class latency objective",
             lambda c: c["objective"]["latency_s"]),
            ("kao_slo_target", "gauge",
             "configured per-class success target",
             lambda c: c["objective"]["target"]),
        )
        for name, kind, help_text, get in slo_families:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for cls in sorted(classes):
                lines.append(f'{name}{{class="{cls}"}} '
                             f"{get(classes[cls])}")
        lines.append("# HELP kao_slo_burn_rate fleet-wide error-budget "
                     "burn rate per class and window")
        lines.append("# TYPE kao_slo_burn_rate gauge")
        for cls in sorted(classes):
            for win, w in sorted(classes[cls]["windows"].items()):
                lines.append(
                    f'kao_slo_burn_rate{{class="{cls}",window="{win}"}} '
                    f'{w["burn_rate"]}'
                )
    lines.extend(_odrift.render_families(
        view.get("drift_rows") or [], "the merged flight stream",
    ))
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# the kao-fleet CLI
# --------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="kao-fleet",
        description="Merge N workers' flight streams (JSONL dirs or "
                    "live /debug/stream URLs) into one ordered view: "
                    "fleet-wide SLO burn rates, drift alarms, "
                    "per-worker lag (docs/OBSERVABILITY.md)",
    )
    ap.add_argument("sources", nargs="+", metavar="DIR|FILE|URL",
                    help="flight JSONL dirs/files, or worker base URLs "
                         "(http://host:port — fetched via "
                         "/debug/stream?follow=0)")
    ap.add_argument("--tail", type=int, default=DEFAULT_TAIL,
                    metavar="N",
                    help="records fetched per live worker (URL "
                         "sources only; default %(default)s)")
    ap.add_argument("--timeout-s", type=float, default=DEFAULT_TIMEOUT_S,
                    help="per-worker HTTP timeout (default %(default)s)")
    ap.add_argument("--slo", default=None, metavar="SPEC",
                    help="per-class SLO objectives for the fleet "
                         "recompute, e.g. 'solve:5:0.99,delta:2' "
                         "(defaults match the serve engine)")
    ap.add_argument("--now", type=float, default=None, metavar="UNIX_TS",
                    help="evaluate windows/lag at this instant "
                         "(default: wall clock; useful on archived "
                         "dirs)")
    ap.add_argument("--format", default="json",
                    choices=["json", "metrics", "records"],
                    help="json: the merged view object; metrics: "
                         "Prometheus text (kao_fleet_*/kao_slo_*/"
                         "kao_drift_*); records: the ordered merged "
                         "stream as JSONL")
    return ap


def resolve_sources(specs, *, tail: int = DEFAULT_TAIL,
                    timeout: float = DEFAULT_TIMEOUT_S
                    ) -> tuple[list, dict]:
    """Resolve CLI/HTTP source specs into merge sources. URL specs
    fetch CONCURRENTLY — N dead peers cost ~one timeout, not N
    stacked (the same bound /debug/fleet keeps). Any failure degrades
    to an ``errors`` entry, whatever the exception type: a peer
    hanging up mid-response raises http.client.HTTPException, not an
    OSError — the merged view over the readable sources must still
    serve."""
    from concurrent.futures import ThreadPoolExecutor

    urls = [s for s in specs
            if s.startswith(("http://", "https://"))]
    fetched: dict = {}
    if urls:
        with ThreadPoolExecutor(max_workers=min(len(urls), 8)) as ex:
            futures = {
                u: ex.submit(fetch_records, u, tail=tail,
                             timeout=timeout)
                for u in urls
            }
        fetched = {u: f for u, f in futures.items()}
    sources: list = []
    errors: dict = {}
    for spec in specs:
        try:
            if spec in fetched:
                sources.append((spec, fetched[spec].result()))
            else:
                sources.append(
                    (spec, iter_source(spec, tail=tail,
                                       timeout=timeout))
                )
        except Exception as e:
            errors[spec] = repr(e)[:200]
    return sources, errors


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    sources, errors = resolve_sources(
        args.sources, tail=args.tail, timeout=args.timeout_s,
    )
    for spec, err in errors.items():
        # kao: disable=KAO106 -- "error: ..." on stderr is the CLI's UX contract
        print(f"error: {spec}: {err}", file=sys.stderr)
    if not sources:
        return 3  # every source unreadable
    if args.format == "records":
        for rec in merged_records(sources):
            # kao: disable=KAO106 -- the merged stream on stdout IS the product
            print(json.dumps(rec, separators=(",", ":"), default=str))
        return 0
    try:
        view = build_view(sources, now=args.now, slo_spec=args.slo,
                          errors=errors or None)
    except ValueError as e:  # a malformed --slo spec fails loudly
        # kao: disable=KAO106 -- "error: ..." on stderr is the CLI's UX contract
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.format == "metrics":
        # kao: disable=KAO106 -- the exposition on stdout IS the product
        print(render_fleet_metrics(view), end="")
    else:
        view.pop("drift_rows", None)  # exposition-internal detail
        # kao: disable=KAO106 -- the view JSON on stdout IS the product
        print(json.dumps(view, indent=2, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
