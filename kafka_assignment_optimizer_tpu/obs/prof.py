"""Continuous roofline observatory (docs/OBSERVABILITY.md).

The ROADMAP's standing perf justification is a hand-measured roofline
("~15% HBM / ~4% compute, device mostly idle") that nothing in the tree
re-measures. This module makes it a live, per-executable invariant:

- **Cost models**: at every AOT compile site (``parallel.mesh._dispatch``
  — single, lanes, mega, mega-lanes), :func:`note_cost_model` captures
  the executable's XLA ``cost_analysis()`` (flops, bytes accessed) and
  ``memory_analysis()`` (peak HBM) ONCE and caches it under the same
  ``(solver_key, arg_signature)`` key as the exec-cache entry. Capture
  is compile-time work: a warm re-solve reuses the cached analysis with
  zero recomputation (tests pin this by counting captures while
  monkeypatching ``_lower_and_compile``).
- **Occupancy**: every dispatch stamps an enqueue-end timestamp
  (:func:`note_dispatch`); the engine's retire-side device wait pairs
  with it (:func:`note_device`), and the enqueue→retire window plus the
  cost model yield achieved FLOP/s and GB/s versus backend peaks — a
  rolling per-executable roofline with occupancy percentiles. The
  pairing queue is a per-solve contextvar, so pipelined ladders (two
  dispatches in flight) pair honestly and concurrent serve workers
  never cross streams.
- **Dispatch gaps**: :func:`observe_gaps` derives the gap series (end
  of one ladder dispatch to the start of the next) from the existing
  solve-report span timestamps and lands it in an
  :class:`~obs.trace.ExemplarHistogram`, so the p99 gap carries a
  trace_id that resolves through ``GET /debug/solves/<id>`` into the
  ISSUE 15 trace chain.
- **Attribution**: :func:`attribution_summary` / :func:`worst_solves`
  aggregate the flight ledgers (``obs.flight`` builds them; this module
  reads them) for ``GET /debug/profile`` and the offline ``kao-prof``
  CLI, which runs the same aggregation over flight JSONL dirs —
  fleet-wide via the ``obs.fleet`` merge.

Every hook self-accounts its own wall cost (``overhead()``); tier-1
asserts the profiler stays under 2% of solve wall. Peaks default per
platform and are env-overridable (``KAO_PROF_PEAK_FLOPS`` /
``KAO_PROF_PEAK_BYTES_S``) — absolute occupancy is only as good as the
peak it is normalized by, so the regression gate (``obs.regress``)
compares occupancy RATIOS between artifacts of the same environment,
never absolutes.
"""

from __future__ import annotations

import argparse
import contextvars
import hashlib
import json
import os
import sys
import threading
import time
from collections import OrderedDict, deque

from .trace import ExemplarHistogram

__all__ = [
    "note_cost_model", "note_dispatch", "note_device", "reset_pending",
    "observe_gaps", "forget_key", "clear", "peaks", "snapshot",
    "roofline", "attribution_summary", "worst_solves", "overhead",
    "gap_snapshot", "gap_exemplars", "main",
]

# dispatch-gap histogram bounds: warm ladder gaps sit in the 0.1-5 ms
# band on CPU (sub-ms on TPU); the tail buckets catch a host stall or
# GC pause between chunks
GAP_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
               0.5, 2.0)

# rolling occupancy window per executable: enough dispatches to make
# p99 meaningful on a long ladder, bounded so a service never grows
_OCC_SAMPLES = 256
# cost-model cache bound: follows the exec cache (_EXECUTABLES_MAX=32)
# with headroom — entries are a few floats, eviction mirrors the exec
# cache via forget_key, this cap is only the orphan backstop
_COST_MAX = 128

# per-platform peak defaults for the occupancy denominator. The TPU
# numbers are v5e-ish (bf16 MXU peak, HBM bandwidth); CPU/GPU defaults
# are order-of-magnitude placeholders — override with
# KAO_PROF_PEAK_FLOPS / KAO_PROF_PEAK_BYTES_S for real hardware.
# Absolute occupancy is advisory; the regression gate compares ratios.
_PEAK_DEFAULTS = {
    "tpu": (197e12, 819e9),
    "gpu": (60e12, 1000e9),
    "cpu": (100e9, 50e9),
}

_LOCK = threading.Lock()
# exec key -> cost model row (captured once per compile)
_COST: OrderedDict = OrderedDict()
# exec key -> runtime totals + rolling occupancy samples
_RUNTIME: dict = {}
_COUNTERS = {
    "captures_total": 0,       # cost models captured (one per compile)
    "capture_errors_total": 0,  # cost_analysis unavailable/raised
    "reuses_total": 0,         # dispatches served by a cached model
    "unpaired_device_total": 0,  # device waits with no pending dispatch
    "ledger_overruns_total": 0,  # ledgers whose parts exceeded wall+eps
}
# profiler self-accounting: wall seconds spent inside the note_* hooks
# (the <2% overhead assertion reads this; sampler.py idiom)
_OVERHEAD = {"seconds_total": 0.0, "ops_total": 0}

# per-solve pairing queue: (exec_key, enqueue_end_ts) in dispatch
# order. Contextvar — each serve worker thread pairs its own stream.
_PENDING: contextvars.ContextVar = contextvars.ContextVar(
    "kao_prof_pending", default=None
)

GAP_HIST = ExemplarHistogram(GAP_BUCKETS)


def _account(t0: float) -> None:
    dt = time.perf_counter() - t0
    with _LOCK:
        _OVERHEAD["seconds_total"] += dt
        _OVERHEAD["ops_total"] += 1


def peaks() -> dict:
    """The occupancy denominators for this process's backend:
    ``{"platform", "flops", "bytes_s"}`` (env-overridable)."""
    platform = "cpu"
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        pass
    flops, bw = _PEAK_DEFAULTS.get(platform, _PEAK_DEFAULTS["cpu"])
    try:
        flops = float(os.environ.get("KAO_PROF_PEAK_FLOPS") or flops)
    except ValueError:
        pass
    try:
        bw = float(os.environ.get("KAO_PROF_PEAK_BYTES_S") or bw)
    except ValueError:
        pass
    return {"platform": platform, "flops": flops, "bytes_s": bw}


# --------------------------------------------------------------------------
# cost-model capture (mesh's compile site calls this once per compile)
# --------------------------------------------------------------------------


def _first_analysis(obj):
    """``cost_analysis()`` returns a dict on current jax, a list of
    per-computation dicts on older versions; normalize to one dict."""
    if isinstance(obj, dict):
        return obj
    if isinstance(obj, (list, tuple)) and obj and isinstance(obj[0], dict):
        return obj[0]
    return None


def _extract_cost(ex) -> dict:
    """Flops / bytes / peak HBM from a compiled executable's XLA
    analyses. Defensive by contract: any backend may decline any field
    (None then rides the row; consumers skip None denominators)."""
    flops = bytes_accessed = None
    try:
        ca = _first_analysis(ex.cost_analysis())
        if ca:
            v = ca.get("flops")
            flops = float(v) if v is not None else None
            v = ca.get("bytes accessed", ca.get("bytes_accessed"))
            bytes_accessed = float(v) if v is not None else None
    except Exception:
        pass
    peak_hbm = None
    try:
        ma = ex.memory_analysis()
        # field names vary across jax versions/backends; peak device
        # memory = arguments + outputs + temps (generated code is
        # negligible and not HBM-resident on TPU)
        parts = [
            getattr(ma, f, None)
            for f in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes")
        ]
        vals = [float(p) for p in parts if p is not None]
        if vals:
            peak_hbm = sum(vals)
            alias = getattr(ma, "alias_size_in_bytes", None)
            if alias is not None:
                # donated/aliased buffers are counted in both argument
                # and output totals but occupy HBM once
                peak_hbm -= float(alias)
    except Exception:
        pass
    return {"flops": flops, "bytes_accessed": bytes_accessed,
            "peak_hbm_bytes": peak_hbm}


def note_cost_model(key: tuple, ex, compile_s: float) -> None:
    """Capture + cache the XLA cost analysis for a freshly compiled
    executable (called from ``mesh._dispatch``'s miss path, right after
    ``_lower_and_compile``). Never raises."""
    t0 = time.perf_counter()
    try:
        row = _extract_cost(ex)
        row["compile_s"] = round(float(compile_s), 4)
        ok = row["flops"] is not None or row["bytes_accessed"] is not None
        with _LOCK:
            _COST[key] = row
            _COST.move_to_end(key)
            while len(_COST) > _COST_MAX:
                old = _COST.popitem(last=False)[0]
                _RUNTIME.pop(old, None)
            _COUNTERS["captures_total"] += 1
            if not ok:
                _COUNTERS["capture_errors_total"] += 1
    except Exception:
        with _LOCK:
            _COUNTERS["capture_errors_total"] += 1
    finally:
        _account(t0)


def has_cost_model(key: tuple) -> bool:
    with _LOCK:
        return key in _COST


def forget_key(key: tuple) -> None:
    """Drop one executable's cost model + runtime totals (mesh calls
    this wherever the exec cache evicts the key, so the two lifecycles
    stay aligned — docs/DESIGN.md)."""
    with _LOCK:
        _COST.pop(key, None)
        _RUNTIME.pop(key, None)


def clear() -> None:
    """Full reset (exec-cache clear + tests)."""
    with _LOCK:
        _COST.clear()
        _RUNTIME.clear()
        for k in _COUNTERS:
            _COUNTERS[k] = 0
        _OVERHEAD["seconds_total"] = 0.0
        _OVERHEAD["ops_total"] = 0
    GAP_HIST.reset()


# --------------------------------------------------------------------------
# dispatch/device pairing -> occupancy samples
# --------------------------------------------------------------------------


def reset_pending() -> None:
    """Fresh pairing queue for a new solve (flight.start_accounting
    calls this): an abandoned speculative dispatch from a previous
    solve must not mispair with this solve's first device wait."""
    d = _PENDING.get()
    if d is not None:
        d.clear()


def note_dispatch(key: tuple) -> None:
    """One executable dispatch ENQUEUED (the ``ex(*args)`` call
    returned): stamp the pairing queue and count the dispatch against
    the key's cost model. Called from ``mesh._dispatch`` hit+miss
    paths; fallback (plain jit) dispatches carry no exec key and are
    not profiled."""
    t0 = time.perf_counter()
    try:
        d = _PENDING.get()
        if d is None:
            d = deque(maxlen=8)
            _PENDING.set(d)
        d.append((key, time.perf_counter()))
        with _LOCK:
            if key in _COST:
                _COUNTERS["reuses_total"] += 1
            rt = _RUNTIME.get(key)
            if rt is None:
                rt = _RUNTIME[key] = {
                    "dispatches": 0, "device_s": 0.0, "window_s": 0.0,
                    "samples": deque(maxlen=_OCC_SAMPLES),
                }
            rt["dispatches"] += 1
    finally:
        _account(t0)


def note_device(seconds: float) -> None:
    """The device wait that retires the oldest in-flight dispatch
    (engine's ``block_until_ready`` sites): close the enqueue→retire
    window, attribute device seconds, and take one occupancy sample
    against the key's cost model."""
    t0 = time.perf_counter()
    try:
        d = _PENDING.get()
        if not d:
            with _LOCK:
                _COUNTERS["unpaired_device_total"] += 1
            return
        key, t_enq = d.popleft()
        window = max(t0 - t_enq, float(seconds), 1e-9)
        pk = peaks()
        with _LOCK:
            cost = _COST.get(key)
            rt = _RUNTIME.get(key)
            if rt is None:
                rt = _RUNTIME[key] = {
                    "dispatches": 0, "device_s": 0.0, "window_s": 0.0,
                    "samples": deque(maxlen=_OCC_SAMPLES),
                }
            rt["device_s"] += float(seconds)
            rt["window_s"] += window
            if cost:
                occ_f = occ_b = None
                if cost.get("flops"):
                    occ_f = (cost["flops"] / window) / pk["flops"]
                if cost.get("bytes_accessed"):
                    occ_b = (cost["bytes_accessed"] / window) / pk["bytes_s"]
                if occ_f is not None or occ_b is not None:
                    rt["samples"].append((occ_f, occ_b))
    finally:
        _account(t0)


def note_ledger_overrun() -> None:
    """flight's ledger builder reports a components-exceed-wall ledger
    here (the sums-to-wall invariant's failure counter)."""
    with _LOCK:
        _COUNTERS["ledger_overruns_total"] += 1


# --------------------------------------------------------------------------
# dispatch-gap series from span timestamps (ISSUE 15 trace linkage)
# --------------------------------------------------------------------------


def _dispatch_spans(span: dict, out: list) -> None:
    if span.get("name") == "dispatch" and span.get("wall_s") is not None:
        out.append((span["start_s"], span["start_s"] + span["wall_s"]))
    for child in span.get("spans") or ():
        _dispatch_spans(child, out)


def observe_gaps(report: dict, trace_id: str | None = None) -> None:
    """Derive the dispatch-gap series of one traced solve from its
    span timestamps (gap = end of one ladder dispatch to the start of
    the next) and land it in the exemplar histogram — the p99 gap's
    trace_id resolves via ``GET /debug/solves/<id>``. Never raises."""
    t0 = time.perf_counter()
    try:
        spans: list = []
        _dispatch_spans(report.get("spans") or {}, spans)
        spans.sort()
        for (_s0, e0), (s1, _e1) in zip(spans, spans[1:]):
            gap = s1 - e0
            if gap >= 0:
                GAP_HIST.observe("ladder", gap, trace_id=trace_id)
    except Exception:
        pass
    finally:
        _account(t0)


def gap_snapshot() -> dict:
    return GAP_HIST.snapshot()


def gap_exemplars() -> list:
    return GAP_HIST.exemplars("path")


# --------------------------------------------------------------------------
# snapshots: per-executable rows + per-bucket roofline
# --------------------------------------------------------------------------

_TAGS = {"lanes", "mega", "mega-lanes"}


def _pct(sorted_vals: list, q: float):
    if not sorted_vals:
        return None
    i = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return round(sorted_vals[i], 6)


def _render_key(key: tuple) -> dict:
    """Human fields from one ``(solver_key, arg_signature)`` exec-cache
    key: the dispatch-path tag, engine/scorer, device count, and the
    bucket dims (trailing two dims of the largest-rank leaf shape —
    the padded [P, R] every bucket shape ends with). Lane-split
    dispatches carry spec-suffixed tags (``"lanes@4x2"``,
    docs/MESH.md): the base tag renders as the path and the ``dcxdl``
    split as its own field, so roofline rows group by dispatch shape
    AND device layout."""
    solver_key, arg_sig = key
    tag = "single"
    sharding = None
    engine = scorer = None
    ndev = chains = None
    try:
        last = solver_key[-1]
        if isinstance(last, str):
            base, _, spec = last.partition("@")
            if base in _TAGS:
                tag = base
                sharding = spec or None
        ndev = len(solver_key[0])
        chains = int(solver_key[1])
        engine, scorer = solver_key[3], solver_key[4]
    except Exception:
        pass
    bucket = None
    try:
        big = max((s for s, _dt in arg_sig), key=len)
        if len(big) >= 2:
            bucket = [int(big[-2]), int(big[-1])]
    except Exception:
        pass
    kid = hashlib.sha1(repr(key).encode()).hexdigest()[:12]
    return {"key_id": kid, "path": tag, "sharding": sharding,
            "engine": engine, "scorer": scorer, "devices": ndev,
            "chains": chains, "bucket": bucket}


def snapshot() -> dict:
    """Full observatory state: per-executable roofline rows (cost model
    + measured totals + occupancy percentiles), counters, peaks, and
    the profiler's own overhead accounting."""
    pk = peaks()
    with _LOCK:
        cost = {k: dict(v) for k, v in _COST.items()}
        runtime = {
            k: {"dispatches": v["dispatches"],
                "device_s": v["device_s"], "window_s": v["window_s"],
                "samples": list(v["samples"])}
            for k, v in _RUNTIME.items()
        }
        counters = dict(_COUNTERS)
        ovh = dict(_OVERHEAD)
    rows = []
    for key in set(cost) | set(runtime):
        c = cost.get(key) or {}
        rt = runtime.get(key) or {}
        row = {**_render_key(key), **{
            "flops": c.get("flops"),
            "bytes_accessed": c.get("bytes_accessed"),
            "peak_hbm_bytes": c.get("peak_hbm_bytes"),
            "compile_s": c.get("compile_s"),
            "dispatches": rt.get("dispatches", 0),
            "device_s": round(rt.get("device_s", 0.0), 4),
            "window_s": round(rt.get("window_s", 0.0), 4),
        }}
        win = rt.get("window_s") or 0.0
        n = rt.get("dispatches") or 0
        if win > 0 and n:
            if c.get("flops"):
                row["achieved_flops_s"] = round(c["flops"] * n / win, 1)
                row["occupancy_flops"] = round(
                    row["achieved_flops_s"] / pk["flops"], 6)
            if c.get("bytes_accessed"):
                row["achieved_bytes_s"] = round(
                    c["bytes_accessed"] * n / win, 1)
                row["occupancy_hbm"] = round(
                    row["achieved_bytes_s"] / pk["bytes_s"], 6)
        occ_f = sorted(s[0] for s in rt.get("samples", ()) if s[0] is not None)
        occ_b = sorted(s[1] for s in rt.get("samples", ()) if s[1] is not None)
        if occ_f:
            row["occupancy_flops_p50"] = _pct(occ_f, 0.50)
            row["occupancy_flops_p99"] = _pct(occ_f, 0.99)
        if occ_b:
            row["occupancy_hbm_p50"] = _pct(occ_b, 0.50)
            row["occupancy_hbm_p90"] = _pct(occ_b, 0.90)
            row["occupancy_hbm_p99"] = _pct(occ_b, 0.99)
        rows.append(row)
    rows.sort(key=lambda r: -(r.get("device_s") or 0.0))
    if ovh["ops_total"]:
        ovh["avg_op_s"] = round(
            ovh["seconds_total"] / ovh["ops_total"], 9)
    ovh["seconds_total"] = round(ovh["seconds_total"], 6)
    return {"peaks": pk, "executables": rows, "counters": counters,
            "overhead": ovh}


def roofline() -> list:
    """Per-bucket aggregation of :func:`snapshot` rows (the
    ``/debug/profile`` table): executables grouped by bucket dims, with
    summed device/window seconds and the occupancy of the dominant
    (most device seconds) executable per bucket."""
    snap = snapshot()
    groups: dict = {}
    for row in snap["executables"]:
        bk = tuple(row["bucket"] or ())
        g = groups.setdefault(bk, {
            "bucket": row["bucket"], "executables": 0, "dispatches": 0,
            "device_s": 0.0, "window_s": 0.0, "paths": [],
        })
        g["executables"] += 1
        g["dispatches"] += row["dispatches"]
        g["device_s"] = round(g["device_s"] + row["device_s"], 4)
        g["window_s"] = round(g["window_s"] + row["window_s"], 4)
        if row["path"] not in g["paths"]:
            g["paths"].append(row["path"])
        best = g.get("_best_dev", -1.0)
        if row["device_s"] > best:
            g["_best_dev"] = row["device_s"]
            for f in ("occupancy_flops", "occupancy_hbm",
                      "occupancy_hbm_p50", "occupancy_hbm_p99",
                      "flops", "bytes_accessed", "peak_hbm_bytes"):
                if row.get(f) is not None:
                    g[f] = row[f]
    out = []
    for g in groups.values():
        g.pop("_best_dev", None)
        out.append(g)
    out.sort(key=lambda g: -(g["device_s"] or 0.0))
    return out


def overhead() -> dict:
    with _LOCK:
        return dict(_OVERHEAD)


# --------------------------------------------------------------------------
# ledger aggregation (records in -> attribution out; shared by
# /debug/profile and the offline kao-prof CLI)
# --------------------------------------------------------------------------

LEDGER_FIELDS = ("queue_wait_s", "constructor_s", "compile_s",
                 "dispatch_gap_s", "device_s", "transfer_s",
                 "boundary_s", "other_s")


def attribution_summary(records: list) -> dict:
    """Aggregate attribution over flight records carrying a ledger:
    per-kind mean share of wall for every ledger component, plus the
    sums-to-wall conformance count."""
    per_kind: dict = {}
    for rec in records:
        led = rec.get("ledger")
        if not isinstance(led, dict):
            continue
        wall = float(led.get("wall_s") or 0.0)
        k = rec.get("kind") or "solve"
        g = per_kind.setdefault(k, {
            "solves": 0, "wall_s": 0.0, "ok": 0,
            **{f: 0.0 for f in LEDGER_FIELDS},
        })
        g["solves"] += 1
        g["wall_s"] += wall
        g["ok"] += int(bool(led.get("ok")))
        for f in LEDGER_FIELDS:
            g[f] += float(led.get(f) or 0.0)
    for g in per_kind.values():
        wall = g["wall_s"]
        g["shares"] = {
            f: round(g[f] / wall, 4) if wall > 0 else None
            for f in LEDGER_FIELDS
        }
        for f in LEDGER_FIELDS:
            g[f] = round(g[f], 4)
        g["wall_s"] = round(wall, 4)
    return per_kind


def worst_solves(records: list, n: int = 5) -> list:
    """The n solves losing the most wall to non-device time (the
    worst-attribution list): rows link by trace_id into
    ``GET /debug/solves/<id>`` and the Perfetto export."""
    rows = []
    for rec in records:
        led = rec.get("ledger")
        if not isinstance(led, dict):
            continue
        wall = float(led.get("wall_s") or 0.0)
        lost = wall - float(led.get("device_s") or 0.0)
        rows.append({
            "trace_id": rec.get("trace_id"),
            "kind": rec.get("kind"),
            "bucket": rec.get("bucket"),
            "wall_s": round(wall, 4),
            "lost_s": round(lost, 4),
            "lost_share": round(lost / wall, 4) if wall > 0 else None,
            "ledger": {f: led.get(f) for f in LEDGER_FIELDS},
            "ok": bool(led.get("ok")),
        })
    rows.sort(key=lambda r: -r["lost_s"])
    return rows[:n]


# --------------------------------------------------------------------------
# kao-prof CLI: offline attribution over flight JSONL dirs
# --------------------------------------------------------------------------


def _fmt_share(v) -> str:
    return f"{100.0 * v:5.1f}%" if v is not None else "    --"


def main(argv: list | None = None) -> int:
    """``kao-prof``: wall-clock attribution over flight JSONL
    files/dirs (or live worker URLs) — the offline view of
    ``GET /debug/profile``. Multiple sources merge fleet-wide through
    ``obs.fleet.merge_sources`` (seq-dedup, per-worker order)."""
    ap = argparse.ArgumentParser(
        prog="kao-prof",
        description="offline wall-clock attribution + worst-solve "
                    "report over flight JSONL dirs (fleet-wide when "
                    "given several sources; docs/OBSERVABILITY.md "
                    "'Reading a roofline')")
    ap.add_argument("sources", nargs="+",
                    help="flight JSONL file(s)/dir(s) or http(s) "
                         "worker base URLs")
    ap.add_argument("--kind", default=None,
                    help="only records of this kind (solve/lane/delta)")
    ap.add_argument("--top", type=int, default=5,
                    help="worst-attribution solves to list (default 5)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    from . import fleet as _fleet

    try:
        sources = [(s, _fleet.iter_source(s)) for s in args.sources]
    except OSError as e:
        # kao: disable=KAO106 -- CLI stderr diagnostic, not serve-path
        print(f"kao-prof: {e}", file=sys.stderr)
        return 2
    records, per_worker, dups = _fleet.merge_sources(sources)
    if args.kind:
        records = [r for r in records if r.get("kind") == args.kind]
    summary = attribution_summary(records)
    worst = worst_solves(records, args.top)
    out = {
        "records": len(records),
        "workers": len(per_worker),
        "duplicates_dropped": dups,
        "attribution": summary,
        "worst_solves": worst,
    }
    if args.json:
        print(json.dumps(out, indent=2))  # kao: disable=KAO106 -- CLI stdout is the product
        return 0
    print(f"{len(records)} records from {len(per_worker)} worker(s)"  # kao: disable=KAO106 -- CLI stdout is the product
          + (f", {dups} duplicates dropped" if dups else ""))
    for kind, g in sorted(summary.items()):
        print(f"\n[{kind}] {g['solves']} solves, "  # kao: disable=KAO106 -- CLI stdout is the product
              f"{g['wall_s']:.2f}s wall, ledgers ok "
              f"{g['ok']}/{g['solves']}")
        for f in LEDGER_FIELDS:
            print(f"  {f:<15} {_fmt_share(g['shares'][f])} "  # kao: disable=KAO106 -- CLI stdout is the product
                  f"({g[f]:.3f}s)")
    if worst:
        print("\nworst-attribution solves (most non-device wall):")  # kao: disable=KAO106 -- CLI stdout is the product
        for row in worst:
            print(f"  {row['lost_s']:7.3f}s lost / "  # kao: disable=KAO106 -- CLI stdout is the product
                  f"{row['wall_s']:7.3f}s wall  "
                  f"kind={row['kind']} trace={row['trace_id']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
