"""Drift alarms over the flight-record stream (docs/OBSERVABILITY.md
"Fleet plane").

``bench.py --compare`` catches build-over-build regressions at the
endpoints of a run; nothing so far catches a run getting slower (or
stopping certifying) MID-WAY — a compile-cache eviction storm, a
noisy neighbor, thermal throttling, a leaking executable cache. This
module watches two rolling signals per record class and raises an
alarm when either drifts from its learned baseline:

- ``p99`` — the rolling p99 of ``wall_s`` over the last
  ``WINDOW`` records of the class;
- ``certify_rate`` — the rolling certified fraction (fed to the
  detector as the FAILURE fraction, so the drift direction is "up is
  bad" for both signals).

Detector: an EWMA-baselined one-sided Page-Hinkley test. The baseline
is learned as the median of the first ``warmup`` signal values, then
tracked with a slow EWMA (benign drift is absorbed); the PH statistic
accumulates each step's exceedance beyond a tolerance ``delta`` and
alarms when it crosses ``lam`` — a sustained shift trips in a few
observations, a single outlier never does. After an alarm the
detector re-learns its baseline at the new level, so one regression
fires one alarm, not one per record.

Surfaces (all fed by ``obs.flight.record`` — serve, CLI, and
``kao-fleet`` merges share the same monitor class):

- ``kao_drift_alarms_total{class=,signal=}`` + the
  ``kao_drift_ph{class=,signal=}`` statistic gauge on ``/metrics``;
- the ``drift`` section of ``GET /debug/slo`` (and ``/healthz``'s
  ``slo`` block carries the alarm count);
- a zero-duration ``drift`` trace mark on whatever solve's record
  tripped the detector, so ``/debug/solves/<id>`` shows the tripwire
  inline with the phases;
- one ``drift_alarm`` structured log line per trip.
"""

from __future__ import annotations

import threading
from collections import deque

from . import log as _olog
from . import trace as _otrace

__all__ = ["PageHinkley", "DriftMonitor", "MONITOR", "SIGNALS"]

SIGNALS = ("p99", "certify_rate")

# rolling-signal geometry: the window the per-class p99/certify-rate
# is computed over, the stride between detector updates, and how many
# STRIDED signal values seed a baseline. The stride matters: the p99
# of a 32-record window is dominated by its maximum, so a single
# outlier would otherwise feed ~32 consecutive inflated updates into
# the PH sum and trip on noise — strided, it contributes at most
# ceil(WINDOW/STRIDE) updates, which the lam threshold absorbs
# (single-outlier immunity is regression-pinned).
WINDOW = 32
MIN_WINDOW = 8
STRIDE = 8
WARMUP = 4


class PageHinkley:
    """One-sided (upward) Page-Hinkley changepoint detector with an
    EWMA-tracked baseline.

    ``mode="relative"`` normalizes each step's exceedance by the
    baseline (right for latencies, scale-free); ``mode="absolute"``
    uses raw differences (right for rates already in [0, 1]).
    ``update(x)`` returns True exactly when this observation trips an
    alarm."""

    __slots__ = ("delta", "lam", "alpha", "warmup", "mode", "baseline",
                 "ph", "alarms", "_warm", "last_value")

    def __init__(self, *, delta: float, lam: float, mode: str,
                 alpha: float = 0.02, warmup: int = WARMUP):
        if mode not in ("relative", "absolute"):
            raise ValueError(f"bad PageHinkley mode {mode!r}")
        self.delta = float(delta)   # tolerated per-step drift
        self.lam = float(lam)       # cumulative exceedance that alarms
        self.alpha = float(alpha)   # baseline EWMA weight
        self.warmup = int(warmup)
        self.mode = mode
        self.baseline: float | None = None
        self.ph = 0.0
        self.alarms = 0
        self._warm: list[float] = []
        self.last_value: float | None = None

    def update(self, x: float) -> bool:
        x = float(x)
        self.last_value = x
        if self.baseline is None:
            self._warm.append(x)
            if len(self._warm) >= self.warmup:
                w = sorted(self._warm)
                self.baseline = w[len(w) // 2]
                self._warm = []
            return False
        if self.mode == "relative":
            step = x / max(self.baseline, 1e-9) - 1.0
        else:
            step = x - self.baseline
        self.ph = max(0.0, self.ph + step - self.delta)
        # slow EWMA: benign creep moves the baseline instead of the
        # statistic; an abrupt shift outruns alpha and accumulates
        self.baseline += self.alpha * (x - self.baseline)
        if self.ph > self.lam:
            self.alarms += 1
            self.ph = 0.0
            self.baseline = None  # re-learn at the new level
            return True
        return False

    def snapshot(self) -> dict:
        return {
            "baseline": (round(self.baseline, 6)
                         if self.baseline is not None else None),
            "ph": round(self.ph, 4),
            "alarms": self.alarms,
            "last_value": (round(self.last_value, 6)
                           if self.last_value is not None else None),
            "warming": self.baseline is None,
        }


# detector tuning per signal (docs/OBSERVABILITY.md "drift alarm
# tuning"): p99 is relative — a sustained >25% slowdown accumulates
# (a 2x shift trips in ~6 strided updates, a 10x shift on the first),
# while one 2x outlier tops out at ceil(32/8) x 0.75 = 3.0 < lam and
# never trips; the certify failure rate is absolute — a sustained
# >0.10 drop accumulates, one flaky lane in a window never trips
_SIGNAL_PARAMS = {
    "p99": {"mode": "relative", "delta": 0.25, "lam": 4.0},
    "certify_rate": {"mode": "absolute", "delta": 0.10, "lam": 0.5},
}


class DriftMonitor:
    """Per-(class, signal) drift detection over a record stream."""

    def __init__(self, window: int = WINDOW,
                 min_window: int = MIN_WINDOW, stride: int = STRIDE,
                 warmup: int = WARMUP, quiet: bool = False):
        self._lock = threading.Lock()
        self.window = int(window)
        self.min_window = int(min_window)
        self.stride = max(int(stride), 1)
        self.warmup = int(warmup)
        # quiet: no trace marks, no warn logs — for AGGREGATE replays
        # of historical records (obs.fleet builds a fresh monitor per
        # merge; a dashboard polling /debug/fleet must not re-announce
        # a long-resolved alarm on every poll). Counters and snapshots
        # are unaffected.
        self.quiet = bool(quiet)
        self._wall: dict[str, deque] = {}
        self._cert: dict[str, deque] = {}
        self._count: dict[str, int] = {}
        self._det: dict[tuple, PageHinkley] = {}
        # (class, signal) -> info dict of the most recent alarm
        self.last_alarms: dict[tuple, dict] = {}

    def reset(self) -> None:
        with self._lock:
            self._wall.clear()
            self._cert.clear()
            self._count.clear()
            self._det.clear()
            self.last_alarms.clear()

    def _detector(self, cls: str, signal: str) -> PageHinkley:
        det = self._det.get((cls, signal))
        if det is None:
            det = self._det[(cls, signal)] = PageHinkley(
                warmup=self.warmup, **_SIGNAL_PARAMS[signal]
            )
        return det

    def observe_record(self, rec: dict) -> list[str]:
        """Feed one flight record; returns the signals (if any) that
        tripped, after landing the mark/log side effects. Never raises
        into the solve path (the caller wraps)."""
        cls = rec.get("kind") or "solve"
        wall = float(rec.get("wall_s") or 0.0)
        q = rec.get("quality") or {}
        certified = bool(q.get("certified"))
        tripped: list[str] = []
        with self._lock:
            wq = self._wall.setdefault(cls, deque(maxlen=self.window))
            cq = self._cert.setdefault(cls, deque(maxlen=self.window))
            wq.append(wall)
            cq.append(certified)
            n = self._count[cls] = self._count.get(cls, 0) + 1
            if len(wq) >= self.min_window and n % self.stride == 0:
                xs = sorted(wq)
                p99 = xs[min(len(xs) - 1, int(0.99 * len(xs)))]
                if self._detector(cls, "p99").update(p99):
                    tripped.append("p99")
                fail = 1.0 - sum(cq) / len(cq)
                if self._detector(cls, "certify_rate").update(fail):
                    tripped.append("certify_rate")
            for sig in tripped:
                det = self._det[(cls, sig)]
                self.last_alarms[(cls, sig)] = {
                    "ts": rec.get("ts"),
                    "trace_id": rec.get("trace_id"),
                    "value": det.last_value,
                    "alarms": det.alarms,
                }
        if not self.quiet:
            for sig in tripped:
                # zero-duration trace mark: if this record landed
                # inside a live request trace, the tripwire shows up
                # inline in /debug/solves/<id>; a no-op otherwise
                _otrace.mark("drift", signal=sig, record_class=cls)
                _olog.warn("drift_alarm", record_class=cls, signal=sig,
                           value=self._det[(cls, sig)].last_value,
                           trace_id=rec.get("trace_id"))
        return tripped

    def snapshot(self) -> dict:
        """The ``/debug/slo`` ``drift`` section: per class x signal —
        baseline, current PH statistic, alarm count, last alarm."""
        with self._lock:
            classes: dict[str, dict] = {}
            total = 0
            for (cls, sig), det in self._det.items():
                row = det.snapshot()
                last = self.last_alarms.get((cls, sig))
                if last is not None:
                    row["last_alarm"] = dict(last)
                classes.setdefault(cls, {})[sig] = row
                total += det.alarms
            return {
                "signals": list(SIGNALS),
                "window": self.window,
                "alarms_total": total,
                "classes": classes,
            }

    def metric_rows(self) -> list[tuple[str, str, int, float]]:
        """(class, signal, alarms_total, ph) rows for the kao_drift_*
        exposition families (serve and kao-fleet render the same
        rows)."""
        with self._lock:
            return [
                (cls, sig, det.alarms, round(det.ph, 4))
                for (cls, sig), det in sorted(self._det.items())
            ]


def render_families(rows, stream_desc: str = "the flight stream"
                    ) -> list[str]:
    """The ``kao_drift_*`` exposition lines from :meth:`metric_rows`
    — the ONE renderer serve's ``/metrics`` and ``kao-fleet --format
    metrics`` both use, so the family names/shapes/HELP cannot drift
    between the per-worker and fleet-wide views."""
    lines = [
        f"# HELP kao_drift_alarms_total drift alarms over "
        f"{stream_desc}, by class and signal",
        "# TYPE kao_drift_alarms_total counter",
    ]
    for cls, sig, alarms, _ph in rows:
        lines.append(
            f'kao_drift_alarms_total{{class="{cls}",signal="{sig}"}} '
            f"{alarms}"
        )
    lines.append("# HELP kao_drift_ph current Page-Hinkley drift "
                 "statistic by class and signal")
    lines.append("# TYPE kao_drift_ph gauge")
    for cls, sig, _alarms, ph in rows:
        lines.append(
            f'kao_drift_ph{{class="{cls}",signal="{sig}"}} {ph}'
        )
    return lines


MONITOR = DriftMonitor()
