"""``kao-trace`` — offline solve-trace and flight-record tooling
(docs/OBSERVABILITY.md).

Subcommands:

``kao-trace convert REPORT.json [-o OUT.json]``
    Convert a solve report to Chrome trace-event JSON (loadable in
    ``chrome://tracing`` / Perfetto). Accepts a bare solve report, a
    CLI ``--trace`` stderr report (the ``solve_report`` field is
    extracted), or a saved ``GET /debug/solves/<id>`` response.

``kao-trace fetch --url http://host:port [TRACE_ID] [--chrome] [-o F]``
    List the server's retrievable trace IDs, or fetch one report —
    converted to Chrome trace JSON with ``--chrome``.

``kao-trace flight PATH [--tail N] [--kind K] [--follow [--max N]]``
    Dump flight records (one JSON line each) from a flight JSONL file
    or a ``--flight-dir`` directory (archives first, then the live
    file). Torn/corrupt lines are skipped, matching the recorder's
    crash-safety contract. Records carry the worker identity stamp
    (host/pid/port/boot) and per-worker ``seq`` the fleet merge keys
    on. ``--follow`` tails the LIVE file like ``tail -f``, surviving
    the recorder's atomic rotation (the archived file is drained
    before the fresh live file is opened from its start — no record
    is ever printed twice or skipped); ``--max N`` exits after N
    followed records (tests/pipelines), Ctrl-C exits 0.

Exit codes: 0 ok, 2 usage/input error, 3 not found.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def _load_report(path: str) -> dict:
    doc = json.loads(Path(path).read_text())
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object")
    # a CLI --trace report wraps the solve report; unwrap transparently
    if "spans" not in doc and isinstance(doc.get("solve_report"), dict):
        doc = doc["solve_report"]
    if "spans" not in doc:
        raise ValueError(
            f"{path}: no span tree — not a solve report (want the JSON "
            "from GET /debug/solves/<id> or a --trace report)"
        )
    return doc


def _write(text: str, out: str | None) -> None:
    if out:
        Path(out).write_text(text + "\n")
    else:
        # kao: disable=KAO106 -- the converted JSON on stdout IS the product
        print(text)


def _cmd_convert(args) -> int:
    from .chrome import report_to_json

    rep = _load_report(args.report)
    _write(report_to_json(rep, indent=args.indent), args.output)
    return 0


def _cmd_fetch(args) -> int:
    import urllib.error
    import urllib.request

    base = args.url.rstrip("/")
    path = "/debug/solves" + (f"/{args.trace_id}" if args.trace_id else "")
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            doc = json.loads(r.read())
    except urllib.error.HTTPError as e:
        # kao: disable=KAO106 -- "error: ..." on stderr is the CLI's UX contract
        print(f"error: {base + path} -> HTTP {e.code}", file=sys.stderr)
        return 3 if e.code == 404 else 2
    except (urllib.error.URLError, OSError) as e:
        # kao: disable=KAO106 -- "error: ..." on stderr is the CLI's UX contract
        print(f"error: {base + path}: {e}", file=sys.stderr)
        return 2
    if args.trace_id and args.chrome:
        from .chrome import report_to_json

        _write(report_to_json(doc, indent=args.indent), args.output)
    else:
        _write(json.dumps(doc, indent=args.indent, default=str),
               args.output)
    return 0


def _cmd_flight(args) -> int:
    from .flight import follow_records, iter_records, snapshot_records

    if not Path(args.path).exists():
        # kao: disable=KAO106 -- "error: ..." on stderr is the CLI's UX contract
        print(f"error: no such file or directory: {args.path}",
              file=sys.stderr)
        return 3
    resume = None
    if args.follow and args.tail:
        # gap-free handoff: the snapshot returns a resume token (live
        # inode + byte offset + archive watermark) and the follow
        # continues at exactly that point, rotation-safe — a record
        # landing DURING the replay is delivered by the follow, never
        # skipped and never printed twice
        recs, resume = snapshot_records(args.path)
    elif not args.follow:
        recs = list(iter_records(args.path))
    else:
        recs = []
    if recs:
        recs = [r for r in recs
                if args.kind is None or r.get("kind") == args.kind]
        if args.tail:
            recs = recs[-args.tail:]
        for r in recs:
            # kao: disable=KAO106 -- the record stream on stdout IS the product
            print(json.dumps(r, separators=(",", ":"), default=str))
    if not args.follow:
        return 0
    printed = 0
    try:
        for r in follow_records(args.path, resume=resume):
            if args.kind is not None and r.get("kind") != args.kind:
                continue
            # kao: disable=KAO106 -- the record stream on stdout IS the product
            print(json.dumps(r, separators=(",", ":"), default=str),
                  flush=True)
            printed += 1
            if args.max is not None and printed >= args.max:
                break
    except KeyboardInterrupt:
        pass
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="kao-trace",
        description="Dump/convert solve traces and flight records "
                    "(docs/OBSERVABILITY.md)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("convert",
                       help="solve report -> Chrome trace JSON")
    c.add_argument("report", help="solve-report JSON file")
    c.add_argument("-o", "--output", help="write here (default stdout)")
    c.add_argument("--indent", type=int, default=None)
    c.set_defaults(fn=_cmd_convert)

    f = sub.add_parser("fetch",
                       help="list/fetch solve reports from a server")
    f.add_argument("trace_id", nargs="?", default=None)
    f.add_argument("--url", required=True,
                   help="server base URL, e.g. http://127.0.0.1:8787")
    f.add_argument("--chrome", action="store_true",
                   help="convert the fetched report to Chrome trace JSON")
    f.add_argument("-o", "--output")
    f.add_argument("--indent", type=int, default=None)
    f.set_defaults(fn=_cmd_fetch)

    fl = sub.add_parser("flight", help="dump flight records")
    fl.add_argument("path", help="flight JSONL file or --flight-dir dir")
    fl.add_argument("--tail", type=int, default=None,
                    help="only the last N records")
    fl.add_argument("--kind", default=None,
                    help="filter by record kind (solve/delta/lane)")
    fl.add_argument("--follow", action="store_true",
                    help="tail -f the live file (rotation-safe: never "
                         "double-reads a record); combine with --tail "
                         "to replay history first")
    fl.add_argument("--max", type=int, default=None, metavar="N",
                    help="with --follow: exit after N followed records")
    fl.set_defaults(fn=_cmd_flight)
    return ap


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except (ValueError, OSError, json.JSONDecodeError) as e:
        # kao: disable=KAO106 -- "error: ..." on stderr is the CLI's UX contract
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
