"""Observability subsystem: solve traces, flight records, SLOs.

- ``obs.trace`` — dependency-free nested-span tracer. Every traced solve
  produces a structured *solve report* (span tree + annealing trajectory
  summary) registered in a process-wide ring buffer keyed by trace ID
  (``GET /debug/solves/<trace_id>`` in serve). Disabled is the default
  and costs one contextvar read per instrumentation site.
- ``obs.log`` — single-line ``key=value`` structured logger; includes
  the active trace ID automatically.
- ``obs.flight`` — per-solve flight recorder: one compact cost+quality
  record per solve/delta/batch-lane, in-memory + crash-safe JSONL
  (``--flight-dir``), feeding the ``kao_solve_seconds`` histograms
  (with worst-recent exemplars) and the SLO engine.
- ``obs.slo`` — sliding-window SLO engine with multi-window burn rates
  (``kao_slo_*`` on /metrics, ``GET /debug/slo``).
- ``obs.chrome`` — solve report -> Chrome trace-event JSON
  (``?format=chrome``, Perfetto-loadable); ``obs.trace_cli`` is the
  ``kao-trace`` offline dump/convert CLI.
- ``obs.regress`` — noise-aware bench-artifact comparator
  (``bench.py --compare OLD NEW``), the perf-regression gate.
- ``obs.fleet`` — fleet telemetry plane: merge N workers' flight
  streams (JSONL dirs or live ``GET /debug/stream``) into one
  ordered, (worker, seq)-deduped view with fleet-wide burn rates —
  the ``kao-fleet`` CLI and ``GET /debug/fleet``.
- ``obs.sampler`` — low-overhead device-occupancy sampler
  (``--sample-devices HZ``): per-device memory, dispatch duty cycle,
  per-bucket roofline summary.
- ``obs.drift`` — EWMA/Page-Hinkley drift alarms on per-class p99 and
  certify rate over the flight stream (``kao_drift_*``).

See ``docs/OBSERVABILITY.md`` for the trace-ID flow, the flight-record
schema, the fleet plane, SLO configuration, and the metric naming
conventions.
"""

from . import log, trace  # noqa: F401
