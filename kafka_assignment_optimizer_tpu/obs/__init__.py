"""Observability subsystem: solve-trace spans + structured logging.

- ``obs.trace`` — dependency-free nested-span tracer. Every traced solve
  produces a structured *solve report* (span tree + annealing trajectory
  summary) registered in a process-wide ring buffer keyed by trace ID
  (``GET /debug/solves/<trace_id>`` in serve). Disabled is the default
  and costs one contextvar read per instrumentation site.
- ``obs.log`` — single-line ``key=value`` structured logger; includes
  the active trace ID automatically.

See ``docs/OBSERVABILITY.md`` for the trace-ID flow, the solve-report
schema, and the metric naming conventions.
"""

from . import log, trace  # noqa: F401
