"""Observability subsystem: solve traces, flight records, SLOs.

- ``obs.trace`` — dependency-free nested-span tracer. Every traced solve
  produces a structured *solve report* (span tree + annealing trajectory
  summary) registered in a process-wide ring buffer keyed by trace ID
  (``GET /debug/solves/<trace_id>`` in serve). Disabled is the default
  and costs one contextvar read per instrumentation site.
- ``obs.log`` — single-line ``key=value`` structured logger; includes
  the active trace ID automatically.
- ``obs.flight`` — per-solve flight recorder: one compact cost+quality
  record per solve/delta/batch-lane, in-memory + crash-safe JSONL
  (``--flight-dir``), feeding the ``kao_solve_seconds`` histograms
  (with worst-recent exemplars) and the SLO engine.
- ``obs.slo`` — sliding-window SLO engine with multi-window burn rates
  (``kao_slo_*`` on /metrics, ``GET /debug/slo``).
- ``obs.chrome`` — solve report -> Chrome trace-event JSON
  (``?format=chrome``, Perfetto-loadable); ``obs.trace_cli`` is the
  ``kao-trace`` offline dump/convert CLI.
- ``obs.regress`` — noise-aware bench-artifact comparator
  (``bench.py --compare OLD NEW``), the perf-regression gate.

See ``docs/OBSERVABILITY.md`` for the trace-ID flow, the flight-record
schema, SLO configuration, and the metric naming conventions.
"""

from . import log, trace  # noqa: F401
