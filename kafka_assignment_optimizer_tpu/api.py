"""High-level entry point: ``optimize()`` — the reference's end-to-end
``submit -> parse -> model -> solve -> decode -> diff -> emit`` call stack
(``/root/reference/README.md:189-195``; SURVEY.md §3.1)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Sequence

from .models.cluster import Assignment, MoveReport, Topology, move_diff
from .models.instance import ProblemInstance, build_instance
from .solvers.base import SolveResult, get_solver


@dataclass
class OptimizeResult:
    """Everything a caller (CLI, HTTP service, tests) needs: the plan, the
    move diff vs the current assignment (plan minimality,
    ``README.md:83-91``), and solver telemetry (observability per
    SURVEY.md §5)."""

    assignment: Assignment
    moves: MoveReport
    solve: SolveResult
    instance: ProblemInstance = field(repr=False, default=None)
    wall_clock_s: float = 0.0

    @property
    def replica_moves(self) -> int:
        return self.moves.replica_moves

    def report(self) -> dict:
        viol = self.instance.violations(self.solve.a)
        return {
            "solver": self.solve.solver,
            "replica_moves": self.moves.replica_moves,
            "leader_changes": self.moves.leader_changes,
            "objective_weight": self.instance.preservation_weight(self.solve.a),
            # tightest bound already computed for this instance: the
            # leader-band LP bound if an engine certificate evaluated it
            # (memoized), else the cheap unconstrained bound
            "objective_upper_bound": (
                self.instance.best_known_weight_ub()
                if self.instance.best_known_weight_ub() is not None
                else self.instance.max_weight()
            ),
            "violations": viol,
            "feasible": all(v == 0 for v in viol.values()),
            "proven_optimal": self.solve.optimal,
            "solver_wall_clock_s": round(self.solve.wall_clock_s, 4),
            "total_wall_clock_s": round(self.wall_clock_s, 4),
            "brokers": self.instance.num_brokers,
            "partitions": self.instance.num_parts,
            "racks": self.instance.num_racks,
            **{f"solver_{k}": v for k, v in self.solve.stats.items()
               if isinstance(v, (int, float, str, bool))},
            # degradation rungs taken during this solve
            # (docs/RESILIENCE.md): the scalar fold above drops lists,
            # but the ladder must be visible on the serving surface —
            # a degraded plan that looks searched is an operator trap
            **({"degradations": list(self.solve.stats["degradations"])}
               if self.solve.stats.get("degradations") else {}),
            # portfolio winner provenance (docs/PORTFOLIO.md): a dict,
            # so the scalar fold above drops it — but which lane config
            # produced the plan belongs on the serving surface
            **({"solver_portfolio": dict(self.solve.stats["portfolio"])}
               if self.solve.stats.get("portfolio") else {}),
            # fused-ladder provenance (docs/PIPELINE.md "Megachunks"):
            # also a dict — resolved width, chooser mode, dispatches,
            # executed chunks, early_exit
            **({"solver_megachunk": dict(self.solve.stats["megachunk"])}
               if self.solve.stats.get("megachunk") else {}),
        }


def optimize(
    current: Assignment | str | dict,
    broker_list: Sequence[int],
    topology: Topology | dict | None = None,
    target_rf: int | dict | None = None,
    solver: str = "auto",
    instance: ProblemInstance | None = None,
    **solver_kwargs,
) -> OptimizeResult:
    """Compute a minimal-move, constraint-satisfying reassignment plan.

    Args mirror the reference's inputs (``README.md:27-48``): the current
    assignment (JSON text, dict, or :class:`Assignment`), the target broker
    list, the broker->rack topology, and optionally a new replication
    factor (the reference's RF-change use case, ``README.md:8-10``).

    ``instance`` may carry a prebuilt :class:`ProblemInstance` for these
    same inputs (the serving path builds it early for bucket-key routing);
    it skips the rebuild, nothing else.
    """
    t0 = time.perf_counter()
    if isinstance(current, str):
        current = Assignment.from_json(current)
    elif isinstance(current, dict):
        current = Assignment.from_dict(current)
    if isinstance(topology, dict):
        topology = Topology.from_dict(topology)

    inst = (
        instance if instance is not None
        else build_instance(current, broker_list, topology, target_rf)
    )
    result = get_solver(solver)(inst, **solver_kwargs)
    plan = inst.decode(result.a)
    moves = move_diff(current, plan)
    out = OptimizeResult(
        assignment=plan,
        moves=moves,
        solve=result,
        instance=inst,
        wall_clock_s=time.perf_counter() - t0,
    )
    if result.solver != "tpu":
        # the TPU engine records its own (richer) flight record; the
        # exact oracles have no engine-level recorder, so the ledger
        # entry lands here — small-instance delta/solve traffic that
        # "auto" routes to MILP/native must not be an SLO blind spot
        from .obs import flight as _flight

        _flight.record_optimize(out)
    return out


def optimize_delta(
    current: Assignment | str | dict,
    broker_list: Sequence[int],
    topology: Topology | dict | None = None,
    target_rf: int | dict | None = None,
    prev_plan: Assignment | str | dict | None = None,
    solver: str = "auto",
    instance: ProblemInstance | None = None,
    **solver_kwargs,
) -> OptimizeResult:
    """One step of the cluster-watch delta path (docs/WATCH.md):
    :func:`optimize`, warm-started from ``prev_plan`` — the previous
    certified plan adapted to the post-event topology (dead brokers and
    racks evicted, surviving replicas kept in place,
    ``watch.adapt.adapt_plan``). Adaptation that produces no usable
    candidate takes the ``warm_start_rejected`` degradation rung and
    the solve runs cold; solvers without a warm-start path (the exact
    MILP/LP backends certify from scratch anyway) simply ignore it.
    """
    if isinstance(current, str):
        current = Assignment.from_json(current)
    elif isinstance(current, dict):
        current = Assignment.from_dict(current)
    if isinstance(topology, dict):
        topology = Topology.from_dict(topology)
    if isinstance(prev_plan, str):
        prev_plan = Assignment.from_json(prev_plan)
    elif isinstance(prev_plan, dict):
        prev_plan = Assignment.from_dict(prev_plan)

    inst = (
        instance if instance is not None
        else build_instance(current, broker_list, topology, target_rf)
    )
    from .solvers.base import resolve_solver

    solver_eff = resolve_solver(solver, inst)
    if prev_plan is not None and solver_eff == "tpu":
        from .resilience import ladder as _ladder
        from .watch.adapt import adapt_plan

        warm_a, reason = adapt_plan(inst, prev_plan)
        if warm_a is None:
            # rejection is a LADDER step, not a silent downgrade: the
            # rung lands on the counter/trace/stats like every other
            # (the engine's own validator covers the in-engine cases)
            _ladder.note_rung("warm_start_rejected", reason=reason[:200])
        else:
            solver_kwargs.setdefault("warm_start", warm_a)
    return optimize(
        current, broker_list, topology, target_rf=target_rf,
        solver=solver_eff, instance=inst, **solver_kwargs,
    )


def optimize_batch(
    currents: Sequence[Assignment],
    instances: Sequence[ProblemInstance],
    seeds: int | Sequence[int] = 0,
    **solver_kwargs,
) -> list[OptimizeResult]:
    """Solve L independent prebuilt instances through ONE batched TPU
    dispatch (``solvers.tpu.engine.solve_tpu_batch``) and decode each
    lane back to its own reassignment plan + move diff. The serving
    path's coalescing dispatcher is the caller: it groups same-bucket
    requests, hands them here as one solve, and demultiplexes the
    returned per-request results. ``currents[i]`` must be the assignment
    ``instances[i]`` was built from (the diff is computed against it)."""
    if len(currents) != len(instances):
        raise ValueError(
            f"{len(currents)} assignments for {len(instances)} instances"
        )
    from .solvers.tpu.engine import solve_tpu_batch

    t0 = time.perf_counter()
    results = solve_tpu_batch(list(instances), seeds=seeds,
                              **solver_kwargs)
    wall = time.perf_counter() - t0
    out = []
    for current, inst, res in zip(currents, instances, results):
        plan = inst.decode(res.a)
        out.append(OptimizeResult(
            assignment=plan,
            moves=move_diff(current, plan),
            solve=res,
            instance=inst,
            wall_clock_s=wall,
        ))
    return out


def evaluate(
    current: Assignment | str | dict,
    broker_list: Sequence[int],
    plan: Assignment | str | dict,
    topology: Topology | dict | None = None,
    target_rf: int | dict | None = None,
    time_budget_s: float | None = None,
) -> dict:
    """Audit an EXISTING plan — ours, another tool's, or
    ``kafka-reassign-partitions`` output — against the same model and
    bounds every solver uses. Returns a JSON-able report: feasibility
    with per-constraint violation counts, replica moves vs the provable
    minimum, objective weight vs its provable upper bound, and whether
    the plan is certifiably globally optimal. The reference's worked
    demo is exactly this comparison (its README shows Kafka's own tool
    proposing a near-total reshuffle where one move suffices,
    ``README.md:65-91``) — this makes the audit a first-class surface."""
    if isinstance(current, str):
        current = Assignment.from_json(current)
    elif isinstance(current, dict):
        current = Assignment.from_dict(current)
    if isinstance(plan, str):
        plan = Assignment.from_json(plan)
    elif isinstance(plan, dict):
        plan = Assignment.from_dict(plan)
    if isinstance(topology, dict):
        topology = Topology.from_dict(topology)

    inst = build_instance(current, broker_list, topology, target_rf)
    if time_budget_s is not None:
        # cap the audit's bound LPs (level-0/1/2 + certification) at the
        # caller's wall budget; expired tiers fall back to cheaper
        # bounds — looser verdicts, never a blown deadline
        inst.set_bounds_deadline(time_budget_s)
    a = inst.encode(plan)
    viol = inst.violations(a)
    feasible = all(v == 0 for v in viol.values())
    # diff the plan AS GIVEN (an infeasible plan may reference
    # ineligible brokers, which the index space cannot round-trip)
    moves = move_diff(current, plan)
    weight = inst.preservation_weight(a)
    return {
        "feasible": feasible,
        "violations": viol,
        "replica_moves": moves.replica_moves,
        "min_moves_lower_bound": inst.move_lower_bound_exact(),
        "leader_changes": moves.leader_changes,
        "objective_weight": weight,
        "objective_upper_bound": inst.weight_upper_bound(level=2),
        # exact-flow-tier declines (int32 BIG overflow -> LP fallback):
        # nonzero means the bound above may be the looser tier
        "flow_bound_declines": getattr(inst, "_flow_big_declines", 0),
        "proven_optimal": feasible and inst.certify_optimal(a),
        "brokers": inst.num_brokers,
        "partitions": inst.num_parts,
        "racks": inst.num_racks,
    }
