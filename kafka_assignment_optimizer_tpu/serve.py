"""HTTP service — optimizer-as-a-service (reference C16).

The reference runs a hosted public instance with a ``POST /submit``
endpoint (``/root/reference/README.md:187-195``); its payload schema is
not documented in the mount, so this build defines its own (SURVEY.md §1
L7). Stdlib-only (ThreadingHTTPServer) — no web-framework dependency.

Endpoints:

``POST /submit``
    Request JSON::

        {
          "assignment": {"version": 1, "partitions": [...]},   # required
          "brokers": "0-18" | [0, 1, ...],                     # required
          "topology": {"0": "rackA", ...} | "even-odd" | null,
          "rf": 3 | {"topic": 3} | null,
          "solver": "auto" | "milp" | "native" | "tpu" | "lp_solve",
          "options": {"seed": 0, "batch": 512, ...}            # solver kwargs
        }

    Response 200::

        {"assignment": {...reassignment JSON...},              # the plan
         "report": {...observability report (SURVEY.md §5)...}}

    ``options`` accepts search knobs only (``ALLOWED_OPTIONS``);
    path-valued solver kwargs are rejected. Every solve is capped at the
    server's ``--max-solve-s`` budget.

    Errors: 400 malformed JSON/schema or disallowed option (body
    ``{"error": ...}``), 422 model rejected the inputs, 500 solver
    failure, 503 solver saturated past ``--lock-wait-s``.

``POST /evaluate``
    Audit an EXISTING plan (same fields as ``/submit`` minus
    ``solver``/``options``, plus required ``plan``: a reassignment
    JSON object). Response 200 is the audit report: feasibility with
    per-constraint violation counts, replica moves vs the provable
    minimum, objective weight vs its provable upper bound, and
    ``proven_optimal``. Audits hold their own lock — host-only bound
    work never queues behind a device solve — and shed with 503 the
    same way when saturated.

``GET /``
    Human-usable front door (the reference hosts a public instance
    with a usage/extended-example page, ``README.md:189-195``): HTML
    usage + a live form prefilled with the reference demo. Clients
    sending ``Accept: application/json`` get the request schema.

``GET /schema``
    Machine-readable request/response shapes (JSON).

``GET /healthz``
    ``{"status": "ok", "solvers": [...], "platform": "tpu"}``

``GET /metrics``
    Prometheus text counters: requests/solves/evaluates/errors/sheds
    and solve wall-clock totals (``kao_*``).

Run: ``python -m kafka_assignment_optimizer_tpu.serve --port 8787``.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import landing
from .api import optimize
from .models.cluster import Assignment, Topology, parse_broker_list

# one solve at a time: solver backends (XLA executables, the native lib)
# are process-wide resources; concurrent HTTP readers stay responsive,
# solves serialize
_SOLVE_LOCK = threading.Lock()

# audits (/evaluate) hold their OWN lock (VERDICT r4 item 8): they are
# pure host-side work (numpy + bound LPs + the native flow kernel — no
# jax, no device, no jit caches), so serializing them behind a long
# device solve bought nothing and 503-shed cheap audits for up to
# --lock-wait-s. One audit at a time still bounds host CPU: the bound
# LPs cost seconds at 10k partitions.
_AUDIT_LOCK = threading.Lock()

MAX_BODY_BYTES = 64 << 20  # 64 MiB — a 10k-partition cluster is ~1 MiB

# Options the HTTP surface forwards to solvers: search-effort knobs only.
# Path-valued solver kwargs (``checkpoint``, ``profile_dir``) are
# deliberately NOT forwardable — a remote client must never be able to
# make the service create directories or read/write files at
# client-chosen paths. Operators who want checkpointing use the CLI.
ALLOWED_OPTIONS = frozenset({
    "seed", "batch", "rounds", "sweeps", "steps_per_round", "engine",
    "time_limit_s", "t_hi", "t_lo", "n_devices",
})

# saturation policy: how long a request waits for the solve lock before
# the service sheds it with 503 (a single 10k-partition solve must not
# make every later POST hang indefinitely), and the time limit injected
# into each solve unless the client sets a smaller one
DEFAULT_LOCK_WAIT_S = 30.0
DEFAULT_MAX_SOLVE_S = 300.0

# service counters (GET /metrics, Prometheus text format); guarded by
# their own lock so readers never contend with a solve
_METRICS_LOCK = threading.Lock()
_METRICS = {
    "requests_total": 0,      # POST /submit or /evaluate received
    "solves_total": 0,        # solves completed successfully
    "evaluates_total": 0,     # plan audits completed successfully
    "errors_total": 0,        # 4xx/5xx responses (excl. 503 sheds)
    "shed_total": 0,          # 503 saturation sheds
    "solve_seconds_total": 0.0,
    "last_solve_seconds": 0.0,
}


def _count(**updates) -> None:
    with _METRICS_LOCK:
        for k, v in updates.items():
            _METRICS[k] += v


def render_metrics() -> str:
    with _METRICS_LOCK:
        snap = dict(_METRICS)
    lines = []
    for k, v in snap.items():
        name = f"kao_{k}"
        kind = "counter" if k.endswith("_total") else "gauge"
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {v}")
    return "\n".join(lines) + "\n"


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


def _parse_brokers(spec) -> list[int]:
    if isinstance(spec, str):
        try:
            return parse_broker_list(spec)
        except ValueError as e:
            raise ApiError(400, f"bad 'brokers' range string: {e}") from e
    if isinstance(spec, list) and all(isinstance(b, int) for b in spec):
        return spec
    raise ApiError(400, "'brokers' must be a list of ints or a range string")


def _parse_topology(spec, broker_ids: list[int]) -> Topology | None:
    if spec is None:
        return None
    if spec == "even-odd":
        return Topology.even_odd(broker_ids)
    if isinstance(spec, dict):
        return Topology.from_dict(spec)
    raise ApiError(400, "'topology' must be a broker->rack object, 'even-odd', or null")


def handle_submit(
    payload: dict,
    *,
    lock_wait_s: float = DEFAULT_LOCK_WAIT_S,
    max_solve_s: float | None = DEFAULT_MAX_SOLVE_S,
) -> dict:
    """Pure request handler (also the unit-test surface): payload dict in,
    response dict out; raises ApiError with an HTTP status on bad input,
    and 503 when the solver is saturated past ``lock_wait_s``."""
    if not isinstance(payload, dict):
        raise ApiError(400, "request body must be a JSON object")
    if "assignment" not in payload:
        raise ApiError(400, "missing required field 'assignment'")
    if "brokers" not in payload:
        raise ApiError(400, "missing required field 'brokers'")
    try:
        current = Assignment.from_dict(payload["assignment"])
    except (KeyError, TypeError, ValueError) as e:
        raise ApiError(400, f"bad 'assignment': {e}") from e
    brokers = _parse_brokers(payload["brokers"])
    all_ids = sorted(set(brokers) | set(current.broker_ids()))
    topology = _parse_topology(payload.get("topology"), all_ids)
    rf = payload.get("rf")
    if rf is not None and not isinstance(rf, (int, dict)):
        raise ApiError(400, "'rf' must be an int, a topic->int object, or null")
    solver = payload.get("solver", "auto")
    if not isinstance(solver, str):
        raise ApiError(400, "'solver' must be a string")
    from .solvers.base import available_solvers

    if solver != "auto" and solver not in available_solvers():
        raise ApiError(
            400,
            f"unknown solver {solver!r}; available: "
            f"{['auto', *available_solvers()]}",
        )
    options = payload.get("options") or {}
    if not isinstance(options, dict):
        raise ApiError(400, "'options' must be an object")
    rejected = sorted(set(options) - ALLOWED_OPTIONS)
    if rejected:
        raise ApiError(
            400,
            f"unsupported option(s) {rejected}; allowed: "
            f"{sorted(ALLOWED_OPTIONS)}",
        )
    options = dict(options)  # never mutate the caller's payload
    limit = options.get("time_limit_s")
    if limit is not None and (
        isinstance(limit, bool) or not isinstance(limit, (int, float))
        or not limit > 0
    ):
        raise ApiError(400, "'time_limit_s' must be a positive number")
    if max_solve_s is not None:
        # cap every solve: client may tighten the limit but not exceed it
        options["time_limit_s"] = (
            max_solve_s if limit is None else min(float(limit), max_solve_s)
        )

    if not _SOLVE_LOCK.acquire(timeout=lock_wait_s):
        _count(shed_total=1)
        raise ApiError(
            503,
            f"solver busy (no capacity within {lock_wait_s:.0f}s); retry later",
        )
    try:
        t0 = time.perf_counter()
        res = optimize(
            current, brokers, topology, target_rf=rf, solver=solver,
            **options,
        )
        dt = time.perf_counter() - t0
        with _METRICS_LOCK:
            _METRICS["solves_total"] += 1
            _METRICS["solve_seconds_total"] += dt
            _METRICS["last_solve_seconds"] = dt
            solves = _METRICS["solves_total"]
        if solves % 64 == 0:
            # long-lived-process executable bound: a stream of
            # differently shaped clusters accumulates jitted
            # executables without limit, and past a few hundred
            # distinct compiles jaxlib's XLA:CPU compile has been
            # observed to segfault (soak-found; not memory — see
            # tests/test_lp_fuzz.py). Dropping the in-process caches
            # periodically keeps the service in the stable regime;
            # warm same-shape re-solves refill from the persistent
            # disk cache at ~cache-load cost. Must run while
            # _SOLVE_LOCK is still held: under ThreadingHTTPServer a
            # released lock lets another request start tracing before
            # the clear lands, and the _PENDING_AOT check would
            # otherwise race a daemon AOT compile from a timed-out
            # solve. The inner try swallows clear-time failures so
            # they can never discard the finished plan.
            try:
                from .solvers.tpu.engine import _PENDING_AOT

                if not _PENDING_AOT:
                    import jax

                    jax.clear_caches()
            except Exception:
                pass
    except (ValueError, KeyError) as e:
        msg = e.args[0] if e.args and isinstance(e.args[0], str) else str(e)
        raise ApiError(422, f"model rejected inputs: {msg}") from e
    except TypeError as e:
        raise ApiError(400, f"bad solver options: {e}") from e
    except RuntimeError as e:
        raise ApiError(500, f"solver failed: {e}") from e
    finally:
        _SOLVE_LOCK.release()
    return {
        "assignment": res.assignment.to_dict(),
        "report": res.report(),
    }


def handle_evaluate(payload: dict, lock_wait_s: float,
                    max_solve_s: float | None = DEFAULT_MAX_SOLVE_S) -> dict:
    """POST /evaluate — audit an existing plan (``api.evaluate``):
    feasibility, violation counts, moves vs the provable minimum, and
    an optimality verdict. Same input fields as /submit plus the
    required ``plan``. No solver runs; the bound computations (LP,
    max-flow) are host-only but cost seconds at scale, so audits
    serialize on their OWN lock (a device solve never blocks them —
    VERDICT r4 item 8), shed with 503 when saturated, and cap their
    bound LPs at the same ``--max-solve-s`` budget as solves (expired
    tiers degrade to cheaper bounds rather than hold the lock)."""
    if not isinstance(payload, dict):
        raise ApiError(400, "payload must be a JSON object")
    for field in ("assignment", "brokers", "plan"):
        if field not in payload:
            raise ApiError(400, f"missing required field '{field}'")
    try:
        current = Assignment.from_dict(payload["assignment"])
        plan = Assignment.from_dict(payload["plan"])
    except (KeyError, TypeError, ValueError) as e:
        raise ApiError(400, f"bad assignment/plan: {e}") from e
    brokers = _parse_brokers(payload["brokers"])
    all_ids = sorted(set(brokers) | set(current.broker_ids()))
    topology = _parse_topology(payload.get("topology"), all_ids)
    rf = payload.get("rf")
    if rf is not None and not isinstance(rf, (int, dict)):
        raise ApiError(400, "'rf' must be an int, a topic->int object, or null")
    from .api import evaluate

    if not _AUDIT_LOCK.acquire(timeout=lock_wait_s):
        _count(shed_total=1)
        raise ApiError(
            503,
            f"auditor busy (no capacity within {lock_wait_s:.0f}s); retry later",
        )
    try:
        out = evaluate(current, brokers, plan, topology, target_rf=rf,
                       time_budget_s=max_solve_s)
    except (ValueError, KeyError) as e:
        msg = e.args[0] if e.args and isinstance(e.args[0], str) else str(e)
        raise ApiError(422, f"model rejected inputs: {msg}") from e
    finally:
        _AUDIT_LOCK.release()
    _count(evaluates_total=1)
    return out


def handle_healthz() -> dict:
    import jax

    from .solvers.base import available_solvers

    return {
        "status": "ok",
        "solvers": available_solvers(),
        "platform": jax.devices()[0].platform,
    }


class Handler(BaseHTTPRequestHandler):
    server_version = "kafka-assignment-optimizer-tpu/1.0"

    def _send(self, status: int, obj: dict) -> None:
        body = json.dumps(obj, default=str).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # route access logs to stderr quietly
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _route(self) -> str:
        # drop any query string (LB health probes append them) and a
        # trailing slash before matching
        path = self.path.split("?", 1)[0]
        return path.rstrip("/") or "/"

    def do_GET(self):
        route = self._route()
        if route == "/":
            # the human-usable front door (reference hosted-instance UX,
            # README.md:189-195); JSON clients negotiate the schema
            accept = self.headers.get("Accept", "")
            if "application/json" in accept and "text/html" not in accept:
                self._send(200, landing.request_schema())
                return
            body = landing.render_landing().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif route == "/schema":
            self._send(200, landing.request_schema())
        elif route == "/healthz":
            self._send(200, handle_healthz())
        elif route == "/metrics":
            body = render_metrics().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            _count(errors_total=1)
            self._send(404, {"error": f"no such endpoint: {self.path}"})

    def do_POST(self):
        route = self._route()
        if route not in ("/submit", "/evaluate"):
            _count(errors_total=1)
            self._send(404, {"error": f"no such endpoint: {self.path}"})
            return
        _count(requests_total=1)
        try:
            try:
                n = int(self.headers.get("Content-Length", 0))
            except ValueError as e:
                raise ApiError(400, f"bad Content-Length header: {e}") from e
            if n > MAX_BODY_BYTES:
                raise ApiError(413, "request body too large")
            raw = self.rfile.read(n)
            try:
                payload = json.loads(raw)
            except json.JSONDecodeError as e:
                raise ApiError(400, f"invalid JSON: {e}") from e
            if route == "/evaluate":
                self._send(200, handle_evaluate(
                    payload,
                    lock_wait_s=getattr(self.server, "lock_wait_s",
                                        DEFAULT_LOCK_WAIT_S),
                    max_solve_s=getattr(self.server, "max_solve_s",
                                        DEFAULT_MAX_SOLVE_S),
                ))
                return
            self._send(200, handle_submit(
                payload,
                lock_wait_s=getattr(self.server, "lock_wait_s",
                                    DEFAULT_LOCK_WAIT_S),
                max_solve_s=getattr(self.server, "max_solve_s",
                                    DEFAULT_MAX_SOLVE_S),
            ))
        except ApiError as e:
            if e.status != 503:
                _count(errors_total=1)
            self._send(e.status, {"error": str(e)})
        except Exception as e:  # never leak a traceback as a hung socket
            _count(errors_total=1)
            self._send(500, {"error": f"internal error: {e}"})


def make_server(host: str = "127.0.0.1", port: int = 8787,
                verbose: bool = False,
                lock_wait_s: float = DEFAULT_LOCK_WAIT_S,
                max_solve_s: float | None = DEFAULT_MAX_SOLVE_S,
                ) -> ThreadingHTTPServer:
    srv = ThreadingHTTPServer((host, port), Handler)
    srv.verbose = verbose
    srv.lock_wait_s = lock_wait_s
    srv.max_solve_s = max_solve_s
    return srv


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kafka_assignment_optimizer_tpu.serve",
        description="Kafka reassignment optimizer HTTP service (POST /submit)",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8787)
    ap.add_argument("--verbose", action="store_true", help="access logs")
    ap.add_argument("--lock-wait-s", type=float,
                    default=DEFAULT_LOCK_WAIT_S,
                    help="max seconds a request waits for the solver "
                         "before 503 (saturation shedding)")
    ap.add_argument("--max-solve-s", type=float,
                    default=DEFAULT_MAX_SOLVE_S,
                    help="time limit injected into every solve; clients "
                         "may tighten but not exceed it (0 = uncapped)")
    args = ap.parse_args(argv)
    if args.lock_wait_s < 0:
        ap.error("--lock-wait-s must be >= 0")
    if args.max_solve_s < 0:
        ap.error("--max-solve-s must be >= 0 (0 = uncapped)")
    from .utils.platform import pin_platform

    pin_platform()
    srv = make_server(
        args.host, args.port, verbose=args.verbose,
        lock_wait_s=args.lock_wait_s,
        max_solve_s=args.max_solve_s or None,
    )
    print(f"listening on http://{args.host}:{srv.server_address[1]}", file=sys.stderr)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
