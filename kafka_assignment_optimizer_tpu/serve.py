"""HTTP service — optimizer-as-a-service (reference C16).

The reference runs a hosted public instance with a ``POST /submit``
endpoint (``/root/reference/README.md:187-195``); its payload schema is
not documented in the mount, so this build defines its own (SURVEY.md §1
L7). Stdlib-only (ThreadingHTTPServer) — no web-framework dependency.

Endpoints:

``POST /submit``
    Request JSON::

        {
          "assignment": {"version": 1, "partitions": [...]},   # required
          "brokers": "0-18" | [0, 1, ...],                     # required
          "topology": {"0": "rackA", ...} | "even-odd" | null,
          "rf": 3 | {"topic": 3} | null,
          "solver": "auto" | "milp" | "native" | "tpu" | "lp_solve",
          "options": {"seed": 0, "batch": 512, ...}            # solver kwargs
        }

    Response 200::

        {"assignment": {...reassignment JSON...},              # the plan
         "report": {...observability report (SURVEY.md §5)...}}

    ``options`` accepts search knobs only (``ALLOWED_OPTIONS``);
    path-valued solver kwargs are rejected. Every solve is capped at the
    server's ``--max-solve-s`` budget.

    Errors: 400 malformed JSON/schema or disallowed option (body
    ``{"error": ...}``), 422 model rejected the inputs, 500 solver
    failure, 503 load shed — queue full past ``--lock-wait-s``, open
    circuit, or an exhausted per-request ``deadline_s`` — always with a
    ``Retry-After`` header and ``reason``/``retry_after_s`` in the body
    (docs/RESILIENCE.md).

``POST /evaluate``
    Audit an EXISTING plan (same fields as ``/submit`` minus
    ``solver``/``options``, plus required ``plan``: a reassignment
    JSON object). Response 200 is the audit report: feasibility with
    per-constraint violation counts, replica moves vs the provable
    minimum, objective weight vs its provable upper bound, and
    ``proven_optimal``. Audits hold their own lock — host-only bound
    work never queues behind a device solve — and shed with 503 the
    same way when saturated.

``GET /``
    Human-usable front door (the reference hosts a public instance
    with a usage/extended-example page, ``README.md:189-195``): HTML
    usage + a live form prefilled with the reference demo. Clients
    sending ``Accept: application/json`` get the request schema.

``GET /schema``
    Machine-readable request/response shapes (JSON).

``POST /warmup``
    Pre-pay XLA compiles: ``{"shapes": [{"brokers": 256, "partitions":
    10000, "rf": 3, "racks": 8}, ...], "engine": "sweep"}`` solves one
    synthetic cluster per shape so every later production solve in the
    same bucket (``solvers.tpu.bucket``) runs fully warm. Also runs at
    startup via ``--warmup B:P[:R[:K]],...``.

``POST /clusters/<id>/events``
    The cluster-watch delta API (docs/WATCH.md): one typed, epoch-
    fenced state diff — ``bootstrap``, ``broker_add``,
    ``broker_remove``, ``broker_drain``, ``rack_fail``,
    ``partition_growth``, ``rf_change`` — against a named cluster whose
    last certified plan and topology the service remembers (durably,
    with ``--watch-dir``). 200 returns the new plan (warm-started from
    the previous one); 202 acknowledges an event coalesced behind an
    in-flight solve; 409 rejects a stale/replayed epoch (structured,
    provably without a solve); 503 ``event_storm`` is backpressure
    with a Retry-After from the coalescing window.

``GET /clusters`` / ``GET /clusters/<id>``
    Watched-cluster listing / one cluster's state, epoch, and last
    certified plan.

``GET /clusters/<id>/rollout`` /
``POST /clusters/<id>/rollout/{start,advance,pause,rollback}``
    Streaming plan rollout (docs/ROLLOUT.md): execute the cluster's
    certified plan as bandwidth-budgeted move waves — no broker or
    rack exceeds a per-wave transfer cap — with canary verification
    gating advancement, epoch-fenced commands (stale -> structured
    409 without touching the store), bit-exact rollback via inverse
    waves, and mid-rollout cluster events re-planning the REMAINING
    waves against the partially-moved ground truth. Each wave is
    emitted as upstream-compatible reassignment JSON.

``GET /healthz``
    ``{"status": "ok", "solvers": [...], "platform": "tpu",
    "cache": {...bucket/executable counters...}, "queue": {...}}``

``GET /metrics``
    Prometheus text counters: requests/solves/evaluates/errors/sheds,
    solve wall-clock totals, executable-cache hit/miss/compile-seconds
    and solve-queue gauges (``kao_*``), plus per-phase solve latency
    histograms aggregated from solve traces
    (``kao_phase_seconds{phase=...}``).

``GET /debug/solves`` / ``GET /debug/solves/<trace_id>``
    Solve-trace telemetry (docs/OBSERVABILITY.md): every request gets a
    trace ID (echoed as ``trace_id`` in the /submit response) and its
    solve report — the span tree over the engine pipeline plus the
    annealing trajectory summary — lands in a bounded ring buffer,
    retrievable here until it ages out. ``--no-trace`` disables;
    ``--profile-dir`` adds ``jax.profiler`` captures for the first N
    solves per bucket. Requests carrying a W3C ``traceparent`` header
    ADOPT the propagated trace ID (remote-parented root; the header is
    echoed back) — the cross-process join a ``kao-router`` resolves
    via ``GET /debug/traces/<id>`` (docs/OBSERVABILITY.md
    "Distributed traces"). Coalesced batch members each keep their OWN
    trace ID: the member's report links to the shared batch report via
    ``coalesced_into``, so every member's ID resolves here.
    ``KAO_TRACE_TAIL`` arms tail-based retention: full span trees are
    kept for slow/degraded/chaos-touched/hedged traces plus a
    deterministic head sample; fast-clean traces feed histograms only.

Concurrency: solves run on a bounded request queue drained by a small
worker pool (``--workers`` / ``--queue-depth``) — overlapping submits
proceed concurrently on warm, shape-bucketed executables instead of
serializing on a global lock; the queue sheds with 503 once full past
``--lock-wait-s``.

Coalescing (docs/BATCHING.md): same-bucket TPU solves that arrive while
the pool is busy are grouped for up to ``--batch-window-ms`` (or
``--max-batch`` lanes) and dispatched as ONE batched lane solve
(``engine.solve_tpu_batch``), then demultiplexed per request; sparse
requests bypass the window and keep single-solve latency. ``/metrics``
carries the batch-size histogram (``kao_batch_size_total{size=...}``),
coalesce-wait totals, and per-lane quality counters.

Run: ``python -m kafka_assignment_optimizer_tpu.serve --port 8787``.
"""

from __future__ import annotations

import argparse
import json
import queue as _queue
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import landing
from .analysis import sanitize as _sanitize_mod
from .api import optimize
from .models.cluster import Assignment, Topology, parse_broker_list
from .obs import chrome as _ochrome
from .obs import drift as _odrift
from .obs import expo as _expo
from .obs import flight as _oflight
from .obs import log as _olog
from .obs import prof as _oprof
from .obs import sampler as _osampler
from .obs import slo as _oslo
from .obs import trace as _otrace
from .resilience import breaker as _breaker
from .resilience import budget as _rbudget
from .utils import platform as _platform
from .resilience import chaos as _chaos
from .resilience import ladder as _ladder
from .rollout import exec as _rexec
from .rollout import state as _rstate
from .rollout import waves as _rwaves
from .watch import events as _wevents
from .watch import manager as _wmanager
from .watch import store as _wstore

# audits (/evaluate) hold their OWN lock (VERDICT r4 item 8): they are
# pure host-side work (numpy + bound LPs + the native flow kernel — no
# jax, no device, no jit caches), so serializing them behind a long
# device solve bought nothing and 503-shed cheap audits for up to
# --lock-wait-s. One audit at a time still bounds host CPU: the bound
# LPs cost seconds at 10k partitions.
_AUDIT_LOCK = threading.Lock()

MAX_BODY_BYTES = 64 << 20  # 64 MiB — a 10k-partition cluster is ~1 MiB

# Options the HTTP surface forwards to solvers: search-effort knobs only.
# Path-valued solver kwargs (``checkpoint``, ``profile_dir``) are
# deliberately NOT forwardable — a remote client must never be able to
# make the service create directories or read/write files at
# client-chosen paths. Operators who want checkpointing use the CLI.
ALLOWED_OPTIONS = frozenset({
    "seed", "batch", "rounds", "sweeps", "steps_per_round", "engine",
    "time_limit_s", "t_hi", "t_lo", "n_devices", "pipeline",
    "portfolio", "decompose", "megachunk",
})

# saturation policy: how long a request waits for a queue slot before
# the service sheds it with 503 (a single 10k-partition solve must not
# make every later POST hang indefinitely), and the time limit injected
# into each solve unless the client sets a smaller one
DEFAULT_LOCK_WAIT_S = 30.0
DEFAULT_MAX_SOLVE_S = 300.0
DEFAULT_WORKERS = 2
DEFAULT_QUEUE_DEPTH = 4
# maintenance drain window (--queue-wait-s): how long the periodic
# cache-clear waits for in-flight solves before skipping the clear
# (satellite fix, ISSUE 6: this was a hard-coded 15.0)
DEFAULT_QUEUE_WAIT_S = 15.0

# serve-side resilience knobs (docs/RESILIENCE.md), set by main():
# - default_deadline_s: per-request end-to-end deadline applied when
#   the request carries no "deadline_s" field (None = no deadline
#   beyond --max-solve-s);
# - checkpoint_dir: operator-chosen directory for per-cluster solve
#   checkpoints, keyed by instance FINGERPRINT (never a client path —
#   the path-valued-option rejection above still stands). Enables
#   crash-safe auto-resume: a retried or repeated solve of the same
#   cluster warm-starts from the last completed plan.
RESILIENCE = {
    "default_deadline_s": None,
    "checkpoint_dir": None,
    # --checkpoint-dir hygiene (ISSUE 7 satellite): the periodic
    # maintenance pass GCs fingerprint-keyed .npz checkpoints past
    # these caps (age first, then oldest beyond the count cap); the
    # live file count is exported as the kao_checkpoint_files gauge
    "checkpoint_max_files": 512,
    "checkpoint_max_age_s": 7 * 24 * 3600.0,
}

# cluster-watch delta API (docs/WATCH.md): POST /clusters/<id>/events.
# "dir" is the OPERATOR-chosen durable plan-store directory
# (--watch-dir); without it the watch endpoints still work but state is
# process-local only (healthz says durable: false). The registry is
# built lazily so tests can point "dir" somewhere and reset.
WATCH = {
    "dir": None,
    "window_s": _wmanager.DEFAULT_WINDOW_S,
    "max_backlog": _wmanager.DEFAULT_MAX_BACKLOG,
    "registry": None,
    "lock_wait_s": DEFAULT_LOCK_WAIT_S,
    "max_solve_s": DEFAULT_MAX_SOLVE_S,
}

# streaming plan rollout (docs/ROLLOUT.md): GET /clusters/<id>/rollout
# + POST /clusters/<id>/rollout/{start,advance,pause,rollback}. The
# manager rides the watch registry (same plan store, same solve path
# for mid-rollout re-plans) and is rebuilt whenever the registry is —
# tests that reset WATCH["registry"] get a fresh manager for free.
ROLLOUT = {
    "manager": None,
    "broker_cap": _rwaves.DEFAULT_BROKER_CAP,
    "rack_cap": _rwaves.DEFAULT_RACK_CAP,
    "packer": "greedy",
    "lanes": _rwaves.DEFAULT_LANES,
}
# the kao_rollout_* counter families, pre-declared at zero so
# dashboards see them before the first rollout (the PR 6
# removed-but-referenced KeyError discipline)
_ROLLOUT_COUNTER_NAMES = (
    "started_total", "commands_total", "fenced_total",
    "waves_emitted_total", "waves_applied_total", "canary_fail_total",
    "rollbacks_total", "replans_total", "completed_total", "active",
)

# circuit breaker on repeated solver failures per bucket key
# (resilience.breaker): a bucket that keeps failing compile/dispatch
# sheds instantly with Retry-After instead of burning a full
# compile-and-crash cycle per request
_BREAKER = _breaker.CircuitBreaker()
# request coalescing (--batch-window-ms / --max-batch): same-bucket TPU
# solves that arrive while the pool is busy are grouped for up to the
# window, then submitted as ONE batched lane solve (engine.solve_tpu_batch)
# and demultiplexed. A request that finds free capacity bypasses the
# window entirely — sparse traffic pays zero added latency.
DEFAULT_BATCH_WINDOW_MS = 25.0
DEFAULT_MAX_BATCH = 8
# options the batched lane path understands; a request carrying any
# other knob (e.g. steps_per_round) takes the single-solve path
_BATCHABLE_OPTIONS = frozenset({
    "seed", "batch", "rounds", "sweeps", "engine", "time_limit_s",
    "t_hi", "t_lo", "n_devices", "pipeline", "portfolio", "megachunk",
})
# executable-accumulation hygiene: drop in-process jit caches after this
# many completed solves (see _SolveQueue._maintenance)
_CLEAR_CACHES_EVERY = 64

# solve-trace telemetry (docs/OBSERVABILITY.md): every request gets a
# trace ID; the solve runs under an ambient obs.trace span tree whose
# report lands in the ring buffer behind GET /debug/solves/<trace_id>
# and is echoed as "trace_id" in the response envelope. --no-trace
# disables it (requests then carry no trace_id). --profile-dir
# additionally wraps the first --profile-solves TPU solves per bucket
# in a jax.profiler trace capture (XLA-level evidence next to the
# span-level reports).
OBS = {
    "trace": True,
    "profile_dir": None,
    "profile_solves": 1,
    # continuous-performance observatory (docs/OBSERVABILITY.md):
    # --flight-dir persists one compact flight record per
    # solve/delta/batch-lane (obs.flight); the SLO engine (obs.slo)
    # runs over the record stream either way
    "flight_dir": None,
}
# fleet telemetry plane (docs/OBSERVABILITY.md "Fleet plane"):
# GET /debug/fleet merges THIS worker's record ring with the recent
# streams of the operator-named peers (--fleet-peers; client-supplied
# peer URLs are deliberately not accepted — the server must never be
# pointable at attacker-chosen endpoints). This merged view is the
# bucket-affinity router's future data source (ROADMAP item 1).
FLEET = {
    "peers": [],
    "timeout_s": 5.0,
    "tail": 512,
}

# process start, for the kao_uptime_seconds gauge
_START_UNIX = time.time()
# kao_build_info labels, resolved once (jax.devices() initializes the
# backend; cache the answer so /metrics scrapes stay cheap)
_BUILD_INFO: dict = {}


def _build_info(resolve: bool = False) -> dict:
    """kao_build_info labels. ``/metrics`` reads the CACHE only — a
    monitoring scrape must never be the thing that pays jax backend
    init (multi-second on TPU, on the handler thread). Resolution
    happens where init is already deliberate: ``handle_healthz``
    (which calls ``jax.devices()`` anyway) passes ``resolve=True``,
    so the labels fill on the first health probe."""
    if not _BUILD_INFO and resolve:
        try:
            import jax

            from . import __version__

            _BUILD_INFO.update({
                "version": __version__,
                "jax": jax.__version__,
                "backend": jax.default_backend(),
                "devices": str(jax.device_count()),
            })
        except Exception:  # init failed: uncached, retried next probe
            pass
    if not _BUILD_INFO:
        return {"version": "unknown", "jax": "unknown",
                "backend": "unknown", "devices": "0"}
    return dict(_BUILD_INFO)
_PROFILE_LOCK = threading.Lock()
_PROFILED_BUCKETS: dict[tuple, int] = {}  # bucket key -> solves profiled


def _profile_dir_for(bucket_key: tuple, trace_id: str | None) -> str | None:
    """Claim one profiled solve for ``bucket_key`` if the per-bucket
    budget (--profile-solves) has room; returns the capture directory
    (unique per solve) or None."""
    base = OBS["profile_dir"]
    if not base:
        return None
    with _PROFILE_LOCK:
        n = _PROFILED_BUCKETS.get(bucket_key, 0)
        if n >= max(int(OBS["profile_solves"]), 0):
            return None
        _PROFILED_BUCKETS[bucket_key] = n + 1
    import os

    safe = "-".join(
        str(x) for x in bucket_key if isinstance(x, (int, str))
    ) or "default"
    return os.path.join(base, safe, trace_id or _otrace.new_trace_id())


class _QueueItem:
    __slots__ = ("fn", "done", "result", "exc", "abandoned", "enq")

    def __init__(self, fn):
        self.fn = fn
        self.done = threading.Event()
        self.result = None
        self.exc: BaseException | None = None
        self.abandoned = False
        # enqueue timestamp: the worker differences it at pickup so the
        # flight ledger's queue-wait share is measured, not inferred
        self.enq = time.perf_counter()


class _SolveQueue:
    """Bounded request queue + worker pool — the serving path that
    replaced the serialize-everything solve lock. Overlapping submits
    enqueue and run on ``workers`` daemon threads (warm, shape-bucketed
    executables are process-wide, so two warm solves genuinely overlap:
    host-side constructor races, bound LPs, and device dispatches
    interleave instead of convoying behind one lock). Saturation policy:
    a request that cannot get a queue slot within its wait budget is
    shed with 503, exactly like the old lock timeout — but a queued
    request keeps its place instead of stampeding on a lock."""

    def __init__(self, workers: int = DEFAULT_WORKERS,
                 depth: int = DEFAULT_QUEUE_DEPTH):
        self.workers = max(1, int(workers))
        self.queue_wait_s = DEFAULT_QUEUE_WAIT_S
        self._q: _queue.Queue = _queue.Queue(maxsize=max(1, int(depth)))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._started = False
        self._active = 0
        self._done_count = 0
        self._draining = False  # maintenance holds new solves at the gate

    def configure(self, workers: int | None = None,
                  depth: int | None = None,
                  queue_wait_s: float | None = None) -> None:
        """Resize before the workers start (server startup); a no-op
        once traffic has begun (``queue_wait_s`` may change anytime —
        it only gates the next maintenance drain)."""
        with self._lock:
            if queue_wait_s is not None:
                self.queue_wait_s = max(float(queue_wait_s), 0.0)
            if self._started:
                return
            if workers is not None:
                self.workers = max(1, int(workers))
            if depth is not None:
                self._q = _queue.Queue(maxsize=max(1, int(depth)))

    def _ensure_started(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            for i in range(self.workers):
                threading.Thread(
                    target=self._run, daemon=True, name=f"kao-solve-{i}"
                ).start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item.abandoned:  # waiter gave up while queued
                continue
            if _chaos.fires("worker_crash"):
                # chaos (docs/RESILIENCE.md): this worker dies holding
                # a request. The containment path is _respawn — a
                # replacement worker starts and the crashed request
                # gets its one retry there, so pool capacity is never
                # silently lost and the waiter never hangs.
                self._respawn(item)
                return  # the crash: this worker thread exits
            self._execute(item)

    def _execute(self, item: _QueueItem) -> None:
        with self._cv:
            # maintenance in progress: no new trace/compile may
            # start until the cache clear has landed
            while self._draining:
                self._cv.wait()
            self._active += 1
        # queue-wait tagging (obs/flight ledger): everything between the
        # submit's enqueue and this pickup — including a maintenance
        # drain hold — is time the REQUEST waited, attributed to the
        # solve this worker is about to run
        qw_tok = _oflight.set_queue_wait(time.perf_counter() - item.enq)
        try:
            try:
                item.result = item.fn()
            except BaseException as e:  # delivered to the waiter
                item.exc = e
            item.done.set()
        finally:
            _oflight.reset_queue_wait(qw_tok)
            with self._cv:
                self._active -= 1
                self._done_count += 1
                n = self._done_count
                self._cv.notify_all()
        if n % _CLEAR_CACHES_EVERY == 0:
            self._maintenance()

    def _respawn(self, item: _QueueItem) -> None:
        """A worker crashed mid-request (today only the ``worker_crash``
        chaos point can get here — ``_execute`` contains genuine solve
        exceptions and delivers them to the waiter). Start a replacement
        worker and give the in-flight request its ONE retry on it; with
        ``--checkpoint-dir`` the retried solve auto-resumes from the
        last completed checkpoint of the same cluster."""
        _ladder.note_rung("worker_restart")
        _olog.error("worker_crashed", respawned=True,
                    retrying=not item.abandoned)

        def run():
            if not item.abandoned:
                self._execute(item)
            self._run()

        threading.Thread(target=run, daemon=True,
                         name="kao-solve-respawn").start()

    def _maintenance(self) -> None:
        """Long-lived-process executable bound: a stream of distinct
        cluster shapes accumulates jitted executables without limit, and
        past a few hundred distinct compiles jaxlib's XLA:CPU compile
        has been observed to segfault (soak-found; see
        tests/test_lp_fuzz.py). Shape bucketing collapses most of that
        variety, but the periodic clear stays as the backstop.

        Exclusion contract (the lock the old serialize-everything path
        provided implicitly): ``_draining`` gates new solves at the
        worker loop, this thread then waits (bounded) for in-flight
        solves to finish, and only with zero active solves and no
        daemon AOT compile in flight does the clear run — a clear can
        never race an in-progress trace. If the pool stays busy past
        the bound, the clear is skipped and retried at the next
        multiple; warm same-bucket re-solves refill from the
        persistent disk cache at ~cache-load cost."""
        with self._cv:
            if self._draining:
                return
            self._draining = True
            # drain window (--queue-wait-s; was a hard-coded 15.0 —
            # satellite fix, ISSUE 6): a busy pool bounds how long the
            # clear may hold the gate before skipping
            deadline = time.monotonic() + self.queue_wait_s
            while self._active > 0:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(timeout=left):
                    break
            drained = self._active == 0
        try:
            from .solvers.tpu.engine import _PENDING_AOT

            if drained and not _PENDING_AOT:
                import jax

                from .parallel.mesh import clear_exec_cache

                clear_exec_cache()
                jax.clear_caches()
        except Exception:
            pass
        finally:
            with self._cv:
                self._draining = False
                self._cv.notify_all()
        # checkpoint-dir hygiene rides the same maintenance cadence
        # (ISSUE 7 satellite): age + count caps, never fatal. Runs even
        # when the cache clear was skipped — file GC needs no exclusion
        # (utils.checkpoint.load treats a vanished file as no
        # checkpoint, and writes are atomic-rename).
        _gc_checkpoints()

    def stats(self) -> dict:
        with self._lock:
            return {
                "queue_depth": self._q.qsize(),
                "active_solves": self._active,
                "workers": self.workers,
                "solves_completed": self._done_count,
            }

    def submit(self, fn, wait_s: float, budget_s: float | None):
        """Run ``fn`` on the worker pool; raises ApiError(503) when the
        queue stays full past ``wait_s`` or the solve outlives the
        service window."""
        self._ensure_started()
        item = _QueueItem(fn)
        try:
            if _chaos.fires("queue_overload"):
                # chaos: the queue reports no capacity — the request
                # must take the exact shed path a saturated pool takes
                raise _queue.Full
            self._q.put(item, timeout=max(float(wait_s), 0.0))
        except _queue.Full:
            raise _shed(
                "queue_full",
                f"solver busy (no capacity within {wait_s:.0f}s); "
                "retry later",
                retry_after_s=self._retry_after_hint(),
                queue_wait_s=self.queue_wait_s,
            ) from None
        # budget_s None means the operator runs uncapped solves
        # (--max-solve-s 0 with no client limit): wait to completion,
        # exactly like the pre-queue synchronous path did
        window = (
            None if budget_s is None
            else max(float(wait_s), 0.0) + float(budget_s) + 60.0
        )
        if not item.done.wait(window):
            item.abandoned = True  # dropped if still queued; best effort
            raise _shed(
                "service_window",
                f"solve did not finish within the {window:.0f}s service "
                "window; retry later",
                retry_after_s=self._retry_after_hint(),
            )
        if item.exc is not None:
            raise item.exc
        return item.result

    def _retry_after_hint(self) -> float:
        """Retry-After for queue sheds: roughly one queue's worth of
        the last observed solve time, clamped to [1, 60] s — an honest
        hint beats a constant, and the clamp keeps a pathological
        sample from telling clients to go away for an hour."""
        with _METRICS_LOCK:
            last = _METRICS["last_solve_seconds"]
        backlog = max(self._q.qsize(), 1)
        return min(max(last * backlog, 1.0), 60.0)


def _checkpoint_files() -> list:
    """The ``.npz`` checkpoints currently under --checkpoint-dir (empty
    when the feature is off or the dir vanished)."""
    d = RESILIENCE["checkpoint_dir"]
    if not d:
        return []
    import glob
    import os

    return glob.glob(os.path.join(d, "*.npz"))


def _gc_checkpoints() -> int:
    """--checkpoint-dir hygiene (ISSUE 7 satellite): fingerprint-keyed
    checkpoints accumulate one file per distinct cluster forever. Drop
    files older than ``checkpoint_max_age_s``, then the oldest beyond
    ``checkpoint_max_files``. Returns how many were removed; never
    raises (a GC failure must not take down maintenance)."""
    import os
    import time as _time

    removed = 0
    try:
        files = _checkpoint_files()
        if not files:
            return 0
        now = _time.time()
        max_age = RESILIENCE["checkpoint_max_age_s"]
        max_files = RESILIENCE["checkpoint_max_files"]
        aged = []
        for f in files:
            try:
                mtime = os.path.getmtime(f)
            except OSError:
                continue  # raced with another GC / a fresh write
            if max_age is not None and now - mtime > max_age:
                try:
                    os.remove(f)
                    removed += 1
                except OSError:
                    pass
            else:
                aged.append((mtime, f))
        if max_files is not None and len(aged) > max_files:
            aged.sort()  # oldest first
            for _, f in aged[: len(aged) - int(max_files)]:
                try:
                    os.remove(f)
                    removed += 1
                except OSError:
                    pass
        if removed:
            _olog.log("checkpoint_gc", removed=removed,
                      remaining=len(files) - removed)
    except Exception:
        pass
    return removed


_SOLVES = _SolveQueue()

# service counters (GET /metrics, Prometheus text format); guarded by
# their own lock so readers never contend with a solve
_METRICS_LOCK = threading.Lock()
_METRICS = {
    "requests_total": 0,      # POST /submit or /evaluate received
    "solves_total": 0,        # solves completed successfully
    "evaluates_total": 0,     # plan audits completed successfully
    "errors_total": 0,        # 4xx/5xx responses (excl. 503 sheds)
    "solve_seconds_total": 0.0,
    "last_solve_seconds": 0.0,
    # request coalescing (the batched lane path)
    "batch_solves_total": 0,        # batched dispatches completed
    "batched_requests_total": 0,    # requests served THROUGH a batch
    "batch_bypass_total": 0,        # sparse requests that skipped the window
    "coalesce_wait_seconds_total": 0.0,  # enqueue -> flush, summed
    "batch_lanes_feasible_total": 0,     # per-lane quality counters
    "batch_lane_moves_total": 0,
    "batch_lane_weight_total": 0,
    # portfolio lanes (docs/PORTFOLIO.md): single-path solves that
    # raced a config portfolio, and how many retired the ladder on a
    # first-to-certify boundary certificate
    "portfolio_solves_total": 0,
    "portfolio_early_exit_total": 0,
}
# portfolio winner-lane histogram (rendered as the labeled counter
# family kao_portfolio_winner_total{lane="N"}): which configs actually
# win is the evidence the diversity table earns its lanes
_PORTFOLIO_WINNERS: dict[int, int] = {}
# batch-size histogram: coalesced dispatch size -> count (rendered as
# the labeled counter family kao_batch_size_total{size="N"})
_BATCH_SIZES: dict[int, int] = {}
# 503 sheds by reason (rendered as kao_shed_total{reason="..."}):
# every shed path names why it shed, and the full reason set is
# pre-declared so /metrics always exposes the family at zero
_SHED_REASON_NAMES = (
    "queue_full", "service_window", "coalesce_window", "audit_busy",
    "circuit_open", "deadline", "event_storm", "stream_clients",
)
_SHED_REASONS: dict[str, int] = {}


def _count(**updates) -> None:
    with _METRICS_LOCK:
        for k, v in updates.items():
            _METRICS[k] += v


def _shed(reason: str, message: str, retry_after_s: float,
          **body_extra) -> "ApiError":
    """Count one load shed and build its 503: the response carries a
    ``Retry-After`` header (and ``retry_after_s``/``reason`` in the
    body) so well-behaved clients back off instead of hammering a
    saturated service. The body additionally names THIS worker
    (``worker``, the flight-record identity stamp) so a fleet router
    attributes the shed to the right peer and fails over with the
    precise ``retry_after_s`` float instead of the coarse integer
    header (docs/FLEET.md). Callers ``raise _shed(...)``."""
    with _METRICS_LOCK:
        _SHED_REASONS[reason] = _SHED_REASONS.get(reason, 0) + 1
    return ApiError(
        503, message, retry_after_s=retry_after_s,
        body={"reason": reason, "retry_after_s": round(retry_after_s, 3),
              "worker": _oflight.worker_identity(), **body_extra},
    )


def _breaker_guarded(key: tuple, call):
    """Run one dispatch under the per-bucket circuit breaker: an OPEN
    circuit sheds instantly with 503 + Retry-After (no compile-and-
    crash cycle); solver-side failures trip it, client-side errors
    (ApiError sheds/validation, model rejections) never do."""
    admitted, retry_after = _BREAKER.allow(key)
    if not admitted:
        # the bucket key in the body scopes the shed for a fleet
        # router: other buckets on this worker are still healthy, so
        # only THIS bucket's traffic should fail over (docs/FLEET.md)
        raise _shed(
            "circuit_open",
            "circuit open for this cluster bucket after repeated "
            "solver failures; retry later",
            retry_after_s=retry_after,
            **({"bucket": list(key)}
               if all(isinstance(x, int) for x in key) else {}),
        )
    try:
        out = call()
    except (ApiError, ValueError, KeyError, TypeError):
        # saturation sheds / model rejections — no solver verdict, not
        # this bucket's fault. If this caller held the half-open probe,
        # release it so a later request can probe again (a shed probe
        # must not wedge the circuit open forever).
        _BREAKER.release_probe(key)
        raise
    except BaseException:
        _BREAKER.record_failure(key)
        raise
    _BREAKER.record_success(key)
    return out


def _record_batch(size: int, waited_s: float, reports: list[dict]) -> None:
    """Metrics for one coalesced dispatch: size histogram, coalesce
    wait, and per-lane solve quality."""
    with _METRICS_LOCK:
        _BATCH_SIZES[size] = _BATCH_SIZES.get(size, 0) + 1
        _METRICS["batch_solves_total"] += 1
        _METRICS["batched_requests_total"] += size
        _METRICS["coalesce_wait_seconds_total"] += waited_s
        for rep in reports:
            _METRICS["batch_lanes_feasible_total"] += int(
                bool(rep.get("feasible"))
            )
            _METRICS["batch_lane_moves_total"] += int(
                rep.get("replica_moves") or 0
            )
            _METRICS["batch_lane_weight_total"] += int(
                rep.get("objective_weight") or 0
            )


def _render_histogram(lines: list, name: str, label: str,
                      snap: dict, help_text: str) -> None:
    """One Prometheus histogram family from an ExemplarHistogram
    snapshot: cumulative ``_bucket{le=}`` rows, ``_sum``/``_count``,
    HELP/TYPE pair. Shared by kao_phase_seconds and kao_solve_seconds
    so the exposition shape cannot drift between them."""
    if not snap:
        return
    lines.append(f"# HELP {name} {help_text}")
    lines.append(f"# TYPE {name} histogram")
    for key in sorted(snap):
        row = snap[key]
        for le, n in row["buckets"]:
            lines.append(
                f'{name}_bucket{{{label}="{key}",le="{le}"}} {n}'
            )
        lines.append(
            f'{name}_bucket{{{label}="{key}",le="+Inf"}} '
            f'{row["count"]}'
        )
        lines.append(f'{name}_sum{{{label}="{key}"}} {row["sum"]}')
        lines.append(f'{name}_count{{{label}="{key}"}} {row["count"]}')


def _render_exemplars(lines: list, name: str, label: str,
                      exemplars: list) -> None:
    """The exemplar sidecar gauge family for one histogram: the worst
    recent observation per (key, bucket) with its trace ID as a
    label."""
    if not exemplars:
        return
    lines.append(
        f"# HELP {name} worst recent observation per ({label}, "
        "bucket); trace_id resolves via /debug/solves"
    )
    lines.append(f"# TYPE {name} gauge")
    for e in exemplars:
        lines.append(
            f'{name}{{{label}="{e[label]}",le="{e["le"]}",'
            f'trace_id="{e["trace_id"]}"}} {e["value"]}'
        )


def render_metrics() -> str:
    # ONE atomic snapshot of everything behind _METRICS_LOCK: the
    # dispatchers mutate _METRICS and _BATCH_SIZES while this renders,
    # and two separate lock acquisitions let a batch land between them
    # — torn reads where kao_batch_solves_total disagrees with its own
    # size histogram (satellite fix, ISSUE 3)
    with _METRICS_LOCK:
        snap = dict(_METRICS)
        sizes = dict(_BATCH_SIZES)
        sheds = {r: 0 for r in _SHED_REASON_NAMES}
        sheds.update(_SHED_REASONS)
        port_winners = dict(_PORTFOLIO_WINNERS)
    # portfolio geometry gauge: the width a defaulted solve races now
    # (0-vs-N is the --no-portfolio toggle made scrapeable). Read ONLY
    # from an already-imported engine module — a /metrics scrape must
    # never be the thing that pays the engine's jax import (same
    # invariant as the _BUILD_INFO cache); the gauge appears after the
    # first solve or health probe, like kao_build_info's labels.
    eng = sys.modules.get(
        __name__.rsplit(".", 1)[0] + ".solvers.tpu.engine"
    )
    if eng is not None:
        try:
            snap["portfolio_width"] = eng.portfolio_width_default()
        except Exception:
            pass
    # executable/bucket cache counters (solvers.tpu.bucket.STATS): the
    # operational evidence that shape bucketing is absorbing compiles —
    # kao_cache_exec_hits climbing while kao_cache_compiles_total stays
    # flat is the steady state a warmed service should show
    try:
        from .solvers.tpu.bucket import STATS as _cache_stats

        for k, v in _cache_stats.snapshot().items():
            snap[f"cache_{k}"] = v
    except Exception:
        pass
    try:
        for k, v in _SOLVES.stats().items():
            snap[f"queue_{k}"] = v
    except Exception:
        pass
    # runtime sanitizer counters (analysis.sanitize): zero and inert
    # unless KAO_SANITIZE / --sanitize armed the guards
    for k, v in _sanitize_mod.snapshot().items():
        snap[f"sanitizer_{k}"] = v
    # process uptime (satellite, ISSUE 9): rate() denominators and
    # restart detection for every counter family above
    snap["uptime_seconds"] = round(time.time() - _START_UNIX, 3)
    # flight-recorder counters (obs.flight, docs/OBSERVABILITY.md)
    for k, v in _oflight.snapshot().items():
        if isinstance(v, (int, float)):
            snap[f"flight_{k}"] = v
    # live-stream fan-out (GET /debug/stream): subscriber count and the
    # slow-client shed counter — dropped records mean a reader fell
    # behind its bounded queue, never that the solve path blocked
    stream = _oflight.stream_stats()
    snap["stream_clients"] = stream["clients"]
    snap["stream_dropped_total"] = stream["dropped_total"]
    # device-occupancy sampler (obs.sampler): cached tick scalars only
    # — the sampler thread reads the devices, a scrape never touches
    # jax and never rebuilds the /healthz roofline summary
    samp = _osampler.SAMPLER.stats()
    snap["device_sampler_enabled"] = samp["enabled"]
    snap["device_sampler_samples_total"] = samp["samples_total"]
    snap["device_sampler_overhead"] = samp["overhead_frac"]
    snap["device_duty_cycle"] = samp["duty_cycle"]
    # solve-report ring occupancy: the /debug/solves payload bound in
    # action (bytes resident + reports truncated to fit)
    ring = _otrace.RECENT.stats()
    snap["trace_ring_bytes"] = ring["bytes"]
    snap["trace_ring_reports"] = ring["reports"]
    snap["trace_ring_truncated_total"] = ring["truncated_total"]
    # --checkpoint-dir hygiene gauge (ISSUE 7 satellite): live .npz
    # count under the operator's checkpoint dir; the maintenance GC
    # (age + count caps) is what keeps this bounded
    snap["checkpoint_files"] = len(_checkpoint_files())
    # cluster-watch delta API counters (docs/WATCH.md): pre-declared at
    # zero so dashboards see the families before the first event; the
    # live registry overlays its actual counts
    watch_zeroes = {
        "events_total": 0, "fenced_total": 0, "coalesced_total": 0,
        "superseded_total": 0, "storm_sheds_total": 0,
        "solves_total": 0, "warm_solves_total": 0,
        "solve_errors_total": 0, "clusters": 0,
    }
    reg = WATCH.get("registry")
    if reg is not None:
        watch_zeroes.update({
            k: v for k, v in reg.snapshot().items()
            if isinstance(v, (int, float)) and k in watch_zeroes
        })
    for k, v in watch_zeroes.items():
        snap[f"watch_{k}"] = v
    # streaming plan rollout counters (docs/ROLLOUT.md): the full
    # family set is pre-declared at zero; the live manager (built on
    # first rollout touch — never by a scrape) overlays its counts
    rollout_zeroes = {k: 0 for k in _ROLLOUT_COUNTER_NAMES}
    rmgr = ROLLOUT.get("manager")
    if rmgr is not None:
        rollout_zeroes.update({
            k: v for k, v in rmgr.snapshot().items()
            if isinstance(v, (int, float)) and k in rollout_zeroes
        })
    for k, v in rollout_zeroes.items():
        snap[f"rollout_{k}"] = v
    # resilience gauges (docs/RESILIENCE.md): circuit-breaker state and
    # whether a chaos spec is armed (a production scrape showing
    # kao_chaos_armed 1 is itself an alert)
    brk = _BREAKER.snapshot()
    snap["breaker_open_keys"] = brk["open"]
    snap["breaker_tracked_keys"] = brk["tracked"]
    snap["breaker_trips_total"] = brk["trips_total"]
    snap["chaos_armed"] = _chaos.snapshot()["armed"]
    # roofline-observatory scalars (obs.prof): cost-model capture and
    # pairing health, ledger-overrun tripwire, and the profiler's own
    # self-accounted overhead (the <2% invariant's numerator)
    psnap = _oprof.snapshot()
    for k, v in psnap["counters"].items():
        snap[f"prof_{k}"] = v
    snap["prof_executables"] = len(psnap["executables"])
    snap["prof_overhead_seconds_total"] = psnap["overhead"][
        "seconds_total"]
    lines = []
    for k, v in snap.items():
        name = f"kao_{k}"
        kind = "counter" if k.endswith("_total") else "gauge"
        lines.append(f"# HELP {name} {k.replace('_', ' ')} ({kind})")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {v}")
    # batched-dispatch size histogram: one labeled sample per observed
    # size — the operational proof coalescing is (or is not) engaging
    lines.append("# HELP kao_batch_size_total coalesced dispatch sizes")
    lines.append("# TYPE kao_batch_size_total counter")
    for size in sorted(sizes):
        lines.append(
            f'kao_batch_size_total{{size="{size}"}} {sizes[size]}'
        )
    # portfolio winner-lane histogram (docs/PORTFOLIO.md): which lane
    # configs actually win solves — a lane that never wins is a slot
    # the diversity table should respend
    lines.append("# HELP kao_portfolio_winner_total portfolio solves "
                 "won, by winning lane index")
    lines.append("# TYPE kao_portfolio_winner_total counter")
    for lane in sorted(port_winners):
        lines.append(
            f'kao_portfolio_winner_total{{lane="{lane}"}} '
            f"{port_winners[lane]}"
        )
    # decomposed map-reduce solves (docs/DECOMPOSE.md): the full
    # counter set is pre-declared at zero (the rollout-counter
    # discipline), plus the last solve's certificate-or-gap outcome
    from .decompose import STATS as _dstats

    dsnap = _dstats.snapshot()
    lines.append("# HELP kao_decompose_total decomposed map-reduce "
                 "solve events, by kind (docs/DECOMPOSE.md)")
    lines.append("# TYPE kao_decompose_total counter")
    for k in sorted(dsnap["counters"]):
        lines.append(
            f'kao_decompose_total{{kind="{k}"}} '
            f'{dsnap["counters"][k]}'
        )
    lines.append("# HELP kao_decompose_last_bound_gap bound gap of "
                 "the last decomposed solve (0 when certified)")
    lines.append("# TYPE kao_decompose_last_bound_gap gauge")
    lines.append(
        f"kao_decompose_last_bound_gap "
        f'{int(dsnap["last"].get("bound_gap") or 0)}'
    )
    lines.append("# HELP kao_decompose_last_subproblems sub-problem "
                 "count of the last decomposed solve")
    lines.append("# TYPE kao_decompose_last_subproblems gauge")
    lines.append(
        f"kao_decompose_last_subproblems "
        f'{int(dsnap["last"].get("subproblems") or 0)}'
    )
    # sharded solve mesh (docs/MESH.md): axis sizes of the last built
    # mesh, the counter families pre-declared at zero (the rollout
    # discipline), and one row per bucket the sharding chooser has
    # evidence for — the choice a new dispatch of that bucket gets
    from .parallel.mesh import mesh_snapshot as _mesh_snapshot

    msnap = _mesh_snapshot()
    lines.append("# HELP kao_mesh_axis_size solve-mesh axis sizes "
                 "(chains x lanes device split, docs/MESH.md)")
    lines.append("# TYPE kao_mesh_axis_size gauge")
    for ax in sorted(msnap["axes"]):
        lines.append(
            f'kao_mesh_axis_size{{axis="{ax}"}} {msnap["axes"][ax]}'
        )
    lines.append("# HELP kao_mesh_sharding_search_evals_total sharding "
                 "candidates timed by run_sharding_search")
    lines.append("# TYPE kao_mesh_sharding_search_evals_total counter")
    lines.append(
        "kao_mesh_sharding_search_evals_total "
        f'{msnap["counters"]["search_evals"]}'
    )
    lines.append("# HELP kao_mesh_reshard_bytes_total carried-state "
                 "bytes that arrived at a dispatch under the wrong "
                 "sharding (resharding transfer)")
    lines.append("# TYPE kao_mesh_reshard_bytes_total counter")
    lines.append(
        "kao_mesh_reshard_bytes_total "
        f'{msnap["counters"]["reshard_bytes"]}'
    )
    lines.append("# HELP kao_mesh_bucket_sharding per-bucket chosen "
                 "(chains x lanes) split; value is evidence solve "
                 "count behind the choice")
    lines.append("# TYPE kao_mesh_bucket_sharding gauge")
    for bkt in sorted(msnap["buckets"]):
        row = msnap["buckets"][bkt]
        ev = row["evidence"].get(row["chosen"], {})
        lines.append(
            f'kao_mesh_bucket_sharding{{bucket="{bkt}",'
            f'spec="{row["chosen"]}"}} {int(ev.get("solves", 0))}'
        )
    # load sheds by reason: every 503 names why it shed, and the full
    # reason set is pre-declared at zero so dashboards can alert on
    # rate() without waiting for the first shed
    lines.append("# HELP kao_shed_total load sheds (503) by reason")
    lines.append("# TYPE kao_shed_total counter")
    for reason in sorted(sheds):
        lines.append(
            f'kao_shed_total{{reason="{reason}"}} {sheds[reason]}'
        )
    # graceful-degradation ladder rungs (resilience.ladder): the full
    # rung catalog is pre-declared at zero; any nonzero rate here means
    # the service is trading quality/latency for availability
    lines.append(
        "# HELP kao_degradations_total graceful-degradation ladder "
        "rungs taken (docs/RESILIENCE.md)"
    )
    lines.append("# TYPE kao_degradations_total counter")
    for rung, n in _ladder.snapshot().items():
        lines.append(f'kao_degradations_total{{rung="{rung}"}} {n}')
    # device memory in use, one gauge per device the sampler saw (CPU
    # backends report no memory stats, so the family renders empty
    # there — the HELP/TYPE pair still pre-declares it)
    lines.append("# HELP kao_device_hbm_bytes device memory in use by "
                 "device (obs.sampler; --sample-devices)")
    lines.append("# TYPE kao_device_hbm_bytes gauge")
    for dev in sorted(samp["devices"]):
        lines.append(
            f'kao_device_hbm_bytes{{device="{dev}"}} '
            f'{samp["devices"][dev]["bytes_in_use"]}'
        )
    # drift alarms (obs.drift, docs/OBSERVABILITY.md): the mid-run
    # "this got slower" tripwire, per record class and signal — the
    # family renderer is shared with kao-fleet so the two views
    # cannot drift apart
    lines.extend(_odrift.render_families(_odrift.MONITOR.metric_rows()))
    # per-phase solve latency histograms, aggregated from solve traces
    # (obs.trace): which pipeline phase the wall-clock goes to, across
    # every traced solve this process has served
    _render_histogram(
        lines, "kao_phase_seconds", "phase", _otrace.phase_snapshot(),
        "solve pipeline phase latency (from solve traces)",
    )
    # end-to-end solve latency histograms per record class (obs.flight):
    # the SLO denominators — kao_phase_seconds says which PHASE ate a
    # budget, kao_solve_seconds says which CLASS of traffic is slow
    _render_histogram(
        lines, "kao_solve_seconds", "class", _oflight.solve_snapshot(),
        "end-to-end solve latency by record class (from flight "
        "records)",
    )
    # exemplar linkage (docs/OBSERVABILITY.md): the worst recent
    # observation per histogram bucket, its trace ID as a label — a
    # spike on a bucket links DIRECTLY to GET /debug/solves/<id>
    # (and ?format=chrome for the Perfetto flame chart). Rendered as
    # sidecar gauge families: the classic text exposition has no
    # native exemplar syntax, and a labeled gauge survives every
    # Prometheus scraper while carrying the same linkage.
    _render_exemplars(lines, "kao_solve_seconds_exemplar", "class",
                      _oflight.solve_exemplars())
    _render_exemplars(lines, "kao_phase_seconds_exemplar", "phase",
                      _otrace.phase_exemplars())
    # SLO engine (obs.slo): cumulative per-class counters + per-window
    # burn-rate gauges. Families are emitted only when classes exist —
    # the engine pre-declares the default classes, so they always do.
    slo = _oslo.ENGINE.snapshot()
    classes = slo.get("classes") or {}
    if classes:
        # table-driven per-class families (same factoring discipline
        # as _render_histogram): one loop, one place to add the next
        slo_families = (
            ("kao_slo_events_total", "counter",
             "flight records observed per SLO class",
             lambda c: c["events_total"]),
            ("kao_slo_latency_breaches_total", "counter",
             "observations over the class latency objective",
             lambda c: c["latency_breaches_total"]),
            ("kao_slo_quality_breaches_total", "counter",
             "infeasible/degraded plans per SLO class",
             lambda c: c["quality_breaches_total"]),
            ("kao_slo_latency_objective_seconds", "gauge",
             "configured per-class latency objective",
             lambda c: c["objective"]["latency_s"]),
            ("kao_slo_target", "gauge",
             "configured per-class success target",
             lambda c: c["objective"]["target"]),
        )
        for name, kind, help_text, get in slo_families:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for cls in sorted(classes):
                lines.append(
                    f'{name}{{class="{cls}"}} {get(classes[cls])}'
                )
        lines.append("# HELP kao_slo_burn_rate error-budget burn rate "
                     "per class and window (>1 burns the budget)")
        lines.append("# TYPE kao_slo_burn_rate gauge")
        for cls in sorted(classes):
            for win, w in sorted(classes[cls]["windows"].items()):
                lines.append(
                    f'kao_slo_burn_rate{{class="{cls}",'
                    f'window="{win}"}} {w["burn_rate"]}'
                )
    # causal-tracing families (docs/OBSERVABILITY.md "Distributed
    # traces"): tail-retention decisions + traceparent codec traffic,
    # rendered through the SAME shared helpers the kao-router uses so
    # the two surfaces cannot drift (obs.trace.trace_families)
    for fam in _otrace.trace_families():
        lines.extend(_expo.family_lines(*fam))
    # roofline observatory (obs.prof, docs/OBSERVABILITY.md "Reading a
    # roofline"): per-executable achieved/peak occupancy + measured
    # device seconds, keyed by the exec-cache identity hash — the
    # /debug/profile table's scrapeable projection
    lines.append("# HELP kao_prof_occupancy achieved/peak occupancy "
                 "per executable and dimension (obs.prof; ratios, "
                 "peak from KAO_PROF_PEAK_*)")
    lines.append("# TYPE kao_prof_occupancy gauge")
    for row in psnap["executables"]:
        for dim, f in (("flops", "occupancy_flops"),
                       ("hbm", "occupancy_hbm")):
            if row.get(f) is not None:
                lines.append(
                    f'kao_prof_occupancy{{key="{row["key_id"]}",'
                    f'path="{row["path"]}",dim="{dim}"}} {row[f]}'
                )
    lines.append("# HELP kao_prof_device_seconds_total measured "
                 "device seconds per executable (obs.prof)")
    lines.append("# TYPE kao_prof_device_seconds_total counter")
    for row in psnap["executables"]:
        lines.append(
            f'kao_prof_device_seconds_total{{key="{row["key_id"]}",'
            f'path="{row["path"]}"}} {row["device_s"]}'
        )
    # dispatch-gap histogram: host time between consecutive ladder
    # dispatches, derived from solve-report span timestamps; the
    # exemplar sidecar links the p99 gap to its trace
    _render_histogram(
        lines, "kao_prof_dispatch_gap_seconds", "path",
        _oprof.gap_snapshot(),
        "host gap between consecutive ladder dispatches (obs.prof)",
    )
    _render_exemplars(lines, "kao_prof_dispatch_gap_seconds_exemplar",
                      "path", _oprof.gap_exemplars())
    # build identity (satellite, ISSUE 9): which code/runtime produced
    # every number above — the first thing to check when two scrapes
    # disagree
    bi = _build_info()
    lines.append("# HELP kao_build_info build/runtime identity "
                 "(value is always 1; the labels carry the info)")
    lines.append("# TYPE kao_build_info gauge")
    lines.append(
        'kao_build_info{'
        f'version="{bi["version"]}",jax="{bi["jax"]}",'
        f'backend="{bi["backend"]}",devices="{bi["devices"]}"'
        "} 1"
    )
    return "\n".join(lines) + "\n"


class ApiError(Exception):
    """HTTP-status-carrying error. ``retry_after_s`` becomes the
    response's ``Retry-After`` header (503 sheds); ``body`` merges
    extra structured fields into the JSON error body."""

    def __init__(self, status: int, message: str, *,
                 retry_after_s: float | None = None,
                 body: dict | None = None):
        super().__init__(message)
        self.status = status
        self.retry_after_s = retry_after_s
        self.body_extra = body or {}


class _BatchGroup:
    """One pending same-bucket batch: entries + their waiters, the
    window timer, and the flush latch."""

    __slots__ = ("key", "entries", "waiters", "timer", "flushed",
                 "created", "wait_s", "budget_s")

    def __init__(self, key, wait_s, budget_s):
        self.key = key
        self.entries: list[dict] = []
        self.waiters: list[_QueueItem] = []
        self.timer = None
        self.flushed = False
        self.created = time.perf_counter()
        self.wait_s = wait_s
        self.budget_s = budget_s


class _Coalescer:
    """The request-coalescing dispatcher in front of the solve queue.

    Same-bucket TPU solves that arrive while the worker pool is busy
    are held for up to ``window_s`` (or until ``max_batch`` of them
    accumulate), then submitted as ONE batched lane solve
    (``api.optimize_batch`` -> ``engine.solve_tpu_batch``) whose
    per-lane results are demultiplexed back to the waiting requests.
    The group key is the exact executable identity — (brokers, racks,
    partition-bucket, rf-bucket) plus the shared non-seed solver knobs —
    so every batch is stackable by construction.

    Latency contract: a request that finds FREE capacity (idle worker,
    empty queue, no pending same-key group) bypasses the window and
    runs the full single-solve path — sparse traffic pays nothing for
    the batching machinery. Coalescing only engages where the request
    would have queued anyway, turning queue wait into batch width."""

    def __init__(self, window_s: float = DEFAULT_BATCH_WINDOW_MS / 1e3,
                 max_batch: int = DEFAULT_MAX_BATCH):
        self._lock = threading.Lock()
        self._groups: dict[tuple, _BatchGroup] = {}
        self.window_s = window_s
        self.max_batch = max_batch

    def configure(self, window_ms: float | None = None,
                  max_batch: int | None = None) -> None:
        with self._lock:
            if window_ms is not None:
                self.window_s = max(float(window_ms), 0.0) / 1e3
            if max_batch is not None:
                self.max_batch = max(int(max_batch), 1)

    def enabled(self) -> bool:
        return self.max_batch > 1

    def should_bypass(self, key) -> bool:
        """True when this request should skip coalescing entirely: no
        same-key group is already pending AND the pool has free
        capacity (the solve would start immediately, so holding it for
        the window could only add latency)."""
        with self._lock:
            if key in self._groups:
                return False
        q = _SOLVES.stats()
        idle = (q["active_solves"] < q["workers"]
                and q["queue_depth"] == 0)
        if idle:
            _count(batch_bypass_total=1)
        return idle

    def submit(self, key, entry: dict, wait_s: float,
               budget_s: float | None) -> dict:
        """Join (or open) the pending group for ``key`` and wait for
        the batched solve to deliver this request's result."""
        waiter = _QueueItem(None)
        flush_me = None
        with self._lock:
            grp = self._groups.get(key)
            if grp is None:
                grp = _BatchGroup(key, wait_s, budget_s)
                self._groups[key] = grp
                t = threading.Timer(self.window_s, self._flush,
                                    args=(grp,))
                t.daemon = True
                grp.timer = t
                t.start()
            grp.entries.append(entry)
            grp.waiters.append(waiter)
            if budget_s is not None:
                # the batch runs under the TIGHTEST member budget
                grp.budget_s = (
                    budget_s if grp.budget_s is None
                    else min(grp.budget_s, budget_s)
                )
            if len(grp.entries) >= self.max_batch:
                flush_me = grp
        if flush_me is not None:
            self._flush(flush_me)
        window = (
            None if budget_s is None
            else float(wait_s) + float(budget_s) + 60.0 + self.window_s
        )
        if not waiter.done.wait(window):
            waiter.abandoned = True
            raise _shed(
                "coalesce_window",
                f"batched solve did not finish within the {window:.0f}s "
                "service window; retry later",
                retry_after_s=_SOLVES._retry_after_hint(),
            )
        if waiter.exc is not None:
            raise waiter.exc
        return waiter.result

    def _flush(self, grp: _BatchGroup) -> None:
        """Close the group (idempotent: the window timer and the
        max-batch filler may race here), run its batched solve through
        the bounded queue, and demux per-lane results to the waiters."""
        with self._lock:
            if grp.flushed:
                return
            grp.flushed = True
            if self._groups.get(grp.key) is grp:
                del self._groups[grp.key]
            entries = list(grp.entries)
            waiters = list(grp.waiters)
        if grp.timer is not None:
            grp.timer.cancel()
        waited = time.perf_counter() - grp.created

        def job():
            return _run_batch_job(entries)

        # the group key is (*bucket_key, non_seed_options): the breaker
        # verdict for this dispatch lands on the bucket identity, ONCE
        # — handle_submit already did the admission check per request
        bucket_key = grp.key[:-1]
        try:
            outs = _SOLVES.submit(job, wait_s=grp.wait_s,
                                  budget_s=grp.budget_s)
        except BaseException as e:
            if isinstance(e, (ApiError, ValueError, KeyError,
                              TypeError)):
                _BREAKER.release_probe(bucket_key)  # shed: no verdict
            else:
                _BREAKER.record_failure(bucket_key)
            for w in waiters:
                w.exc = e
                w.done.set()
            return
        # per-entry results: a dict to deliver, or the ApiError shed
        # for a member whose deadline expired while the batch queued.
        # The breaker verdict needs a solve to have RUN: if every
        # member was shed pre-solve there is no evidence either way,
        # so a pending half-open probe is released, not judged
        solved = [o for o in outs if not isinstance(o, BaseException)]
        if solved:
            _BREAKER.record_success(bucket_key)
            _record_batch(len(solved), waited,
                          [o["report"] for o in solved])
        else:
            _BREAKER.release_probe(bucket_key)
        for w, out in zip(waiters, outs):
            if isinstance(out, BaseException):
                w.exc = out
            else:
                w.result = out
            w.done.set()


def _run_batch_job(entries: list[dict]) -> list:
    """Worker-pool body of one coalesced dispatch: one batched lane
    solve, per-request response dicts out (same shape as /submit's
    single-solve response) — or, per entry, the ApiError to deliver
    instead. The batch runs under ONE trace with its OWN fresh ID, and
    every member keeps ITS OWN request trace ID (ISSUE 15 satellite —
    the PR 3 shared-first-member-ID scheme aliased every coalesced
    client, and a router-propagated trace, onto one trace): each
    member's envelope echoes its own ``trace_id`` plus
    ``coalesced_into`` (the batch ID), and a per-member stub report
    carrying the same link lands in the ring, so
    ``GET /debug/solves/<id>`` resolves for every member and the
    router join never collides two clients.

    Deadline contract (docs/RESILIENCE.md): each entry carries its
    request Budget. The queue wait between _flush and here is bounded
    by the worker pool, not by any member's deadline — so members
    whose deadline expired while the batch was queued are shed NOW
    with the same 503 "deadline" the single-solve path returns, and
    the solve runs on the TIGHTEST remaining member window instead of
    the full one."""
    from .api import optimize_batch

    t0 = time.perf_counter()
    results: list = [None] * len(entries)
    live: list[int] = []
    for i, e in enumerate(entries):
        rem = e["budget"].remaining() if e.get("budget") else None
        if rem is not None and rem <= 0.0:
            results[i] = _shed(
                "deadline",
                "request deadline exhausted while the batched solve "
                "was queued; retry with a larger deadline_s",
                retry_after_s=1.0,
            )
        else:
            live.append(i)
    if not live:
        return results
    entries = [entries[i] for i in live]
    member_tids = [e.get("trace_id") for e in entries]
    # the batch trace gets a FRESH ID (never a member's): member IDs
    # stay unique per client and link here via coalesced_into
    trace_id = _otrace.new_trace_id() if any(member_tids) else None
    opts = dict(entries[0]["options"])
    budgets = [e["options"].get("time_limit_s") for e in entries
               if e["options"].get("time_limit_s") is not None]
    budgets += [
        e["budget"].remaining() for e in entries
        if e.get("budget") and e["budget"].remaining() is not None
    ]
    if budgets:
        opts["time_limit_s"] = min(budgets)
    tr = _otrace.begin(trace_id, name="request_batch",
                       lanes=len(entries))
    if tr is not None:
        tr.root.set(coalesced_members=",".join(
            t for t in member_tids if t))
    try:
        outs = optimize_batch(
            [e["current"] for e in entries],
            [e["instance"] for e in entries],
            seeds=[e["seed"] for e in entries],
            **{k: v for k, v in opts.items() if k != "seed"},
        )
    except BaseException as e:
        if tr is not None:
            tr.root.set(error=repr(e)[:200])
            _otrace.finish(tr)
        _olog.error("batch_solve_failed", trace_id=trace_id,
                    lanes=len(entries), error=repr(e)[:200])
        raise
    dt = time.perf_counter() - t0
    with _METRICS_LOCK:
        _METRICS["solves_total"] += len(outs)
        _METRICS["solve_seconds_total"] += dt
        _METRICS["last_solve_seconds"] = dt
    reps = [o.report() for o in outs]
    batch_rep = None
    if tr is not None:
        tr.root.set(wall_s=round(dt, 4),
                    lanes_feasible=sum(
                        1 for r in reps if r.get("feasible")))
        batch_rep = _otrace.finish(tr)
    _olog.log("solve_batch", trace_id=trace_id, lanes=len(outs),
              wall_s=round(dt, 4))
    for j, (o, rep) in enumerate(zip(outs, reps)):
        member_tid = member_tids[j]
        # member stubs follow the BATCH's tail-retention decision: a
        # dropped batch registers no stubs (a dangling coalesced_into
        # would 404, and untail-sampled stubs would flood the ring the
        # policy exists to bound)
        if member_tid and batch_rep is not None \
                and batch_rep.get("retention") != "dropped":
            _register_member_trace(member_tid, batch_rep,
                                   entries[j].get("remote_parent"),
                                   lane=j)
        results[live[j]] = {
            "assignment": o.assignment.to_dict(),
            "report": rep,
            **({"trace_id": member_tid,
                "coalesced_into": trace_id} if member_tid else {}),
        }
    return results


def _register_member_trace(member_tid: str, batch_rep: dict,
                           remote_parent: str | None,
                           lane: int) -> None:
    """One coalesced member's OWN ring entry: a stub report under the
    member's trace ID whose root span links to the shared batch report
    (``coalesced_into``) and — when the request carried a propagated
    traceparent — records its remote parent span, so the router-side
    merge still attaches this member to the exact attempt that sent
    it. Registered directly (not via a Trace): the real span tree
    lives in the batch report one hop away."""
    attrs: dict = {"coalesced_into": batch_rep["trace_id"],
                   "lane": lane}
    if remote_parent:
        attrs["parent_span_id"] = str(remote_parent)
        attrs["span_kind"] = "server"
    _otrace.RECENT.put({
        "trace_id": member_tid,
        "name": "request",
        "started_unix": batch_rep.get("started_unix"),
        "wall_s": batch_rep.get("wall_s"),
        "coalesced_into": batch_rep["trace_id"],
        "phases": batch_rep.get("phases") or {},
        "spans": {
            "name": "request",
            "start_s": 0.0,
            "wall_s": batch_rep.get("wall_s"),
            "attrs": attrs,
        },
    })


_COALESCER = _Coalescer()


def _parse_brokers(spec) -> list[int]:
    if isinstance(spec, str):
        try:
            return parse_broker_list(spec)
        except ValueError as e:
            raise ApiError(400, f"bad 'brokers' range string: {e}") from e
    if isinstance(spec, list) and all(
        isinstance(b, int) and not isinstance(b, bool) for b in spec
    ):
        return spec
    raise ApiError(400, "'brokers' must be a list of ints or a range string")


def _parse_topology(spec, broker_ids: list[int]) -> Topology | None:
    # every malformed shape must come back as a structured 400, never a
    # raw exception bubbling into a 500 (e.g. a rack map with non-string
    # keys or nested values used to die inside Topology.from_dict)
    if spec is None:
        return None
    try:
        if spec == "even-odd":
            return Topology.even_odd(broker_ids)
        if isinstance(spec, dict):
            return Topology.from_dict(spec)
    except ApiError:
        raise
    except Exception as e:
        raise ApiError(400, f"bad 'topology': {e}") from e
    raise ApiError(400, "'topology' must be a broker->rack object, 'even-odd', or null")


def _validate_rf(rf) -> None:
    if rf is None:
        return
    if isinstance(rf, bool) or not isinstance(rf, (int, dict)):
        raise ApiError(400, "'rf' must be an int, a topic->int object, or null")
    if isinstance(rf, dict) and not all(
        isinstance(k, str)
        and isinstance(v, int) and not isinstance(v, bool)
        for k, v in rf.items()
    ):
        raise ApiError(400, "'rf' object must map topic names to ints")


def handle_submit(
    payload: dict,
    *,
    lock_wait_s: float = DEFAULT_LOCK_WAIT_S,
    max_solve_s: float | None = DEFAULT_MAX_SOLVE_S,
    trace_ctx=None,
) -> dict:
    """Pure request handler (also the unit-test surface): payload dict in,
    response dict out; raises ApiError with an HTTP status on bad input,
    and 503 when the solver is saturated past ``lock_wait_s``.

    ``trace_ctx`` (an ``obs.trace.RemoteContext`` from a validated
    ``traceparent`` header) makes the solve ADOPT the propagated trace
    ID and record the remote parent span, so a router-edge trace and
    this worker's solve phases share one retrievable tree."""
    if not isinstance(payload, dict):
        raise ApiError(400, "request body must be a JSON object")
    if "assignment" not in payload:
        raise ApiError(400, "missing required field 'assignment'")
    if "brokers" not in payload:
        raise ApiError(400, "missing required field 'brokers'")
    try:
        current = Assignment.from_dict(payload["assignment"])
    except (KeyError, TypeError, ValueError) as e:
        raise ApiError(400, f"bad 'assignment': {e}") from e
    brokers = _parse_brokers(payload["brokers"])
    all_ids = sorted(set(brokers) | set(current.broker_ids()))
    topology = _parse_topology(payload.get("topology"), all_ids)
    rf = payload.get("rf")
    _validate_rf(rf)
    solver = payload.get("solver", "auto")
    if not isinstance(solver, str):
        raise ApiError(400, "'solver' must be a string")
    from .solvers.base import available_solvers

    if solver != "auto" and solver not in available_solvers():
        raise ApiError(
            400,
            f"unknown solver {solver!r}; available: "
            f"{['auto', *available_solvers()]}",
        )
    options = payload.get("options") or {}
    if not isinstance(options, dict):
        raise ApiError(400, "'options' must be an object")
    rejected = sorted(set(options) - ALLOWED_OPTIONS)
    if rejected:
        raise ApiError(
            400,
            f"unsupported option(s) {rejected}; allowed: "
            f"{sorted(ALLOWED_OPTIONS)}",
        )
    options = dict(options)  # never mutate the caller's payload
    limit = options.get("time_limit_s")
    if limit is not None and (
        isinstance(limit, bool) or not isinstance(limit, (int, float))
        or not limit > 0
    ):
        raise ApiError(400, "'time_limit_s' must be a positive number")
    if "pipeline" in options and not isinstance(
        options["pipeline"], bool
    ):
        raise ApiError(400, "'pipeline' must be a boolean")
    # portfolio lanes (docs/PORTFOLIO.md): bool only — the width is an
    # operator knob (KAO_PORTFOLIO_WIDTH), never a per-request one (a
    # client naming an arbitrary width could multiply device work)
    if "portfolio" in options and not isinstance(
        options["portfolio"], bool
    ):
        raise ApiError(400, "'portfolio' must be a boolean")
    # decomposed map-reduce solves (docs/DECOMPOSE.md): bool only —
    # group structure comes from the cluster's rack names, never the
    # client
    if "decompose" in options and not isinstance(
        options["decompose"], bool
    ):
        raise ApiError(400, "'decompose' must be a boolean")
    # fused ladder megachunks (docs/PIPELINE.md): bool only — the fused
    # width is an operator knob (KAO_MEGACHUNK / --megachunk), never a
    # per-request one (a client naming an arbitrary width could force
    # fresh compiles per request). true opts the solve into the
    # evidence-driven chooser, false pins the per-chunk ladder.
    if "megachunk" in options and not isinstance(
        options["megachunk"], bool
    ):
        raise ApiError(400, "'megachunk' must be a boolean")
    if max_solve_s is not None:
        # cap every solve: client may tighten the limit but not exceed it
        options["time_limit_s"] = (
            max_solve_s if limit is None else min(float(limit), max_solve_s)
        )
    # per-request end-to-end deadline (docs/RESILIENCE.md): the request
    # field wins, --default-deadline-s covers requests that carry none.
    # One Budget object threads the REMAINING time through queue wait
    # and solve — the solve gets what is left after validation and
    # queueing, never the full window again.
    deadline_s = payload.get("deadline_s", RESILIENCE["default_deadline_s"])
    if deadline_s is not None and (
        isinstance(deadline_s, bool)
        or not isinstance(deadline_s, (int, float)) or not deadline_s > 0
    ):
        raise ApiError(400, "'deadline_s' must be a positive number")
    budget = _rbudget.Budget(deadline_s)
    if deadline_s is not None:
        lim = options.get("time_limit_s")
        options["time_limit_s"] = (
            float(deadline_s) if lim is None
            else min(float(lim), float(deadline_s))
        )
    lock_wait_s = budget.cap(lock_wait_s)

    # request-scoped trace ID: adopted from a propagated traceparent
    # context when one arrived (the router join), generated fresh
    # otherwise; threaded into the solve (ambient obs.trace), echoed
    # in the response envelope, stamped into the flight record, and
    # retrievable via GET /debug/solves/<trace_id>
    trace_id, remote_parent = None, None
    if OBS["trace"]:
        if trace_ctx is not None:
            trace_id, remote_parent = trace_ctx.trace_id, \
                trace_ctx.span_id
        else:
            trace_id = _otrace.new_trace_id()
    try:
        # coalescing path: explicit TPU solves whose knobs the batched
        # lane solver understands may ride a shared dispatch. The
        # instance is built NOW (host-side numpy, milliseconds) so the
        # group key is the EXACT executable identity; the single-solve
        # path below reuses it either way.
        inst = None
        bucket_key = None
        # every per-bucket gate below (coalescing eligibility, circuit
        # breaker, checkpoint auto-resume, profiling budget) keys on
        # the solver that will ACTUALLY run: "auto" resolves
        # deterministically from the instance size, and at production
        # scale that is the TPU engine — a defaulted request must get
        # the same per-cluster isolation and resume behavior as an
        # explicit "solver": "tpu", not one shared ("solver", "auto")
        # circuit that a single pathological cluster could open for
        # the whole fleet
        solver_eff = solver
        if solver == "auto":
            from .models.instance import build_instance
            from .solvers.base import resolve_solver

            inst = build_instance(current, brokers, topology, rf)
            solver_eff = resolve_solver("auto", inst)
        if (
            solver_eff == "tpu"
            and _COALESCER.enabled()
            # a request carrying an EXPLICIT deadline takes the
            # single-solve path (its owner asked for precise deadline
            # semantics; _solve_job threads the remaining budget and
            # sheds pre-dispatch). Defaulted requests ride the lane —
            # the operator's --default-deadline-s must NOT disable
            # coalescing fleet-wide — and carry their Budget into the
            # batch: _run_batch_job sheds members whose deadline
            # expired while the batch was queued and runs the solve on
            # the TIGHTEST remaining member window
            and payload.get("deadline_s") is None
            and set(options) <= _BATCHABLE_OPTIONS
        ):
            from .models.instance import build_instance
            from .solvers.tpu import bucket

            if inst is None:
                inst = build_instance(current, brokers, topology, rf)
            non_seed = tuple(sorted(
                (k, v) for k, v in options.items() if k != "seed"
            ))
            bucket_key = (inst.num_brokers, inst.num_racks,
                          *bucket.bucket_shape(inst))
            key = (*bucket_key, non_seed)
            if not _COALESCER.should_bypass(key):
                # breaker admission only: the failure/success verdict
                # is recorded ONCE per batched dispatch in
                # _Coalescer._flush — per-waiter recording would turn
                # one failed batch into >= threshold trips
                admitted, retry_after = _BREAKER.allow(bucket_key)
                if not admitted:
                    raise _shed(
                        "circuit_open",
                        "circuit open for this cluster bucket after "
                        "repeated solver failures; retry later",
                        retry_after_s=retry_after,
                        bucket=list(bucket_key),
                    )
                entry = {
                    "current": current,
                    "instance": inst,
                    "seed": options.get("seed", 0),
                    "trace_id": trace_id,
                    "remote_parent": remote_parent,
                    "budget": budget,
                    "options": {k: v for k, v in options.items()
                                if k != "seed"},
                }
                return _COALESCER.submit(
                    key, entry, wait_s=lock_wait_s,
                    budget_s=options.get("time_limit_s"),
                )

        # the bucket/instance identity is needed even when the request
        # was not coalescing-eligible (non-batchable knobs, --max-batch
        # 1, an explicit deadline): the circuit breaker isolates
        # failures PER BUCKET — one pathological cluster must not open
        # the circuit for all TPU traffic — each bucket draws on ITS
        # OWN --profile-solves budget, and each cluster resumes its OWN
        # checkpoint. Build it now (host-side numpy, milliseconds); the
        # solve reuses the instance either way
        if solver_eff == "tpu" and bucket_key is None:
            from .models.instance import build_instance
            from .solvers.tpu import bucket

            if inst is None:
                inst = build_instance(current, brokers, topology, rf)
            bucket_key = (inst.num_brokers, inst.num_racks,
                          *bucket.bucket_shape(inst))

        def _solve_job():
            t0 = time.perf_counter()
            kw = dict(options)
            left = budget.remaining()
            if left is not None:
                if left <= 0.0:
                    # the queue wait consumed the whole request
                    # deadline: shed instead of starting a solve whose
                    # result nobody is waiting for
                    raise _shed(
                        "deadline",
                        "request deadline exhausted before the solve "
                        "started; retry with a larger deadline_s",
                        retry_after_s=1.0,
                        deadline_s=float(deadline_s),
                    )
                # remaining-time threading: the solve runs on what is
                # LEFT of the request deadline, not the full window
                kw["time_limit_s"] = (
                    left if kw.get("time_limit_s") is None
                    else min(float(kw["time_limit_s"]), left)
                )
            if solver_eff == "tpu" and inst is not None \
                    and RESILIENCE["checkpoint_dir"]:
                # crash-safe auto-resume: the checkpoint path is keyed
                # by instance fingerprint under the OPERATOR-chosen
                # directory (clients still cannot name paths); a
                # worker-crash retry or a repeated solve of the same
                # cluster warm-starts from the last completed plan
                import os

                from .utils.checkpoint import instance_fingerprint

                kw["checkpoint"] = os.path.join(
                    RESILIENCE["checkpoint_dir"],
                    instance_fingerprint(inst)[:32] + ".npz",
                )
            if solver_eff == "tpu" and bucket_key is not None:
                prof = _profile_dir_for(bucket_key, trace_id)
                if prof:
                    kw["profile_dir"] = prof
            tr = _otrace.begin(trace_id, name="request", solver=solver,
                               remote_parent=remote_parent)
            try:
                res = optimize(
                    current, brokers, topology, target_rf=rf,
                    solver=solver, instance=inst, **kw,
                )
            except BaseException as e:
                if tr is not None:
                    tr.root.set(error=repr(e)[:200])
                    _otrace.finish(tr)
                _olog.error("solve_failed", trace_id=trace_id,
                            solver=solver, error=repr(e)[:200])
                raise
            dt = time.perf_counter() - t0
            port = res.solve.stats.get("portfolio") or None
            with _METRICS_LOCK:
                _METRICS["solves_total"] += 1
                _METRICS["solve_seconds_total"] += dt
                _METRICS["last_solve_seconds"] = dt
                if port:
                    _METRICS["portfolio_solves_total"] += 1
                    if port.get("early_exit"):
                        _METRICS["portfolio_early_exit_total"] += 1
                    wl = port.get("winner_lane")
                    if wl is not None:
                        _PORTFOLIO_WINNERS[int(wl)] = (
                            _PORTFOLIO_WINNERS.get(int(wl), 0) + 1
                        )
            rep = res.report()
            if tr is not None:
                tr.root.set(solver=res.solve.solver,
                            feasible=rep.get("feasible"),
                            replica_moves=rep.get("replica_moves"),
                            wall_s=round(dt, 4))
                _otrace.finish(tr)
            _olog.log("solve", trace_id=trace_id, solver=res.solve.solver,
                      wall_s=round(dt, 4), feasible=rep.get("feasible"),
                      moves=rep.get("replica_moves"),
                      proved_optimal=rep.get("proven_optimal"))
            out = {
                "assignment": res.assignment.to_dict(),
                "report": rep,
            }
            if trace_id:
                out["trace_id"] = trace_id
            return out

        brk_key = (
            bucket_key if bucket_key is not None
            else ("solver", solver_eff)
        )
        return _breaker_guarded(
            brk_key,
            lambda: _SOLVES.submit(
                _solve_job, wait_s=lock_wait_s,
                budget_s=options.get("time_limit_s"),
            ),
        )
    except ApiError:
        raise
    except (ValueError, KeyError) as e:
        msg = e.args[0] if e.args and isinstance(e.args[0], str) else str(e)
        raise ApiError(422, f"model rejected inputs: {msg}") from e
    except TypeError as e:
        raise ApiError(400, f"bad solver options: {e}") from e
    except RuntimeError as e:
        raise ApiError(500, f"solver failed: {e}") from e


def handle_evaluate(payload: dict, lock_wait_s: float,
                    max_solve_s: float | None = DEFAULT_MAX_SOLVE_S) -> dict:
    """POST /evaluate — audit an existing plan (``api.evaluate``):
    feasibility, violation counts, moves vs the provable minimum, and
    an optimality verdict. Same input fields as /submit plus the
    required ``plan``. No solver runs; the bound computations (LP,
    max-flow) are host-only but cost seconds at scale, so audits
    serialize on their OWN lock (a device solve never blocks them —
    VERDICT r4 item 8), shed with 503 when saturated, and cap their
    bound LPs at the same ``--max-solve-s`` budget as solves (expired
    tiers degrade to cheaper bounds rather than hold the lock)."""
    if not isinstance(payload, dict):
        raise ApiError(400, "payload must be a JSON object")
    for field in ("assignment", "brokers", "plan"):
        if field not in payload:
            raise ApiError(400, f"missing required field '{field}'")
    try:
        current = Assignment.from_dict(payload["assignment"])
        plan = Assignment.from_dict(payload["plan"])
    except (KeyError, TypeError, ValueError) as e:
        raise ApiError(400, f"bad assignment/plan: {e}") from e
    brokers = _parse_brokers(payload["brokers"])
    all_ids = sorted(set(brokers) | set(current.broker_ids()))
    topology = _parse_topology(payload.get("topology"), all_ids)
    rf = payload.get("rf")
    _validate_rf(rf)
    from .api import evaluate

    if not _AUDIT_LOCK.acquire(timeout=lock_wait_s):
        raise _shed(
            "audit_busy",
            f"auditor busy (no capacity within {lock_wait_s:.0f}s); "
            "retry later",
            retry_after_s=min(max(lock_wait_s, 1.0), 30.0),
        )
    try:
        out = evaluate(current, brokers, plan, topology, target_rf=rf,
                       time_budget_s=max_solve_s)
    except (ValueError, KeyError) as e:
        msg = e.args[0] if e.args and isinstance(e.args[0], str) else str(e)
        raise ApiError(422, f"model rejected inputs: {msg}") from e
    finally:
        _AUDIT_LOCK.release()
    _count(evaluates_total=1)
    return out


def _watch_solve_fn(state, prev_plan, budget) -> tuple[dict, dict]:
    """The registry-injected delta solver (docs/WATCH.md): build the
    post-event instance, warm-start from the previous certified plan
    (``api.optimize_delta``), and run it through the SAME serving
    machinery a /submit solve uses — the bounded worker queue, the
    per-bucket circuit breaker, and the solve-trace ring. The caller's
    ``budget`` threads into the engine, so a superseding event
    cancelling it retires this solve at the next chunk boundary."""
    from .api import optimize_delta
    from .models.instance import build_instance
    from .solvers.base import resolve_solver

    inst = build_instance(state.assignment, state.brokers,
                          state.topology, state.rf)
    solver_eff = resolve_solver("auto", inst)
    bucket_key: tuple
    if solver_eff == "tpu":
        from .solvers.tpu import bucket

        bucket_key = (inst.num_brokers, inst.num_racks,
                      *bucket.bucket_shape(inst))
    else:
        bucket_key = ("solver", solver_eff)
    trace_id = _otrace.new_trace_id() if OBS["trace"] else None
    max_solve_s = WATCH["max_solve_s"]

    def job():
        t0 = time.perf_counter()
        kw: dict = {}
        if solver_eff == "tpu":
            kw["budget"] = budget
            if max_solve_s is not None:
                kw["time_limit_s"] = max_solve_s
            prof = _profile_dir_for(bucket_key, trace_id)
            if prof:
                kw["profile_dir"] = prof
        tr = _otrace.begin(trace_id, name="watch_event",
                           cluster=state.cluster_id, epoch=state.epoch)
        if tr is not None:
            # mid-rollout re-solve linkage (ISSUE 15, docs/ROLLOUT.md):
            # while a rollout owns this cluster's ground truth, the
            # delta re-solve trace links to the rollout's durable root
            # trace ID (persisted in the plan-store record), so the
            # whole wave story — start, re-solve, replan — joins under
            # one ID
            rmgr = ROLLOUT.get("manager")
            if rmgr is not None:
                root_tid = rmgr.active_trace_root(state.cluster_id)
                if root_tid:
                    tr.root.set(rollout_root=root_tid)
        try:
            # flight-record tagging on THIS worker thread: the watch
            # manager's own context() does not cross the queue hop, so
            # the delta identity is re-established where the engine
            # actually runs (obs.flight, docs/OBSERVABILITY.md)
            with _oflight.context("delta", cluster=state.cluster_id,
                                  epoch=state.epoch):
                res = optimize_delta(
                    state.assignment, state.brokers, state.topology,
                    target_rf=state.rf, prev_plan=prev_plan,
                    solver=solver_eff, instance=inst, **kw,
                )
        except BaseException as e:
            if tr is not None:
                tr.root.set(error=repr(e)[:200])
                _otrace.finish(tr)
            _olog.error("watch_solve_failed", trace_id=trace_id,
                        cluster=state.cluster_id, epoch=state.epoch,
                        error=repr(e)[:200])
            raise
        dt = time.perf_counter() - t0
        with _METRICS_LOCK:
            _METRICS["solves_total"] += 1
            _METRICS["solve_seconds_total"] += dt
            _METRICS["last_solve_seconds"] = dt
        rep = res.report()
        if tr is not None:
            tr.root.set(solver=res.solve.solver,
                        feasible=rep.get("feasible"),
                        replica_moves=rep.get("replica_moves"),
                        warm_started=bool(
                            rep.get("solver_warm_started")
                        ),
                        wall_s=round(dt, 4))
            _otrace.finish(tr)
        _olog.log("watch_solve", trace_id=trace_id,
                  cluster=state.cluster_id, epoch=state.epoch,
                  solver=res.solve.solver, wall_s=round(dt, 4),
                  feasible=rep.get("feasible"),
                  moves=rep.get("replica_moves"),
                  warm=bool(rep.get("solver_warm_started")))
        if trace_id:
            rep["trace_id"] = trace_id
        return res.assignment.to_dict(), rep

    return _breaker_guarded(
        bucket_key,
        lambda: _SOLVES.submit(job, wait_s=WATCH["lock_wait_s"],
                               budget_s=max_solve_s),
    )


_WATCH_CONFIG_LOCK = threading.Lock()  # kao: guards(WATCH)


def _watch_registry() -> _wmanager.WatchRegistry:
    """The process's one watch registry, built lazily from WATCH (so
    main() and tests configure before first touch).

    Double-checked under ``_WATCH_CONFIG_LOCK`` (KAO116): this is
    called from concurrent HTTP handler threads (events, rollouts,
    /debug/watch), and the unlocked check-then-act let two first-touch
    requests each build a registry — the loser's clusters simply
    vanished from the winner's view."""
    reg = WATCH.get("registry")
    if reg is not None:
        return reg
    with _WATCH_CONFIG_LOCK:
        reg = WATCH.get("registry")
        if reg is None:
            store = (
                _wstore.PlanStore(WATCH["dir"]) if WATCH["dir"]
                else None
            )
            reg = _wmanager.WatchRegistry(
                _watch_solve_fn, store,
                window_s=WATCH["window_s"],
                max_backlog=WATCH["max_backlog"],
                solve_budget_s=WATCH["max_solve_s"],
            )
            WATCH["registry"] = reg
    return reg


def handle_cluster_event(
    cluster_id: str,
    payload: dict,
    *,
    lock_wait_s: float = DEFAULT_LOCK_WAIT_S,
    max_solve_s: float | None = DEFAULT_MAX_SOLVE_S,
) -> tuple[int, dict]:
    """POST /clusters/<id>/events — one fenced, typed state diff
    (docs/WATCH.md). Returns ``(http_status, body)``: 200 with the new
    certified plan when this request ran the solve, 202 when the event
    was coalesced behind an in-flight solve. Raises ApiError for
    malformed events (400), stale/replayed epochs (409, provably
    without a solve), impossible states (422), and storm backpressure
    (503 ``event_storm`` with Retry-After from the coalescing window)."""
    with _WATCH_CONFIG_LOCK:
        WATCH["lock_wait_s"] = lock_wait_s
        WATCH["max_solve_s"] = max_solve_s
    reg = _watch_registry()
    try:
        out = reg.handle_event(cluster_id, payload)
    except _wmanager.FencedEpoch as e:
        # the fencing contract: structured 409, idempotent (nothing was
        # applied), and PROVABLY no solve — kao_watch_fenced_total moves,
        # kao_solves_total does not, and no trace is born
        raise ApiError(
            409,
            str(e),
            body={
                "reason": "stale_epoch",
                "cluster_id": e.cluster_id,
                "epoch": e.got,
                "current_epoch": e.current,
                "expected_min_epoch": e.current + 1,
                "plan_epoch": e.plan_epoch,
            },
        ) from e
    except _wmanager.StormShed as e:
        raise _shed(
            "event_storm",
            str(e),
            retry_after_s=e.retry_after_s,
            cluster_id=e.cluster_id,
            backlog=e.backlog,
        ) from e
    except _wevents.EventError as e:
        raise ApiError(400, str(e)) from e
    except ApiError:
        raise
    except (ValueError, KeyError) as e:
        msg = e.args[0] if e.args and isinstance(e.args[0], str) else str(e)
        raise ApiError(422, f"model rejected the post-event state: "
                            f"{msg}") from e
    except RuntimeError as e:
        raise ApiError(500, f"delta solve failed: {e}") from e
    status = 202 if out.get("status") == "accepted" else 200
    return status, out


def _rollout_manager() -> _rexec.RolloutManager:
    """The process's one rollout manager, lazily built over the current
    watch registry (and rebuilt when tests swap the registry out)."""
    reg = _watch_registry()
    mgr = ROLLOUT.get("manager")
    if mgr is None or mgr.registry is not reg:
        mgr = _rexec.RolloutManager(
            reg, reg.store,
            broker_cap=ROLLOUT["broker_cap"],
            rack_cap=ROLLOUT["rack_cap"],
            packer=ROLLOUT["packer"],
            lanes=ROLLOUT["lanes"],
            trace=bool(OBS["trace"]),
        )
        ROLLOUT["manager"] = mgr
    return mgr


def handle_rollout_get(cluster_id: str) -> dict:
    """GET /clusters/<id>/rollout — the rollout record: status, wave
    schedule + per-wave transfer accounting, and the current wave as
    upstream-compatible reassignment JSON."""
    try:
        view = _rollout_manager().get(cluster_id)
    except (_wevents.EventError, ValueError) as e:
        raise ApiError(400, str(e)) from e
    if view is None:
        raise ApiError(
            404,
            f"no rollout for cluster {cluster_id!r}; start one with "
            "POST /clusters/<id>/rollout/start",
        )
    return view


def handle_rollout_command(
    cluster_id: str,
    cmd: str,
    payload: dict,
    *,
    lock_wait_s: float = DEFAULT_LOCK_WAIT_S,
) -> dict:
    """POST /clusters/<id>/rollout/{start,advance,pause,rollback} —
    one fenced rollout command (docs/ROLLOUT.md). 400 malformed, 404
    unknown cluster, 409 stale rollout epoch (structured, provably
    without touching the store) or a command the state machine cannot
    accept, 200 with the updated rollout view (including the current
    wave's reassignment JSON) otherwise."""
    mgr = _rollout_manager()
    budget = _rbudget.Budget(lock_wait_s)
    try:
        return mgr.command(cluster_id, cmd, payload, budget=budget)
    except _rstate.RolloutFenced as e:
        raise ApiError(
            409,
            str(e),
            body={
                "reason": "stale_rollout_epoch",
                "cluster_id": e.cluster_id,
                "epoch": e.got,
                "current_rollout_epoch": e.current,
                "expected_min_epoch": e.current + 1,
            },
        ) from e
    except _rstate.RolloutConflict as e:
        raise ApiError(
            409, str(e), body={"reason": "bad_state"},
        ) from e
    except _rstate.RolloutError as e:
        raise ApiError(400, str(e)) from e
    except _wevents.EventError as e:
        raise ApiError(404, str(e)) from e
    except ApiError:
        raise
    except (ValueError, KeyError) as e:
        msg = e.args[0] if e.args and isinstance(e.args[0], str) else str(e)
        raise ApiError(422, f"rollout rejected: {msg}") from e


def handle_clusters_get(cluster_id: str | None = None) -> dict:
    """GET /clusters (listing) and GET /clusters/<id> (state + last
    certified plan)."""
    reg = _watch_registry()
    if cluster_id is None:
        return {"clusters": reg.list_clusters(),
                "watch": reg.snapshot()}
    try:
        info = reg.get_cluster(cluster_id)
    except _wevents.EventError as e:
        raise ApiError(400, str(e)) from e
    if info is None:
        raise ApiError(
            404,
            f"unknown cluster {cluster_id!r}; bootstrap it with "
            "POST /clusters/<id>/events",
        )
    return info


def handle_healthz() -> dict:
    import jax

    from .parallel import mesh
    from .solvers.base import available_solvers
    from .solvers.tpu import bucket

    _build_info(resolve=True)  # populate the /metrics build-info cache
    return {
        "status": "ok",
        "solvers": available_solvers(),
        "platform": jax.devices()[0].platform,
        "cache": {
            "bucketing_enabled": bucket.enabled(),
            "part_ladder_head": bucket.ladder(10),
            "executables_held": len(mesh._EXECUTABLES),
            "persistent_cache_dir": jax.config.jax_compilation_cache_dir,
            # shared persistent compile cache traffic (docs/FLEET.md):
            # hits are executables served from disk (another worker —
            # or a previous boot — already paid the XLA compile),
            # misses are fresh compiles this process performed
            "persistent_cache": _platform.compile_cache_stats(),
            # the affinity ledger (docs/FLEET.md): bucket keys
            # (brokers, racks, part-bucket, rf-bucket) this worker has
            # solved — the kao-router biases routing toward workers
            # reporting a request's bucket here
            "warm_buckets": bucket.STATS.seen(),
            # lane consolidation (ISSUE 10): the active lane-padding
            # rungs ([] = padding off), and per bucket the padded width
            # compiled plus the raw batch widths it has served — one
            # lane-padded executable per bucket, not one per width
            "lane_ladder": bucket.lane_ladder(),
            "lane_executables": mesh.lane_serve_report(),
            **bucket.STATS.snapshot(),
        },
        "queue": _SOLVES.stats(),
        "coalescing": {
            "enabled": _COALESCER.enabled(),
            "window_ms": round(_COALESCER.window_s * 1e3, 3),
            "max_batch": _COALESCER.max_batch,
        },
        # portfolio lanes (docs/PORTFOLIO.md): what a defaulted
        # single-path sweep solve races right now — width 1 means
        # --no-portfolio (or KAO_NO_PORTFOLIO) turned racing off
        "portfolio": _healthz_portfolio(),
        # fused ladder megachunks (docs/PIPELINE.md): the effective
        # default (--megachunk / KAO_MEGACHUNK), the per-bucket fusion
        # evidence table, and the width "auto" would pick per bucket
        "megachunk": _healthz_megachunk(),
        # decomposed map-reduce rung (docs/DECOMPOSE.md): selection
        # mode, sub-bucket ladder, counters, and whether the last
        # sub-bucket's map-lane executable is warm in-process
        "decompose": _healthz_decompose(),
        # sharded solve mesh (docs/MESH.md): axis sizes of the last
        # built mesh, the KAO_MESH_SHARDING mode, per-bucket sharding
        # evidence with each bucket's current choice, the reshard /
        # search counters, and the multi-process probe's cached verdict
        "mesh": _healthz_mesh(),
        "observability": {
            "trace_enabled": bool(OBS["trace"]),
            "solve_reports_held": len(_otrace.RECENT.ids()),
            "report_ring_capacity": _otrace.RECENT.capacity,
            "report_ring": _otrace.RECENT.stats(),
            # tail-based retention state (KAO_TRACE_TAIL — decisions
            # so far + the active policy knobs)
            "trace_tail": _otrace.TAIL.snapshot(),
            "profile_dir": OBS["profile_dir"],
            "flight": _oflight.snapshot(),
            # live-stream fan-out + fleet identity (/debug/stream,
            # /debug/fleet — docs/OBSERVABILITY.md "Fleet plane")
            "stream": _oflight.stream_stats(),
            "worker": _oflight.worker_identity(),
            "fleet_peers": list(FLEET["peers"]),
        },
        # device-occupancy sampler (--sample-devices; obs.sampler):
        # per-device memory, the dispatch-accumulator duty cycle, and
        # the rolling per-bucket roofline summary — the continuously
        # measured version of the "device is mostly idle" headroom
        # claim the portfolio lanes spend
        "devices": _osampler.SAMPLER.snapshot(),
        # the SLO engine's verdict (obs.slo): worst status across
        # classes + per-class burn rates — the one line a fleet
        # health dashboard reads first (full detail: GET /debug/slo)
        "slo": _healthz_slo(),
        "sanitizer": _sanitize_mod.snapshot(),
        "resilience": {
            "chaos": _chaos.snapshot(),
            "breaker": _BREAKER.snapshot(),
            "degradations": _ladder.snapshot(),
            "default_deadline_s": RESILIENCE["default_deadline_s"],
            "checkpoint_dir": RESILIENCE["checkpoint_dir"],
            "checkpoint_files": len(_checkpoint_files()),
            "checkpoint_max_files": RESILIENCE["checkpoint_max_files"],
            "checkpoint_max_age_s": RESILIENCE["checkpoint_max_age_s"],
            "queue_wait_s": _SOLVES.queue_wait_s,
        },
        "watch": _healthz_watch(),
        "rollout": _healthz_rollout(),
    }


def _healthz_portfolio() -> dict:
    """The /healthz portfolio section: effective default width, the
    lane-padded dispatch width it maps to (shared with the coalescing
    batch path — one executable per bucket), and the config table the
    lanes race."""
    import dataclasses as _dc

    from .solvers.tpu import bucket
    from .solvers.tpu.arrays import portfolio_configs
    from .solvers.tpu.engine import portfolio_width_default

    from .solvers.tpu.arrays import portfolio_adapt_snapshot

    width = portfolio_width_default()
    return {
        "enabled": width > 1,
        "width": width,
        "lane_bucket": bucket.lane_bucket(width),
        "configs": [
            _dc.asdict(c) for c in portfolio_configs(width)
        ] if width > 1 else [],
        # adaptive table evidence (ISSUE 12 satellite): wins per table
        # slot and the order currently racing (KAO_PORTFOLIO_ADAPT)
        "adapt": portfolio_adapt_snapshot(),
    }


def _healthz_megachunk() -> dict:
    """The /healthz megachunk section (docs/PIPELINE.md): the resolved
    process default plus the evidence table the "auto" chooser reads —
    measured per-dispatch host overhead vs per-chunk device wall, and
    the width each warmed bucket would fuse to right now."""
    from .solvers.tpu.engine import megachunk_snapshot

    return megachunk_snapshot()


def _healthz_mesh() -> dict:
    """The /healthz mesh section (docs/MESH.md): the named-mesh axis
    sizes, env override mode, per-bucket sharding evidence + current
    choice, and the running search/reshard counters — one snapshot
    shared with the kao_mesh_* metric families so the views agree. The
    multi-process probe's MEMOIZED verdict rides along (never probed
    here: /healthz must stay cheap), so a fleet dashboard can see why
    multi-controller wiring is or is not armed."""
    import jax

    from .parallel import distributed as _dist
    from .parallel.mesh import mesh_snapshot

    snap = mesh_snapshot()
    probe = _dist._PROBE_MEMO
    snap["processes"] = {
        "n_processes": jax.process_count(),
        "process_index": jax.process_index(),
        "multiprocess_probe": (
            {"probed": True, "ok": probe[0], "reason": probe[1]}
            if probe is not None else {"probed": False}
        ),
    }
    return snap


def _healthz_decompose() -> dict:
    """The /healthz decompose section (docs/DECOMPOSE.md): selection
    config, the sub-bucket ladder the map phase pads into, counters,
    and the map-lane executable warm state — one snapshot shared with
    the kao_decompose_* metric families so the views agree."""
    from .decompose import config_snapshot

    return config_snapshot()


def _healthz_slo() -> dict:
    """The /healthz slo section: compact — status + per-class burn
    rates, not the full event detail (that is GET /debug/slo)."""
    snap = _oslo.ENGINE.snapshot()
    return {
        "status": snap.get("status", "ok"),
        "classes": {
            cls: {
                "status": c["status"],
                "events_total": c["events_total"],
                "burn_rates": {
                    win: w["burn_rate"]
                    for win, w in c["windows"].items()
                },
            }
            for cls, c in (snap.get("classes") or {}).items()
        },
    }


def handle_debug_slo() -> dict:
    """GET /debug/slo — the full SLO snapshot: per-class objectives,
    multi-window burn rates, worst-recent exemplars, the drift-alarm
    state (obs.drift), and the tail of the flight-record stream."""
    return {
        "slo": _oslo.ENGINE.snapshot(),
        "flight": _oflight.snapshot(),
        "drift": _odrift.MONITOR.snapshot(),
        "exemplars": {
            "solve_seconds": _oflight.solve_exemplars(),
            "phase_seconds": _otrace.phase_exemplars(),
        },
        "recent_records": _oflight.recent(32),
    }


def handle_debug_profile() -> dict:
    """GET /debug/profile — the continuous roofline observatory
    (docs/OBSERVABILITY.md "Reading a roofline"): per-bucket
    achieved-vs-peak roofline from the cached XLA cost analyses,
    wall-clock attribution aggregated from the flight ledgers, the
    worst-attribution solves (trace_id links into /debug/solves/<id>),
    and the dispatch-gap histogram with p99 exemplars."""
    recent = _oflight.recent()
    psnap = _oprof.snapshot()
    return {
        "peaks": psnap["peaks"],
        "roofline": _oprof.roofline(),
        "executables": psnap["executables"],
        "attribution": _oprof.attribution_summary(recent),
        "worst_solves": _oprof.worst_solves(recent),
        "dispatch_gaps": {
            "histogram": _oprof.gap_snapshot(),
            "exemplars": _oprof.gap_exemplars(),
        },
        "counters": psnap["counters"],
        "overhead": psnap["overhead"],
    }


def handle_fleet_get() -> dict:
    """GET /debug/fleet — this worker's record ring merged with the
    recent streams of the --fleet-peers workers (obs.fleet): one
    ordered, dedup'd view with fleet-wide burn rates, drift alarms,
    and per-worker lag. A dead peer degrades to an ``errors`` entry,
    never a 500 — the merged view over the reachable workers still
    serves."""
    from concurrent.futures import ThreadPoolExecutor

    from .obs import fleet as _ofleet

    sources = [("self", _oflight.recent())]
    errors: dict = {}
    peers = list(FLEET["peers"])
    if peers:
        # fetch peers CONCURRENTLY: N dead peers must cost ~one
        # timeout on this handler thread, not N stacked timeouts
        def _fetch(url):
            return _ofleet.fetch_records(
                url, tail=FLEET["tail"], timeout=FLEET["timeout_s"],
            )

        with ThreadPoolExecutor(max_workers=min(len(peers), 8)) as ex:
            futures = [(url, ex.submit(_fetch, url)) for url in peers]
            for url, fut in futures:
                try:
                    sources.append((url, fut.result()))
                except Exception as e:
                    errors[url] = repr(e)[:200]
    view = _ofleet.build_view(sources, errors=errors or None)
    view.pop("drift_rows", None)  # exposition-internal detail
    view["peers"] = list(FLEET["peers"])
    view["stream"] = _oflight.stream_stats()
    return view


def _healthz_watch() -> dict:
    """The /healthz watch section. The registry is built lazily and its
    PlanStore touches the filesystem — a probe endpoint must degrade to
    an error field, never die with a traceback, if the watch dir went
    bad after boot (startup validates it; permissions can change)."""
    try:
        return {"dir": WATCH["dir"], **_watch_registry().snapshot()}
    except Exception as e:  # pragma: no cover - post-boot dir breakage
        return {"dir": WATCH["dir"], "error": repr(e)[:200]}


def _healthz_rollout() -> dict:
    """The /healthz rollout section — same degrade-to-error discipline
    as the watch section (the manager's lazy build touches the plan
    store)."""
    try:
        return _rollout_manager().snapshot()
    except Exception as e:  # pragma: no cover - post-boot dir breakage
        return {"error": repr(e)[:200]}


def _synthetic_cluster(brokers: int, partitions: int, rf: int,
                       racks: int):
    """A steady-state round-robin cluster of the requested shape, used
    only to drive a warmup solve whose executables land in the bucket
    (brokers, racks, rf-bucket, partition-bucket)."""
    from .models.cluster import PartitionAssignment

    parts = [
        PartitionAssignment(
            topic="warmup", partition=p,
            replicas=[(p + i) % brokers for i in range(rf)],
        )
        for p in range(partitions)
    ]
    topo = Topology.from_dict(
        {str(b): f"rack{b % racks}" for b in range(brokers)}
    )
    return Assignment(partitions=parts), list(range(brokers)), topo


def _parse_warmup_shape(sh) -> tuple[int, int, int, int]:
    """One warmup shape: {brokers, partitions, rf?, racks?} or a
    [brokers, partitions, rf?, racks?] array. Returns (B, P, R, K)."""
    if isinstance(sh, dict):
        vals = (sh.get("brokers"), sh.get("partitions"),
                sh.get("rf", 3), sh.get("racks", 1))
    elif isinstance(sh, list) and 2 <= len(sh) <= 4:
        vals = tuple(sh) + (3, 1)[len(sh) - 2:]
    else:
        raise ApiError(
            400,
            "each warmup shape must be {brokers, partitions, rf?, racks?} "
            "or a [brokers, partitions, rf?, racks?] array",
        )
    if not all(isinstance(v, int) and not isinstance(v, bool) and v > 0
               for v in vals):
        raise ApiError(400, f"warmup shape values must be positive ints: {sh}")
    b, p, r, k = vals
    if r > b:
        raise ApiError(400, f"warmup shape has rf {r} > brokers {b}")
    if k > b:
        raise ApiError(400, f"warmup shape has racks {k} > brokers {b}")
    # resource caps: the synthetic cluster is built server-side on the
    # handler thread, so a ~60-byte body must never be able to request a
    # multi-GB allocation (brokers/partitions far past any bucket this
    # service could ever serve). Caps sit an order of magnitude above
    # the jumbo benchmark config.
    if b > 65_536:
        raise ApiError(400, f"warmup brokers {b} exceeds cap 65536")
    if p > 1_000_000 or p * r > 4_000_000:
        raise ApiError(
            400,
            f"warmup shape {p} partitions x rf {r} exceeds the "
            "1M-partition / 4M-replica-slot cap",
        )
    return b, p, r, k


def handle_warmup(
    payload: dict,
    *,
    lock_wait_s: float = DEFAULT_LOCK_WAIT_S,
    max_solve_s: float | None = DEFAULT_MAX_SOLVE_S,
) -> dict:
    """POST /warmup — pre-pay XLA compiles for a list of cluster shapes
    before they carry traffic. Each shape is solved once on a synthetic
    cluster with the engine pinned and the host-side constructor races
    disabled (``precompile=True`` — a symmetric synthetic cluster would
    otherwise certify on the host and never compile), through the same
    queue and time budget as real traffic; afterwards every production
    solve whose (brokers, racks, rf-bucket, partition-bucket) matches
    runs fully warm. Returns per-shape bucket keys, wall clocks, and the
    compile counters each warmup actually moved.

    Counter caveat: ``compiles``/``already_warm`` are derived from
    process-global cache deltas, so a PRODUCTION solve running
    concurrently with the warmup can bleed its compiles into (or absorb
    them out of) a shape's row. Warm up before taking traffic — the
    startup ``--warmup`` path — or treat overlapping rows as
    approximate; per-solve counter attribution is the clean fix and is
    deliberately out of scope here.

    Lane consolidation (ISSUE 10): unless ``"lanes": false``, each
    shape additionally precompiles the CONSOLIDATED lane-padded batch
    executable — once per bucket, not once per lane count, because
    every batch width 2..Lmax pads to one rung
    (``solvers.tpu.bucket.lane_bucket``) and dispatches one executable
    with the padding lanes masked inert. Before the consolidation a
    fleet warming the coalescing path paid one compile per distinct
    batch width per bucket."""
    if not isinstance(payload, dict):
        raise ApiError(400, "request body must be a JSON object")
    shapes = payload.get("shapes")
    if not isinstance(shapes, list) or not shapes:
        raise ApiError(400, "missing required field 'shapes' (non-empty list)")
    if len(shapes) > 16:
        raise ApiError(400, "at most 16 warmup shapes per request")
    engine = payload.get("engine", "sweep")
    if engine not in ("sweep", "chain"):
        raise ApiError(400, "warmup 'engine' must be 'sweep' or 'chain'")
    warm_lanes = payload.get("lanes", True)
    if not isinstance(warm_lanes, bool):
        raise ApiError(400, "warmup 'lanes' must be a boolean")
    # portfolio warmup (docs/PORTFOLIO.md): unless "portfolio": false,
    # each shape also runs one portfolio-enabled precompile solve so
    # the portfolio-width lane executable — with the SINGLE-solve
    # path's chunk schedule — is warm before traffic races it
    warm_portfolio = payload.get("portfolio", True)
    if not isinstance(warm_portfolio, bool):
        raise ApiError(400, "warmup 'portfolio' must be a boolean")
    # decompose warmup (docs/DECOMPOSE.md): "decompose": true (2
    # groups) or an explicit group count precompiles the MAP-phase
    # lane executable for each shape's sub-bucket — the shape a
    # decomposed solve actually dispatches — so the first ultra-jumbo
    # request finds the map phase warm
    warm_decompose = payload.get("decompose", False)
    if warm_decompose is True:
        warm_decompose = 2
    if warm_decompose is not False and not (
        isinstance(warm_decompose, int)
        and not isinstance(warm_decompose, bool)
        and 2 <= warm_decompose <= 16
    ):
        raise ApiError(
            400, "warmup 'decompose' must be a boolean or a group "
                 "count 2..16")
    parsed = [_parse_warmup_shape(sh) for sh in shapes]

    from .solvers.tpu import bucket

    results = []
    for b, p, r, k in parsed:
        current, broker_list, topo = _synthetic_cluster(b, p, r, k)
        # precompile=True disables the host-side constructor races: the
        # symmetric synthetic cluster would otherwise certify on the
        # host and never compile the device executables this endpoint
        # exists to warm
        options: dict = {"engine": engine, "seed": 0, "precompile": True}
        if max_solve_s is not None:
            options["time_limit_s"] = max_solve_s

        def _job(current=current, broker_list=broker_list, topo=topo,
                 options=options):
            t0 = time.perf_counter()
            res = optimize(current, broker_list, topo, solver="tpu",
                           **options)
            return time.perf_counter() - t0, res.solve.stats

        before = bucket.STATS.snapshot()
        pc_before = _platform.compile_cache_stats()
        try:
            wall, stats = _SOLVES.submit(
                _job, wait_s=lock_wait_s, budget_s=max_solve_s
            )
        except ApiError:
            raise
        except Exception as e:
            raise ApiError(500, f"warmup solve failed: {e}") from e
        after = bucket.STATS.snapshot()
        pc_after = _platform.compile_cache_stats()
        row = {
            "shape": {"brokers": b, "partitions": p, "rf": r, "racks": k},
            "bucket_parts": stats.get("bucket_parts"),
            "bucket_rf": stats.get("bucket_rf"),
            "engine": engine,
            "wall_s": round(wall, 3),
            "compiles": after["compiles_total"] - before["compiles_total"],
            "compile_s": round(
                after["compile_seconds_total"]
                - before["compile_seconds_total"], 3,
            ),
            "already_warm": (
                after["compiles_total"] == before["compiles_total"]
            ),
            # persistent-cache movement for this shape (docs/FLEET.md):
            # with a shared KAO_COMPILE_CACHE, a non-owner worker's
            # warmup should land ~all hits and ZERO fresh misses — the
            # fleet-warmup acceptance evidence. Same process-global-
            # delta caveat as compiles above.
            "persistent": {
                "hits": pc_after["hits"] - pc_before["hits"],
                "misses": pc_after["misses"] - pc_before["misses"],
            },
        }
        if warm_lanes:
            row.update(_warmup_lanes(
                current, broker_list, topo, engine, max_solve_s,
                lock_wait_s,
            ))
        if warm_portfolio and engine == "sweep":
            row.update(_warmup_portfolio(
                current, broker_list, topo, max_solve_s, lock_wait_s,
            ))
        if warm_decompose:
            row.update(_warmup_decompose(
                b, p, r, k, int(warm_decompose), engine, max_solve_s,
                lock_wait_s,
            ))
        results.append(row)
    return {"warmed": results, "cache": bucket.STATS.snapshot()}


def _warmup_portfolio(current, broker_list, topo,
                      max_solve_s: float | None,
                      lock_wait_s: float) -> dict:
    """Precompile the portfolio-width lane executable for one warmup
    shape: a single precompile solve with ``portfolio=True`` races the
    full config table through the lane-padded dispatch the production
    single-solve path uses — the chunk schedule (and with it the
    executable identity) matches what real portfolio traffic sends.
    Best-effort like the lane warmup; width 1 (portfolio disabled
    process-wide) is a cheap no-op row."""
    from .solvers.tpu import bucket
    from .solvers.tpu.engine import portfolio_width_default

    width = portfolio_width_default()
    if width <= 1:
        return {"portfolio_width": 1}

    def _job():
        t0 = time.perf_counter()
        options: dict = {"engine": "sweep", "seed": 0,
                         "precompile": True, "portfolio": True}
        if max_solve_s is not None:
            options["time_limit_s"] = max_solve_s
        optimize(current, broker_list, topo, solver="tpu", **options)
        return time.perf_counter() - t0

    before = bucket.STATS.snapshot()
    try:
        wall = _SOLVES.submit(
            _job, wait_s=lock_wait_s, budget_s=max_solve_s
        )
    except Exception as e:  # best-effort: the single-path row stands
        _olog.warn("warmup_portfolio_failed", error=repr(e)[:200])
        return {"portfolio_error": repr(e)[:200]}
    after = bucket.STATS.snapshot()
    return {
        "portfolio_width": width,
        "portfolio_lane_bucket": bucket.lane_bucket(width),
        "portfolio_compiles": (
            after["compiles_total"] - before["compiles_total"]
        ),
        "portfolio_wall_s": round(wall, 3),
        "portfolio_already_warm": (
            after["compiles_total"] == before["compiles_total"]
        ),
    }


def _warmup_decompose(b: int, p: int, r: int, k: int, groups: int,
                      engine: str, max_solve_s: float | None,
                      lock_wait_s: float) -> dict:
    """Precompile the MAP-phase lane executable for one warmup shape's
    decomposed sub-bucket: a decomposed solve of (B, P, R, K) splits
    into ``groups`` sub-instances of ~(B/G, P/G, R, K/G) and dispatches
    them as ONE lane-padded batch — so that batch executable, at lane
    rung ``lane_bucket(groups)``, is what must be warm. Best-effort
    like the lane/portfolio warmups."""
    from .models.instance import build_instance
    from .solvers.tpu import bucket
    from .solvers.tpu.engine import solve_tpu_batch

    bg = max(b // groups, r, 1)
    pg = max(p // groups, 1)
    kg = max(min(k // groups if k >= groups else k, bg), 1)

    def _job():
        t0 = time.perf_counter()
        current, broker_list, topo = _synthetic_cluster(bg, pg, r, kg)
        insts = [
            build_instance(current, broker_list, topo)
            for _ in range(groups)
        ]
        kw: dict = {"seeds": list(range(groups)), "engine": engine,
                    "precompile": True}
        if max_solve_s is not None:
            kw["time_limit_s"] = max_solve_s
        solve_tpu_batch(insts, **kw)
        return time.perf_counter() - t0

    before = bucket.STATS.snapshot()
    try:
        wall = _SOLVES.submit(
            _job, wait_s=lock_wait_s, budget_s=max_solve_s
        )
    except Exception as e:  # best-effort: the single-path row stands
        _olog.warn("warmup_decompose_failed", error=repr(e)[:200])
        return {"decompose_error": repr(e)[:200]}
    after = bucket.STATS.snapshot()
    return {
        "decompose_groups": groups,
        "decompose_sub_shape": {
            "brokers": bg, "partitions": pg, "rf": r, "racks": kg,
        },
        "decompose_lane_bucket": bucket.lane_bucket(groups),
        "decompose_compiles": (
            after["compiles_total"] - before["compiles_total"]
        ),
        "decompose_wall_s": round(wall, 3),
        "decompose_already_warm": (
            after["compiles_total"] == before["compiles_total"]
        ),
    }


def _warmup_lanes(current, broker_list, topo, engine: str,
                  max_solve_s: float | None,
                  lock_wait_s: float) -> dict:
    """Precompile the consolidated lane-padded batch executables for
    one warmup shape: ONE small batch per lane-ladder rung >= 2, each
    padded to its rung, so every batch width 2..Lmax the coalescing
    dispatcher can send finds its executable warm. On the default
    ladder (1, 8) that is exactly one executable per bucket; a custom
    multi-rung ``KAO_LANE_BUCKETS`` ladder warms each rung once
    (the minimal width mapping to it). ``precompile=True`` keeps the
    synthetic batches out of the flight/SLO ledgers; the batch path's
    own defaults plus the service solve budget make the compiled chunk
    schedule match what the coalescing dispatcher sends under
    ``--default-deadline-s``."""
    from .models.instance import build_instance
    from .solvers.tpu import bucket
    from .solvers.tpu.engine import solve_tpu_batch

    rungs = [r for r in bucket.lane_ladder() if r >= 2]
    if not rungs:
        return {}  # lane padding off: nothing to consolidate
    # the cheapest batch width mapping to each rung: one past the
    # previous rung (first rung: width 2)
    widths, prev = [], 1
    for r in rungs:
        widths.append(min(prev + 1, r))
        prev = r

    def _job():
        t0 = time.perf_counter()
        for w in widths:
            insts = [
                build_instance(current, broker_list, topo)
                for _ in range(w)
            ]
            kw: dict = {"seeds": list(range(w)), "engine": engine,
                        "precompile": True}
            if max_solve_s is not None:
                kw["time_limit_s"] = max_solve_s
            solve_tpu_batch(insts, **kw)
        return time.perf_counter() - t0

    before = bucket.STATS.snapshot()
    try:
        wall = _SOLVES.submit(
            _job, wait_s=lock_wait_s, budget_s=max_solve_s
        )
    except Exception as e:  # best-effort: the single-path row stands
        _olog.warn("warmup_lanes_failed", error=repr(e)[:200])
        return {"lane_error": repr(e)[:200]}
    after = bucket.STATS.snapshot()
    return {
        "lane_bucket": rungs[-1],
        "lane_buckets": rungs,
        "lane_compiles": (
            after["compiles_total"] - before["compiles_total"]
        ),
        "lane_wall_s": round(wall, 3),
        "lanes_already_warm": (
            after["compiles_total"] == before["compiles_total"]
        ),
    }


def parse_warmup_flag(spec: str) -> list[dict]:
    """``--warmup "B:P[:R[:K]],..."`` -> /warmup shapes list."""
    shapes = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if not 2 <= len(fields) <= 4:
            raise ValueError(
                f"bad warmup shape {part!r}; want brokers:partitions[:rf[:racks]]"
            )
        vals = [int(f) for f in fields]
        shape = {"brokers": vals[0], "partitions": vals[1]}
        if len(vals) > 2:
            shape["rf"] = vals[2]
        if len(vals) > 3:
            shape["racks"] = vals[3]
        shapes.append(shape)
    if not shapes:
        raise ValueError("empty --warmup spec")
    return shapes


def start_warmup_thread(shapes: list[dict], *, engine: str = "sweep",
                        max_solve_s: float | None = DEFAULT_MAX_SOLVE_S):
    """Server-start precompile: run the configured bucket list through
    /warmup on a daemon thread so the listener is live immediately;
    early traffic simply queues behind the warmup solves."""

    def run():
        try:
            out = handle_warmup(
                {"shapes": shapes, "engine": engine},
                lock_wait_s=3600.0, max_solve_s=max_solve_s,
            )
            for row in out["warmed"]:
                _olog.log(
                    "warmup", shape=str(row["shape"]),
                    bucket_parts=row["bucket_parts"],
                    bucket_rf=row["bucket_rf"], wall_s=row["wall_s"],
                    compiles=row["compiles"],
                )
        except Exception as e:  # warmup is best-effort, never fatal
            _olog.warn("warmup_failed", error=repr(e)[:200])

    t = threading.Thread(target=run, daemon=True, name="kao-warmup")
    t.start()
    return t


class Handler(BaseHTTPRequestHandler):
    server_version = "kafka-assignment-optimizer-tpu/1.0"

    def _send(self, status: int, obj: dict,
              headers: dict | None = None) -> None:
        body = json.dumps(obj, default=str).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        # which worker answered: the flight-record identity stamp as a
        # header, so a fleet router (and anything behind it) attributes
        # every response — success or shed — without parsing the body
        w = _oflight.worker_identity()
        self.send_header(
            "X-KAO-Worker",
            f"{w['host']}:{w['pid']}:{w['port'] or 0}:{w['boot']}",
        )
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # route access logs to stderr quietly
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _route(self) -> str:
        # drop any query string (LB health probes append them) and a
        # trailing slash before matching
        path = self.path.split("?", 1)[0]
        return path.rstrip("/") or "/"

    def do_GET(self):
        route = self._route()
        if route == "/":
            # the human-usable front door (reference hosted-instance UX,
            # README.md:189-195); JSON clients negotiate the schema
            accept = self.headers.get("Accept", "")
            if "application/json" in accept and "text/html" not in accept:
                self._send(200, landing.request_schema())
                return
            body = landing.render_landing().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif route == "/schema":
            self._send(200, landing.request_schema())
        elif route == "/healthz":
            self._send(200, handle_healthz())
        elif route == "/metrics":
            body = render_metrics().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif route == "/clusters":
            self._send(200, handle_clusters_get())
        elif route.startswith("/clusters/") \
                and route.endswith("/rollout") \
                and len(route) > len("/clusters//rollout"):
            # the length guard keeps a cluster legitimately NAMED
            # "rollout" readable: GET /clusters/rollout has no cluster
            # segment before the suffix and falls through to the
            # normal cluster view below
            try:
                self._send(200, handle_rollout_get(
                    route[len("/clusters/"):-len("/rollout")]
                ))
            except ApiError as e:
                if e.status != 503:
                    _count(errors_total=1)
                self._send(e.status, {"error": str(e), **e.body_extra})
        elif route.startswith("/clusters/"):
            try:
                self._send(200, handle_clusters_get(
                    route[len("/clusters/"):]
                ))
            except ApiError as e:
                if e.status != 503:
                    _count(errors_total=1)
                self._send(e.status, {"error": str(e), **e.body_extra})
        elif route == "/debug/solves":
            # most-recent-first listing of retrievable solve reports
            self._send(200, {"trace_ids": _otrace.RECENT.ids()})
        elif route.startswith("/debug/solves/"):
            tid = route.rsplit("/", 1)[1]
            rep = _otrace.RECENT.get(tid)
            if rep is None:
                self._send(404, {
                    "error": f"no solve report for trace_id {tid!r} "
                             f"(ring holds the last "
                             f"{_otrace.RECENT.capacity} traced solves)",
                })
                return
            # ?format=chrome: the span tree as Chrome trace-event JSON
            # (obs.chrome) — save it and load in chrome://tracing or
            # Perfetto; the offline path is `kao-trace convert`
            from urllib.parse import parse_qs, urlparse

            fmt = (parse_qs(urlparse(self.path).query)
                   .get("format") or ["json"])[0]
            if fmt == "chrome":
                self._send(200, _ochrome.to_chrome(rep))
            elif fmt == "json":
                self._send(200, rep)
            else:
                self._send(400, {
                    "error": f"unknown format {fmt!r}; "
                             "want 'json' or 'chrome'",
                })
        elif route == "/debug/slo":
            # the full SLO snapshot: per-class objectives, multi-window
            # burn rates, worst-recent exemplars, drift-alarm state,
            # and the tail of the flight-record stream
            # (docs/OBSERVABILITY.md)
            self._send(200, handle_debug_slo())
        elif route == "/debug/profile":
            # the roofline observatory: per-bucket achieved-vs-peak
            # occupancy from cached XLA cost analyses + wall-clock
            # attribution over the flight ledgers (docs/OBSERVABILITY.md
            # "Reading a roofline")
            self._send(200, handle_debug_profile())
        elif route == "/debug/fleet":
            # the merged fleet view: this worker + --fleet-peers
            # (docs/OBSERVABILITY.md "Fleet plane"); peer failures
            # degrade to an "errors" field inside the handler, so
            # this always answers 200
            self._send(200, handle_fleet_get())
        elif route == "/debug/stream":
            self._stream_flight()
        else:
            _count(errors_total=1)
            self._send(404, {"error": f"no such endpoint: {self.path}"})

    def _stream_flight(self) -> None:
        """GET /debug/stream — flight records as newline-delimited
        JSON, as they land (docs/OBSERVABILITY.md "Fleet plane").

        Query params: ``follow`` (default 1; 0 = dump the ring tail
        and close — the snapshot mode /debug/fleet and kao-fleet use),
        ``tail`` (replay the last N ring records first, default 0 in
        follow mode / 512 in snapshot mode), ``kind`` (filter).

        Live mode subscribes a bounded per-client queue BEFORE the
        tail replay and skips queued records the replay already sent
        (seq-deduped), so a record landing concurrently is delivered
        exactly once. A slow client overflows its own queue — the
        newest records are dropped FOR THAT CLIENT ONLY and counted in
        ``kao_stream_dropped_total``; the solve path never blocks.
        Blank lines are heartbeats; readers skip them."""
        from urllib.parse import parse_qs, urlparse

        qs = parse_qs(urlparse(self.path).query)

        def _qint(name: str, default: int) -> int:
            try:
                return int((qs.get(name) or [default])[0])
            except (TypeError, ValueError):
                return default

        follow = (qs.get("follow") or ["1"])[0] not in ("0", "false")
        kind = (qs.get("kind") or [None])[0]
        tail = _qint("tail", 0 if follow else FLEET["tail"])
        client = None
        if follow:
            try:
                client = _oflight.subscribe()
            except RuntimeError as e:
                err = _shed("stream_clients", str(e), retry_after_s=5.0)
                self._send(err.status,
                           {"error": str(err), **err.body_extra},
                           headers={"Retry-After": "5"})
                return
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.end_headers()
            max_seq = 0
            for rec in (_oflight.recent(tail) if tail > 0 else []):
                if kind is not None and rec.get("kind") != kind:
                    continue
                seq = rec.get("seq")
                if isinstance(seq, int):
                    max_seq = max(max_seq, seq)
                self.wfile.write(json.dumps(
                    rec, separators=(",", ":"), default=str,
                ).encode() + b"\n")
            self.wfile.flush()
            if not follow:
                return
            while True:
                rec = client.get(timeout=10.0)
                if rec is None:
                    # heartbeat: detects a dead socket within ~10 s
                    # and keeps LB idle timeouts at bay
                    self.wfile.write(b"\n")
                else:
                    seq = rec.get("seq")
                    if isinstance(seq, int) and seq <= max_seq:
                        continue  # the tail replay already sent it
                    if kind is not None and rec.get("kind") != kind:
                        continue
                    self.wfile.write(json.dumps(
                        rec, separators=(",", ":"), default=str,
                    ).encode() + b"\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away: normal stream teardown
        finally:
            if client is not None:
                _oflight.unsubscribe(client)

    def do_POST(self):
        route = self._route()
        cluster_id = None
        rollout_cmd = None
        if route.startswith("/clusters/"):
            rest = route[len("/clusters/"):]
            if rest.endswith("/events"):
                cluster_id = rest[: -len("/events")]
            else:
                for cmd in ("start", "advance", "pause", "rollback"):
                    suffix = "/rollout/" + cmd
                    if rest.endswith(suffix):
                        cluster_id = rest[: -len(suffix)]
                        rollout_cmd = cmd
                        break
        if route not in ("/submit", "/evaluate", "/warmup") \
                and cluster_id is None:
            _count(errors_total=1)
            self._send(404, {"error": f"no such endpoint: {self.path}"})
            return
        _count(requests_total=1)
        # chaos slow_client (docs/RESILIENCE.md): a slow client holding
        # a handler thread — fires before the body read, exactly where
        # a real trickling upload would stall
        _chaos.sleep_if("slow_client")
        try:
            try:
                n = int(self.headers.get("Content-Length", 0))
            except ValueError as e:
                raise ApiError(400, f"bad Content-Length header: {e}") from e
            if n > MAX_BODY_BYTES:
                raise ApiError(413, "request body too large")
            if n < 0:
                raise ApiError(400, "negative Content-Length")
            raw = self.rfile.read(n)
            try:
                payload = json.loads(raw)
            except json.JSONDecodeError as e:
                raise ApiError(400, f"invalid JSON: {e}") from e
            lock_wait_s = getattr(self.server, "lock_wait_s",
                                  DEFAULT_LOCK_WAIT_S)
            max_solve_s = getattr(self.server, "max_solve_s",
                                  DEFAULT_MAX_SOLVE_S)
            if route == "/evaluate":
                self._send(200, handle_evaluate(
                    payload, lock_wait_s=lock_wait_s,
                    max_solve_s=max_solve_s,
                ))
                return
            if route == "/warmup":
                self._send(200, handle_warmup(
                    payload, lock_wait_s=lock_wait_s,
                    max_solve_s=max_solve_s,
                ))
                return
            if rollout_cmd is not None:
                self._send(200, handle_rollout_command(
                    cluster_id, rollout_cmd, payload,
                    lock_wait_s=lock_wait_s,
                ))
                return
            if cluster_id is not None:
                status, body = handle_cluster_event(
                    cluster_id, payload, lock_wait_s=lock_wait_s,
                    max_solve_s=max_solve_s,
                )
                self._send(status, body)
                return
            # cross-process causal tracing (docs/OBSERVABILITY.md
            # "Distributed traces"): a valid W3C traceparent header
            # makes this solve ADOPT the propagated trace ID (the
            # kao-router join); malformed headers are tolerated as a
            # fresh root. The accepted context is echoed back.
            tp_ctx = _otrace.extract(
                self.headers.get(_otrace.TRACEPARENT))
            out = handle_submit(
                payload, lock_wait_s=lock_wait_s,
                max_solve_s=max_solve_s, trace_ctx=tp_ctx,
            )
            echo = None
            if tp_ctx is not None and out.get("trace_id"):
                tp = _otrace.inject(out["trace_id"], tp_ctx.span_id)
                if tp:
                    echo = {_otrace.TRACEPARENT: tp}
            self._send(200, out, headers=echo)
        except ApiError as e:
            if e.status != 503:
                _count(errors_total=1)
            headers = None
            if e.retry_after_s is not None:
                # integer seconds per RFC 9110; never advertise 0 (a
                # client retrying immediately defeats the shed)
                import math

                headers = {
                    "Retry-After": str(max(1, math.ceil(e.retry_after_s)))
                }
            self._send(e.status, {"error": str(e), **e.body_extra},
                       headers=headers)
        except Exception as e:  # never leak a traceback as a hung socket
            _count(errors_total=1)
            self._send(500, {"error": f"internal error: {e}"})


def make_server(host: str = "127.0.0.1", port: int = 8787,
                verbose: bool = False,
                lock_wait_s: float = DEFAULT_LOCK_WAIT_S,
                max_solve_s: float | None = DEFAULT_MAX_SOLVE_S,
                ) -> ThreadingHTTPServer:
    srv = ThreadingHTTPServer((host, port), Handler)
    srv.verbose = verbose
    srv.lock_wait_s = lock_wait_s
    srv.max_solve_s = max_solve_s
    return srv


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kafka_assignment_optimizer_tpu.serve",
        description="Kafka reassignment optimizer HTTP service (POST /submit)",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8787)
    ap.add_argument("--verbose", action="store_true", help="access logs")
    ap.add_argument("--lock-wait-s", type=float,
                    default=DEFAULT_LOCK_WAIT_S,
                    help="max seconds a request waits for the solver "
                         "before 503 (saturation shedding)")
    ap.add_argument("--max-solve-s", type=float,
                    default=DEFAULT_MAX_SOLVE_S,
                    help="time limit injected into every solve; clients "
                         "may tighten but not exceed it (0 = uncapped)")
    ap.add_argument("--workers", type=int, default=DEFAULT_WORKERS,
                    help="solve worker threads (overlapping requests run "
                         "concurrently up to this many)")
    ap.add_argument("--queue-depth", type=int,
                    default=DEFAULT_QUEUE_DEPTH,
                    help="bounded solve queue length; requests past it "
                         "shed with 503 after --lock-wait-s")
    ap.add_argument("--batch-window-ms", type=float,
                    default=DEFAULT_BATCH_WINDOW_MS,
                    help="request-coalescing window: same-bucket TPU "
                         "solves arriving while the pool is busy are "
                         "grouped for up to this long, then run as one "
                         "batched lane solve (sparse requests bypass "
                         "the window entirely)")
    ap.add_argument("--max-batch", type=int, default=DEFAULT_MAX_BATCH,
                    help="max lanes per coalesced solve "
                         "(1 disables coalescing)")
    ap.add_argument("--warmup", default=None, metavar="B:P[:R[:K]],...",
                    help="bucket shapes to precompile at startup "
                         "(brokers:partitions[:rf[:racks]] comma list); "
                         "runs in the background, early traffic queues "
                         "behind it")
    ap.add_argument("--jit-cache", default=None, metavar="DIR",
                    help="persistent XLA compile-cache directory "
                         "(sets KAO_JIT_CACHE, so warmth survives "
                         "process restarts)")
    ap.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent XLA compile-cache directory "
                         "(sets KAO_COMPILE_CACHE; same as --jit-cache "
                         "— this is the fleet spelling: point every "
                         "worker at ONE shared dir so one worker's "
                         "cold compile is every other worker's disk "
                         "hit, docs/FLEET.md)")
    ap.add_argument("--no-pipeline", action="store_true",
                    help="disable the double-buffered ladder dispatch "
                         "for every solve this service runs "
                         "(docs/PIPELINE.md); clients may still opt a "
                         "request back in with options.pipeline=true")
    ap.add_argument("--no-portfolio", action="store_true",
                    help="disable portfolio lane racing by default "
                         "(docs/PORTFOLIO.md); clients may still opt a "
                         "request back in with options.portfolio=true")
    ap.add_argument("--megachunk", default=None, metavar="K|auto|off",
                    help="fused ladder megachunks (docs/PIPELINE.md): "
                         "default fused width for sweep solves — an "
                         "integer pins K chunks per dispatch, 'auto' "
                         "engages the per-bucket evidence chooser, "
                         "'off'/unset keeps the per-chunk ladder. Same "
                         "as KAO_MEGACHUNK; clients may opt a request "
                         "out with options.megachunk=false")
    ap.add_argument("--no-trace", action="store_true",
                    help="disable per-request solve traces (responses "
                         "then carry no trace_id and /debug/solves "
                         "stays empty)")
    ap.add_argument("--profile-dir", default=None, metavar="DIR",
                    help="capture a jax.profiler trace of the first "
                         "--profile-solves TPU solves per bucket under "
                         "this directory (XLA-level traces next to the "
                         "span-level solve reports)")
    ap.add_argument("--profile-solves", type=int, default=1,
                    metavar="N",
                    help="profiled solves per bucket with "
                         "--profile-dir (default 1)")
    ap.add_argument("--flight-dir", default=None, metavar="DIR",
                    help="solve-cost flight recorder "
                         "(docs/OBSERVABILITY.md): append one compact "
                         "JSONL record per solve/delta/batch-lane "
                         "under this directory (crash-safe, "
                         "auto-rotated); the SLO engine and "
                         "kao_solve_seconds run off the same stream "
                         "either way. Same as KAO_FLIGHT_DIR")
    ap.add_argument("--slo", default=None, metavar="SPEC",
                    help="per-class SLO objectives, e.g. "
                         "'solve:5:0.99,delta:2:0.995' "
                         "(class:latency_s[:target]); defaults in "
                         "docs/OBSERVABILITY.md. Burn rates on "
                         "/metrics (kao_slo_*), /healthz 'slo', and "
                         "GET /debug/slo")
    ap.add_argument("--fleet-peers", default=None, metavar="URL,URL",
                    help="peer worker base URLs for GET /debug/fleet "
                         "(e.g. 'http://10.0.0.2:8787,"
                         "http://10.0.0.3:8787'): the merged "
                         "fleet-wide flight/SLO/drift view "
                         "(docs/OBSERVABILITY.md). Peers are "
                         "operator-named only; clients cannot point "
                         "the server at URLs")
    ap.add_argument("--sample-devices", type=float, default=None,
                    metavar="HZ",
                    help="device-occupancy sampler (obs.sampler; same "
                         "as KAO_SAMPLE_DEVICES): read jax device "
                         "memory stats + the dispatch-accumulator "
                         "duty cycle at this rate into "
                         "kao_device_hbm_bytes/kao_device_duty_cycle "
                         "and the /healthz per-bucket roofline "
                         "summary. Off by default; <1%% overhead at "
                         "the documented 1 Hz")
    ap.add_argument("--queue-wait-s", type=float,
                    default=DEFAULT_QUEUE_WAIT_S,
                    help="maintenance drain window: how long the "
                         "periodic cache clear waits for in-flight "
                         "solves before skipping (was hard-coded 15); "
                         "echoed in queue-full 503 bodies")
    ap.add_argument("--default-deadline-s", type=float, default=None,
                    metavar="S",
                    help="per-request end-to-end deadline applied when "
                         "the request carries no 'deadline_s' field "
                         "(docs/RESILIENCE.md); the solve runs on the "
                         "time REMAINING after validation and queueing")
    ap.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                    help="crash-safe auto-resume: persist per-cluster "
                         "solve checkpoints (keyed by instance "
                         "fingerprint) under this directory, so a "
                         "worker-crash retry or a repeated solve of "
                         "the same cluster warm-starts from the last "
                         "completed plan")
    ap.add_argument("--checkpoint-max-files", type=int, default=512,
                    metavar="N",
                    help="checkpoint-dir hygiene: keep at most this "
                         "many fingerprint-keyed .npz checkpoints "
                         "(oldest GC'd on the maintenance pass; live "
                         "count on /metrics as kao_checkpoint_files)")
    ap.add_argument("--checkpoint-max-age-s", type=float,
                    default=7 * 24 * 3600.0, metavar="S",
                    help="checkpoint-dir hygiene: GC checkpoints older "
                         "than this on the maintenance pass")
    ap.add_argument("--watch-dir", default=None, metavar="DIR",
                    help="durable per-cluster plan store for the "
                         "cluster-watch delta API (docs/WATCH.md): "
                         "POST /clusters/<id>/events remembers each "
                         "cluster's last certified plan + epoch here, "
                         "atomically, surviving kill -9 + restart. "
                         "Without it the delta API still works but "
                         "state is process-local")
    ap.add_argument("--watch-window-ms", type=float,
                    default=_wmanager.DEFAULT_WINDOW_S * 1e3,
                    metavar="MS",
                    help="event-storm coalescing window: after a "
                         "superseded solve, the re-solve of the latest "
                         "cluster state waits this long for the burst "
                         "to settle (one re-solve per burst, not per "
                         "event)")
    ap.add_argument("--watch-max-backlog", type=int,
                    default=_wmanager.DEFAULT_MAX_BACKLOG, metavar="N",
                    help="event-storm backpressure: events piling up "
                         "behind one in-flight solve past this count "
                         "shed with 503 reason=event_storm and a "
                         "Retry-After derived from the coalescing "
                         "window; admitted events are never dropped")
    ap.add_argument("--rollout-broker-cap", type=int,
                    default=_rwaves.DEFAULT_BROKER_CAP, metavar="N",
                    help="streaming plan rollout (docs/ROLLOUT.md): "
                         "default per-wave transfer cap per broker, in "
                         "transfer units (replica copies in + out); a "
                         "rollout start may override per rollout")
    ap.add_argument("--rollout-rack-cap", type=int,
                    default=_rwaves.DEFAULT_RACK_CAP, metavar="N",
                    help="default per-wave inbound transfer cap per "
                         "rack (docs/ROLLOUT.md)")
    ap.add_argument("--rollout-packer", default="greedy",
                    choices=["greedy", "scored"],
                    help="default wave packer: 'greedy' (host "
                         "reference, first-fit-decreasing) or 'scored' "
                         "(races diverse move orderings and keeps the "
                         "packing minimizing makespan x peak cross-"
                         "rack traffic; same as KAO_ROLLOUT_PACKER)")
    ap.add_argument("--rollout-lanes", type=int,
                    default=_rwaves.DEFAULT_LANES, metavar="N",
                    help="orderings the scored packer races (>= 1)")
    ap.add_argument("--breaker-threshold", type=int, default=3,
                    metavar="N",
                    help="consecutive solver failures on one bucket "
                         "key before its circuit opens (sheds with "
                         "Retry-After instead of compiling-and-"
                         "crashing per request)")
    ap.add_argument("--breaker-cooldown-s", type=float, default=30.0,
                    metavar="S",
                    help="initial circuit-open cooldown; escalates "
                         "exponentially (jittered) on repeated trips")
    ap.add_argument("--chaos", default=None, metavar="SPEC",
                    help="arm the fault-injection harness (same as "
                         "KAO_CHAOS; docs/RESILIENCE.md), e.g. "
                         "'seed=7,pallas_fault,queue_overload:0.5:-1'. "
                         "NEVER in production: kao_chaos_armed on "
                         "/metrics exposes it")
    ap.add_argument("--sanitize", action="store_true",
                    help="runtime sanitizer mode (same as "
                         "KAO_SANITIZE=1; docs/ANALYSIS.md): "
                         "jax_debug_nans, a recompile sentinel over "
                         "the executable cache, and a donation "
                         "use-after-free guard; trips are counted on "
                         "/metrics (kao_sanitizer_*) and fail the "
                         "offending solve")
    args = ap.parse_args(argv)
    if args.lock_wait_s < 0:
        ap.error("--lock-wait-s must be >= 0")
    if args.max_solve_s < 0:
        ap.error("--max-solve-s must be >= 0 (0 = uncapped)")
    if args.workers < 1:
        ap.error("--workers must be >= 1")
    if args.queue_depth < 1:
        ap.error("--queue-depth must be >= 1")
    if args.batch_window_ms < 0:
        ap.error("--batch-window-ms must be >= 0")
    if args.max_batch < 1:
        ap.error("--max-batch must be >= 1")
    if args.queue_wait_s < 0:
        ap.error("--queue-wait-s must be >= 0")
    if args.default_deadline_s is not None and args.default_deadline_s <= 0:
        ap.error("--default-deadline-s must be > 0")
    if args.breaker_threshold < 1:
        ap.error("--breaker-threshold must be >= 1")
    if args.breaker_cooldown_s <= 0:
        ap.error("--breaker-cooldown-s must be > 0")
    warmup_shapes = None
    if args.warmup:
        try:
            warmup_shapes = parse_warmup_flag(args.warmup)
        except ValueError as e:
            ap.error(str(e))
    if args.jit_cache:
        import os

        os.environ["KAO_JIT_CACHE"] = args.jit_cache
    if args.compile_cache:
        import os

        os.environ["KAO_COMPILE_CACHE"] = args.compile_cache
    if args.profile_solves < 0:
        ap.error("--profile-solves must be >= 0")
    from .utils.platform import pin_platform

    pin_platform()
    if args.sanitize:
        from .analysis import sanitize as _sanitize

        _sanitize.enable()
    if args.no_pipeline:
        from .solvers.tpu.engine import set_pipeline_default

        set_pipeline_default(False)
    if args.no_portfolio:
        from .solvers.tpu.engine import set_portfolio_default

        set_portfolio_default(False)
    if args.megachunk is not None:
        from .solvers.tpu.engine import set_megachunk_default

        try:
            set_megachunk_default(args.megachunk)
        except ValueError:
            ap.error(f"--megachunk {args.megachunk!r}: expected an "
                     "integer width, 'auto', or 'off'")
    OBS["trace"] = not args.no_trace
    OBS["profile_dir"] = args.profile_dir
    OBS["profile_solves"] = args.profile_solves
    import os

    flight_dir = (args.flight_dir or os.environ.get("KAO_FLIGHT_DIR")
                  or None)
    if flight_dir:
        # fail fast at boot like --watch-dir: an unwritable flight dir
        # must be a clean startup error, not a per-solve warn loop
        try:
            _oflight.configure(flight_dir)
        except OSError as e:
            ap.error(f"--flight-dir {flight_dir!r}: {e}")
    OBS["flight_dir"] = flight_dir
    slo_spec = args.slo or os.environ.get("KAO_SLO")
    if slo_spec:
        try:
            _oslo.ENGINE.configure(spec=slo_spec)
        except ValueError as e:
            ap.error(f"--slo/KAO_SLO: {e}")
    if args.fleet_peers:
        peers = [p.strip().rstrip("/")
                 for p in args.fleet_peers.split(",") if p.strip()]
        bad = [p for p in peers
               if not p.startswith(("http://", "https://"))]
        if bad:
            ap.error(f"--fleet-peers URLs must be http(s)://: {bad}")
        FLEET["peers"] = peers
    sample_hz = args.sample_devices
    if sample_hz is None and os.environ.get("KAO_SAMPLE_DEVICES"):
        try:
            sample_hz = float(os.environ["KAO_SAMPLE_DEVICES"])
        except ValueError:
            ap.error("KAO_SAMPLE_DEVICES must be a number (Hz)")
    if sample_hz is not None and sample_hz < 0:
        ap.error("--sample-devices must be >= 0 (0 = off)")
    if sample_hz:
        _osampler.SAMPLER.configure(sample_hz)
    _SOLVES.configure(workers=args.workers, depth=args.queue_depth,
                      queue_wait_s=args.queue_wait_s)
    _COALESCER.configure(window_ms=args.batch_window_ms,
                         max_batch=args.max_batch)
    RESILIENCE["default_deadline_s"] = args.default_deadline_s
    if args.checkpoint_max_files < 1:
        ap.error("--checkpoint-max-files must be >= 1")
    if args.checkpoint_max_age_s <= 0:
        ap.error("--checkpoint-max-age-s must be > 0")
    if args.watch_window_ms < 0:
        ap.error("--watch-window-ms must be >= 0")
    if args.watch_max_backlog < 1:
        ap.error("--watch-max-backlog must be >= 1")
    RESILIENCE["checkpoint_max_files"] = args.checkpoint_max_files
    RESILIENCE["checkpoint_max_age_s"] = args.checkpoint_max_age_s
    if args.checkpoint_dir:
        import os

        os.makedirs(args.checkpoint_dir, exist_ok=True)
        RESILIENCE["checkpoint_dir"] = args.checkpoint_dir
    if args.watch_dir:
        # fail fast at boot like --checkpoint-dir: the registry is
        # built lazily on first touch, and /healthz is one of those
        # touches — an unwritable plan-store dir must be a clean
        # startup error, never a liveness-probe traceback
        import os

        try:
            os.makedirs(args.watch_dir, exist_ok=True)
        except OSError as e:
            ap.error(f"--watch-dir {args.watch_dir!r}: {e}")
    WATCH["dir"] = args.watch_dir
    WATCH["window_s"] = args.watch_window_ms / 1e3
    WATCH["max_backlog"] = args.watch_max_backlog
    WATCH["lock_wait_s"] = args.lock_wait_s
    WATCH["max_solve_s"] = args.max_solve_s or None
    WATCH["registry"] = None  # rebuilt lazily with this config
    if args.rollout_broker_cap < 1:
        ap.error("--rollout-broker-cap must be >= 1")
    if args.rollout_rack_cap < 1:
        ap.error("--rollout-rack-cap must be >= 1")
    if args.rollout_lanes < 1:
        ap.error("--rollout-lanes must be >= 1")
    ROLLOUT["broker_cap"] = args.rollout_broker_cap
    ROLLOUT["rack_cap"] = args.rollout_rack_cap
    ROLLOUT["packer"] = args.rollout_packer
    ROLLOUT["lanes"] = args.rollout_lanes
    ROLLOUT["manager"] = None  # rebuilt lazily over the new registry
    _BREAKER.configure(threshold=args.breaker_threshold,
                       cooldown_s=args.breaker_cooldown_s)
    if args.chaos:
        try:
            _chaos.arm(args.chaos)
        except ValueError as e:
            ap.error(str(e))
        _olog.warn("chaos_armed", spec=args.chaos)
    srv = make_server(
        args.host, args.port, verbose=args.verbose,
        lock_wait_s=args.lock_wait_s,
        max_solve_s=args.max_solve_s or None,
    )
    # stamp the bound port into this worker's flight-record identity
    # (host/pid/port/boot-id — the fleet merge key, obs.flight)
    _oflight.set_worker_port(srv.server_address[1])
    if warmup_shapes:
        start_warmup_thread(
            warmup_shapes, max_solve_s=args.max_solve_s or None
        )
    _olog.log("listening", host=args.host, port=srv.server_address[1],
              workers=args.workers, trace_enabled=OBS["trace"])
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
