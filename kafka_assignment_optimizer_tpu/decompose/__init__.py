"""Hierarchical rack/AZ decomposition — the "decomposed" rung of the
bucket ladder (ROADMAP item 4, docs/DECOMPOSE.md).

Map-reduce over the cluster's AZ structure: ``split`` extracts per-AZ
sub-instances whose feasibility nests under the flat instance
(inherited global bands — split.py), the **map** phase solves them as
vmapped lanes through the existing lane-padded batch executables
(``engine.solve_tpu_batch``: one padded executable serves every AZ at
once), and the **reduce** phase stitches the local plans back into one
global candidate, verifies it against the ORIGINAL flat instance's
oracle, and proves a global certificate or reports the bound gap.
Map<->reduce iterates (re-seeding unlucky lanes) up to
``KAO_DECOMPOSE_ITERS`` times.

Selection: ``engine.solve_tpu`` consults :func:`should_decompose` —
opt-in via the ``decompose`` kwarg (CLI ``--decompose``, serve
``options.decompose``) or ``KAO_DECOMPOSE=1``; automatic when the flat
instance exceeds ``KAO_DECOMPOSE_AUTO_PARTS`` (default 150k) or the
top rung of a custom ``KAO_BUCKETS`` ladder. ``KAO_DECOMPOSE=0``
disables it everywhere.

Degradation (PR 6 discipline): a failed reduce — chaos point
``decompose_reduce``, a NaN abort, or a stitched plan the oracle
rejects after all iterations — notes the ``decompose_to_flat`` rung
and returns None, letting the flat path solve where it fits. Never
raises into the solve path except genuine programming errors.
"""

from __future__ import annotations

import os
import time

from ..obs import log as _olog
from ..obs import trace as _otrace
from ..resilience import chaos as _chaos
from ..resilience import ladder as _ladder
from ..solvers.base import SolveResult
from .split import Split, split as split_instance
from .stats import COUNTER_NAMES, STATS
from .stitch import stitch

_AUTO_PARTS_DEFAULT = 150_000
_ITERS_DEFAULT = 2


def mode() -> str:
    """``KAO_DECOMPOSE`` -> 'off' | 'on' | 'auto' (unset = auto)."""
    v = os.environ.get("KAO_DECOMPOSE", "").strip().lower()
    if v in ("0", "off", "false", "no"):
        return "off"
    if v in ("1", "on", "true", "yes", "force"):
        return "on"
    return "auto"


def auto_parts() -> int:
    try:
        return int(os.environ.get("KAO_DECOMPOSE_AUTO_PARTS",
                                  _AUTO_PARTS_DEFAULT))
    except ValueError:
        return _AUTO_PARTS_DEFAULT


def max_iters() -> int:
    try:
        return max(1, int(os.environ.get("KAO_DECOMPOSE_ITERS",
                                         _ITERS_DEFAULT)))
    except ValueError:
        return _ITERS_DEFAULT


def _above_custom_ladder(num_parts: int) -> bool:
    """True when a bounded custom ``KAO_BUCKETS`` ladder is active and
    the instance exceeds its top rung — the flat path's OOM/compile
    cliff the decomposed rung exists to take over from."""
    raw = os.environ.get("KAO_BUCKETS", "").strip().lower()
    if not raw or raw in ("on", "1", "true", "off", "0", "none",
                          "false"):
        return False  # default ladder is unbounded; bucketing off
    from ..solvers.tpu import bucket

    rungs = bucket.ladder(64)
    return bool(rungs) and int(num_parts) > max(rungs)


def should_decompose(inst, requested: bool | None = None) -> bool:
    """The selection rule: explicit kwarg wins, then ``KAO_DECOMPOSE``,
    then the auto trigger (instance past the flat ladder's reach)."""
    if requested is not None:
        return bool(requested)
    m = mode()
    if m == "off":
        return False
    if m == "on":
        return True
    p = inst.num_parts
    return p >= auto_parts() or _above_custom_ladder(p)


def maybe_decompose(
    inst, *, seed: int = 0, engine: str | None = None,
    time_limit_s: float | None = None, budget=None,
    portfolio: bool | None = None, n_devices: int | None = None,
    rounds: int | None = None, t_hi: float | None = None,
    t_lo: float | None = None,
) -> SolveResult | None:
    """Run the decomposed solve. Returns the stitched SolveResult, or
    None when the instance is undecomposable or the reduce failed —
    the caller (``engine._solve_tpu``) then continues down the flat
    path (``decompose_to_flat`` has been noted on failure)."""
    t0 = time.perf_counter()
    with _otrace.span("decompose_split"):
        sp = split_instance(inst)
    if sp is None:
        STATS.note_unsplittable()
        _olog.info("decompose_unsplittable", parts=inst.num_parts,
                   racks=inst.num_racks)
        return None
    try:
        return _map_reduce(
            inst, sp, t0, seed=seed, engine=engine,
            time_limit_s=time_limit_s, budget=budget,
            portfolio=portfolio, n_devices=n_devices, rounds=rounds,
            t_hi=t_hi, t_lo=t_lo,
        )
    except (_chaos.ChaosFault, FloatingPointError) as e:
        STATS.note_fallback(subproblems=sp.n_groups)
        _ladder.note_rung(
            "decompose_to_flat", error=repr(e)[:120],
            subproblems=sp.n_groups,
        )
        _olog.warn("decompose_fallback", error=repr(e)[:200],
                   subproblems=sp.n_groups)
        return None


def _map_reduce(inst, sp: Split, t0: float, *, seed, engine,
                time_limit_s, budget, portfolio, n_devices, rounds,
                t_hi, t_lo) -> SolveResult | None:
    from ..solvers.tpu.engine import solve_tpu_batch

    G = sp.n_groups
    best = [None] * G  # per-lane best SolveResult across iterations
    todo = list(range(G))
    iters = 0
    a = None
    proved, gap = False, None
    for it in range(1, max_iters() + 1):
        iters = it
        rem = budget.remaining() if budget is not None else None
        lane_limit = rem if rem is not None else time_limit_s
        with _otrace.span("decompose_map", iteration=it,
                          lanes=len(todo)):
            kw: dict = {
                "seeds": [seed + g + 1000 * (it - 1) for g in todo],
                "engine": engine,
            }
            if lane_limit is not None:
                kw["time_limit_s"] = lane_limit
            if portfolio is not None:
                kw["portfolio"] = portfolio
            if n_devices is not None:
                kw["n_devices"] = n_devices
            if rounds is not None:
                kw["rounds"] = rounds
            if t_hi is not None:
                kw["t_hi"] = t_hi
            if t_lo is not None:
                kw["t_lo"] = t_lo
            lane_res = solve_tpu_batch([sp.subs[g] for g in todo], **kw)
        for g, r in zip(todo, lane_res):
            if best[g] is None or _rank(r) > _rank(best[g]):
                best[g] = r
        with _otrace.span("decompose_reduce", iteration=it) as rsp:
            _chaos.raise_if("decompose_reduce")
            a = stitch(inst, sp, [b.a for b in best])
            nviol = int(sum(inst.violations(a).values()))
            if rsp is not None:
                rsp.set(violations=nviol)
        if nviol == 0:
            with _otrace.span("decompose_stitch", iteration=it):
                rem = budget.remaining() if budget is not None else None
                if rem is not None:
                    inst.set_bounds_deadline(max(0.1, min(rem, 10.0)))
                proved = bool(inst.certify_optimal(a, allow_tight=False))
                if proved:
                    gap = 0
                else:
                    ub = int(inst.weight_upper_bound(level=0))
                    gap = max(0, ub - int(inst.preservation_weight(a)))
            if proved or gap == 0:
                break
            if it >= max_iters() or (budget is not None
                                     and budget.expired()):
                break  # report the gap — the contract's other half
            todo = list(range(G))  # re-seed every lane to chase the gap
        else:
            if it >= max_iters() or (budget is not None
                                     and budget.expired()):
                STATS.note_fallback(iterations=iters, subproblems=G)
                _ladder.note_rung(
                    "decompose_to_flat", reason="stitch_infeasible",
                    violations=nviol, subproblems=G,
                )
                _olog.warn("decompose_stitch_infeasible",
                           violations=nviol, iterations=iters)
                return None
            todo = [g for g in range(G)
                    if not best[g].stats.get("feasible")] or list(range(G))

    if a is None or int(sum(inst.violations(a).values())) != 0:
        STATS.note_fallback(iterations=iters, subproblems=G)
        _ladder.note_rung("decompose_to_flat",
                          reason="stitch_infeasible", subproblems=G)
        return None

    first = best[0].stats if best[0] is not None else {}
    sub_shape = {
        "brokers": int(sp.subs[0].num_brokers),
        "racks": int(sp.subs[0].num_racks),
        "parts": int(max(s.num_parts for s in sp.subs)),
        "bucket_parts": first.get("bucket_parts"),
        "bucket_rf": first.get("bucket_rf"),
        "lane_bucket": first.get("lane_bucket"),
    }
    STATS.note_solve(subproblems=G, iterations=iters, certified=proved,
                     bound_gap=gap, sub_shape=sub_shape)
    w = int(inst.preservation_weight(a))
    moves = int(inst.move_count(a))
    stats = {
        "engine": "decomposed",
        "map_engine": first.get("engine"),
        "feasible": True,
        "violations": 0,
        "moves": moves,
        "seed_moves": moves,
        "proved_optimal": proved,
        "timed_out": any(b is not None and b.stats.get("timed_out")
                         for b in best),
        "early_stopped": False,
        "constructed": False,
        "warm_started": False,
        "resumed_from_checkpoint": False,
        "rounds_run": int(sum(int(b.stats.get("rounds_run") or 0)
                              for b in best if b is not None)),
        "time_limit_s": time_limit_s,
        "bucket_parts": first.get("bucket_parts"),
        "bucket_rf": first.get("bucket_rf"),
        "decompose": {
            "subproblems": G,
            "groups": list(sp.group_names),
            "iterations": iters,
            "boundary_parts": int(sp.boundary.sum()),
            "moved_for_slack": int(sp.moved_for_slack),
            "certified": proved,
            "bound_gap": int(gap or 0),
            "uniform_shape": bool(sp.uniform_shape),
            "lane_fallback": bool(first.get("lane_fallback")),
            "sub_shape": sub_shape,
        },
    }
    return SolveResult(
        a=a, solver="tpu",
        wall_clock_s=time.perf_counter() - t0,
        objective=w, optimal=proved, stats=stats,
    )


def _rank(r) -> tuple:
    """Lane ordering for keep-best across iterations."""
    if r is None:
        return (-1, -1)
    return (1 if r.stats.get("feasible") else 0,
            int(r.objective if r.objective is not None else -1))


def config_snapshot() -> dict:
    """The /healthz ``decompose`` section: selection config, counters,
    sub-bucket ladder, and whether the last sub-bucket's map-lane
    executable is warm in-process (bucket.STATS affinity ledger)."""
    from ..solvers.tpu import bucket

    snap = STATS.snapshot()
    last = snap["last"]
    sub = (last.get("sub_shape") or {})
    warm = False
    if sub.get("brokers") is not None:
        want = [sub.get("brokers"), sub.get("racks"),
                sub.get("bucket_parts"), sub.get("bucket_rf")]
        warm = any(list(k)[:4] == want for k in bucket.STATS.seen())
    return {
        "mode": mode(),
        "auto_parts": auto_parts(),
        "max_iters": max_iters(),
        "sub_bucket_ladder": bucket.ladder(8),
        "lane_ladder": bucket.lane_ladder(),
        "map_lane_warm": warm,
        "counters": snap["counters"],
        "last": last,
    }


__all__ = [
    "COUNTER_NAMES", "STATS", "Split", "config_snapshot",
    "maybe_decompose", "max_iters", "mode", "should_decompose",
    "split_instance", "stitch",
]
