"""Process-global decompose counters — the ``kao_decompose_*``
metric families (serve.py /metrics) and the /healthz ``decompose``
section both read one snapshot, so the views can never disagree.
"""

from __future__ import annotations

import threading

# counter suffixes, pre-declared at zero in /metrics (the PR 11
# rollout-counter discipline: a scrape-time family appearing only
# after its first increment breaks rate() over restarts)
COUNTER_NAMES = (
    "solves",        # decomposed solves that returned a stitched plan
    "certified",     # ... with a global optimality certificate
    "gap_reported",  # ... that reported a bound gap instead
    "fallback",      # decompose_to_flat degradations (failed reduce)
    "unsplittable",  # instances the splitter declined (no structure)
    "subproblems",   # sub-instances solved across all map phases
    "iterations",    # map<->reduce iterations across all solves
)


class DecomposeStats:
    def __init__(self):
        self._lock = threading.Lock()
        self._c = {k: 0 for k in COUNTER_NAMES}
        self._last: dict = {}

    def note_solve(self, *, subproblems: int, iterations: int,
                   certified: bool, bound_gap: int | None,
                   sub_shape: dict | None) -> None:
        with self._lock:
            self._c["solves"] += 1
            self._c["subproblems"] += int(subproblems)
            self._c["iterations"] += int(iterations)
            if certified:
                self._c["certified"] += 1
            else:
                self._c["gap_reported"] += 1
            self._last = {
                "subproblems": int(subproblems),
                "iterations": int(iterations),
                "certified": bool(certified),
                "bound_gap": 0 if certified else int(bound_gap or 0),
                "sub_shape": dict(sub_shape or {}),
            }

    def note_fallback(self, iterations: int = 0,
                      subproblems: int = 0) -> None:
        with self._lock:
            self._c["fallback"] += 1
            self._c["iterations"] += int(iterations)
            self._c["subproblems"] += int(subproblems)

    def note_unsplittable(self) -> None:
        with self._lock:
            self._c["unsplittable"] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"counters": dict(self._c), "last": dict(self._last)}

    def reset(self) -> None:
        """Test isolation only."""
        with self._lock:
            self._c = {k: 0 for k in COUNTER_NAMES}
            self._last = {}


STATS = DecomposeStats()
