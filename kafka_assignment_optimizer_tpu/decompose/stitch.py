"""Reduce-phase stitch: per-group local plans -> one global plan.

Pure index translation — each group's local broker indices map back
through its global broker list (local null ``B_g`` -> global null
``B``), and each group's partition rows scatter into their original
global positions. Because sub-feasibility nests under the flat
instance (see split.py), the stitched plan needs no repair; the
orchestrator still runs the flat instance's oracle
(``inst.violations``) over the result so quality is never taken on
faith.

KAO112 (analysis/rules_ast.py): decompose HOT module — per-partition
work stays vectorized; Python loops range only over groups.
"""

from __future__ import annotations

import numpy as np

from ..models.instance import ProblemInstance
from .split import Split


def stitch(inst: ProblemInstance, sp: Split,
           lane_plans: list[np.ndarray]) -> np.ndarray:
    """Scatter each group's local plan ``[P_g, R]`` back into a global
    ``[P, R]`` candidate in flat broker-index space."""
    P, R = inst.a0.shape
    B = inst.num_brokers
    a = np.full((P, R), B, np.int32)
    for g in range(sp.n_groups):
        glob = np.append(sp.broker_idx[g], B).astype(np.int32)
        a[sp.part_idx[g]] = glob[np.asarray(lane_plans[g], np.int64)]
    return a


def lane_feasible(lane_results) -> list[bool]:
    """Per-lane feasibility flags from the map phase's SolveResults."""
    return [bool(r is not None and r.stats.get("feasible"))
            for r in lane_results]
