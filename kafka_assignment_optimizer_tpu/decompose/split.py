"""Map-phase split: an AZ/rack-structured instance -> per-AZ
sub-instances whose feasibility NESTS under the flat instance.

The nesting argument (docs/DECOMPOSE.md): every sub-instance inherits
the flat instance's band values verbatim — ``broker_lo/hi`` and
``leader_lo/hi`` as the same global scalars, ``rack_lo/hi`` and
``part_rack_hi`` as slices of the same global arrays. Because the
broker and rack axes are *partitioned* across groups (each broker and
each rack belongs to exactly one group) and every partition is
assigned wholly to one group, a plan that satisfies every sub-instance
satisfies every constraint family of the flat instance exactly — the
stitched plan is globally feasible *by construction*, and the reduce
phase's oracle check is a redundant proof, not a repair pass.

What the splitter must therefore guarantee up front is only
*admissibility*: each group's partition load must land inside the
windows the inherited bands imply (replicas in
``[max(broker_lo*B_g, sum rack_lo_g), min(broker_hi*B_g, sum
rack_hi_g)]``, leaders in ``[leader_lo*B_g, leader_hi*B_g]``) and each
partition must be *placeable* in its group (``rf <= B_g`` and
``sum_k min(part_rack_hi, rack_size_k) >= rf``). The band-slack
reconciliation below moves boundary partitions between groups until
every window holds, or reports the instance undecomposable (None ->
the flat path).

KAO112 (analysis/rules_ast.py): this is a decompose HOT module — all
per-partition work is vectorized numpy; Python loops may range only
over groups/racks.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..models.instance import ProblemInstance

# bounded reconciliation: each pass fixes the worst window violation by
# moving partitions between one (donor, receiver) pair; 4 passes per
# group pair is far past what any band geometry needs to converge
_RECONCILE_PASSES_PER_GROUP = 8


@dataclass
class Split:
    """One decomposition: group structure + extracted sub-instances."""

    n_groups: int
    group_names: list[str]
    group_of_rack: np.ndarray  # [K] int32
    group_of_part: np.ndarray  # [P] int32
    boundary: np.ndarray  # [P] bool — current members span >1 group
    subs: list[ProblemInstance]
    part_idx: list[np.ndarray]  # global partition indices per group
    broker_idx: list[np.ndarray]  # global broker indices per group
    moved_for_slack: int  # partitions re-homed by band reconciliation

    @property
    def uniform_shape(self) -> bool:
        """All groups share (brokers, racks) — the stacking invariant
        that lets the map phase run ONE lane-padded executable."""
        shapes = {(s.num_brokers, s.num_racks) for s in self.subs}
        return len(shapes) == 1


def infer_groups(inst: ProblemInstance):
    """Group racks by AZ prefix (rack names like ``az0-rack1`` group on
    the text before the last ``-``). Returns ``(names, group_of_rack)``
    or None when the topology carries no usable group structure
    (unprefixed racks, or fewer than 2 groups)."""
    prefixes = []
    for n in inst.rack_names:  # racks, not partitions (KAO112-clean)
        if "-" not in str(n):
            return None
        prefixes.append(str(n).rsplit("-", 1)[0])
    uniq = sorted(set(prefixes))
    if len(uniq) < 2:
        return None
    gmap = {p: i for i, p in enumerate(uniq)}
    return uniq, np.array([gmap[p] for p in prefixes], np.int32)


def split(inst: ProblemInstance) -> Split | None:
    """Build the decomposition, or None when the instance is not
    decomposable (no group structure, a partition no group can place,
    or band windows that no reconciliation satisfies)."""
    got = infer_groups(inst)
    if got is None:
        return None
    names, g_rack = got
    G = len(names)
    B, P, K = inst.num_brokers, inst.num_parts, inst.num_racks
    g_broker = g_rack[inst.rack_of_broker[:B]]
    sizes_b = np.bincount(g_broker, minlength=G).astype(np.int64)
    if int(sizes_b.min()) == 0:
        return None

    # per-(partition, group) current-member counts, one bincount over
    # the flattened (p, group) key — null slots (a0 == B) land in the
    # discarded G column
    g_ext = np.append(g_broker, G).astype(np.int64)
    key = (np.arange(P, dtype=np.int64)[:, None] * (G + 1)
           + g_ext[inst.a0]).ravel()
    cnt = np.bincount(key, minlength=P * (G + 1)).reshape(
        P, G + 1)[:, :G].astype(np.int64)
    boundary = (cnt > 0).sum(axis=1) > 1

    # placeability: group g can host partition p iff rf_p <= B_g and
    # the group's racks admit rf_p replicas under part_rack_hi
    rack_size = np.bincount(inst.rack_of_broker[:B],
                            minlength=K).astype(np.int64)
    cap_pk = np.minimum(inst.part_rack_hi.astype(np.int64)[:, None],
                        rack_size[None, :])  # [P, K]
    fit = np.empty((P, G), bool)
    for g in range(G):
        fit[:, g] = (cap_pk[:, g_rack == g].sum(axis=1)
                     >= inst.rf) & (inst.rf <= sizes_b[g])
    if not fit.any(axis=1).all():
        return None  # some partition fits no group: undecomposable

    # home each partition with its current-member majority, restricted
    # to fitting groups; memberless partitions take their first fit
    score = np.where(fit, cnt, -1)
    g_part = np.argmax(score, axis=1).astype(np.int32)

    # band-slack reconciliation: inherited global bands imply per-group
    # replica/leader windows; move least-attached partitions between
    # groups until every window holds
    rf64 = inst.rf.astype(np.int64)
    rack_lo_g = np.array(
        [int(inst.rack_lo[g_rack == g].sum()) for g in range(G)],
        np.int64)
    rack_hi_g = np.array(
        [int(inst.rack_hi[g_rack == g].sum()) for g in range(G)],
        np.int64)
    r_lo = np.maximum(inst.broker_lo * sizes_b, rack_lo_g)
    r_hi = np.minimum(inst.broker_hi * sizes_b, rack_hi_g)
    p_lo = inst.leader_lo * sizes_b
    p_hi = inst.leader_hi * sizes_b
    moved = 0
    for _ in range(_RECONCILE_PASSES_PER_GROUP * G):
        r_g = np.bincount(g_part, weights=rf64,
                          minlength=G).astype(np.int64)
        p_g = np.bincount(g_part, minlength=G).astype(np.int64)
        if ((r_g > r_hi).any() or (r_g < r_lo).any()
                or (p_g > p_hi).any() or (p_g < p_lo).any()):
            pass
        else:
            break
        # worst violation picks the (donor, receiver, amount) move, in
        # the violated unit (replica slots or leader counts)
        over_r, under_r = r_g - r_hi, r_lo - r_g
        over_p, under_p = p_g - p_hi, p_lo - p_g
        if max(over_r.max(), under_r.max()) > 0:
            units, tot, lo, hi = rf64, r_g, r_lo, r_hi
            over, under = over_r, under_r
        else:
            units, tot, lo, hi = np.ones(P, np.int64), p_g, p_lo, p_hi
            over, under = over_p, under_p
        if over.max() >= under.max():
            donor = int(np.argmax(over))
            receiver = int(np.argmax(hi - tot))
        else:
            receiver = int(np.argmax(under))
            donor = int(np.argmax(tot - lo))
        amount = int(min(max(over[donor], under[receiver]),
                         hi[receiver] - tot[receiver],
                         tot[donor] - lo[donor]))
        if donor == receiver or amount <= 0:
            return None  # no slack anywhere to absorb the violation
        cand = np.nonzero((g_part == donor) & fit[:, receiver])[0]
        if cand.size == 0:
            return None
        # move the partitions least attached to the donor first (and
        # most attached to the receiver): minimal preservation loss
        order = cand[np.argsort(cnt[cand, donor] - cnt[cand, receiver],
                                kind="stable")]
        take = int(np.searchsorted(np.cumsum(units[order]), amount) + 1)
        take = min(take, order.size)
        g_part[order[:take]] = receiver
        moved += take
    else:
        return None  # reconciliation did not converge

    # per-rack admissibility audit: within a group the inherited
    # proportional rack bands must be reachable under the per-partition
    # diversity caps. For rack k of group g:
    #   achievable ceiling  sum_p min(prh_p, size_k)   >= rack_lo_k
    #   forced floor  sum_p max(0, rf_p - cap(other racks)) <= rack_hi_k
    # (a group whose largest rack's proportional share exceeds
    # P_g * prh, or whose rack count pins every partition onto a small
    # rack, is undecomposable under inherited bands -> flat path)
    for g in range(G):
        in_g = g_part == g
        racks_g = np.nonzero(g_rack == g)[0]
        rowsum = cap_pk[np.ix_(in_g.nonzero()[0], racks_g)]  # [Pg, Kg]
        total = rowsum.sum(axis=1)
        rf_g = rf64[in_g]
        ceil_k = rowsum.sum(axis=0)
        floor_k = np.maximum(
            rf_g[:, None] - (total[:, None] - rowsum), 0).sum(axis=0)
        if ((ceil_k < inst.rack_lo[racks_g]).any()
                or (floor_k > inst.rack_hi[racks_g]).any()):
            return None

    # extraction: pure index translation, one vectorized gather per
    # group — local broker/rack ids via lookup arrays (null B -> B_g,
    # null rack K -> K_g)
    subs, part_idx, broker_idx = [], [], []
    for g in range(G):
        Pg = np.nonzero(g_part == g)[0]
        Sg = np.nonzero(g_broker == g)[0]
        Rg = np.nonzero(g_rack == g)[0]
        if Pg.size == 0:
            return None  # empty lane: nothing to stack
        Bg, Kg = int(Sg.size), int(Rg.size)
        loc = np.full(B + 1, Bg, np.int32)
        loc[Sg] = np.arange(Bg, dtype=np.int32)
        rloc = np.full(K + 1, Kg, np.int32)
        rloc[Rg] = np.arange(Kg, dtype=np.int32)
        cols = np.append(Sg, B)  # group brokers + shared null column
        subs.append(ProblemInstance(
            broker_ids=inst.broker_ids[Sg].copy(),
            rack_of_broker=rloc[inst.rack_of_broker[cols]],
            rack_names=[inst.rack_names[int(k)] for k in Rg],
            topics=inst.topics,
            topic_of_part=inst.topic_of_part[Pg].copy(),
            part_id=inst.part_id[Pg].copy(),
            rf=inst.rf[Pg].copy(),
            a0=loc[inst.a0[Pg]],
            current=None,
            w_leader=np.ascontiguousarray(inst.w_leader[np.ix_(Pg, cols)]),
            w_follower=np.ascontiguousarray(
                inst.w_follower[np.ix_(Pg, cols)]),
            broker_lo=inst.broker_lo, broker_hi=inst.broker_hi,
            leader_lo=inst.leader_lo, leader_hi=inst.leader_hi,
            rack_lo=inst.rack_lo[Rg].copy(),
            rack_hi=inst.rack_hi[Rg].copy(),
            part_rack_hi=inst.part_rack_hi[Pg].copy(),
        ))
        part_idx.append(Pg)
        broker_idx.append(Sg)
    return Split(
        n_groups=G, group_names=list(names), group_of_rack=g_rack,
        group_of_part=g_part, boundary=boundary, subs=subs,
        part_idx=part_idx, broker_idx=broker_idx,
        moved_for_slack=moved,
    )
