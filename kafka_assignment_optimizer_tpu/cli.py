"""CLI — JSON in/out mirroring the Kafka tooling UX the reference slots
into (``kafka-reassign-partitions`` style, ``/root/reference/README.md:35-48``).

Usage:
    python -m kafka_assignment_optimizer_tpu \
        --input current.json --broker-list 0-18 --topology topology.json \
        [--rf 3] [--solver auto|milp|lp_solve|native|tpu] [--report]

Reads the current assignment (reassignment JSON) from ``--input`` or stdin,
writes the optimized plan (same dialect, ``README.md:67-78``) to stdout,
and an observability report (moves, violations, objective, wall-clock —
SURVEY.md §5) to stderr with ``--report``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .api import optimize
from .models.cluster import Assignment, Topology, parse_broker_list


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="kafka_assignment_optimizer_tpu",
        description="Minimal-move, rack-aware Kafka partition reassignment "
        "optimizer (TPU-native rebuild of kafka-assignment-optimizer).",
    )
    ap.add_argument("--input", "-i", help="current assignment JSON file (default: stdin)")
    ap.add_argument("--output", "-o", help="write plan JSON here (default: stdout)")
    ap.add_argument(
        "--broker-list",
        help="target brokers, e.g. '0,1,2' or '0-18' (README.md:48); "
        "required except with --events (the event stream carries its "
        "own broker lists)",
    )
    ap.add_argument(
        "--topology",
        help="broker->rack map: JSON file, inline JSON, or 'even-odd' "
        "(the reference demo topology, README.md:27-29). Default: one rack.",
    )
    ap.add_argument(
        "--rf",
        help="target replication factor (RF change): an int for all "
        "topics, or an inline/file JSON object mapping topic -> RF "
        '(e.g. \'{"logs": 3}\'; unlisted topics keep their current RF)',
    )
    ap.add_argument(
        "--solver",
        default="auto",
        help="auto | milp | lp_solve | native | tpu (BASELINE.json:5)",
    )
    ap.add_argument("--report", action="store_true", help="print solve report to stderr")
    ap.add_argument("--indent", type=int, default=2, help="output JSON indent")
    # TPU engine knobs (SURVEY.md §5 config system)
    ap.add_argument("--seed", type=int, default=0, help="search RNG seed")
    ap.add_argument("--batch", type=int, help="candidates per device (tpu solver)")
    ap.add_argument("--sweeps", type=int, help="annealing outer iterations (tpu solver)")
    ap.add_argument(
        "--engine",
        choices=["chain", "sweep"],
        help="tpu solver inner engine: per-move Metropolis chains (small "
        "instances) or sweep-parallel proposals (default above "
        "512 partitions)",
    )
    ap.add_argument("--time-limit", type=float, help="solver time limit seconds")
    ap.add_argument(
        "--no-pipeline",
        action="store_true",
        help="disable the double-buffered ladder dispatch (tpu solver; "
        "docs/PIPELINE.md): chunks then run strictly one at a time, "
        "with all boundary work on the critical path — the A/B and "
        "debugging escape hatch; results are bit-identical either way",
    )
    ap.add_argument(
        "--megachunk",
        default=None,
        metavar="K|auto|off",
        help="fused ladder megachunks (tpu sweep engine; "
        "docs/PIPELINE.md): run K consecutive schedule chunks as ONE "
        "device-resident scan dispatch — bit-identical to the "
        "per-chunk ladder, K fewer host round-trips. An int pins the "
        "width, 'auto' reads the per-bucket evidence table, 'off' "
        "keeps the per-chunk dispatcher (same as KAO_MEGACHUNK)",
    )
    ap.add_argument(
        "--decompose",
        action="store_true",
        help="force the decomposed map-reduce solve path (tpu solver; "
        "docs/DECOMPOSE.md): split the AZ/rack-structured instance "
        "into per-AZ sub-instances, solve them as one lane-padded "
        "batch, stitch and oracle-verify the global plan. Auto-"
        "selected above KAO_DECOMPOSE_AUTO_PARTS partitions; "
        "KAO_DECOMPOSE=0 disables everywhere",
    )
    ap.add_argument(
        "--checkpoint",
        metavar="PATH",
        help="warm-start from / save the best plan to this .npz (tpu solver); "
        "re-solves of the same instance never regress below it",
    )
    ap.add_argument(
        "--profile-dir",
        metavar="DIR",
        help="write a jax.profiler trace of the solve loop here (tpu solver)",
    )
    ap.add_argument(
        "--trace",
        action="store_true",
        help="record a span-level solve trace (tpu solver; see "
        "docs/OBSERVABILITY.md): the solve report — phase spans + "
        "annealing trajectory — is attached to the stderr report as "
        "'solve_report' (implies --report)",
    )
    ap.add_argument(
        "--flight-dir",
        metavar="DIR",
        help="solve-cost flight recorder (docs/OBSERVABILITY.md): "
        "append one compact JSONL cost+quality record per solve under "
        "this directory (crash-safe, auto-rotated; same as "
        "KAO_FLIGHT_DIR). Inspect with 'kao-trace flight DIR'",
    )
    ap.add_argument(
        "--emit-lp",
        metavar="PATH",
        help="also write the lp_solve LP-format equation file (README.md:144-185)",
    )
    ap.add_argument(
        "--emit-waves",
        metavar="DIR",
        help="streaming rollout (docs/ROLLOUT.md): also decompose the "
        "plan into bandwidth-budgeted move waves and write one "
        "reassignment JSON file per wave (wave-000.json, ...) under "
        "DIR — each file byte-compatible with the plan output schema "
        "(README.md:52-78), applied in file order; within a wave, "
        "leader-changing moves come last",
    )
    ap.add_argument(
        "--wave-broker-cap",
        type=int,
        default=None,
        metavar="N",
        help="--emit-waves: per-wave transfer cap per broker in "
        "transfer units (replica copies in + out; default 4, raised "
        "to the largest single move when below it)",
    )
    ap.add_argument(
        "--wave-rack-cap",
        type=int,
        default=None,
        metavar="N",
        help="--emit-waves: per-wave inbound transfer cap per rack "
        "(default 16)",
    )
    ap.add_argument(
        "--wave-packer",
        choices=["greedy", "scored"],
        default=None,
        help="--emit-waves: wave packer (default greedy; 'scored' "
        "races diverse move orderings and keeps the packing "
        "minimizing makespan x peak cross-rack traffic; same as "
        "KAO_ROLLOUT_PACKER)",
    )
    ap.add_argument(
        "--evaluate",
        metavar="PLAN.json",
        help="audit an existing plan instead of solving: print its "
        "feasibility, violation counts, moves vs the provable minimum, "
        "and optimality verdict (e.g. score kafka-reassign-partitions "
        "output, README.md:65-91)",
    )
    ap.add_argument(
        "--sanitize",
        action="store_true",
        help="runtime sanitizer mode (same as KAO_SANITIZE=1; see "
        "docs/ANALYSIS.md): jax_debug_nans, a recompile sentinel on "
        "the executable cache, and a donation use-after-free guard — "
        "trips fail the solve loudly instead of corrupting it quietly",
    )
    ap.add_argument(
        "--chaos",
        default=None,
        metavar="SPEC",
        help="arm the fault-injection harness for this solve (same as "
        "KAO_CHAOS; docs/RESILIENCE.md), e.g. 'seed=7,pallas_fault' — "
        "the solve must still return a valid certified-or-degraded "
        "plan, with every degradation rung in the --report stats",
    )
    ap.add_argument(
        "--events",
        metavar="FILE",
        help="cluster-watch replay (docs/WATCH.md): apply a JSON file "
        "of epoch-fenced change events — a list, or {'cluster_id', "
        "'events': [...]} — through the same fencing/warm-start "
        "machinery the serve delta API runs. The first event of an "
        "unknown cluster must be a 'bootstrap'. Prints the final plan "
        "to stdout and a per-event report line to stderr; --input / "
        "--broker-list are not used",
    )
    ap.add_argument(
        "--cluster-id",
        default="default",
        help="cluster name for --events (default: 'default')",
    )
    ap.add_argument(
        "--watch-dir",
        metavar="DIR",
        help="durable plan store for --events: state + last certified "
        "plan per cluster persist here (atomic, fingerprint-verified), "
        "so a later replay resumes at the stored epoch",
    )
    ap.add_argument(
        "--distributed",
        action="store_true",
        help="initialize jax's multi-host runtime before solving. Run "
        "the CLI under a pod launcher on EVERY worker with the same "
        "input (multi-controller SPMD; cluster auto-detected by jax, "
        "or JAX_COORDINATOR_ADDRESS). No-op on single-host launches — "
        "see parallel/distributed.py",
    )
    return ap


def _spec_text(spec: str) -> str:
    """Resolve a flag value that may be a file path or inline text."""
    p = Path(spec)
    return p.read_text() if p.exists() else spec


def parse_megachunk(spec: str):
    """``--megachunk``: an int width, 'auto', or 'off'. A typo fails
    loudly (the engine-side resolver is tolerant because it also eats
    env values; the CLI's contract is exit 2 on bad flags)."""
    v = spec.strip().lower()
    if v == "auto":
        return "auto"
    if v in ("off", "0", "none"):
        return "off"
    try:
        return max(1, int(v))
    except ValueError:
        raise ValueError(
            f"--megachunk {spec!r}: expected an integer width, "
            "'auto', or 'off'"
        ) from None


def parse_rf(spec: str | None) -> int | dict | None:
    """``--rf``: an int, inline JSON object, or a JSON file path."""
    if spec is None:
        return None
    try:
        return int(spec)
    except ValueError:
        pass
    try:
        rf = json.loads(_spec_text(spec))
    except json.JSONDecodeError as e:
        raise ValueError(
            f"--rf {spec!r} is neither an int, an existing JSON file, "
            f"nor valid inline JSON ({e})"
        ) from e
    if not isinstance(rf, dict) or not all(
        isinstance(v, int) and not isinstance(v, bool) for v in rf.values()
    ):
        raise ValueError(
            "--rf must be an int or a topic->int JSON object"
        )
    return rf


def load_topology(spec: str | None, broker_ids: list[int]) -> Topology | None:
    if spec is None:
        return None
    if spec == "even-odd":
        return Topology.even_odd(broker_ids)
    return Topology.from_json(_spec_text(spec))


def main(argv: list[str] | None = None) -> int:
    from .utils.platform import pin_platform

    pin_platform()
    try:
        return _run(build_parser().parse_args(argv))
    except (ValueError, KeyError, FileNotFoundError, RuntimeError, OSError) as e:
        msg = e.args[0] if e.args and isinstance(e.args[0], str) else e
        # kao: disable=KAO106 -- "error: ..." on stderr is the CLI's UX contract
        print(f"error: {msg}", file=sys.stderr)
        return 2
    except json.JSONDecodeError as e:
        # kao: disable=KAO106 -- "error: ..." on stderr is the CLI's UX contract
        print(f"error: invalid JSON input: {e}", file=sys.stderr)
        return 2


def _run(args: argparse.Namespace) -> int:
    if args.sanitize:
        from .analysis import sanitize as _sanitize

        _sanitize.enable()
    import os

    flight_dir = args.flight_dir or os.environ.get("KAO_FLIGHT_DIR")
    if flight_dir:
        from .obs import flight as _flight

        try:
            _flight.configure(flight_dir)
        except OSError as e:
            # name the flag in the message (main() renders ValueError
            # as the CLI's clean "error: ..." exit-2 contract)
            raise ValueError(
                f"--flight-dir {flight_dir!r}: {e}"
            ) from e
    if args.chaos:
        from .resilience import chaos as _chaos

        try:
            _chaos.arm(args.chaos)
        except ValueError as e:
            # kao: disable=KAO106 -- "error: ..." on stderr is the CLI's UX contract
            print(f"error: bad --chaos spec: {e}", file=sys.stderr)
            return 2
    if args.distributed:
        from .parallel.distributed import init_distributed

        init_distributed()
    if args.events:
        return _run_events(args)
    if not args.broker_list:
        raise ValueError("--broker-list is required (unless --events)")
    text = Path(args.input).read_text() if args.input else sys.stdin.read()
    current = Assignment.from_json(text)
    target_rf = parse_rf(args.rf)
    brokers = parse_broker_list(args.broker_list)
    all_ids = sorted(set(brokers) | set(current.broker_ids()))
    topology = load_topology(args.topology, all_ids)

    if args.evaluate:
        from .api import evaluate

        rep = evaluate(
            current,
            brokers,
            Path(args.evaluate).read_text(),
            topology,
            target_rf=target_rf,
        )
        if args.emit_waves:
            # waves for an AUDITED plan (ours or another tool's): the
            # same current -> plan decomposition the solve path emits
            rep["waves"] = _emit_waves(
                args, current,
                Assignment.from_json(Path(args.evaluate).read_text()),
                topology,
            )
        out = json.dumps(rep, indent=args.indent, default=str)
        if args.output:
            Path(args.output).write_text(out + "\n")
        else:
            # kao: disable=KAO106 -- the report JSON on stdout IS the product
            print(out)
        return 0 if rep["feasible"] else 3

    kw: dict = {}
    if args.seed is not None:
        kw["seed"] = args.seed
    if args.batch:
        kw["batch"] = args.batch
    if args.sweeps:
        kw["sweeps"] = args.sweeps
    if args.engine:
        kw["engine"] = args.engine
    if args.checkpoint:
        kw["checkpoint"] = args.checkpoint
    if args.profile_dir:
        kw["profile_dir"] = args.profile_dir
    if args.trace:
        kw["trace"] = True
    if args.time_limit:
        kw["time_limit_s"] = args.time_limit
    if args.no_pipeline:
        kw["pipeline"] = False
    if args.megachunk is not None:
        kw["megachunk"] = parse_megachunk(args.megachunk)
    if args.decompose:
        kw["decompose"] = True

    res = optimize(
        current,
        brokers,
        topology,
        target_rf=target_rf,
        solver=args.solver,
        **kw,
    )

    if args.emit_lp:
        from .solvers.lp import emit_lp

        Path(args.emit_lp).write_text(emit_lp(res.instance))

    wave_summary = None
    if args.emit_waves:
        wave_summary = _emit_waves(args, current, res.assignment,
                                   topology)

    out = res.assignment.to_json(indent=args.indent)
    if args.output:
        Path(args.output).write_text(out + "\n")
    else:
        # kao: disable=KAO106 -- the plan JSON on stdout IS the product
        print(out)
    rep = res.report()
    if args.trace and "solve_report" in res.solve.stats:
        rep["solve_report"] = res.solve.stats["solve_report"]
    if wave_summary is not None:
        rep["waves"] = wave_summary
    if args.report or args.trace:
        # kao: disable=KAO106 -- --report's stderr JSON is the CLI's UX contract
        print(json.dumps(rep, indent=2, default=str), file=sys.stderr)
    return 0 if rep["feasible"] else 3


def _emit_waves(args: argparse.Namespace, current, plan_assignment,
                topology) -> dict:
    """``--emit-waves DIR``: write one upstream-compatible reassignment
    JSON file per bandwidth-budgeted wave (docs/ROLLOUT.md). File order
    is application order; each file is the exact dialect
    ``kafka-reassign-partitions --execute`` accepts, so an operator can
    feed the waves to the stock tooling one at a time."""
    from .rollout.exec import wave_json
    from .rollout.waves import (
        DEFAULT_BROKER_CAP,
        DEFAULT_RACK_CAP,
        WaveCaps,
        pack_waves,
    )

    caps = WaveCaps(
        broker=(args.wave_broker_cap if args.wave_broker_cap is not None
                else DEFAULT_BROKER_CAP),
        rack=(args.wave_rack_cap if args.wave_rack_cap is not None
              else DEFAULT_RACK_CAP),
    )
    plan = pack_waves(current, plan_assignment, topology, caps=caps,
                      packer=args.wave_packer, seed=args.seed or 0)
    outdir = Path(args.emit_waves)
    outdir.mkdir(parents=True, exist_ok=True)
    files = []
    for w in plan.waves:
        path = outdir / f"wave-{w.index:03d}.json"
        path.write_text(json.dumps(wave_json(w), indent=2) + "\n")
        files.append(path.name)
    return {
        "dir": str(outdir),
        "files": files,
        "makespan": plan.makespan,
        "caps": plan.caps.to_dict(),
        "packer": plan.packer,
        "peak_broker": plan.peak_broker,
        "peak_rack": plan.peak_rack,
        "peak_cross_rack": plan.peak_cross_rack,
    }


def _run_events(args: argparse.Namespace) -> int:
    """``--events``: offline replay of a cluster-change stream through
    the watch state machine (docs/WATCH.md) — fencing, durable store,
    and warm-started delta solves identical to the serve delta API,
    minus the HTTP."""
    from .api import optimize_delta
    from .watch.manager import FencedEpoch, WatchRegistry
    from .watch.store import PlanStore

    doc = json.loads(Path(args.events).read_text())
    if isinstance(doc, dict):
        cluster_id = doc.get("cluster_id", args.cluster_id)
        events = doc.get("events")
    else:
        cluster_id, events = args.cluster_id, doc
    if not isinstance(events, list) or not events:
        raise ValueError(
            "--events file must be a non-empty list of events or "
            "{'cluster_id', 'events': [...]}"
        )

    kw: dict = {"seed": args.seed or 0}
    if args.batch:
        kw["batch"] = args.batch
    if args.sweeps:
        kw["sweeps"] = args.sweeps
    if args.engine:
        kw["engine"] = args.engine
    if args.time_limit:
        kw["time_limit_s"] = args.time_limit
    if args.no_pipeline:
        kw["pipeline"] = False
    if args.megachunk is not None:
        kw["megachunk"] = parse_megachunk(args.megachunk)

    def solve_fn(state, prev_plan, budget):
        res = optimize_delta(
            state.assignment, state.brokers, state.topology,
            target_rf=state.rf, prev_plan=prev_plan,
            solver=args.solver, **kw,
        )
        return res.assignment.to_dict(), res.report()

    store = PlanStore(args.watch_dir) if args.watch_dir else None
    reg = WatchRegistry(solve_fn, store, window_s=0.0)
    last_plan = None
    rc = 0
    for i, ev in enumerate(events):
        try:
            out = reg.handle_event(cluster_id, ev)
        except FencedEpoch as e:
            # kao: disable=KAO106 -- per-event stderr lines are the replay's UX contract
            print(f"event[{i}] FENCED: {e}", file=sys.stderr)
            rc = 3
            continue
        rep = out.get("report") or {}
        # kao: disable=KAO106 -- per-event stderr lines are the replay's UX contract
        print(
            f"event[{i}] type={ev.get('type')} epoch={out['epoch']} "
            f"status={out['status']} "
            f"moves={rep.get('replica_moves')} "
            f"feasible={rep.get('feasible')} "
            f"warm={bool(rep.get('solver_warm_started'))}",
            file=sys.stderr,
        )
        if out.get("assignment") is not None:
            last_plan = out["assignment"]
        if rep and not rep.get("feasible", True):
            rc = 3
    if last_plan is None:
        info = reg.get_cluster(cluster_id) or {}
        last_plan = info.get("plan")
    out_text = json.dumps(last_plan, indent=args.indent)
    if args.output:
        Path(args.output).write_text(out_text + "\n")
    else:
        # kao: disable=KAO106 -- the final plan JSON on stdout IS the product
        print(out_text)
    return rc


if __name__ == "__main__":
    sys.exit(main())
