"""``kao-router`` — the bucket-affinity fleet front process
(docs/FLEET.md).

An HTTP proxy over N ordinary ``serve.py`` workers:

``POST /submit``
    The request's executable bucket key is computed HOST-SIDE
    (``fleet.affinity`` — no jax on the router) and the live worker
    set is ranked rendezvous-first, warm-first: the worker whose exec
    cache and lane-padded executables already hold this bucket gets
    the solve, so a fleet serves every bucket at warm latency while
    each worker only ever compiles its owned slice. Failover walks the
    ranking on connect failures and 503 sheds — honoring each worker's
    ``Retry-After`` promise (the precise ``retry_after_s`` float from
    the shed body, scoped to the shed's bucket when it names one) —
    and latency-sensitive requests (a ``deadline_s`` field) may hedge:
    after ``--hedge-ms`` without an answer the next-ranked worker gets
    a duplicate (solves are idempotent pure compute), first answer
    wins, capped by the ``--hedge-budget`` concurrent-duplicate
    budget.

``POST /clusters/<id>/events`` and everything under ``/clusters``
    Sticky: one owner worker per cluster id (rendezvous over the live
    set, no warmth bias, no parallel hedging) so epoch fencing still
    sees exactly one writer per cluster. Failover only when the owner
    is dead/shedding — the next rendezvous rank IS the new owner, and
    a shared ``--watch-dir`` (deployment recipe in docs/FLEET.md)
    hands it the durable plan store.

``POST /warmup``
    Fleet warmup orchestration: the shape list is partitioned by
    bucket owner so each bucket compiles exactly ONCE fleet-wide
    (phase 1, owners, concurrent across workers), then — unless
    ``"spread": "owners"`` — every other worker warms the remaining
    buckets from the shared persistent compile cache (phase 2, disk
    hits; the per-shape ``persistent.misses`` deltas in the response
    are the proof nothing compiled twice).

``GET /healthz`` / ``GET /metrics``
    The router's own state: per-worker liveness/warmth/cooldowns,
    affinity hit rate, and the ``kao_router_*`` + ``kao_trace_*``
    families (shared exposition helpers, validated by
    tests/test_metrics_format.py).

``GET /debug/traces`` / ``GET /debug/traces/<trace_id>``
    The fleet trace store (docs/OBSERVABILITY.md "Distributed
    traces"): every routed request runs under a causal trace — route
    decisions, per-worker attempts with their Retry-After verdicts,
    hedge launches and wins — whose context is ``inject()``-ed into
    every upstream call as a W3C ``traceparent`` header. Solve
    traffic (``/submit``) ADOPTS it worker-side, so the solve trace
    carries the SAME trace ID; cluster commands carry the header but
    the delta solve keeps its own ID (event coalescing means one
    solve can serve many clients' events — adopting one would alias
    the rest, and a fenced event provably births no trace at all) and
    joins the story via cluster/epoch attrs and ``rollout_root``
    instead.
    ``/debug/traces/<id>`` fans ``GET /debug/solves/<id>`` out to the
    live workers, unions the remote span trees under the router's root
    span (``obs.causal``), and ``?format=chrome`` exports the merged
    tree as ONE Perfetto file with per-process track groups — the
    hedge duplicate's worker included. Clients carrying their own
    ``traceparent`` are joined to it; responses echo the context and a
    successful ``/submit`` envelope carries ``route``: the answering
    worker plus both attempt span IDs (primary + hedge), so a hedge
    win is attributable client-side. ``KAO_TRACE_TAIL`` arms
    tail-based retention on the router's ring exactly as on workers.

The router is stdlib-only and never imports jax (pinned by test).
"""

from __future__ import annotations

import argparse
import http.client
import json
import sys
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..obs import causal as _ocausal
from ..obs import chrome as _ochrome
from ..obs import expo as _expo
from ..obs import log as _olog
from ..obs import trace as _otrace
from . import affinity as _aff
from .health import FleetTracker

__all__ = ["Router", "make_router_server", "render_router_metrics",
           "main"]

MAX_BODY_BYTES = 64 << 20

DEFAULT_LOCK_WAIT_S = 30.0
DEFAULT_SOLVE_TIMEOUT_S = 600.0
DEFAULT_CONNECT_TIMEOUT_S = 5.0
DEFAULT_HEDGE_MS = 250.0
DEFAULT_HEDGE_BUDGET = 2

_RETRY_REASONS = ("connect_fail", "shed", "cooldown_wait", "error")


class Router:
    """Routing state + policy. Pure logic over a :class:`FleetTracker`
    — the HTTP handler below is a thin shell, so tests drive this
    class directly against fake workers."""

    def __init__(self, tracker: FleetTracker, *,
                 lock_wait_s: float = DEFAULT_LOCK_WAIT_S,
                 solve_timeout_s: float = DEFAULT_SOLVE_TIMEOUT_S,
                 connect_timeout_s: float = DEFAULT_CONNECT_TIMEOUT_S,
                 hedge_ms: float = DEFAULT_HEDGE_MS,
                 hedge_budget: int = DEFAULT_HEDGE_BUDGET):
        self.tracker = tracker
        self.lock_wait_s = float(lock_wait_s)
        self.solve_timeout_s = float(solve_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.hedge_s = max(float(hedge_ms), 0.0) / 1e3
        self.hedge_budget = max(int(hedge_budget), 0)
        self._hedges_inflight = 0
        self._lock = threading.Lock()
        self._rr = 0  # round-robin cursor for unkeyed routes
        self.counters = {
            "requests_total": {},        # route -> n
            "affinity_hits_total": 0,    # keyed request -> warm worker
            "affinity_misses_total": 0,  # keyed request -> cold worker
            "affinity_unkeyed_total": 0,  # no computable bucket key
            "retries_total": {r: 0 for r in _RETRY_REASONS},
            "hedges_total": 0,
            "hedge_wins_total": 0,
            "sticky_total": 0,           # cluster-sticky routed
            "exhausted_total": 0,        # router-originated 503s
            "warmups_total": 0,
            "proxied_total": 0,          # upstream responses relayed
        }

    # -- low-level proxy ---------------------------------------------

    def _proxy_once(self, url: str, method: str, path: str,
                    body: bytes | None, timeout: float,
                    headers: dict | None = None,
                    ) -> tuple[int, dict, bytes]:
        """One upstream exchange. Raises OSError-family on transport
        failure; returns (status, headers, body) otherwise. Connect
        runs under the SHORT timeout (a dead host must fail over in
        seconds), then the socket is re-armed with the long read
        timeout (a solve may legitimately hold the line for minutes).
        ``headers`` carries per-attempt extras — the ``traceparent``
        context _attempt_one injects (KAO111)."""
        parsed = urllib.parse.urlsplit(url)
        conn_cls = (http.client.HTTPSConnection
                    if parsed.scheme == "https"
                    else http.client.HTTPConnection)
        conn = conn_cls(parsed.hostname, parsed.port,
                        timeout=self.connect_timeout_s)
        try:
            conn.connect()
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            send_headers = {"Content-Type": "application/json",
                            **(headers or {})}
            conn.request(method, path, body=body, headers=send_headers)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

    def _count(self, key, sub=None, n: int = 1) -> None:
        with self._lock:
            c = self.counters[key]
            if isinstance(c, dict):
                c[sub] = c.get(sub, 0) + n
            else:
                self.counters[key] = c + n

    @staticmethod
    def _shed_info(status: int, headers: dict,
                   data: bytes) -> tuple[float, list | None] | None:
        """(retry_after_s, bucket|None) when the response is a 503
        shed; None otherwise. Prefers the body's precise float over
        the integer header."""
        if status != 503:
            return None
        retry_after, bucket = None, None
        try:
            body = json.loads(data)
            retry_after = float(body["retry_after_s"])
            bucket = body.get("bucket")
        except (KeyError, ValueError, TypeError):
            pass
        if retry_after is None:
            # a worker without the precise body float (older build,
            # proxy in between): fall back to the integer header
            try:
                retry_after = float(headers.get("Retry-After", 1))
            except (TypeError, ValueError):
                retry_after = 1.0
        if not isinstance(bucket, list):
            bucket = None
        return max(retry_after, 0.05), bucket

    # -- routing core ------------------------------------------------

    def _ranked(self, key, *, warm: dict | None = None,
                sticky: str | None = None) -> list[str]:
        live = self.tracker.live()
        if sticky is not None:
            return _aff.rendezvous_rank(("cluster", sticky), live)
        if key is None:
            # unkeyed traffic rotates so it cannot convoy one worker
            with self._lock:
                self._rr += 1
                rot = self._rr
            ranked = sorted(live)
            return ranked[rot % len(ranked):] + \
                ranked[: rot % len(ranked)] if ranked else []
        return _aff.rank_workers(key, live, warm)

    def route(self, method: str, path: str, body: bytes | None, *,
              key=None, sticky: str | None = None,
              hedge: bool = False,
              timeout: float | None = None,
              info: dict | None = None) -> tuple[int, dict, bytes]:
        """Proxy one request with ranked failover. Returns the first
        non-shed upstream answer (any status — a worker's 400/422/500
        is a real verdict and is relayed), failing over on transport
        errors and 503 sheds while honoring per-worker Retry-After.
        Exhaustion returns a router-originated 503 with the soonest
        cooldown as Retry-After.

        Runs under the caller's ambient trace when one is active: each
        ranking pass lands a ``route_decision`` span, each upstream
        try an ``attempt`` span (its context ``inject()``-ed
        downstream so the worker's solve tree roots under it), and
        cooldown sleeps a ``cooldown_wait`` span. ``info`` (when given)
        collects the attribution the HTTP shell merges into the
        response envelope: answering worker + both attempt span IDs."""
        timeout = self.solve_timeout_s if timeout is None else timeout
        parent_sp = _otrace.current_span()
        t_end = time.time() + self.lock_wait_s
        first_choice_counted = False
        soonest = None
        while True:
            # ONE warm-map snapshot per pass: ranking and the affinity
            # hit/miss verdict must agree (two snapshots could race a
            # concurrent poll), and the copy is a locked full clone of
            # every worker's ledger — once per pass, not twice
            warm = (self.tracker.warm_map()
                    if key is not None and sticky is None else None)
            ranked = self._ranked(key, warm=warm, sticky=sticky)
            if parent_sp is not None:
                dsp = _otrace.open_span(parent_sp, "route_decision")
                _otrace.close_span(
                    dsp,
                    bucket=(str(list(key)) if key is not None
                            else None),
                    sticky=sticky,
                    ranked=",".join(ranked),
                    warm_first=bool(
                        key is not None and ranked
                        and tuple(key) in (warm or {}).get(
                            ranked[0], ())
                    ),
                )
            if not ranked:
                break
            for url in ranked:
                if self.tracker.cooling_s(url, key) > 0.0:
                    continue
                if not first_choice_counted and sticky is None:
                    # affinity accounting: did the FIRST actually-
                    # attempted worker hold the bucket warm?
                    first_choice_counted = True
                    if key is None:
                        self._count("affinity_unkeyed_total")
                    elif tuple(key) in (warm or {}).get(url, ()):
                        self._count("affinity_hits_total")
                    else:
                        self._count("affinity_misses_total")
                out = self._attempt(url, method, path, body, timeout,
                                    key=key, hedge=hedge,
                                    ranked=ranked,
                                    parent_sp=parent_sp, info=info)
                if out is not None:
                    return out
            # every live worker failed or is cooling down. Cooldowns
            # are re-read AFTER the attempts: a shed observed this
            # pass just started one, and a short Retry-After inside
            # the request's wait budget is worth sleeping out rather
            # than shedding back to the client (whose header-level
            # backoff is a full second at minimum).
            cooling = [self.tracker.cooling_s(u, key) for u in ranked]
            positive = [c for c in cooling if c > 0.0]
            soonest = min(positive) if positive else None
            now = time.time()
            if soonest is None or now + soonest >= t_end:
                break
            self._count("retries_total", "cooldown_wait")
            wsp = _otrace.open_span(parent_sp, "cooldown_wait",
                                    soonest_s=round(soonest, 3))
            time.sleep(min(soonest + 0.01, max(t_end - now, 0.0)))
            _otrace.close_span(wsp)
        self._count("exhausted_total")
        if parent_sp is not None:
            parent_sp.set(exhausted=True)
        retry_after = max(soonest or 1.0, 0.5)
        return 503, {"Retry-After": str(max(1, int(retry_after + 1)))}, \
            json.dumps({
                "error": "no fleet worker accepted the request",
                "reason": "fleet_exhausted",
                "retry_after_s": round(retry_after, 3),
            }).encode()

    def _attempt(self, url: str, method: str, path: str,
                 body: bytes | None, timeout: float, *, key,
                 hedge: bool, ranked: list[str],
                 parent_sp=None,
                 info: dict | None = None,
                 ) -> tuple[int, dict, bytes] | None:
        """One (possibly hedged) upstream attempt; None = try the next
        worker."""
        if hedge and self.hedge_budget > 0:
            # the hedge target must itself be routable RIGHT NOW: not
            # the primary, not inside a Retry-After cooldown, and
            # ranked after the primary (a just-failed earlier worker
            # never becomes the duplicate's target)
            nxt = [u for u in ranked[ranked.index(url) + 1:]
                   if self.tracker.cooling_s(u, key) <= 0.0]
            if nxt:
                return self._attempt_hedged(url, nxt[0], method, path,
                                            body, timeout, key=key,
                                            parent_sp=parent_sp,
                                            info=info)
        return self._attempt_one(url, method, path, body, timeout,
                                 key=key, parent_sp=parent_sp,
                                 info=info)

    def _attempt_one(self, url: str, method: str, path: str,
                     body: bytes | None, timeout: float,
                     *, key, parent_sp=None, hedge: bool = False,
                     info: dict | None = None, span=None,
                     ) -> tuple[int, dict, bytes] | None:
        sp = span if span is not None else _otrace.open_span(
            parent_sp, "attempt", worker=url, hedge=hedge)
        inject_headers = None
        if sp is not None:
            if info is not None:
                # recorded at LAUNCH, not at success: a failed primary
                # and its winning hedge must BOTH be attributable
                info["hedge_span_id" if hedge
                     else "primary_span_id"] = sp.sid()
            # causal propagation (KAO111): the worker-side solve trace
            # roots under exactly THIS attempt span
            tp = _otrace.inject(sp.trace.trace_id, sp.sid())
            if tp:
                inject_headers = {_otrace.TRACEPARENT: tp}
        try:
            status, headers, data = self._proxy_once(
                url, method, path, body, timeout,
                headers=inject_headers,
            )
        except Exception as e:
            self.tracker.note_result(url, ok=False)
            self._count("retries_total", "connect_fail")
            _otrace.close_span(sp, error=repr(e)[:200])
            return None
        self.tracker.note_result(url, ok=True)
        shed = self._shed_info(status, headers, data)
        if shed is not None:
            retry_after, bucket = shed
            self.tracker.note_retry_after(
                url, retry_after,
                bucket=bucket if bucket is not None else None,
            )
            self._count("retries_total", "shed")
            _otrace.close_span(sp, status=status, shed=True,
                               retry_after_s=round(retry_after, 3))
            return None
        self._count("proxied_total")
        if info is not None:
            info["worker"] = url
            wid = headers.get("X-KAO-Worker")
            if wid:
                info["worker_identity"] = wid
            info["answered_by_hedge"] = hedge
        _otrace.close_span(sp, status=status)
        return status, headers, data

    def _attempt_hedged(self, primary: str, secondary: str,
                        method: str, path: str, body: bytes | None,
                        timeout: float, *, key, parent_sp=None,
                        info: dict | None = None,
                        ) -> tuple[int, dict, bytes] | None:
        """Race ``primary`` against a delayed duplicate on
        ``secondary``: fire the duplicate only after ``hedge_s``
        without an answer and only inside the concurrent-hedge budget.
        First non-shed answer wins; the loser's work is the budgeted
        cost of the tail latency saved."""
        results: list = []
        done = threading.Condition()
        # per-slot attribution scratch: the racing threads never write
        # one shared dict (the loser finishing late must not overwrite
        # the winner's attribution); the winner's entry merges below
        infos: list[dict] = [{}, {}]

        def run(u, slot, span, hedge=False, release_token=False):
            try:
                out = self._attempt_one(u, method, path, body,
                                        timeout, key=key,
                                        parent_sp=parent_sp,
                                        hedge=hedge, info=infos[slot],
                                        span=span)
            finally:
                if release_token:
                    # the duplicate's budget token is held for as long
                    # as the duplicate actually occupies a worker, not
                    # just until the race resolves
                    with self._lock:
                        self._hedges_inflight -= 1
            with done:
                results.append((slot, out))
                done.notify_all()

        def launch(u, slot, hedge=False, release_token=False):
            # open the attempt span (and stamp its ID into the slot's
            # attribution scratch) BEFORE Thread.start(): the winner's
            # merge below may run before the OS ever schedules the
            # loser's thread, and the envelope must still carry both
            # attempt span IDs
            sp = _otrace.open_span(parent_sp, "attempt", worker=u,
                                   hedge=hedge)
            if sp is not None:
                infos[slot]["hedge_span_id" if hedge
                            else "primary_span_id"] = sp.sid()
            threading.Thread(
                target=run, args=(u, slot, sp),
                kwargs={"hedge": hedge,
                        "release_token": release_token},
                daemon=True,
            ).start()

        launch(primary, 0)
        launched = 1
        hedged = False
        with done:
            done.wait(self.hedge_s)
            if not results:
                with self._lock:
                    can = self._hedges_inflight < self.hedge_budget
                    if can:
                        self._hedges_inflight += 1
                if can:
                    self._count("hedges_total")
                    hedged = True
                    if parent_sp is not None:
                        # the duplicate race is itself a tail-retention
                        # signal (TailPolicy keeps hedged traces full)
                        parent_sp.set(hedged=True)
                        _otrace.close_span(_otrace.open_span(
                            parent_sp, "hedge_launch",
                            secondary=secondary,
                        ))
                    launch(secondary, 1, hedge=True,
                           release_token=True)
                    launched = 2

            def merge_attribution(slot: int) -> None:
                if info is None:
                    return
                if "primary_span_id" in infos[0]:
                    info["primary_span_id"] = infos[0][
                        "primary_span_id"]
                if "hedge_span_id" in infos[1]:
                    info["hedge_span_id"] = infos[1]["hedge_span_id"]
                for k in ("worker", "worker_identity",
                          "answered_by_hedge"):
                    if k in infos[slot]:
                        info[k] = infos[slot][k]
                if hedged:
                    info["hedge_won"] = (slot == 1)

            while True:
                for slot, out in results:
                    if out is not None:
                        if slot == 1:
                            self._count("hedge_wins_total")
                        merge_attribution(slot)
                        if hedged and parent_sp is not None:
                            parent_sp.set(hedge_won=(slot == 1))
                        return out
                if len(results) >= launched:
                    # every launched attempt failed: merge NOTHING —
                    # route() fails over, and a later worker's
                    # successful plain attempt must not inherit this
                    # dead race's hedge_span_id/hedge_won
                    return None
                done.wait()

    # -- warmup orchestration ----------------------------------------

    @staticmethod
    def _parse_shape(sh) -> tuple[int, int, int, int]:
        if isinstance(sh, dict):
            vals = (sh.get("brokers"), sh.get("partitions"),
                    sh.get("rf", 3), sh.get("racks", 1))
        elif isinstance(sh, list) and 2 <= len(sh) <= 4:
            vals = tuple(sh) + (3, 1)[len(sh) - 2:]
        else:
            raise ValueError(
                "each warmup shape must be {brokers, partitions, rf?, "
                "racks?} or a [brokers, partitions, rf?, racks?] array"
            )
        if not all(isinstance(v, int) and not isinstance(v, bool)
                   and v > 0 for v in vals):
            raise ValueError(
                f"warmup shape values must be positive ints: {sh}"
            )
        return vals  # (B, P, R, K)

    def orchestrate_warmup(self, payload: dict) -> tuple[int, dict]:
        """POST /warmup at the router: partition the shapes by bucket
        owner (each bucket compiles exactly once fleet-wide), then
        optionally spread every bucket to every other worker from the
        shared persistent compile cache."""
        if not isinstance(payload, dict):
            return 400, {"error": "request body must be a JSON object"}
        shapes = payload.get("shapes")
        if not isinstance(shapes, list) or not shapes:
            return 400, {"error": "missing required field 'shapes' "
                                  "(non-empty list)"}
        spread = payload.get("spread", "all")
        if spread not in ("all", "owners"):
            return 400, {"error": "warmup 'spread' must be 'all' "
                                  "(every worker ends warm; non-owners "
                                  "pull from the shared compile cache) "
                                  "or 'owners'"}
        try:
            parsed = [self._parse_shape(sh) for sh in shapes]
        except ValueError as e:
            return 400, {"error": str(e)}
        passthrough = {
            k: payload[k]
            for k in ("engine", "lanes", "portfolio")
            if k in payload
        }
        live = self.tracker.live()
        if not live:
            return 503, {"error": "no live workers to warm",
                         "reason": "fleet_exhausted",
                         "retry_after_s": 5.0}
        self._count("warmups_total")
        owned: dict[str, list] = {}
        for b, p, r, k in parsed:
            key = _aff.shape_key(b, p, r, k)
            owner = _aff.rendezvous_rank(key, live)[0]
            owned.setdefault(owner, []).append(
                {"brokers": b, "partitions": p, "rf": r, "racks": k}
            )

        def post_warmup(url, shs):
            body = json.dumps(
                {"shapes": shs, **passthrough}
            ).encode()
            try:
                status, _, data = self._proxy_once(
                    url, "POST", "/warmup", body,
                    self.solve_timeout_s,
                )
                self.tracker.note_result(url, ok=True)
                out = json.loads(data)
                if status != 200:
                    return {"error": out.get("error",
                                             f"status {status}")}
                return out
            except Exception as e:
                self.tracker.note_result(url, ok=False)
                return {"error": repr(e)[:200]}

        def phase(assignments: dict[str, list]) -> dict:
            threads, results = [], {}

            def run(u, shs):
                results[u] = post_warmup(u, shs)

            for u, shs in assignments.items():
                t = threading.Thread(target=run, args=(u, shs),
                                     daemon=True)
                t.start()
                threads.append(t)
            for t in threads:
                t.join()
            return results

        phase1 = phase(owned)
        phase2: dict = {}
        if spread == "all" and len(live) > 1:
            spread_assign = {
                u: [sh for ow, shs in owned.items() if ow != u
                    for sh in shs]
                for u in live
            }
            spread_assign = {u: shs for u, shs in spread_assign.items()
                            if shs}
            phase2 = phase(spread_assign)

        def misses(rows: dict) -> int | None:
            """Summed persistent-cache misses across a phase — None
            when ANY worker in the phase errored: a failed spread must
            read as unproven (consumers compare against 0, and
            None != 0), never as a vacuously perfect shared-cache
            spread."""
            n = 0
            for out in rows.values():
                if "error" in out:
                    return None
                for row in (out.get("warmed") or []):
                    n += int((row.get("persistent") or {})
                             .get("misses") or 0)
            return n

        errors = {
            u: out["error"]
            for u, out in {**phase1, **phase2}.items()
            if "error" in out
        }
        return 200, {
            "workers": len(live),
            "partition": owned,
            "phase1": phase1,
            "phase2": phase2,
            # each bucket should compile exactly once fleet-wide:
            # phase-1 misses are those single cold compiles, phase-2
            # misses should be ZERO with the shared cache armed (and
            # null — not zero — if the phase itself failed anywhere)
            "fresh_compiles": misses(phase1),
            "spread_fresh_compiles": misses(phase2),
            **({"errors": errors} if errors else {}),
        }

    # -- views -------------------------------------------------------

    def affinity_rate(self) -> float | None:
        with self._lock:
            h = self.counters["affinity_hits_total"]
            m = self.counters["affinity_misses_total"]
        return round(h / (h + m), 4) if (h + m) else None

    def snapshot(self) -> dict:
        with self._lock:
            counters = json.loads(json.dumps(self.counters))
            inflight = self._hedges_inflight
        return {
            "status": "ok",
            "role": "router",
            "routing": {
                "affinity_rate": self.affinity_rate(),
                "hedge_ms": round(self.hedge_s * 1e3, 1),
                "hedge_budget": self.hedge_budget,
                "hedges_inflight": inflight,
                "lock_wait_s": self.lock_wait_s,
            },
            "counters": counters,
            "fleet": self.tracker.snapshot(),
        }


def render_router_metrics(router: Router) -> str:
    """The ``kao_router_*`` families (docs/FLEET.md), rendered through
    the shared exposition helpers so the shape matches every other
    surface (KAO107; tests/test_metrics_format.py validates)."""
    snap = router.snapshot()
    c = snap["counters"]
    fleet = snap["fleet"]
    rate = snap["routing"]["affinity_rate"]
    fams = [
        ("kao_router_requests_total", "counter",
         "requests received by the router, by route",
         [({"route": r}, n)
          for r, n in sorted(c["requests_total"].items())]),
        ("kao_router_affinity_hits_total", "counter",
         "keyed requests whose first-ranked worker held the bucket "
         "warm",
         [(None, c["affinity_hits_total"])]),
        ("kao_router_affinity_misses_total", "counter",
         "keyed requests routed to a cold worker",
         [(None, c["affinity_misses_total"])]),
        ("kao_router_affinity_unkeyed_total", "counter",
         "requests with no computable bucket key",
         [(None, c["affinity_unkeyed_total"])]),
        ("kao_router_affinity_rate", "gauge",
         "affinity hit fraction over keyed requests (-1 before the "
         "first keyed request)",
         [(None, -1.0 if rate is None else rate)]),
        ("kao_router_retries_total", "counter",
         "failover attempts, by reason",
         [({"reason": r}, n)
          for r, n in sorted(c["retries_total"].items())]),
        ("kao_router_hedges_total", "counter",
         "duplicate requests fired after the hedge window",
         [(None, c["hedges_total"])]),
        ("kao_router_hedge_wins_total", "counter",
         "hedged duplicates that answered first",
         [(None, c["hedge_wins_total"])]),
        ("kao_router_sticky_total", "counter",
         "cluster-sticky routed requests (one writer per cluster)",
         [(None, c["sticky_total"])]),
        ("kao_router_exhausted_total", "counter",
         "router-originated 503s (every worker shed or unreachable)",
         [(None, c["exhausted_total"])]),
        ("kao_router_warmups_total", "counter",
         "fleet warmup orchestrations",
         [(None, c["warmups_total"])]),
        ("kao_router_proxied_total", "counter",
         "upstream responses relayed to clients",
         [(None, c["proxied_total"])]),
        ("kao_router_workers", "gauge",
         "workers currently live in the routing set",
         [(None, len(fleet["live"]))]),
        ("kao_router_worker_up", "gauge",
         "per-worker liveness (1 = in the routing set)",
         [({"worker": u}, 1 if w["alive"] else 0)
          for u, w in sorted(fleet["workers"].items())]),
        ("kao_router_worker_warm_buckets", "gauge",
         "per-worker warm-bucket ledger size",
         [({"worker": u}, len(w["warm_buckets"]))
          for u, w in sorted(fleet["workers"].items())]),
        ("kao_router_trace_reports", "gauge",
         "route traces resident in the router's ring (the fleet "
         "trace store behind GET /debug/traces)",
         [(None, _otrace.RECENT.stats()["reports"])]),
    ]
    # the shared kao_trace_* families (tail retention + traceparent
    # codec traffic) — same shape the workers render
    fams.extend(_otrace.trace_families())
    return _expo.render(fams)


# --------------------------------------------------------------------------
# the HTTP shell
# --------------------------------------------------------------------------


class RouterHandler(BaseHTTPRequestHandler):
    server_version = "kao-router/1.0"

    @property
    def router(self) -> Router:
        return self.server.router

    def log_message(self, fmt, *args):
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _send_json(self, status: int, obj: dict,
                   headers: dict | None = None) -> None:
        body = json.dumps(obj, default=str).encode()
        self._send_raw(status, {"Content-Type": "application/json",
                                **(headers or {})}, body)

    def _send_raw(self, status: int, headers: dict,
                  body: bytes) -> None:
        self.send_response(status)
        # hop-by-hop headers are this hop's business, and Server/Date
        # are re-stamped by send_response — relaying the upstream's
        # copies would duplicate them
        hop = {"content-length", "connection", "transfer-encoding",
               "keep-alive", "server", "date"}
        for k, v in headers.items():
            if k.lower() not in hop:
                self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _relay(self, out: tuple[int, dict, bytes]) -> None:
        status, headers, body = out
        self._send_raw(status, headers, body)

    def _body(self) -> bytes | None:
        try:
            n = int(self.headers.get("Content-Length", 0))
        except ValueError:
            return None
        if n < 0 or n > MAX_BODY_BYTES:
            return None
        return self.rfile.read(n)

    def _route(self) -> str:
        return self.path.split("?", 1)[0].rstrip("/") or "/"

    def _trace_begin(self, name: str, **attrs):
        """Begin the request's causal trace: adopt a client-supplied
        ``traceparent`` (remote-parented root) or open a fresh root.
        Returns ``(trace, remote_ctx)`` — ``(None, None)`` when router
        tracing is off (--no-trace)."""
        if not getattr(self.server, "trace", True):
            return None, None
        ctx = _otrace.extract(self.headers.get(_otrace.TRACEPARENT))
        tr = _otrace.begin(
            ctx.trace_id if ctx else True, name=name,
            remote_parent=ctx.span_id if ctx else None, **attrs,
        )
        if tr is not None:
            # mint the root's span ID NOW, before finish() snapshots
            # the report: the traceparent echoed after the relay
            # references this ID, so it must exist in the stored tree
            tr.root.sid()
        return tr, ctx

    def _finish_trace(self, tr, out) -> None:
        if tr is not None:
            if out is not None:
                tr.root.set(status=out[0])
            _otrace.finish(tr)

    def _attribute(self, out, info: dict, tr):
        """Post-process a routed answer: merge the attribution the
        route collected — answering worker identity + both attempt
        span IDs (primary + hedge), the ISSUE 15 hedge-attribution
        contract — into a successful JSON envelope, and echo the trace
        context as a ``traceparent`` response header."""
        status, headers, data = out
        if status == 200 and info.get("worker"):
            try:
                obj = json.loads(data)
            except ValueError:
                obj = None
            if isinstance(obj, dict):
                route_info = {"worker": info["worker"]}
                for k in ("worker_identity", "primary_span_id",
                          "hedge_span_id", "answered_by_hedge",
                          "hedge_won"):
                    if info.get(k) is not None:
                        route_info[k] = info[k]
                if tr is not None:
                    route_info["trace_id"] = tr.trace_id
                obj["route"] = route_info
                data = json.dumps(obj, default=str).encode()
        if tr is not None:
            tp = _otrace.inject(tr.trace_id, tr.root.sid())
            if tp:
                headers = {**headers, _otrace.TRACEPARENT: tp}
        return status, headers, data

    def _routed(self, name: str, fn, info: dict | None = None,
                **attrs) -> None:
        """Run one route() call under a request trace and relay its
        (attributed) answer."""
        tr, _ = self._trace_begin(name, **attrs)
        out = None
        try:
            out = fn()
        finally:
            self._finish_trace(tr, out)
        self._relay(self._attribute(out, info or {}, tr))

    def do_GET(self):
        route = self._route()
        r = self.router
        if route == "/healthz":
            self._send_json(200, r.snapshot())
        elif route == "/metrics":
            self._send_raw(
                200, {"Content-Type": "text/plain; version=0.0.4"},
                render_router_metrics(r).encode(),
            )
        elif route == "/":
            self._send_json(200, {
                "service": "kao-router",
                "doc": "docs/FLEET.md",
                "workers": r.tracker.urls(),
                "proxies": ["/submit", "/evaluate", "/warmup",
                            "/clusters/*"],
                "debug": ["/debug/traces", "/debug/traces/<id>"],
            })
        elif route == "/clusters":
            r._count("requests_total", "clusters_get")
            self._merge_cluster_listing()
        elif route == "/debug/traces":
            r._count("requests_total", "debug_traces")
            self._send_json(200, {"trace_ids": _otrace.RECENT.ids()})
        elif route.startswith("/debug/traces/"):
            r._count("requests_total", "debug_traces")
            self._merged_trace(route[len("/debug/traces/"):])
        elif route.startswith("/clusters/"):
            cid = route[len("/clusters/"):].split("/", 1)[0]
            r._count("requests_total", "clusters_get")
            r._count("sticky_total")
            self._routed(
                "route",
                lambda: r.route("GET", self.path, None, sticky=cid,
                                timeout=r.connect_timeout_s * 6),
                route="clusters_get", cluster=cid,
            )
        else:
            self._send_json(404, {
                "error": f"no such router endpoint: {self.path}; "
                         "worker debug surfaces are per-worker "
                         "(see /healthz fleet.workers)",
            })

    def _merged_trace(self, trace_id: str) -> None:
        """GET /debug/traces/<id> — the cross-process causal join
        (docs/OBSERVABILITY.md "Distributed traces"): the router's own
        route trace plus every live worker's /debug/solves/<id> tree
        for the same ID, unioned under the router's root
        (obs.causal.merge_fleet_trace). ``?format=chrome`` exports the
        merged tree as ONE Perfetto file with per-process track
        groups."""
        r = self.router
        own = _otrace.RECENT.get(trace_id)
        remotes, errors = _ocausal.collect_remote(
            r.tracker.live(), trace_id,
            timeout_s=r.connect_timeout_s * 6,
        )
        if own is None and not remotes:
            self._send_json(404, {
                "error": f"no trace {trace_id!r} on the router or any "
                         "live worker (rings hold recent traces only; "
                         "with KAO_TRACE_TAIL a fast-clean trace may "
                         "have been head-sampled away)",
                **({"errors": errors} if errors else {}),
            })
            return
        merged = _ocausal.merge_fleet_trace(trace_id, own, remotes)
        if errors:
            merged["errors"] = errors
        fmt = (urllib.parse.parse_qs(
            urllib.parse.urlsplit(self.path).query,
        ).get("format") or ["json"])[0]
        if fmt == "chrome":
            self._send_json(200, _ochrome.to_chrome_fleet(merged))
        elif fmt == "json":
            self._send_json(200, merged)
        else:
            self._send_json(400, {
                "error": f"unknown format {fmt!r}; want 'json' or "
                         "'chrome'",
            })

    def _merge_cluster_listing(self) -> None:
        """GET /clusters fans out to every live worker CONCURRENTLY —
        N dead workers cost ~one connect timeout on this handler
        thread, not N stacked (the /debug/fleet discipline) — and
        unions the cluster maps (each cluster lives on exactly one
        sticky owner)."""
        r = self.router
        merged: dict = {}
        errors: dict = {}
        lock = threading.Lock()

        def fetch(url):
            try:
                status, _, data = r._proxy_once(
                    url, "GET", "/clusters", None,
                    r.connect_timeout_s * 6,
                )
                r.tracker.note_result(url, ok=True)
                if status == 200:
                    body = json.loads(data)
                    with lock:
                        for cid, info in (body.get("clusters")
                                          or {}).items():
                            merged[cid] = {**info, "worker": url}
                else:
                    with lock:
                        errors[url] = f"status {status}"
            except Exception as e:
                r.tracker.note_result(url, ok=False)
                with lock:
                    errors[url] = repr(e)[:200]

        threads = [threading.Thread(target=fetch, args=(u,),
                                    daemon=True)
                   for u in r.tracker.live()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self._send_json(200, {
            "clusters": merged,
            **({"errors": errors} if errors else {}),
        })

    def do_POST(self):
        route = self._route()
        r = self.router
        body = self._body()
        if body is None:
            self._send_json(400, {"error": "bad Content-Length"})
            return
        if route == "/submit":
            r._count("requests_total", "submit")
            try:
                payload = json.loads(body)
            except ValueError:
                payload = None
            key = (_aff.bucket_key_of(payload)
                   if isinstance(payload, dict) else None)
            hedge = bool(
                isinstance(payload, dict)
                and payload.get("deadline_s") is not None
            )
            info: dict = {}
            self._routed(
                "route",
                lambda: r.route("POST", "/submit", body, key=key,
                                hedge=hedge, info=info),
                info=info, route="submit",
            )
        elif route == "/evaluate":
            r._count("requests_total", "evaluate")
            self._routed(
                "route",
                lambda: r.route("POST", "/evaluate", body),
                route="evaluate",
            )
        elif route == "/warmup":
            r._count("requests_total", "warmup")
            try:
                payload = json.loads(body)
            except ValueError:
                self._send_json(400, {"error": "invalid JSON"})
                return
            status, out = r.orchestrate_warmup(payload)
            self._send_json(status, out)
        elif route.startswith("/clusters/"):
            cid = route[len("/clusters/"):].split("/", 1)[0]
            r._count("requests_total", "clusters_post")
            r._count("sticky_total")
            # sticky + sequential: epoch fencing must see ONE writer
            # per cluster, so cluster commands never hedge in parallel
            self._routed(
                "route",
                lambda: r.route("POST", self.path, body, sticky=cid),
                route="clusters_post", cluster=cid,
            )
        else:
            self._send_json(404,
                            {"error": f"no such endpoint: {self.path}"})


def make_router_server(host: str, port: int, router: Router, *,
                       verbose: bool = False,
                       trace: bool = True) -> ThreadingHTTPServer:
    srv = ThreadingHTTPServer((host, port), RouterHandler)
    srv.router = router
    srv.verbose = verbose
    srv.trace = trace
    return srv


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="kao-router",
        description="Bucket-affinity fleet router: proxy /submit, "
                    "/clusters/*, /evaluate and /warmup across N "
                    "serve workers with warmth-first routing, hedged "
                    "failover, and fleet warmup orchestration "
                    "(docs/FLEET.md)",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8700)
    ap.add_argument("--workers", required=True, metavar="URL,URL",
                    help="worker base URLs, e.g. "
                         "'http://10.0.0.2:8787,http://10.0.0.3:8787'")
    ap.add_argument("--health-interval-s", type=float, default=2.0,
                    help="worker /healthz poll interval (liveness + "
                         "the warm-bucket affinity ledger)")
    ap.add_argument("--fail-after", type=int, default=2,
                    help="consecutive failures before a worker leaves "
                         "the routing set (rejoins on first success)")
    ap.add_argument("--lock-wait-s", type=float,
                    default=DEFAULT_LOCK_WAIT_S,
                    help="max seconds a request may spend in failover "
                         "(incl. waiting out worker Retry-After "
                         "cooldowns) before the router sheds 503")
    ap.add_argument("--solve-timeout-s", type=float,
                    default=DEFAULT_SOLVE_TIMEOUT_S,
                    help="per-attempt upstream read timeout")
    ap.add_argument("--connect-timeout-s", type=float,
                    default=DEFAULT_CONNECT_TIMEOUT_S,
                    help="health-poll/listing timeout")
    ap.add_argument("--hedge-ms", type=float, default=DEFAULT_HEDGE_MS,
                    help="latency hedge: a deadline-carrying /submit "
                         "unanswered after this window fires a "
                         "duplicate at the next-ranked worker (first "
                         "answer wins)")
    ap.add_argument("--hedge-budget", type=int,
                    default=DEFAULT_HEDGE_BUDGET,
                    help="max concurrent hedged duplicates fleet-wide "
                         "(0 disables hedging)")
    ap.add_argument("--no-trace", action="store_true",
                    help="disable per-request causal traces (route "
                         "decisions, attempts, hedges; responses then "
                         "carry no traceparent and /debug/traces "
                         "stays empty on the router). Tail retention "
                         "on the router's ring is the same "
                         "KAO_TRACE_TAIL env the workers honor "
                         "(docs/OBSERVABILITY.md)")
    ap.add_argument("--verbose", action="store_true",
                    help="access logs")
    return ap


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    urls = [u.strip().rstrip("/") for u in args.workers.split(",")
            if u.strip()]
    bad = [u for u in urls
           if not u.startswith(("http://", "https://"))]
    if bad or not urls:
        build_parser().error(
            f"--workers URLs must be http(s)://: {bad or urls}"
        )
    tracker = FleetTracker(
        urls, interval_s=args.health_interval_s,
        timeout_s=args.connect_timeout_s, fail_after=args.fail_after,
    )
    router = Router(
        tracker, lock_wait_s=args.lock_wait_s,
        solve_timeout_s=args.solve_timeout_s,
        connect_timeout_s=args.connect_timeout_s,
        hedge_ms=args.hedge_ms, hedge_budget=args.hedge_budget,
    )
    tracker.start()
    srv = make_router_server(args.host, args.port, router,
                             verbose=args.verbose,
                             trace=not args.no_trace)
    _olog.log("router_listening", host=args.host,
              port=srv.server_address[1], workers=len(urls))
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        tracker.stop()
        srv.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
