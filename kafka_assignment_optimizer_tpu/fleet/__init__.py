"""Bucket-affinity serving fleet (docs/FLEET.md).

The serving half of the pod-scale fleet (ROADMAP item 1): N ordinary
``serve.py`` worker processes behind one ``kao-router`` front process
that

- routes each ``/submit`` to the worker whose lane-padded executables
  and exec cache are already warm for the request's shape bucket
  (``affinity`` — the PR-1 bucket key computed host-side, rendezvous-
  hashed over the live worker set, biased by the workers' ``/healthz``
  warm-bucket ledgers),
- fails over on sheds and dead workers with budget-capped hedging
  (``router``), keeping every watched cluster sticky to one worker so
  epoch fencing still sees a single writer,
- partitions warmup across the fleet so each bucket compiles exactly
  once fleet-wide, with the shared persistent compile cache
  (``KAO_COMPILE_CACHE``, ``utils.platform``) turning that one cold
  compile into every other worker's disk hit.

The router itself never imports jax (pinned by test): it is pure
stdlib HTTP + the dependency-free bucket/cluster model modules, so it
boots in milliseconds and can front heterogeneous worker pools.
"""

from __future__ import annotations

from .affinity import bucket_key_of, rank_workers, rendezvous_rank

__all__ = ["bucket_key_of", "rank_workers", "rendezvous_rank"]
