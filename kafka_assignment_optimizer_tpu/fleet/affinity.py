"""Bucket-affinity routing math (docs/FLEET.md).

Two pieces, both jax-free (the router must boot in milliseconds and
never initialize a device backend — pinned by test):

- :func:`bucket_key_of` recomputes the PR-1 executable bucket key
  ``(brokers, racks, part-bucket, rf-bucket)`` from a raw ``/submit``
  payload, HOST-SIDE, with exactly the semantics ``serve.handle_submit``
  uses when it builds the instance (pinned against ``build_instance``
  by test). The key is the unit of warmth: every solve in a bucket
  reuses one set of compiled executables, so routing by bucket IS
  routing to warmth.

- :func:`rendezvous_rank` / :func:`rank_workers` order the live worker
  set for a key: highest-random-weight (rendezvous) hashing gives every
  key a stable owner that only moves when ITS owner leaves — a worker
  join/leave reshuffles only the buckets the affected worker owned,
  never the whole keyspace — and the warmth bias sorts workers whose
  ``/healthz`` affinity ledger already reports the bucket warm ahead of
  cold ones (a router restart then keeps routing warm even before its
  own routing history rebuilds).
"""

from __future__ import annotations

import hashlib

from ..models.cluster import Assignment, Topology, parse_broker_list
from ..solvers.tpu import bucket as _bucket

__all__ = ["bucket_key_of", "payload_shape", "shape_key",
           "rendezvous_rank", "rank_workers"]


def payload_shape(payload: dict) -> tuple[int, int, int, int] | None:
    """``(B, K, P, R)`` — brokers, racks, partitions, max-RF — of a
    /submit-style payload, mirroring ``build_instance``; None when the
    payload is malformed (the worker will 400/422 it — the router just
    routes it anywhere)."""
    try:
        current = Assignment.from_dict(payload["assignment"])
        spec = payload["brokers"]
        brokers = (parse_broker_list(spec) if isinstance(spec, str)
                   else [int(b) for b in spec])
        broker_ids = sorted(set(int(b) for b in brokers))
        if not broker_ids:
            return None
        topo_spec = payload.get("topology")
        if topo_spec is None:
            topo = None
        elif topo_spec == "even-odd":
            all_ids = sorted(set(broker_ids) | set(current.broker_ids()))
            topo = Topology.even_odd(all_ids)
        elif isinstance(topo_spec, dict):
            topo = Topology.from_dict(topo_spec)
        else:
            return None
        if topo is None:
            num_racks = 1
        else:
            num_racks = len({topo.rack(int(b)) for b in broker_ids})
        parts = current.partitions
        if not parts:
            return None
        rf = payload.get("rf")
        if rf is None:
            max_rf = max(len(p.replicas) for p in parts)
        elif isinstance(rf, bool):
            return None
        elif isinstance(rf, int):
            max_rf = int(rf)
        elif isinstance(rf, dict):
            max_rf = max(
                int(rf.get(p.topic, len(p.replicas))) for p in parts
            )
        else:
            return None
        if not 1 <= max_rf <= len(broker_ids):
            return None
        return len(broker_ids), num_racks, len(parts), max_rf
    except Exception:
        return None


def shape_key(brokers: int, partitions: int, rf: int,
              racks: int) -> tuple[int, int, int, int]:
    """The bucket key of one warmup shape ``(B, P, R, K)`` — what the
    router partitions across workers for fleet warmup."""
    return (int(brokers), int(racks),
            _bucket.part_bucket(partitions), _bucket.rf_bucket(rf))


def bucket_key_of(payload: dict) -> tuple[int, int, int, int] | None:
    """The executable bucket key of a /submit payload, or None. Same
    4-tuple the worker records in its affinity ledger
    (``/healthz`` cache ``warm_buckets``) and keys its circuit breaker
    and exec cache on."""
    shape = payload_shape(payload)
    if shape is None:
        return None
    b, k, p, r = shape
    return b, k, _bucket.part_bucket(p), _bucket.rf_bucket(r)


def _score(key_str: str, worker: str) -> int:
    h = hashlib.sha256(
        (key_str + "|" + worker).encode("utf-8", "replace")
    ).digest()
    return int.from_bytes(h[:8], "big")


def rendezvous_rank(key, workers: list[str]) -> list[str]:
    """Workers ordered by highest-random-weight hash for ``key``:
    deterministic, and minimally disruptive under membership change —
    removing a worker promotes the runner-up for ONLY that worker's
    keys; adding one steals only the keys it now wins."""
    key_str = ("~" if key is None
               else ":".join(str(x) for x in key))
    return sorted(workers,
                  key=lambda w: (-_score(key_str, w), w))


def rank_workers(key, workers: list[str],
                 warm: dict | None = None) -> list[str]:
    """The routing order for ``key``: rendezvous order, with workers
    whose affinity ledger reports the bucket warm sorted first (stable
    within the warm and cold groups, so two warm workers still split
    keys deterministically by rendezvous weight).

    ``warm`` maps worker -> set of bucket-key tuples (from the health
    tracker's /healthz polls); None or an unknown key means no bias —
    pure rendezvous."""
    ranked = rendezvous_rank(key, workers)
    if not warm or key is None:
        return ranked
    kt = tuple(key)
    return sorted(
        ranked,
        key=lambda w: 0 if kt in warm.get(w, ()) else 1,
    )
